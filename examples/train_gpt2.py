"""GPT-2 (up to -small/125M) hybrid-parallel language-model training —
BASELINE.json config #5 ("TinyStories GPT-2-small, data-parallel AllReduce +
grad accumulation").

One jitted step over a pp×dp×sp/cp×tp mesh: GPipe pipeline stages (``--pp``),
Megatron tensor parallelism, ring (or Ulysses) sequence-parallel attention —
``--cp N`` picks the context-parallel flash ring for 128k-token-class
sequences (``ops/ring_attention.py``) — data-parallel batch sharding with
on-device gradient accumulation: the full hybrid-parallelism roadmap the
reference carried only as literature (SURVEY.md §2.3).

Token source: ``--data`` can point at any UTF-8 text file (e.g. a
TinyStories dump). Without one (this container has no egress), a
procedurally generated story corpus is byte-tokenized so the loss measures
real sequence structure, not noise.

    python examples/train_gpt2.py --steps 20 --platform cpu --cpu_devices 8 \
        --model tiny --dp 2 --sp 2 --tp 2
    python examples/train_gpt2.py --steps 200 --model small --grad_accum 4
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")  # repo-root invocation

from dsml_tpu.utils.config import Config, field


@dataclasses.dataclass
class GPT2TrainConfig(Config):
    platform: str = field("", help="jax platform override: cpu|tpu ('' = default)")
    cpu_devices: int = field(0, help="virtual CPU device count for --platform cpu")
    model: str = field("tiny", help="gpt2 family: tiny | small (125M, the BASELINE config) | medium | large | xl; llama family: tiny | tinyllama_1b | llama2_7b | llama3_8b | mixtral_8x7b")
    family: str = field("gpt2", help="model family: gpt2 | llama (RMSNorm/RoPE/SwiGLU/GQA)")
    dtype: str = field("", help="params/activations dtype: float32 | bfloat16 ('' = model default; bfloat16 feeds the MXU at full rate on TPU)")
    remat: bool = field(False, help="rematerialize each block's activations in backward (less HBM, more FLOPs)")
    data: str = field(
        "", help="UTF-8 text file to train on; 'prose' = real on-disk English "
        "corpus (utils.data.load_text_corpus); '' = generated stories"
    )
    tokenizer: str = field(
        "", help="'' = byte-level (vocab 256); 'bpe' = train (or load the "
        "cached) byte-level BPE on the corpus and train over its ids "
        "(utils.tokenizer.BPETokenizer)"
    )
    bpe_vocab: int = field(2048, help="BPE vocab size (with --tokenizer bpe)")
    steps: int = field(50, help="optimizer steps")
    batch_size: int = field(8, help="GLOBAL batch size (rows per optimizer step)")
    seq_len: int = field(0, help="sequence length (0 = model max)")
    grad_accum: int = field(2, help="gradient-accumulation microbatches per step")
    pp: int = field(1, help="pipeline-parallel stages")
    schedule: str = field("gpipe", help="pipeline schedule (pp > 1): gpipe | 1f1b")
    n_micro: int = field(2, help="pipeline microbatches per step (pp > 1)")
    dp: int = field(0, help="data-parallel size (0 = derive from devices)")
    sp: int = field(1, help="sequence-parallel size (legacy XLA ring)")
    cp: int = field(1, help="context-parallel size (flash ring attention: bidirectional KV streaming + causal hop skip + KV re-streaming backward; docs/TUNING.md § Context parallelism)")
    tp: int = field(1, help="tensor-parallel size")
    attn: str = field("", help="attention impl: ring | ring2 | ulysses | ulysses_flash | ring_flash | flash | xla ('' = auto: ring2 on cp meshes, ring otherwise)")
    lr: float = field(3e-4, help="peak learning rate")
    optimizer: str = field("adamw", help="adamw | adafactor (factored second "
                           "moments — O(rows+cols) state instead of two full "
                           "f32 moment trees; with --remat this is what fits "
                           "GPT-2-XL/1.5B on one 16GB chip)")
    clip_norm: float = field(1.0, help="global-norm gradient clip (0 = off)")
    warmup_steps: int = field(10, help="linear warmup steps")
    seed: int = field(0, help="init/data seed")
    log_every: int = field(10, help="log every N steps")
    eval_every: int = field(0, help="held-out perplexity every N steps (0 = off)")
    profile_dir: str = field("", help="write a jax.profiler (TensorBoard) trace of the run here")
    checkpoint_dir: str = field("", help="Orbax checkpoint directory; saves params+opt_state at the end ('' = off), resumes when one exists")


_WORDS = {
    "subj": ["the cat", "a dog", "the girl", "a boy", "the robot", "her friend"],
    "verb": ["found", "chased", "painted", "built", "lost", "shared"],
    "obj": ["a ball", "the kite", "a tiny boat", "the red box", "a shiny coin"],
    "end": ["and smiled.", "and ran home.", "by the river.", "under the tree."],
}


def _generated_stories(n_chars: int, seed: int) -> bytes:
    """TinyStories-shaped filler: simple grammatical sentences, so next-byte
    prediction has learnable structure (articles, spaces, word stems)."""
    rng = np.random.default_rng(seed)
    parts = []
    size = 0
    while size < n_chars:
        s = (
            f"{rng.choice(_WORDS['subj'])} {rng.choice(_WORDS['verb'])} "
            f"{rng.choice(_WORDS['obj'])} {rng.choice(_WORDS['end'])} "
        )
        parts.append(s)
        size += len(s)
    return "".join(parts).encode()


def main(argv=None):
    cfg = GPT2TrainConfig.parse_args(argv)
    from dsml_tpu.utils.platform import configure_platform

    configure_platform(cfg.platform, cfg.cpu_devices)

    import jax
    import optax

    from dsml_tpu.parallel.hybrid import init_hybrid, make_hybrid_train_step
    from dsml_tpu.parallel.mesh import MeshSpec, build_mesh
    from dsml_tpu.utils.logging import get_logger
    from dsml_tpu.utils.schedules import make_schedule

    log = get_logger("gpt2")
    devices = jax.devices()
    dp = cfg.dp or max(len(devices) // (cfg.pp * cfg.sp * cfg.cp * cfg.tp), 1)
    n_used = cfg.pp * dp * cfg.sp * cfg.cp * cfg.tp
    mesh = build_mesh(
        MeshSpec(pp=cfg.pp, dp=dp, sp=cfg.sp, cp=cfg.cp, tp=cfg.tp), devices[:n_used]
    )

    # the batch must split evenly: global batch → grad_accum microbatches →
    # dp shards → (pp>1) pipeline microbatches
    if cfg.batch_size % (cfg.grad_accum * dp):
        raise SystemExit(
            f"batch_size={cfg.batch_size} must be divisible by grad_accum*dp="
            f"{cfg.grad_accum * dp} (use --batch_size {cfg.grad_accum * dp * 2})"
        )
    n_micro = cfg.n_micro
    if cfg.pp > 1:
        rows_per_rank = cfg.batch_size // (cfg.grad_accum * dp)
        while rows_per_rank % n_micro:
            n_micro -= 1  # largest feasible microbatch count ≥ 1
        if n_micro != cfg.n_micro:
            print(
                f"note: n_micro={cfg.n_micro} does not divide the {rows_per_rank} "
                f"rows per dp rank; using n_micro={n_micro}"
            )

    from dsml_tpu.models import model_by_family

    try:
        model, model_cfg = model_by_family(cfg.family, cfg.model, vocab_size=256)  # tiny = byte tokens
    except ValueError as e:
        raise SystemExit(str(e))
    if cfg.dtype:
        model_cfg = dataclasses.replace(model_cfg, dtype=cfg.dtype)
    if cfg.remat:
        model_cfg = dataclasses.replace(model_cfg, remat=True)
    model = type(model)(model_cfg)
    seq = cfg.seq_len or model_cfg.max_seq

    # ---- tokens: file, real prose, or generated corpus — byte-level ------------
    if cfg.data == "prose":
        # REAL English text assembled from on-disk sources
        # (utils.data.load_text_corpus): the loss-goes-down-on-real-text run
        from dsml_tpu.utils.data import load_text_corpus

        toks8, prov = load_text_corpus()
        corpus = bytes(toks8)
        log.info("training on real prose: %s (%d bytes)", prov, len(corpus))
    elif cfg.data:
        # an explicit path that doesn't exist must raise — a typo must not
        # silently train on the generated-stories fallback (same contract
        # as utils.data.load_text_corpus)
        if not os.path.exists(cfg.data):
            raise FileNotFoundError(
                f"--data {cfg.data!r} does not exist (use 'prose' for the "
                "built-in real-text corpus, or '' for generated stories)"
            )
        with open(cfg.data, "rb") as f:
            corpus = f.read()
        log.info("training on %s (%d bytes)", cfg.data, len(corpus))
    else:
        need = cfg.steps * cfg.batch_size * (seq + 1) * 2
        corpus = _generated_stories(max(need, 1 << 20), cfg.seed)
        log.info("no --data file; generated %d bytes of story corpus", len(corpus))
    from dsml_tpu.utils.data import carve_lm_eval_split, lm_window_batches, prefetch_batches

    if cfg.tokenizer == "bpe":
        # train-or-load a BPE on THIS corpus (cache keyed on corpus digest +
        # vocab, under data/ — retraining is pure waste), then rebuild the
        # model at the tokenizer's vocab. Tokens/byte is logged: the
        # compression is the point (more text per sequence position).
        import hashlib

        from dsml_tpu.utils.tokenizer import BPETokenizer

        text = corpus.decode("utf-8", errors="replace")
        digest = hashlib.sha1(corpus).hexdigest()[:8]
        cache = os.path.join("data", f"bpe_v{cfg.bpe_vocab}_{digest}.json")
        if os.path.exists(cache):
            tok = BPETokenizer.load(cache)
            log.info("loaded cached BPE %s (vocab %d)", cache, tok.vocab_size)
        else:
            t0 = time.monotonic()
            tok = BPETokenizer.train(text, vocab_size=cfg.bpe_vocab)
            os.makedirs("data", exist_ok=True)
            tok.save(cache)
            log.info(
                "trained BPE vocab %d in %.1fs → cached at %s",
                tok.vocab_size, time.monotonic() - t0, cache,
            )
        tokens = tok.encode_array(text)
        log.info(
            "BPE tokens: %d (%.2f bytes/token vs 1.0 byte-level)",
            len(tokens), len(corpus) / max(len(tokens), 1),
        )
        # the embedding is vocab-sharded P('tp', ...) under tensor
        # parallelism, and early-stopped training can return any vocab —
        # padded_vocab rounds to an lcm(8, tp) multiple so a checkpoint
        # trained here restores under any serving tp <= 8 (the dead rows
        # are never indexed; rounding also keeps the unembed MXU-tileable)
        from dsml_tpu.utils.tokenizer import padded_vocab

        vocab = padded_vocab(tok.vocab_size, cfg.tp)
        if vocab != tok.vocab_size:
            log.info("padding vocab %d → %d (tp=%d)", tok.vocab_size, vocab, cfg.tp)
        model_cfg = dataclasses.replace(model_cfg, vocab_size=vocab)
        model = type(model)(model_cfg)
    elif cfg.tokenizer:
        raise SystemExit(f"unknown --tokenizer {cfg.tokenizer!r} (use '' or 'bpe')")
    else:
        tokens = np.frombuffer(corpus, np.uint8).astype(np.int32) % model_cfg.vocab_size
    eval_tokens = None
    if cfg.eval_every:
        tokens, eval_tokens = carve_lm_eval_split(tokens, seq, cfg.batch_size)
        if eval_tokens is None:
            log.warning(
                "corpus (%d tokens) too small to carve an eval split at seq=%d; "
                "eval disabled, training keeps the full corpus", len(tokens), seq,
            )

    # probe the checkpoint FIRST: a resumed optimizer count sits at
    # start_step, so the cosine horizon must cover start_step + cfg.steps or
    # every resumed update would land past decay-end at lr = 0
    ckpt = None
    start_step = 0
    if cfg.checkpoint_dir:
        from dsml_tpu.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(cfg.checkpoint_dir)
        start_step = ckpt.latest_step() or 0

    schedule_fn = make_schedule("cosine", cfg.lr, start_step + cfg.steps, cfg.warmup_steps)
    # clip BEFORE the update — spikes from a bad batch can't blow up a bf16
    # run (the standard LM-training guard). The chain is built for EVERY
    # clip_norm value (identity when off) so the opt_state pytree structure
    # — and therefore checkpoint resume — doesn't depend on the flag
    clip = optax.clip_by_global_norm(cfg.clip_norm) if cfg.clip_norm > 0 else optax.identity()
    if cfg.optimizer == "adafactor":
        optimizer = optax.chain(clip, optax.adafactor(schedule_fn))
    elif cfg.optimizer == "adamw":
        optimizer = optax.chain(clip, optax.adamw(schedule_fn))
    else:
        raise SystemExit(f"unknown --optimizer {cfg.optimizer!r} (adamw | adafactor)")
    step = make_hybrid_train_step(
        model, optimizer, mesh, attn_impl=cfg.attn or None,
        grad_accum=cfg.grad_accum, n_microbatches=n_micro, schedule=cfg.schedule,
    )
    params, opt_state = init_hybrid(model, optimizer, mesh, seed=cfg.seed)
    if ckpt is not None and start_step > 0:
        state = ckpt.restore(template={"params": params, "opt_state": opt_state})
        params, opt_state = state["params"], state["opt_state"]
        log.info("resumed from checkpoint at step %d", start_step)
    n_params = model.n_params(params)
    log.info(
        "%s %s: %.1fM params, mesh pp=%d dp=%d sp=%d cp=%d tp=%d, seq=%d, batch=%d x accum=%d",
        "Llama" if cfg.family == "llama" else "GPT-2", cfg.model, n_params / 1e6,
        cfg.pp, dp, cfg.sp, cfg.cp, cfg.tp, seq, cfg.batch_size, cfg.grad_accum,
    )

    import contextlib

    from dsml_tpu.utils.tracing import trace

    eval_loss_fn = None
    if eval_tokens is not None:
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from dsml_tpu.parallel.hybrid import hybrid_loss_fn

        from dsml_tpu.parallel.hybrid import default_attn_impl

        seq_axis = MeshSpec.from_mesh(mesh).seq_axis()
        eval_impl = cfg.attn or default_attn_impl(mesh)
        _lf = hybrid_loss_fn(model, eval_impl, "pp" if cfg.pp > 1 else None,
                             n_micro, seq_axis)
        eval_loss_fn = jax.jit(
            jax.shard_map(
                lambda p, x, y: lax.pmean(_lf(p, x, y), ("dp", seq_axis)),
                mesh=mesh,
                in_specs=(model.param_specs(pp=cfg.pp > 1), P("dp", seq_axis),
                          P("dp", seq_axis)),
                out_specs=P(),
                check_vma=False,
            )
        )
        # one fixed held-out batch, built once (it's deterministic anyway)
        eval_x, eval_y = next(lm_window_batches(eval_tokens, seq, cfg.batch_size, seed=1234))

    # advance the data stream past what the first run consumed, like the
    # Trainer's per-epoch cfg.seed + epoch; window assembly runs in a
    # background thread so host prep overlaps device compute
    batches = prefetch_batches(
        lm_window_batches(tokens, seq, cfg.batch_size, seed=cfg.seed + start_step)
    )
    t0 = time.monotonic()
    tokens_done = 0
    first_loss = None
    profiler = trace(cfg.profile_dir) if cfg.profile_dir else contextlib.nullcontext()
    with profiler:
        for i in range(1, cfg.steps + 1):
            x, y = next(batches)
            params, opt_state, loss = step(params, opt_state, x, y)
            tokens_done += x.size
            if first_loss is None:
                first_loss = float(loss)
            if i % cfg.log_every == 0 or i == cfg.steps:
                loss_f = float(loss)
                tps = tokens_done / max(time.monotonic() - t0, 1e-9)
                log.info("step %d: loss = %.4f, %.0f tokens/s", i, loss_f, tps)
            if eval_loss_fn is not None and (i % cfg.eval_every == 0 or i == cfg.steps):
                el = float(eval_loss_fn(params, eval_x, eval_y))
                log.info("step %d: eval loss = %.4f, perplexity = %.2f", i, el, float(np.exp(el)))
    if ckpt is not None:
        ckpt.save(start_step + cfg.steps, params, opt_state)
        ckpt.close()
    return {"first_loss": first_loss, "last_loss": float(loss)}


if __name__ == "__main__":
    main()
