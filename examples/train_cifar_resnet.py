"""CIFAR-10 ResNet-18, data-parallel with ring all-reduce + adaptive LR —
BASELINE.json config #4 ("CIFAR-10 ResNet-18, v4-8, ring AllReduce +
adaptive LR scheduler").

The reference never trained anything beyond its MLP (SURVEY.md §2.3); this
realizes the baseline ladder's vision config: genuine batch sharding over
the ``dp`` mesh axis, gradient sync through the explicit 2(n−1)-step
``ppermute`` ring, and the reduce-on-plateau adaptive scheduler the
reference README promised (SURVEY.md §8.8).

CIFAR-10 binary batches are loaded from ``--data_dir`` when present
(``data_batch_*.bin``, the standard 3073-byte records); with no dataset on
disk (this container has no egress) it falls back to a synthetic
10-class image workload so the pipeline stays runnable end-to-end.

    python examples/train_cifar_resnet.py --epochs 2 --platform cpu --cpu_devices 8
    python examples/train_cifar_resnet.py --epochs 30   # real chip
"""

from __future__ import annotations

import dataclasses
import glob
import os
import sys

import numpy as np

sys.path.insert(0, ".")  # repo-root invocation

from dsml_tpu.trainer import TrainConfig
from dsml_tpu.utils.config import field


@dataclasses.dataclass
class CIFARConfig(TrainConfig):
    platform: str = field("", help="jax platform override: cpu|tpu ('' = default)")
    cpu_devices: int = field(0, help="virtual CPU device count for --platform cpu")
    data_dir: str = field("data/cifar10", help="CIFAR-10 binary-batch directory")
    synth_n: int = field(4096, help="synthetic sample count when no dataset on disk")
    # config-4 defaults: ring gradient sync + adaptive LR
    batch_size: int = field(256, help="GLOBAL batch size")
    lr: float = field(0.1, help="base learning rate")
    optimizer: str = field("momentum", help="sgd | momentum | adam | adamw")
    algorithm: str = field("ring", help="gradient sync: xla | ring | naive")
    lr_schedule: str = field("plateau", help="adaptive reduce-on-plateau (BASELINE config 4)")


def load_cifar10(data_dir: str, synth_n: int, seed: int):
    """CIFAR-10 binary batches → Dataset; synthetic fallback without files."""
    from dsml_tpu.utils.data import Dataset, synthetic_classification
    from dsml_tpu.utils.logging import get_logger

    train_bins = sorted(glob.glob(os.path.join(data_dir, "data_batch_*.bin")))
    test_bin = os.path.join(data_dir, "test_batch.bin")
    if not train_bins or not os.path.exists(test_bin):
        get_logger("cifar").warning(
            "no CIFAR-10 binaries under %s; using a synthetic 10-class image workload",
            data_dir,
        )
        return synthetic_classification(synth_n, 32 * 32 * 3, seed=seed, image_shape=(32, 32, 3))

    def read_bin(path):
        raw = np.fromfile(path, np.uint8).reshape(-1, 3073)
        y = raw[:, 0].astype(np.int32)
        # stored CHW planar → NHWC float
        x = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        return x, y

    xs, ys = zip(*(read_bin(p) for p in train_bins))
    test_x, test_y = read_bin(test_bin)
    return Dataset(np.concatenate(xs), np.concatenate(ys), test_x, test_y)


def main(argv=None):
    cfg = CIFARConfig.parse_args(argv)
    from dsml_tpu.utils.platform import configure_platform

    configure_platform(cfg.platform, cfg.cpu_devices)

    from dsml_tpu.models.resnet import ResNet18
    from dsml_tpu.trainer import Trainer

    data = load_cifar10(cfg.data_dir, cfg.synth_n, cfg.seed)
    trainer = Trainer(ResNet18(), cfg)
    _, _, test_acc = trainer.train(data)
    return test_acc


if __name__ == "__main__":
    main()
