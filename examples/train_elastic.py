"""Elastic training demo: lose devices mid-run, re-plan, keep training.

The reference's failure story ends at "communicator FAILED" (SURVEY.md §5.3:
recovery none); this example shows the framework's whole elastic loop on a
virtual CPU fleet: train the tiny GPT-2 on N devices, simulate losing some
at ``--fail_at_step`` (the mesh-shrinks-between-steps model a multi-host
drop presents), audit recoverability, re-plan the parallelism for the
survivors with the capacity-rule auto-planner, re-shard params + optimizer
statistics in place, and continue — loss trajectory unbroken.

    python examples/train_elastic.py --devices 8 --lose 3 --fail_at_step 5
"""

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np

sys.path.insert(0, ".")  # repo-root invocation

from dsml_tpu.utils.config import Config, field


@dataclasses.dataclass
class ElasticDemoConfig(Config):
    devices: int = field(8, help="virtual CPU devices to start with")
    lose: int = field(3, help="devices to lose at the failure point")
    fail_at_step: int = field(5, help="step after which the failure hits")
    steps: int = field(10, help="total optimizer steps")
    batch_size: int = field(8, help="global batch size")
    lr: float = field(1e-2, help="adam learning rate")
    seed: int = field(0, help="init/data seed")


def main(argv=None):
    cfg = ElasticDemoConfig.parse_args(argv)
    from dsml_tpu.utils.platform import configure_platform

    configure_platform("cpu", cfg.devices)

    import jax
    import optax

    from dsml_tpu.models.gpt2 import GPT2, GPT2Config
    from dsml_tpu.parallel.elastic import reconfigure
    from dsml_tpu.parallel.hybrid import init_hybrid, make_hybrid_train_step
    from dsml_tpu.parallel.mesh import data_mesh
    from dsml_tpu.utils.data import lm_window_batches
    from dsml_tpu.utils.logging import get_logger

    log = get_logger("elastic")
    if not 0 < cfg.lose < cfg.devices:
        raise SystemExit(f"--lose must be in (0, {cfg.devices})")
    if cfg.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if not 0 < cfg.fail_at_step < cfg.steps:
        raise SystemExit(
            f"--fail_at_step must be in (0, {cfg.steps}) so the run actually "
            "crosses the failure (that's the demo)"
        )
    devices = jax.devices()[: cfg.devices]

    model_cfg = GPT2Config.tiny(vocab_size=256)
    model = GPT2(model_cfg)
    optimizer = optax.adam(cfg.lr)
    mesh = data_mesh(devices=devices)  # pure DP: every leaf replicated → recoverable
    step = make_hybrid_train_step(model, optimizer, mesh, attn_impl="ring")
    params, opt_state = init_hybrid(model, optimizer, mesh, seed=cfg.seed)
    log.info("training on %d devices, mesh %s", cfg.devices, dict(mesh.shape))

    rng_corpus = np.random.default_rng(cfg.seed)
    corpus = rng_corpus.integers(0, 256, size=1 << 18).astype(np.int32)
    batches = lm_window_batches(corpus, model_cfg.max_seq, cfg.batch_size, seed=cfg.seed)

    t0 = time.monotonic()
    for i in range(1, cfg.steps + 1):
        x, y = next(batches)
        params, opt_state, loss = step(params, opt_state, x, y)
        log.info("step %d: loss = %.4f", i, float(loss))

        if i == cfg.fail_at_step:
            survivors = devices[: cfg.devices - cfg.lose]
            lost = devices[cfg.devices - cfg.lose :]
            log.warning("losing %d device(s) %s", cfg.lose, [d.id for d in lost])
            state = reconfigure(
                model, optimizer, params, opt_state,
                surviving_devices=survivors, lost_devices=lost,
                global_batch=cfg.batch_size,
            )
            for reason in state.reasons:
                log.info("plan: %s", reason)
            params, opt_state = state.params, state.opt_state
            step = make_hybrid_train_step(model, optimizer, state.mesh, attn_impl="ring")
            log.info("continuing on %d devices, mesh %s",
                     len(state.mesh.devices.flat), dict(state.mesh.shape))
    log.info("done: %d steps across the failure in %.1fs", cfg.steps, time.monotonic() - t0)
    return float(loss)


if __name__ == "__main__":
    main()
