"""Fault injection: prove the chaos-survival guarantee, don't assert it.

``runtime.controller`` claims it rides preemptions end-to-end. This module
is the adversary that makes the claim testable: scripted and seeded-random
kill/restore schedules driven against the training controller (device
loss) and the serving ``DecodeFleet`` (replica loss), with the invariants
checked afterwards:

- **zero lost steps** — the final lineage contains every step exactly
  once (the step counter reaches the target and nothing was skipped);
- **bit-identity** (``growback="replay"``) — final params bit-identical
  to an uninterrupted run at the same step count on the same full mesh;
- **goodput floor** — productive ÷ wall stays above a documented floor
  for the harness (virtual-8 CPU: compiles dominate, floor 0.02 — the
  number is environment-specific, the FLOOR EXISTING is the guarantee);
- **zero token loss** (serving) — every request killed mid-decode
  re-runs on a survivor and its final tokens equal the single-batcher
  reference (greedy decode is a pure function of the prompt).

Faults are injected through the same three doors the controller watches:
the fleet view (``VirtualFleet.kill`` — the health-probe verdict), the
signal queue (``controller.inject(DeviceLost(...))``), and — when
``DSML_HANGWATCH`` is armed — a hangwatch expiry paired with a fleet
kill (the wedged-device shape).

Env knob ``DSML_CHAOS`` selects a schedule for the smoke entry point
(``python -m dsml_tpu.runtime.chaos``): unset/``0`` → off, ``1`` →
the default scripted schedule, ``seed:<n>`` → seeded-random. CI runs the
scripted schedule on the virtual-8 mesh every push (tier1.yml
``chaos-smoke``).
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time

import numpy as np

from dsml_tpu.utils.logging import get_logger

__all__ = [
    "ChaosEvent",
    "ChaosSchedule",
    "VirtualFleet",
    "WireFault",
    "WireFaultPlan",
    "wire_fault_plan",
    "set_wire_fault_plan",
    "config_from_env",
    "run_chaos_training",
    "run_chaos_serving",
    "run_chaos_serving_fleet",
    "run_smoke",
    "run_migration_smoke",
]

log = get_logger("chaos")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One fault: at ``step`` (training) / ``tick`` (serving), ``kill`` the
    targets or ``restore`` them (empty targets = everything dead)."""

    step: int
    action: str  # "kill" | "restore"
    targets: tuple = ()
    inject: bool = False  # also push a DeviceLost signal (vs probe-only)

    def __post_init__(self):
        if self.action not in ("kill", "restore"):
            raise ValueError(f"unknown chaos action {self.action!r}")


class ChaosSchedule:
    """An ordered list of :class:`ChaosEvent`; scripted or seeded-random."""

    def __init__(self, events):
        self.events = tuple(sorted(events, key=lambda e: e.step))

    @classmethod
    def scripted_default(cls, n_devices: int = 8) -> "ChaosSchedule":
        """The CI smoke schedule: 3 kills at distinct steps (one injected,
        two probe-detected), then a full restore — the ≥3-kills/1-restore
        shape the acceptance criterion names."""
        return cls([
            ChaosEvent(6, "kill", (n_devices - 1,), inject=True),
            ChaosEvent(10, "kill", (2,)),
            ChaosEvent(13, "kill", (0,)),
            ChaosEvent(17, "restore", ()),
        ])

    @classmethod
    def seeded(cls, seed: int, n_steps: int = 24, n_devices: int = 8,
               n_kills: int = 3) -> "ChaosSchedule":
        """Seeded-random schedule: ``n_kills`` distinct devices die at
        distinct steps in the first two-thirds of the run (always leaving
        at least one survivor), then everything restores."""
        rng = random.Random(seed)
        n_kills = min(n_kills, n_devices - 1)
        lo, hi = 2, max(2 * n_steps // 3, 3)
        steps = sorted(rng.sample(range(lo, hi + 1), min(n_kills, hi - lo + 1)))
        targets = rng.sample(range(n_devices), len(steps))
        events = [
            ChaosEvent(s, "kill", (t,), inject=rng.random() < 0.5)
            for s, t in zip(steps, targets)
        ]
        restore_at = min(steps[-1] + rng.randint(2, 5), n_steps - 4)
        events.append(ChaosEvent(max(restore_at, steps[-1] + 1), "restore", ()))
        return cls(events)

    def at(self, step: int) -> list[ChaosEvent]:
        return [e for e in self.events if e.step == step]

    def kills(self) -> int:
        return sum(1 for e in self.events if e.action == "kill")


def config_from_env(spec: str | None = None) -> ChaosSchedule | None:
    """``DSML_CHAOS``: unset/``0`` → None; ``1`` → the scripted default;
    ``seed:<n>`` → :meth:`ChaosSchedule.seeded`."""
    if spec is None:
        spec = os.environ.get("DSML_CHAOS", "")
    spec = spec.strip().lower()
    if spec in ("", "0", "false", "off"):
        return None
    if spec in ("1", "true", "on", "scripted"):
        return ChaosSchedule.scripted_default()
    if spec.startswith("seed:"):
        try:
            return ChaosSchedule.seeded(int(spec[5:]))
        except ValueError as e:
            raise ValueError(f"DSML_CHAOS={spec!r}: bad seed") from e
    raise ValueError(
        f"DSML_CHAOS={spec!r} is not one of 0/1/scripted/seed:<n>"
    )


class VirtualFleet:
    """A fleet view the harness can lie through: ``kill`` hides devices
    from ``available()`` (what a coordinator health probe would report),
    ``restore`` brings them back (capacity returning). Indices are into
    the original device list."""

    def __init__(self, devices):
        self._devices = list(devices)
        self._dead: set[int] = set()

    def available(self) -> list:
        return [d for i, d in enumerate(self._devices) if i not in self._dead]

    def kill(self, *indices: int) -> list:
        dead = []
        for i in indices:
            if i not in self._dead and 0 <= i < len(self._devices):
                self._dead.add(i)
                dead.append(self._devices[i])
        if len(self._dead) >= len(self._devices):
            raise RuntimeError("chaos killed the whole fleet")
        return dead

    def restore(self, *indices: int) -> list:
        back = sorted(self._dead) if not indices else list(indices)
        restored = [self._devices[i] for i in back if i in self._dead]
        self._dead -= set(back)
        return restored

    @property
    def n_dead(self) -> int:
        return len(self._dead)


# ---------------------------------------------------------------------------
# wire faults: the DATA-PLANE adversary (P2P streams)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class WireFault:
    """One data-plane fault, applied by ``device_server._push_stream``:

    - ``drop`` — truncate the StreamSend mid-stream (the receiver keeps a
      partial prefix, the sender's call errors);
    - ``corrupt`` — flip one byte mid-payload (exactly what the migration
      path's per-chunk CRC32C must catch);
    - ``delay`` — sleep ``delay_s`` before pushing (timeout exercise);
    - ``partition`` — sever the link: the push fails before any byte moves.

    ``nth`` selects the 1-based send ordinal the fault fires on (None =
    every matching send); ``src``/``dst`` restrict to one link."""

    action: str
    nth: int | None = None
    src: int | None = None
    dst: int | None = None
    delay_s: float = 0.1

    _ACTIONS = ("drop", "corrupt", "delay", "partition")

    def __post_init__(self):
        if self.action not in self._ACTIONS:
            raise ValueError(f"unknown wire-fault action {self.action!r}")

    def matches(self, ordinal: int, src, dst) -> bool:
        if self.nth is not None and ordinal != self.nth:
            return False
        if self.src is not None and src != self.src:
            return False
        if self.dst is not None and dst != self.dst:
            return False
        return True

    def apply_payload(self, payload: bytes) -> bytes:
        if self.action == "corrupt":
            mutated = bytearray(payload)
            if mutated:
                mutated[len(mutated) // 2] ^= 0xFF
            return bytes(mutated)
        if self.action == "delay":
            time.sleep(self.delay_s)
        return payload


class WireFaultPlan:
    """A per-link wire-fault schedule, keyed on the process-wide send
    ordinal. Spec grammar (``DSML_CHAOS_WIRE``): semicolon-separated
    ``action@sel[,src=N][,dst=N][,s=SECONDS]`` where ``sel`` is a 1-based
    send ordinal or ``*`` (every send) — e.g.
    ``"drop@1;corrupt@3"`` or ``"delay@*,dst=1,s=0.05"``."""

    def __init__(self, faults):
        self.faults = list(faults)
        self._sends = 0
        self._lock = threading.Lock()
        self.fired: list[dict] = []

    @classmethod
    def parse(cls, spec: str) -> "WireFaultPlan":
        faults = []
        for token in spec.split(";"):
            token = token.strip().lower()
            if not token:
                continue
            head, _, rest = token.partition(",")
            if "@" not in head:
                raise ValueError(f"wire-fault token {token!r}: expected action@sel")
            action, sel = head.split("@", 1)
            fault = {"action": action.strip(),
                     "nth": None if sel.strip() == "*" else int(sel)}
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                if k == "src":
                    fault["src"] = int(v)
                elif k == "dst":
                    fault["dst"] = int(v)
                elif k == "s":
                    fault["delay_s"] = float(v)
                else:
                    raise ValueError(f"wire-fault token {token!r}: unknown key {k!r}")
            faults.append(WireFault(**fault))
        return cls(faults)

    def on_send(self, src, dst) -> WireFault | None:
        """Called by the device server once per outbound stream push;
        returns the fault to apply (if any) and records the firing."""
        with self._lock:
            self._sends += 1
            ordinal = self._sends
            for fault in self.faults:
                if fault.matches(ordinal, src, dst):
                    self.fired.append(
                        {"action": fault.action, "ordinal": ordinal,
                         "src": src, "dst": dst}
                    )
                    log.warning("wire fault: %s on send #%d (%s -> %s)",
                                fault.action, ordinal, src, dst)
                    from dsml_tpu.obs import get_registry

                    reg = get_registry()
                    if reg.enabled:
                        reg.counter(
                            "chaos_wire_faults_total",
                            "injected data-plane faults", labels=("action",),
                        ).inc(action=fault.action)
                    return fault
        return None


_WIRE_UNSET = object()
_WIRE_PLAN = _WIRE_UNSET


def wire_fault_plan() -> WireFaultPlan | None:
    """The process's active wire-fault plan: whatever
    :func:`set_wire_fault_plan` installed, else ``DSML_CHAOS_WIRE`` parsed
    once (None when unset/empty — the zero-cost production answer)."""
    global _WIRE_PLAN
    if _WIRE_PLAN is _WIRE_UNSET:
        spec = os.environ.get("DSML_CHAOS_WIRE", "").strip()
        _WIRE_PLAN = WireFaultPlan.parse(spec) if spec else None
    return _WIRE_PLAN


def set_wire_fault_plan(plan: WireFaultPlan | None) -> None:
    """Install (or, with None, clear) the active plan — the in-process
    test hook; subprocesses use the env knob."""
    global _WIRE_PLAN
    _WIRE_PLAN = plan


def run_chaos_training(controller, schedule: ChaosSchedule,
                       n_steps: int) -> dict:
    """Drive ``controller.run(n_steps)`` with ``schedule`` applied through
    the controller's fleet (which must be a :class:`VirtualFleet`).
    Returns the controller report with the schedule appended."""
    from dsml_tpu.runtime.controller import DeviceLost

    fleet = controller.fleet
    fired: set = set()

    def on_step(step: int) -> None:
        for ev in schedule.at(step):
            if id(ev) in fired:
                continue
            fired.add(id(ev))
            if ev.action == "kill":
                dead = fleet.kill(*ev.targets)
                log.warning("chaos: step %d kill %s", step, list(ev.targets))
                if ev.inject and dead:
                    controller.inject(DeviceLost(dead, "chaos kill"))
            else:
                restored = fleet.restore(*ev.targets)
                log.warning("chaos: step %d restore %d device(s)",
                            step, len(restored))

    report = controller.run(n_steps, on_step=on_step)
    report["schedule"] = [dataclasses.asdict(e) for e in schedule.events]
    return report


def run_chaos_serving(fleet, prompts, max_new: int,
                      kill_ticks: dict[int, int | None],
                      max_ticks: int = 100_000) -> dict:
    """Drive a ``DecodeFleet`` to drain ``prompts`` while killing replicas
    at the scheduled ticks (``{tick: replica_id or None=newest}``).
    Returns ``{"results": {frid: tokens}, "ticks": n}``."""
    frids = [fleet.submit(p, max_new) for p in prompts]
    tick = 0
    while fleet.outstanding:
        if tick in kill_ticks and fleet.n_replicas:
            fleet.kill_replica(kill_ticks[tick])
        fleet.tick()
        tick += 1
        if tick > max_ticks:
            raise RuntimeError(f"serving chaos did not drain in {max_ticks}")
    results = fleet.run(max_ticks=1)  # drains the harvested results
    return {"results": {f: results.get(f, []) for f in frids}, "ticks": tick}


def run_chaos_serving_fleet(router, prompts, max_new: int,
                            kill_ticks: dict[int, tuple],
                            max_ticks: int = 100_000) -> dict:
    """The disaggregated-fleet variant of :func:`run_chaos_serving`: drive
    a ``serving.Router`` to drain ``prompts`` while killing WORKERS at the
    scheduled ticks — ``{tick: ("prefill"|"decode", idx or None=last)}``.
    A prefill worker killed mid-handoff loses its partial chunk state; the
    router re-prefills on a survivor (prefill is a pure function of the
    prompt, so the regenerated KV rows — and therefore the tokens — are
    identical). Returns results plus the requeue counts the verdict needs
    to prove the kill actually interrupted work in flight, and the
    request-tracing verdicts: a killed request's re-run must retire under
    the SAME trace_id with its retry recorded, and its SLO burn must
    count the FULL user-visible latency (original submit → final retire,
    not just the post-requeue leg)."""
    frids = [router.submit(p, max_new) for p in prompts]
    minted = {
        f: (router.trace_of(f).trace_id if router.trace_of(f) else None)
        for f in frids
    }
    tick = 0
    while router.outstanding:
        kill = kill_ticks.get(tick)
        if kill is not None:
            kind, idx = kill
            if kind == "prefill":
                router.kill_prefill_worker(idx)
            elif kind == "decode":
                router.kill_decode_worker(idx)
            else:
                raise ValueError(f"unknown worker kind {kind!r}")
        router.tick()
        tick += 1
        if tick > max_ticks:
            raise RuntimeError(f"serving chaos did not drain in {max_ticks}")
    results = router.run(max_ticks=1)  # drains the harvested results
    # -- tracing verdicts over the requeued set -----------------------------
    requeue_t: dict[int, float] = {}
    for f, t in router.requeue_log:
        requeue_t[f] = t  # last requeue wins (the run that finished)
    requeued = [f for f in requeue_t if f in set(frids)]
    records = router.request_records
    same_trace = all(
        records.get(f, {}).get("trace_id") == minted.get(f)
        and minted.get(f) is not None
        for f in requeued
    )
    retry_recorded = all(
        records.get(f, {}).get("retries", 0) >= 1 for f in requeued
    )
    # full-latency burn: the recorded e2e must EXCEED the post-requeue
    # leg alone — i.e. the clock kept running from the ORIGINAL submit
    # through the kill, not from the retry
    burn_full = all(
        records.get(f, {}).get("e2e_s") is not None
        and records[f].get("finished_mono") is not None
        and records[f]["e2e_s"]
        > (records[f]["finished_mono"] - requeue_t[f]) - 1e-9
        for f in requeued
    )
    return {
        "results": {f: results.get(f, []) for f in frids},
        "ticks": tick,
        "requeued_prefill": router.requeued_prefill,
        "requeued_decode": router.requeued_decode,
        "requeued_requests": len(requeued),
        "trace_requeue_same": int(same_trace),
        "trace_retry_recorded": int(retry_recorded),
        "trace_burn_full_latency": int(burn_full),
    }


# ---------------------------------------------------------------------------
# smoke: the end-to-end guarantee as an executable check (CI + bench)
# ---------------------------------------------------------------------------

# documented goodput floor for THIS harness (virtual-8 CPU mesh, tiny
# model): recovery compiles dominate the wall, so the floor is low — the
# guarantee is that a floor EXISTS and holds, not the CPU number itself
# (docs/ELASTIC.md documents the real-chip expectation separately)
SMOKE_GOODPUT_FLOOR = 0.02


def _bit_identical(tree_a, tree_b) -> bool:
    import jax

    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    if len(la) != len(lb):
        return False
    return all(
        np.array_equal(np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))
        for a, b in zip(la, lb)
    )


def run_smoke(n_steps: int = 24, seeds: tuple = (), checkpoint_every: int = 4,
              tmp_dir: str | None = None,
              schedule: "ChaosSchedule | None" = None,
              serving: bool = True) -> dict:
    """The acceptance run: scripted schedule (≥3 kills, 1 restore) on the
    virtual-8 mesh with ``growback="replay"`` — final params must be
    bit-identical to an uninterrupted run at the same step count, zero
    steps lost, goodput above :data:`SMOKE_GOODPUT_FLOOR`. ``seeds`` adds
    seeded-random schedules for the recovery-time distribution. Returns a
    report dict; ``verify`` raises on any violated invariant."""
    import shutil
    import tempfile

    import jax
    import optax

    from dsml_tpu.models.gpt2 import GPT2, GPT2Config

    if n_steps < 20:
        raise ValueError(
            f"chaos smoke needs n_steps >= 20 (the scripted schedule kills "
            f"through step 13, restores at 17, and grows at the next "
            f"checkpoint boundary), got {n_steps}"
        )
    from dsml_tpu.parallel.hybrid import init_hybrid, make_hybrid_train_step
    from dsml_tpu.parallel.mesh import MeshSpec, build_mesh
    from dsml_tpu.runtime.controller import ControllerConfig, ElasticController

    devices = jax.devices()[:8]
    if len(devices) < 8:
        raise RuntimeError(f"chaos smoke needs 8 devices, found {len(devices)}")
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    optimizer = optax.adam(1e-2)
    global_batch = 8
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size,
                        (n_steps + 8, global_batch, cfg.max_seq)).astype(np.int32)

    def batch_provider(step: int):
        x = data[step - 1]
        return x, np.roll(x, -1, 1).astype(np.int32)

    spec = MeshSpec(dp=8)
    base = tmp_dir or tempfile.mkdtemp(prefix="dsml_chaos_")
    created = tmp_dir is None
    report: dict = {"n_steps": n_steps}
    try:
        # the uninterrupted reference: the same mesh, same batches, no
        # controller, no checkpoints, no failures
        mesh = build_mesh(spec, devices)
        step_fn = make_hybrid_train_step(model, optimizer, mesh)
        ref_params, ref_opt = init_hybrid(model, optimizer, mesh, seed=0)
        for s in range(1, n_steps + 1):
            ref_params, ref_opt, ref_loss = step_fn(ref_params, ref_opt,
                                                    *batch_provider(s))
        ref_loss = float(ref_loss)

        def one_run(schedule: ChaosSchedule, name: str) -> dict:
            fleet = VirtualFleet(devices)
            ctl = ElasticController(
                model, optimizer, batch_provider,
                checkpoint_dir=os.path.join(base, name),
                fleet=fleet, mesh=build_mesh(spec, devices), spec=spec,
                config=ControllerConfig(checkpoint_every=checkpoint_every,
                                        growback="replay"),
                global_batch=global_batch, seed=0,
            )
            with ctl:
                rep = run_chaos_training(ctl, schedule, n_steps)
            rep["bit_identical"] = _bit_identical(ctl.params, ref_params)
            rep["final_loss"] = ctl.losses.get(n_steps)
            rep["ref_loss"] = ref_loss
            rep["kills"] = schedule.kills()
            return rep

        report["scripted"] = one_run(
            schedule or ChaosSchedule.scripted_default(), "scripted"
        )
        recov = [r["recovery_ms"] for r in report["scripted"]["recoveries"]]
        for seed in seeds:
            rep = one_run(ChaosSchedule.seeded(seed, n_steps), f"seed{seed}")
            report[f"seed{seed}"] = rep
            recov += [r["recovery_ms"] for r in rep["recoveries"]]
        if recov:
            report["recovery_p50_ms"] = round(float(np.percentile(recov, 50)), 3)
            report["recovery_p99_ms"] = round(float(np.percentile(recov, 99)), 3)
            report["recovery_samples"] = len(recov)
        report["goodput_floor"] = SMOKE_GOODPUT_FLOOR
        # ledger staging audit: after every kill→shrink→grow recovery the
        # controllers (and their checkpoint writers / any migrators) are
        # closed — bytes still claimed as staging are a leak, exactly the
        # class a wedged background commit or an unreleased donor span
        # produces
        from dsml_tpu.obs.memory import get_memory_ledger

        led = get_memory_ledger()
        report["ledger_staging_bytes_final"] = (
            led.claimed_bytes("checkpoint_staging")
            + led.claimed_bytes("migration_staging")
        )
        if serving:
            report["serving"] = _serving_smoke(model, cfg, rng)
            report["serving_fleet"] = _serving_fleet_smoke(model, cfg, rng)
            report["serving_paged"] = _paged_serving_smoke(model, cfg, rng)
    finally:
        if created:
            shutil.rmtree(base, ignore_errors=True)
    return report


def _serving_smoke(model, cfg, rng) -> dict:
    """Replica-loss smoke: a 2-replica decode fleet loses a replica
    mid-drain; every request re-runs on a survivor and the final tokens
    must equal the single-batcher reference (greedy ⇒ pure function of
    the prompt)."""
    from dsml_tpu.runtime.controller import DecodeFleet
    from dsml_tpu.serving import ContinuousBatcher

    params = model.init(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, rng.integers(3, 9)).astype(np.int32)
        for _ in range(6)
    ]
    max_new = 6
    ref = ContinuousBatcher(model, params, n_slots=2)
    ref_rids = [ref.submit(p, max_new) for p in prompts]
    ref_tokens = ref.run()

    fleet = DecodeFleet(
        lambda: ContinuousBatcher(model, params, n_slots=2, max_queue=8),
        min_replicas=2, max_replicas=3, scale_up_queue_depth=2,
        scale_down_idle_ticks=4,
    )
    out = run_chaos_serving(fleet, prompts, max_new, kill_ticks={3: None})
    token_loss = sum(
        1 for frid, rrid in zip(sorted(out["results"]), ref_rids)
        if out["results"][frid] != ref_tokens[rrid]
    )
    return {
        "requests": len(prompts),
        "token_mismatches": token_loss,
        "ticks": out["ticks"],
        "scale_events": len(fleet.scale_events),
    }


def _serving_fleet_smoke(model, cfg, rng) -> dict:
    """Disaggregated-fleet loss smoke: a 2-prefill / 2-decode fleet loses
    a PREFILL worker mid-handoff (work in flight — the kill tick lands
    while chunked prefill is running) and later a decode worker; every
    interrupted request re-prefills/re-decodes on survivors and the final
    tokens must equal the single-batcher reference — zero token loss."""
    from dsml_tpu.serving import ContinuousBatcher, build_fleet

    params = model.init(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, rng.integers(8, 24)).astype(np.int32)
        for _ in range(6)
    ]
    max_new = 6
    ref = ContinuousBatcher(model, params, n_slots=2)
    ref_rids = [ref.submit(p, max_new) for p in prompts]
    ref_tokens = ref.run()

    router = build_fleet(
        model, params, n_prefill=2, n_decode=2, prefill_chunk=8,
        n_slots=2, max_queue=8,
    )
    out = run_chaos_serving_fleet(
        router, prompts, max_new,
        kill_ticks={1: ("prefill", None), 6: ("decode", None)},
    )
    token_loss = sum(
        1 for frid, rrid in zip(sorted(out["results"]), ref_rids)
        if out["results"][frid] != ref_tokens[rrid]
    )
    return {
        "requests": len(prompts),
        "token_mismatches": token_loss,
        "ticks": out["ticks"],
        "requeued_prefill": out["requeued_prefill"],
        "requeued_decode": out["requeued_decode"],
        "requeued_requests": out["requeued_requests"],
        "trace_requeue_same": out["trace_requeue_same"],
        "trace_retry_recorded": out["trace_retry_recorded"],
        "trace_burn_full_latency": out["trace_burn_full_latency"],
    }


def _paged_serving_smoke(model, cfg, rng) -> dict:
    """Paged-KV fleet loss smoke (docs/SERVING.md § Paged KV): a paged
    2-prefill/2-decode fleet with a LIVE CoW prefix (registered fleet-wide,
    shared read-only across matching requests) loses a decode worker
    mid-flight. Invariants: re-prefilled requests on survivors produce
    tokens identical to a monolithic paged batcher (greedy + deterministic
    int4 codec ⇒ pure function of the prompt), the CoW sharing was
    actually live when the kill landed, and EVERY worker's pool — the
    killed one's included — reclaims its request pages without leaking
    capacity (only the prefix registry's pages stay held)."""
    from dsml_tpu.serving import ContinuousBatcher, build_fleet

    params = model.init(0)
    page_size = 8
    prefix = rng.integers(1, cfg.vocab_size, 20).astype(np.int32)
    prompts = []
    for i in range(6):
        tail = rng.integers(1, cfg.vocab_size,
                            int(rng.integers(4, 12))).astype(np.int32)
        prompts.append(np.concatenate([prefix, tail]) if i % 2 else
                       rng.integers(1, cfg.vocab_size,
                                    int(rng.integers(8, 24))).astype(np.int32))
    max_new = 6
    ref = ContinuousBatcher(model, params, n_slots=2, prefill_chunk=8,
                            paged_kv="int4", page_size=page_size, n_pages=80)
    ref.register_prefix(prefix)
    ref_rids = [ref.submit(p, max_new) for p in prompts]
    ref_tokens = ref.run()

    router = build_fleet(
        model, params, n_prefill=2, n_decode=2, prefill_chunk=8,
        paged_kv="int4", page_size=page_size, n_slots=2, max_queue=8,
        n_pages=80,
    )
    router.register_prefix(prefix)
    workers = list(router.decode_workers) + list(router.prefill_workers)
    baseline_used = [w.used_pages if hasattr(w, "used_pages")
                     else w._pages.used_pages for w in workers]
    # ledger-level no-leak audit riding alongside the page-count one: the
    # fleet-wide occupied KV BYTES (live + CoW-shared, every worker's pool
    # summed through the memory ledger's sources) must return to this
    # baseline after the drain — a leak that hid page-for-page inside one
    # pool would still move the byte total
    ledger_kv_baseline = _ledger_kv_occupied_bytes()
    frids = [router.submit(p, max_new) for p in prompts]
    tick = 0
    peak_shared = 0
    while router.outstanding:
        if tick == 6:
            router.kill_decode_worker()
        router.tick()
        peak_shared = max(peak_shared, max(
            dw.shared_pages for dw in router.decode_workers
        ))
        tick += 1
        if tick > 100_000:
            raise RuntimeError("paged serving chaos did not drain")
    results = router.run(max_ticks=1)
    token_loss = sum(
        1 for frid, rrid in zip(sorted(frids), ref_rids)
        if results.get(frid, []) != ref_tokens[rrid]
    )
    # no-leak audit over EVERY pool, the killed worker's included: after
    # the drain each pool holds exactly its prefix-registry pages again
    leaked = 0
    for w, base in zip(workers, baseline_used):
        used = (w.used_pages if hasattr(w, "used_pages")
                else w._pages.used_pages)
        leaked += max(used - base, 0)
    ledger_kv_final = _ledger_kv_occupied_bytes()
    report = {
        "requests": len(prompts),
        "token_mismatches": token_loss,
        "ticks": tick,
        "requeued_decode": router.requeued_decode,
        "peak_shared_pages": peak_shared,
        "leaked_pages": leaked,
        "ledger_kv_baseline_bytes": ledger_kv_baseline,
        "ledger_kv_final_bytes": ledger_kv_final,
        "ledger_balanced": int(ledger_kv_final <= ledger_kv_baseline + 0.5),
    }
    report.update(_paged_eviction_leg(model, cfg, rng))
    return report


def _ledger_kv_occupied_bytes() -> float:
    """Fleet-wide OCCUPIED paged-KV bytes (live + CoW-shared) summed over
    every pool the memory ledger's weakly-held sources still see. GC runs
    first so a retired batcher's constant contribution cannot shift a
    baseline-vs-final comparison mid-audit."""
    import gc

    from dsml_tpu.obs.memory import get_memory_ledger

    gc.collect()
    claims = get_memory_ledger().claimed().get("kv_pages", {})
    return float(claims.get("live", 0.0) + claims.get("shared", 0.0))


def _paged_eviction_leg(model, cfg, rng) -> dict:
    """The EVICTION leg (preemption=True, docs/SERVING.md § Paged KV): a
    pool far too small for the worst case forces mid-decode preemptions —
    the lowest-priority slot's pages swap out (or drop for recompute) and
    the request resumes when pages free. Invariants: every PREEMPTED
    request re-emits tokens identical to the uncontended big-pool run
    (preemption is pure scheduling), and the drained pool is back to
    empty — zero page leaks."""
    from dsml_tpu.serving import ContinuousBatcher

    params = model.init(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, l).astype(np.int32)
        for l in (17, 9, 13)
    ]
    budgets = [12, 12, 10]
    ref = ContinuousBatcher(model, params, n_slots=3, prefill_chunk=8,
                            paged_kv="int4", page_size=8, n_pages=40)
    ref_rids = [ref.submit(p, n) for p, n in zip(prompts, budgets)]
    got = ref.run()
    ref_tokens = [got[r] for r in ref_rids]

    srv = ContinuousBatcher(model, params, n_slots=3, prefill_chunk=8,
                            paged_kv="int4", page_size=8, n_pages=8,
                            preemption=True)
    # ledger baseline AFTER both batchers exist, BEFORE any admission:
    # ref has drained, srv is empty — the eviction/resume churn below
    # must return the fleet-wide occupied KV bytes to exactly this
    ledger_baseline = _ledger_kv_occupied_bytes()
    preempted_rids: set = set()
    evict = srv._evict_slot

    def spy(slot):
        preempted_rids.add(int(srv._slot_rid[slot]))
        evict(slot)

    srv._evict_slot = spy
    rids = [srv.submit(p, n) for p, n in zip(prompts, budgets)]
    out = srv.run()
    mismatches = sum(
        1 for rid, want in zip(rids, ref_tokens) if out.get(rid) != want
    )
    resumed_ok = sum(
        1 for rid, want in zip(rids, ref_tokens)
        if rid in preempted_rids and out.get(rid) == want
    )
    ledger_final = _ledger_kv_occupied_bytes()
    return {
        "eviction_preemptions": srv.n_preemptions,
        "eviction_swap": srv.n_swap_evictions,
        "eviction_recompute": srv.n_recompute_evictions,
        "eviction_resumed_identical": resumed_ok,
        "eviction_token_mismatches": mismatches,
        "eviction_leaked_pages": srv.n_pages - 1 - srv.free_pages,
        "eviction_ledger_baseline_bytes": ledger_baseline,
        "eviction_ledger_final_bytes": ledger_final,
        "eviction_ledger_balanced": int(ledger_final <= ledger_baseline + 0.5),
    }


# ---------------------------------------------------------------------------
# migration smoke: the two-host (subprocess-simulated) shrink, under fault
# ---------------------------------------------------------------------------

_DONOR_FLAG = "--serve-migration-donor"


def _donor_main(npz_path: str) -> None:
    """Subprocess body: the DONOR HOST. Loads the state snapshot (the
    addressable view a real donor host would hold live), registers every
    leaf with its device server's StateDonor, prints the bound address as
    a JSON line, and serves P2P streams until stdin closes. Wire faults
    ride ``DSML_CHAOS_WIRE`` in this process's env — the donor is the
    stream SENDER, so drop/corrupt/delay happen on its pushes."""
    import json as _json
    import sys

    from dsml_tpu.comm.device_server import serve_device

    blob = np.load(npz_path)
    # the staging allocator gets the upper half of the registry, so size
    # the device for 2x the largest piece plus the landing headroom
    total = int(sum(blob[k].nbytes for k in blob.files))
    handle = serve_device(97, mem_size=max(0x200000, 4 * total))
    for key in blob.files:
        if key == "__migration_version__":
            handle.runtime.donor.version = int(blob[key])
            continue
        handle.runtime.donor.register_array(key, blob[key])
    print(_json.dumps({"address": handle.address, "keys": len(blob.files)}),
          flush=True)
    sys.stdin.read()  # parent closes the pipe → exit
    handle.stop()


def _export_state_npz(path: str, params, opt_state, version: int) -> int:
    """Host-state snapshot in the donor registry's key scheme (tree paths
    under ``params/`` / ``opt_state/`` — what ``StateDonor.register_state``
    derives from the same trees), stamped with the snapshot's training
    step so the receiver can refuse a stale donor."""
    import jax

    from dsml_tpu.comm.migration import tree_path_str

    arrays = {"__migration_version__": np.asarray(version)}
    for prefix, tree in (("params", params), ("opt_state", opt_state)):
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        for p, leaf in flat:
            if leaf is not None and hasattr(leaf, "shape"):
                arrays[tree_path_str(prefix, p)] = np.asarray(jax.device_get(leaf))
    np.savez(path, **arrays)
    return len(arrays) - 1


def _bit_identical_host(tree_a, tree_b) -> bool:
    import jax

    la = [np.asarray(jax.device_get(x)) for x in jax.tree.leaves(tree_a)]
    lb = [np.asarray(jax.device_get(x)) for x in jax.tree.leaves(tree_b)]
    return len(la) == len(lb) and all(np.array_equal(a, b) for a, b in zip(la, lb))


def run_migration_smoke(tmp_dir: str | None = None, reps: int = 1) -> dict:
    """The two-host shrink acceptance run (docs/ELASTIC.md § Multi-host
    recovery): host A (this process) shards GPT2-tiny over [dp=4, tp=2],
    loses its local tp-1 holders, and the surviving copies of that shard
    live only on "host B" — a donor SUBPROCESS serving the state over the
    real gRPC P2P streams, routed through the coordinator's membership
    table. Four legs:

    - ``refusal`` — without a migrator the pull refuses loudly (the pinned
      pre-PR behavior; a shrink would degrade to checkpoint restore);
    - ``clean`` — the same shrink completes via P2P migration, no
      checkpoint restore, params BIT-IDENTICAL to what the checkpoint
      fallback would produce;
    - ``drop`` — one dropped StreamSend: the migrator harvests the partial
      prefix and resumes from the offset; same bits;
    - ``corrupt`` — every push corrupted: per-chunk CRC32C fires, the
      migration aborts cleanly, and an ``ElasticController`` riding the
      same failure falls back to the coordinated checkpoint restore with
      ZERO silent corruption (corrupt bytes never land).

    ``reps`` repeats the clean migration + fallback timing pair for the
    bench's recovery-split percentiles. ``verify_migration`` raises the
    violations; the CLI exits nonzero on any."""
    import json
    import shutil
    import subprocess
    import sys
    import tempfile
    import time as _time

    import jax
    import optax

    from dsml_tpu.checkpoint import CheckpointManager
    from dsml_tpu.comm.coordinator import CoordinatorConfig, serve_coordinator
    from dsml_tpu.comm.device_server import serve_device
    from dsml_tpu.comm.migration import (
        MigrationConfig,
        MigrationError,
        ShardMigrator,
    )
    from dsml_tpu.models.gpt2 import GPT2, GPT2Config
    from dsml_tpu.parallel import elastic
    from dsml_tpu.parallel.hybrid import (
        init_hybrid,
        make_hybrid_train_step,
        shard_params,
    )
    from dsml_tpu.parallel.mesh import MeshSpec, build_mesh

    devices = jax.devices()[:8]
    if len(devices) < 8:
        raise RuntimeError(f"migration smoke needs 8 devices, found {len(devices)}")
    base = tmp_dir or tempfile.mkdtemp(prefix="dsml_migrate_")
    created = tmp_dir is None
    report: dict = {}
    procs: list = []
    coordinator = None
    recv = None
    try:
        cfg = GPT2Config.tiny()
        model = GPT2(cfg)
        optimizer = optax.adam(1e-2)
        global_batch = 8
        rng = np.random.default_rng(0)
        data = rng.integers(0, cfg.vocab_size,
                            (16, global_batch, cfg.max_seq)).astype(np.int32)

        def batch_provider(step: int):
            x = data[step - 1]
            return x, np.roll(x, -1, 1).astype(np.int32)

        # host A's live state: 2 steps on [dp=4, tp=2] (device i holds tp
        # rank i%2 — losing {1,3} removes every LOCAL copy of tp shard 1;
        # devices 4..7 play host B, so the shard SURVIVES, remotely)
        spec = MeshSpec(dp=4, sp=1, tp=2)
        mesh8 = build_mesh(spec, devices)
        step_fn = make_hybrid_train_step(model, optimizer, mesh8)
        params, opt_state = init_hybrid(model, optimizer, mesh8, seed=0)
        for s in (1, 2):
            params, opt_state, _ = step_fn(params, opt_state, *batch_provider(s))
        # re-pin DECLARED shardings (jit outputs carry compiler-chosen
        # layouts; the elastic runner's own idiom — see test_elastic)
        import optax.tree_utils as otu
        from jax.sharding import NamedSharding, PartitionSpec as P

        pspecs = model.param_specs()
        params = shard_params(params, mesh8, pspecs)
        param_sh = jax.tree.map(lambda sp: NamedSharding(mesh8, sp), pspecs,
                                is_leaf=lambda sp: isinstance(sp, P))
        repl = NamedSharding(mesh8, P())
        opt_state = otu.tree_map_params(
            optimizer, lambda l, sh: jax.device_put(l, sh), opt_state, param_sh,
            transform_non_params=lambda l: jax.device_put(l, repl),
        )

        ckpt_dir = os.path.join(base, "ckpt")
        manager = CheckpointManager(ckpt_dir, max_to_keep=None)
        manager.save(2, {"params": params, "opt_state": opt_state})
        npz = os.path.join(base, "donor_state.npz")
        n_leaves = _export_state_npz(npz, params, opt_state, version=2)

        lost = [devices[i] for i in (1, 3)]
        survivors = [devices[i] for i in (0, 2, 4, 5, 6, 7)]
        remote_ids = frozenset(devices[i].id for i in (4, 5, 6, 7))
        recv = serve_device(96, mem_size=0x400000)
        coordinator = serve_coordinator(
            config=CoordinatorConfig(health_interval_s=3600.0)
        )

        def spawn_donor(wire_spec: str) -> str:
            env = {**os.environ, "JAX_PLATFORMS": "cpu"}
            env.pop("DSML_CHAOS_WIRE", None)
            if wire_spec:
                env["DSML_CHAOS_WIRE"] = wire_spec
            p = subprocess.Popen(
                [sys.executable, "-m", "dsml_tpu.runtime.chaos",
                 _DONOR_FLAG, npz],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
                text=True,
            )
            procs.append(p)
            return json.loads(p.stdout.readline())["address"]

        def migrator_for(donor_addr: str, **cfg_kw) -> ShardMigrator:
            # coordinator-brokered routing: CommInit installs the peer
            # tables, the membership table names ranks and addresses; the
            # receiver pins the snapshot step it expects (the state at the
            # failure point) so a stale donor would be refused, not landed
            comm = coordinator.runtime.comm_init(2, [recv.address, donor_addr])
            self_rank, donors = coordinator.runtime.broker_migration(
                comm.comm_id, recv.runtime.device_id
            )
            return ShardMigrator(
                recv.runtime, self_rank, donors,
                config=MigrationConfig(**cfg_kw),
                local_address=recv.runtime.bound_address,
                expect_version=2,
            )

        def reconfigure_with(migrator):
            return elastic.reconfigure(
                model, optimizer, params, opt_state,
                surviving_devices=survivors, lost_devices=lost,
                global_batch=global_batch,
                migrator=migrator, non_addressable=remote_ids,
            )

        # --- leg 0: the pinned refusal (no migrator) ----------------------
        try:
            reconfigure_with(None)
            report["refusal"] = {"raised": False}
        except RuntimeError as e:
            report["refusal"] = {
                "raised": True,
                "mentions_non_addressable": "non-addressable" in str(e),
            }

        # --- leg 1: clean migration vs checkpoint fallback, bit-identical -
        donor_addr = spawn_donor("")
        mig = migrator_for(donor_addr, timeout_s=30.0)
        mig_walls, fb_walls = [], []
        state = fb_state = None
        for _ in range(max(reps, 1)):
            t0 = _time.perf_counter()
            state = reconfigure_with(mig)
            mig_walls.append((_time.perf_counter() - t0) * 1e3)
            t0 = _time.perf_counter()
            fb_state = elastic.restore_from_checkpoint(
                manager, model, optimizer, survivors,
                global_batch=global_batch,
            )
            fb_walls.append((_time.perf_counter() - t0) * 1e3)
        report["clean"] = {
            "migrated_pieces": mig.stats["pieces"],
            "migrated_bytes": mig.stats["bytes"],
            "migration_ms": round(mig.stats["ms"], 3),
            "mb_s": round(
                (mig.stats["bytes"] / 1e6) / max(mig.stats["ms"] / 1e3, 1e-9), 3
            ),
            "reps": max(reps, 1),
            "recovery_ms_migration": [round(w, 3) for w in mig_walls],
            "recovery_ms_fallback": [round(w, 3) for w in fb_walls],
            "bit_identical_to_fallback": _bit_identical_host(
                (state.params, state.opt_state),
                (fb_state.params, fb_state.opt_state),
            ),
            "used_fallback": False,
        }
        mig.close()

        # --- leg 2: one dropped StreamSend → harvested prefix + resume ----
        donor_addr = spawn_donor("drop@1")
        mig = migrator_for(donor_addr, timeout_s=30.0)
        drop_state = reconfigure_with(mig)
        report["drop"] = {
            "resumed": mig.stats["resumed"],
            "retries": mig.stats["retries"],
            "bit_identical": _bit_identical_host(
                (drop_state.params, drop_state.opt_state),
                (state.params, state.opt_state),
            ),
        }
        mig.close()

        # --- leg 3: persistent corruption → CRC fires, controller falls
        # back to the coordinated checkpoint restore, zero silent landing --
        donor_addr = spawn_donor("corrupt@*")
        mig = migrator_for(donor_addr, timeout_s=30.0, retries=1)
        crc_fired = False
        try:
            reconfigure_with(mig)
        except MigrationError:
            crc_fired = True
        from dsml_tpu.runtime.controller import (
            ControllerConfig,
            DeviceLost,
            ElasticController,
        )

        fleet = VirtualFleet(devices)
        ctl = ElasticController(
            model, optimizer, batch_provider,
            checkpoint_dir=os.path.join(base, "ctl"),
            fleet=fleet, mesh=mesh8, spec=spec,
            config=ControllerConfig(checkpoint_every=2, growback="keep"),
            global_batch=global_batch, seed=0,
            migrator=mig, non_addressable=remote_ids,
        )

        def on_step(s: int) -> None:
            if s == 3:
                dead = fleet.kill(1, 3)
                if dead:
                    ctl.inject(DeviceLost(dead, "chaos: local tp-1 holders"))

        with ctl:
            ctl_report = ctl.run(4, on_step=on_step)
        rec = ctl_report["recoveries"][0] if ctl_report["recoveries"] else {}
        report["corrupt"] = {
            "crc_fired": crc_fired,
            "integrity_failures": mig.stats["integrity_failures"],
            "controller_kind": rec.get("kind"),
            "controller_fallback_mentions_crc": "CRC" in rec.get("fallback_reason", ""),
            "controller_steps_completed": ctl_report["steps_completed"],
            "losses_finite": bool(
                np.all(np.isfinite(list(ctl.losses.values())))
            ),
        }
        mig.close()
        report["n_leaves"] = n_leaves
        manager.close()
        return report
    finally:
        if coordinator is not None:
            coordinator.stop()
        if recv is not None:
            recv.stop()
        for p in procs:
            try:
                p.stdin.close()
            except OSError:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — teardown must not mask the report
                p.kill()
        if created:
            shutil.rmtree(base, ignore_errors=True)


def verify_migration(report: dict) -> list[str]:
    """The migration invariants, as a list of violations (empty = pass)."""
    bad: list[str] = []
    refusal = report.get("refusal", {})
    if not refusal.get("raised"):
        bad.append("refusal: pull without a migrator did NOT raise")
    clean = report.get("clean", {})
    if not clean.get("migrated_pieces"):
        bad.append("clean: zero pieces moved over P2P streams")
    if clean.get("used_fallback"):
        bad.append("clean: migration leg used the checkpoint fallback")
    if not clean.get("bit_identical_to_fallback"):
        bad.append("clean: migrated state NOT bit-identical to the "
                   "checkpoint-fallback state")
    drop = report.get("drop", {})
    if not (drop.get("resumed") or drop.get("retries")):
        bad.append("drop: dropped stream neither resumed nor retried")
    if not drop.get("bit_identical"):
        bad.append("drop: resumed migration NOT bit-identical")
    corrupt = report.get("corrupt", {})
    if not corrupt.get("crc_fired"):
        bad.append("corrupt: CRC check did not abort the migration")
    if not corrupt.get("integrity_failures"):
        bad.append("corrupt: no integrity failures counted")
    if corrupt.get("controller_kind") != "checkpoint_fallback":
        bad.append(
            f"corrupt: controller recovered via "
            f"{corrupt.get('controller_kind')!r}, expected checkpoint_fallback"
        )
    if corrupt.get("controller_steps_completed", 0) < 4:
        bad.append("corrupt: controller did not complete the run after fallback")
    return bad


def verify(report: dict) -> list[str]:
    """The invariants, as a list of violations (empty = pass)."""
    bad: list[str] = []
    runs = [(k, v) for k, v in report.items()
            if isinstance(v, dict) and "steps_completed" in v]
    for name, rep in runs:
        if rep["steps_completed"] != report["n_steps"]:
            bad.append(f"{name}: lost steps — completed "
                       f"{rep['steps_completed']}/{report['n_steps']}")
        if rep["kills"] and not rep["recoveries"]:
            bad.append(f"{name}: {rep['kills']} kills but zero recoveries")
        if not rep.get("bit_identical"):
            bad.append(f"{name}: final params NOT bit-identical to the "
                       f"uninterrupted run")
        if rep["goodput"] < report["goodput_floor"]:
            bad.append(f"{name}: goodput {rep['goodput']} below the "
                       f"documented floor {report['goodput_floor']}")
    if not runs:
        bad.append("no chaos runs in the report")
    staging = report.get("ledger_staging_bytes_final", 0)
    if staging > 0:
        bad.append(
            f"ledger: {staging:.0f} staging byte(s) still claimed after "
            "every recovery completed — a checkpoint snapshot or migration "
            "span leaked past its commit"
        )
    srv = report.get("serving")
    if srv is not None and srv.get("token_mismatches", 0) > 0:
        bad.append(f"serving: {srv['token_mismatches']} request(s) lost or "
                   "changed tokens across a replica kill")
    fleet = report.get("serving_fleet")
    if fleet is not None:
        if fleet.get("token_mismatches", 0) > 0:
            bad.append(
                f"serving_fleet: {fleet['token_mismatches']} request(s) "
                "lost or changed tokens across worker kills"
            )
        if not fleet.get("requeued_prefill"):
            bad.append(
                "serving_fleet: the prefill-worker kill interrupted no "
                "work — the mid-handoff re-prefill path went unexercised"
            )
        if not fleet.get("requeued_decode"):
            bad.append(
                "serving_fleet: the decode-worker kill interrupted no "
                "work — the full-pipeline re-run path went unexercised"
            )
        if not fleet.get("trace_requeue_same", 1):
            bad.append(
                "serving_fleet: a killed request's re-run retired under a "
                "DIFFERENT trace_id — the retry must stay on the same trace"
            )
        if not fleet.get("trace_retry_recorded", 1):
            bad.append(
                "serving_fleet: a requeued request retired with zero "
                "recorded retries — the requeue span went unrecorded"
            )
        if not fleet.get("trace_burn_full_latency", 1):
            bad.append(
                "serving_fleet: a requeued request's SLO burn counted only "
                "the post-requeue leg — the budget must pay the FULL "
                "user-visible latency, kill included"
            )
    paged = report.get("serving_paged")
    if paged is not None:
        if paged.get("token_mismatches", 0) > 0:
            bad.append(
                f"serving_paged: {paged['token_mismatches']} request(s) "
                "lost or changed tokens across the decode-worker kill"
            )
        if not paged.get("requeued_decode"):
            bad.append(
                "serving_paged: the decode-worker kill interrupted no work "
                "— the paged re-prefill path went unexercised"
            )
        if not paged.get("peak_shared_pages"):
            bad.append(
                "serving_paged: no CoW prefix page was ever shared — the "
                "kill did not land with sharing live"
            )
        if paged.get("leaked_pages", 0) > 0:
            bad.append(
                f"serving_paged: {paged['leaked_pages']} pool page(s) "
                "leaked past request retirement (the dead worker's pages "
                "must reclaim without shrinking pool capacity)"
            )
        if not paged.get("eviction_preemptions"):
            bad.append(
                "serving_paged: the eviction leg forced no preemption — "
                "the swap/resume path went unexercised"
            )
        if not paged.get("eviction_resumed_identical"):
            bad.append(
                "serving_paged: no preempted request resumed with the "
                "reference tokens — eviction must be pure scheduling"
            )
        if paged.get("eviction_token_mismatches", 0) > 0:
            bad.append(
                f"serving_paged: {paged['eviction_token_mismatches']} "
                "request(s) changed tokens across an eviction/resume"
            )
        if paged.get("eviction_leaked_pages", 0) > 0:
            bad.append(
                f"serving_paged: {paged['eviction_leaked_pages']} page(s) "
                "leaked through the preemption tier (swap-out must "
                "release every reference it takes)"
            )
        # ledger-byte balance (ISSUE 15): the fleet-wide occupied KV
        # BYTES must return to their pre-admission baseline after the
        # kill leg and after the eviction/resume churn — .get(..., 1)
        # keeps pre-ledger report files verifiable
        if not paged.get("ledger_balanced", 1):
            bad.append(
                "serving_paged: ledger KV bytes did not return to baseline "
                f"after the drain ({paged.get('ledger_kv_final_bytes')} vs "
                f"{paged.get('ledger_kv_baseline_bytes')} baseline)"
            )
        if not paged.get("eviction_ledger_balanced", 1):
            bad.append(
                "serving_paged: eviction leg leaked ledger KV bytes "
                f"({paged.get('eviction_ledger_final_bytes')} vs "
                f"{paged.get('eviction_ledger_baseline_bytes')} baseline)"
            )
    return bad


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="chaos smoke: scripted kill/restore schedule on the "
        "virtual-8 mesh; exits nonzero if any survival invariant fails"
    )
    parser.add_argument("--steps", type=int, default=24)
    parser.add_argument("--seeds", type=int, nargs="*", default=[],
                        help="extra seeded-random schedules")
    parser.add_argument("--report", default="",
                        help="write the JSON report here")
    parser.add_argument("--migration", action="store_true",
                        help="run the two-host (subprocess-simulated) "
                        "shard-migration smoke instead: clean P2P shrink "
                        "bit-identical to checkpoint fallback, dropped-stream "
                        "resume, corrupt-chunk CRC abort + coordinated "
                        "fallback (docs/ELASTIC.md § Multi-host recovery); "
                        "exits nonzero on any violated invariant")
    parser.add_argument(_DONOR_FLAG, default=None, metavar="NPZ",
                        help=argparse.SUPPRESS)
    parser.add_argument("--cluster-snapshot", default="",
                        help="write this process's cluster-obs snapshot "
                        "(registry + trace, identity-stamped) here so an "
                        "aggregator can merge the chaos run into the fleet "
                        "view offline (docs/OBSERVABILITY.md § Cluster)")
    parser.add_argument("--push", default="",
                        help="push the snapshot to a running aggregator at "
                        "this host:port over the comm/ ObsPlane instead of "
                        "(or in addition to) writing a file")
    args = parser.parse_args(argv)

    if args.serve_migration_donor is not None:
        _donor_main(args.serve_migration_donor)
        return 0

    # force the virtual-8 CPU mesh BEFORE jax initializes a backend
    from dsml_tpu.utils.platform import configure_platform

    configure_platform("cpu", 8)

    if args.migration:
        report = run_migration_smoke()
        violations = verify_migration(report)
        report["violations"] = violations
        line = json.dumps(report, default=str)
        print(line)
        if args.report:
            with open(args.report, "w") as f:
                f.write(line + "\n")
        for v in violations:
            log.error("migration invariant violated: %s", v)
        return 1 if violations else 0

    want_obs = bool(args.cluster_snapshot or args.push)
    if want_obs:
        # the snapshot is only worth merging if the run recorded itself
        from dsml_tpu import obs as _obs

        _obs.enable(forensics=False)

    env_schedule = config_from_env()
    if env_schedule is not None:
        log.info("DSML_CHAOS schedule: %d events", len(env_schedule.events))
    report = run_smoke(n_steps=args.steps, seeds=tuple(args.seeds),
                       schedule=env_schedule)
    violations = verify(report)
    report["violations"] = violations
    line = json.dumps(report, default=str)
    print(line)
    if args.report:
        with open(args.report, "w") as f:
            f.write(line + "\n")
    if want_obs:
        from dsml_tpu.obs import cluster as _cluster

        if args.cluster_snapshot:
            with open(args.cluster_snapshot, "w") as f:
                json.dump(_cluster.snapshot(role="chaos"), f)
        if args.push:
            try:
                _cluster.push_snapshot(args.push, role="chaos")
            except Exception as e:  # noqa: BLE001 — obs must not fail chaos
                log.warning("cluster push to %s failed: %r", args.push, e)
    for v in violations:
        log.error("chaos invariant violated: %s", v)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(_main())
