"""Fault injection: prove the chaos-survival guarantee, don't assert it.

``runtime.controller`` claims it rides preemptions end-to-end. This module
is the adversary that makes the claim testable: scripted and seeded-random
kill/restore schedules driven against the training controller (device
loss) and the serving ``DecodeFleet`` (replica loss), with the invariants
checked afterwards:

- **zero lost steps** — the final lineage contains every step exactly
  once (the step counter reaches the target and nothing was skipped);
- **bit-identity** (``growback="replay"``) — final params bit-identical
  to an uninterrupted run at the same step count on the same full mesh;
- **goodput floor** — productive ÷ wall stays above a documented floor
  for the harness (virtual-8 CPU: compiles dominate, floor 0.02 — the
  number is environment-specific, the FLOOR EXISTING is the guarantee);
- **zero token loss** (serving) — every request killed mid-decode
  re-runs on a survivor and its final tokens equal the single-batcher
  reference (greedy decode is a pure function of the prompt).

Faults are injected through the same three doors the controller watches:
the fleet view (``VirtualFleet.kill`` — the health-probe verdict), the
signal queue (``controller.inject(DeviceLost(...))``), and — when
``DSML_HANGWATCH`` is armed — a hangwatch expiry paired with a fleet
kill (the wedged-device shape).

Env knob ``DSML_CHAOS`` selects a schedule for the smoke entry point
(``python -m dsml_tpu.runtime.chaos``): unset/``0`` → off, ``1`` →
the default scripted schedule, ``seed:<n>`` → seeded-random. CI runs the
scripted schedule on the virtual-8 mesh every push (tier1.yml
``chaos-smoke``).
"""

from __future__ import annotations

import dataclasses
import os
import random

import numpy as np

from dsml_tpu.utils.logging import get_logger

__all__ = [
    "ChaosEvent",
    "ChaosSchedule",
    "VirtualFleet",
    "config_from_env",
    "run_chaos_training",
    "run_chaos_serving",
    "run_smoke",
]

log = get_logger("chaos")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    """One fault: at ``step`` (training) / ``tick`` (serving), ``kill`` the
    targets or ``restore`` them (empty targets = everything dead)."""

    step: int
    action: str  # "kill" | "restore"
    targets: tuple = ()
    inject: bool = False  # also push a DeviceLost signal (vs probe-only)

    def __post_init__(self):
        if self.action not in ("kill", "restore"):
            raise ValueError(f"unknown chaos action {self.action!r}")


class ChaosSchedule:
    """An ordered list of :class:`ChaosEvent`; scripted or seeded-random."""

    def __init__(self, events):
        self.events = tuple(sorted(events, key=lambda e: e.step))

    @classmethod
    def scripted_default(cls, n_devices: int = 8) -> "ChaosSchedule":
        """The CI smoke schedule: 3 kills at distinct steps (one injected,
        two probe-detected), then a full restore — the ≥3-kills/1-restore
        shape the acceptance criterion names."""
        return cls([
            ChaosEvent(6, "kill", (n_devices - 1,), inject=True),
            ChaosEvent(10, "kill", (2,)),
            ChaosEvent(13, "kill", (0,)),
            ChaosEvent(17, "restore", ()),
        ])

    @classmethod
    def seeded(cls, seed: int, n_steps: int = 24, n_devices: int = 8,
               n_kills: int = 3) -> "ChaosSchedule":
        """Seeded-random schedule: ``n_kills`` distinct devices die at
        distinct steps in the first two-thirds of the run (always leaving
        at least one survivor), then everything restores."""
        rng = random.Random(seed)
        n_kills = min(n_kills, n_devices - 1)
        lo, hi = 2, max(2 * n_steps // 3, 3)
        steps = sorted(rng.sample(range(lo, hi + 1), min(n_kills, hi - lo + 1)))
        targets = rng.sample(range(n_devices), len(steps))
        events = [
            ChaosEvent(s, "kill", (t,), inject=rng.random() < 0.5)
            for s, t in zip(steps, targets)
        ]
        restore_at = min(steps[-1] + rng.randint(2, 5), n_steps - 4)
        events.append(ChaosEvent(max(restore_at, steps[-1] + 1), "restore", ()))
        return cls(events)

    def at(self, step: int) -> list[ChaosEvent]:
        return [e for e in self.events if e.step == step]

    def kills(self) -> int:
        return sum(1 for e in self.events if e.action == "kill")


def config_from_env(spec: str | None = None) -> ChaosSchedule | None:
    """``DSML_CHAOS``: unset/``0`` → None; ``1`` → the scripted default;
    ``seed:<n>`` → :meth:`ChaosSchedule.seeded`."""
    if spec is None:
        spec = os.environ.get("DSML_CHAOS", "")
    spec = spec.strip().lower()
    if spec in ("", "0", "false", "off"):
        return None
    if spec in ("1", "true", "on", "scripted"):
        return ChaosSchedule.scripted_default()
    if spec.startswith("seed:"):
        try:
            return ChaosSchedule.seeded(int(spec[5:]))
        except ValueError as e:
            raise ValueError(f"DSML_CHAOS={spec!r}: bad seed") from e
    raise ValueError(
        f"DSML_CHAOS={spec!r} is not one of 0/1/scripted/seed:<n>"
    )


class VirtualFleet:
    """A fleet view the harness can lie through: ``kill`` hides devices
    from ``available()`` (what a coordinator health probe would report),
    ``restore`` brings them back (capacity returning). Indices are into
    the original device list."""

    def __init__(self, devices):
        self._devices = list(devices)
        self._dead: set[int] = set()

    def available(self) -> list:
        return [d for i, d in enumerate(self._devices) if i not in self._dead]

    def kill(self, *indices: int) -> list:
        dead = []
        for i in indices:
            if i not in self._dead and 0 <= i < len(self._devices):
                self._dead.add(i)
                dead.append(self._devices[i])
        if len(self._dead) >= len(self._devices):
            raise RuntimeError("chaos killed the whole fleet")
        return dead

    def restore(self, *indices: int) -> list:
        back = sorted(self._dead) if not indices else list(indices)
        restored = [self._devices[i] for i in back if i in self._dead]
        self._dead -= set(back)
        return restored

    @property
    def n_dead(self) -> int:
        return len(self._dead)


def run_chaos_training(controller, schedule: ChaosSchedule,
                       n_steps: int) -> dict:
    """Drive ``controller.run(n_steps)`` with ``schedule`` applied through
    the controller's fleet (which must be a :class:`VirtualFleet`).
    Returns the controller report with the schedule appended."""
    from dsml_tpu.runtime.controller import DeviceLost

    fleet = controller.fleet
    fired: set = set()

    def on_step(step: int) -> None:
        for ev in schedule.at(step):
            if id(ev) in fired:
                continue
            fired.add(id(ev))
            if ev.action == "kill":
                dead = fleet.kill(*ev.targets)
                log.warning("chaos: step %d kill %s", step, list(ev.targets))
                if ev.inject and dead:
                    controller.inject(DeviceLost(dead, "chaos kill"))
            else:
                restored = fleet.restore(*ev.targets)
                log.warning("chaos: step %d restore %d device(s)",
                            step, len(restored))

    report = controller.run(n_steps, on_step=on_step)
    report["schedule"] = [dataclasses.asdict(e) for e in schedule.events]
    return report


def run_chaos_serving(fleet, prompts, max_new: int,
                      kill_ticks: dict[int, int | None],
                      max_ticks: int = 100_000) -> dict:
    """Drive a ``DecodeFleet`` to drain ``prompts`` while killing replicas
    at the scheduled ticks (``{tick: replica_id or None=newest}``).
    Returns ``{"results": {frid: tokens}, "ticks": n}``."""
    frids = [fleet.submit(p, max_new) for p in prompts]
    tick = 0
    while fleet.outstanding:
        if tick in kill_ticks and fleet.n_replicas:
            fleet.kill_replica(kill_ticks[tick])
        fleet.tick()
        tick += 1
        if tick > max_ticks:
            raise RuntimeError(f"serving chaos did not drain in {max_ticks}")
    results = fleet.run(max_ticks=1)  # drains the harvested results
    return {"results": {f: results.get(f, []) for f in frids}, "ticks": tick}


# ---------------------------------------------------------------------------
# smoke: the end-to-end guarantee as an executable check (CI + bench)
# ---------------------------------------------------------------------------

# documented goodput floor for THIS harness (virtual-8 CPU mesh, tiny
# model): recovery compiles dominate the wall, so the floor is low — the
# guarantee is that a floor EXISTS and holds, not the CPU number itself
# (docs/ELASTIC.md documents the real-chip expectation separately)
SMOKE_GOODPUT_FLOOR = 0.02


def _bit_identical(tree_a, tree_b) -> bool:
    import jax

    la, lb = jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)
    if len(la) != len(lb):
        return False
    return all(
        np.array_equal(np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))
        for a, b in zip(la, lb)
    )


def run_smoke(n_steps: int = 24, seeds: tuple = (), checkpoint_every: int = 4,
              tmp_dir: str | None = None,
              schedule: "ChaosSchedule | None" = None,
              serving: bool = True) -> dict:
    """The acceptance run: scripted schedule (≥3 kills, 1 restore) on the
    virtual-8 mesh with ``growback="replay"`` — final params must be
    bit-identical to an uninterrupted run at the same step count, zero
    steps lost, goodput above :data:`SMOKE_GOODPUT_FLOOR`. ``seeds`` adds
    seeded-random schedules for the recovery-time distribution. Returns a
    report dict; ``verify`` raises on any violated invariant."""
    import shutil
    import tempfile

    import jax
    import optax

    from dsml_tpu.models.gpt2 import GPT2, GPT2Config

    if n_steps < 20:
        raise ValueError(
            f"chaos smoke needs n_steps >= 20 (the scripted schedule kills "
            f"through step 13, restores at 17, and grows at the next "
            f"checkpoint boundary), got {n_steps}"
        )
    from dsml_tpu.parallel.hybrid import init_hybrid, make_hybrid_train_step
    from dsml_tpu.parallel.mesh import MeshSpec, build_mesh
    from dsml_tpu.runtime.controller import ControllerConfig, ElasticController

    devices = jax.devices()[:8]
    if len(devices) < 8:
        raise RuntimeError(f"chaos smoke needs 8 devices, found {len(devices)}")
    cfg = GPT2Config.tiny()
    model = GPT2(cfg)
    optimizer = optax.adam(1e-2)
    global_batch = 8
    rng = np.random.default_rng(0)
    data = rng.integers(0, cfg.vocab_size,
                        (n_steps + 8, global_batch, cfg.max_seq)).astype(np.int32)

    def batch_provider(step: int):
        x = data[step - 1]
        return x, np.roll(x, -1, 1).astype(np.int32)

    spec = MeshSpec(dp=8)
    base = tmp_dir or tempfile.mkdtemp(prefix="dsml_chaos_")
    created = tmp_dir is None
    report: dict = {"n_steps": n_steps}
    try:
        # the uninterrupted reference: the same mesh, same batches, no
        # controller, no checkpoints, no failures
        mesh = build_mesh(spec, devices)
        step_fn = make_hybrid_train_step(model, optimizer, mesh)
        ref_params, ref_opt = init_hybrid(model, optimizer, mesh, seed=0)
        for s in range(1, n_steps + 1):
            ref_params, ref_opt, ref_loss = step_fn(ref_params, ref_opt,
                                                    *batch_provider(s))
        ref_loss = float(ref_loss)

        def one_run(schedule: ChaosSchedule, name: str) -> dict:
            fleet = VirtualFleet(devices)
            ctl = ElasticController(
                model, optimizer, batch_provider,
                checkpoint_dir=os.path.join(base, name),
                fleet=fleet, mesh=build_mesh(spec, devices), spec=spec,
                config=ControllerConfig(checkpoint_every=checkpoint_every,
                                        growback="replay"),
                global_batch=global_batch, seed=0,
            )
            with ctl:
                rep = run_chaos_training(ctl, schedule, n_steps)
            rep["bit_identical"] = _bit_identical(ctl.params, ref_params)
            rep["final_loss"] = ctl.losses.get(n_steps)
            rep["ref_loss"] = ref_loss
            rep["kills"] = schedule.kills()
            return rep

        report["scripted"] = one_run(
            schedule or ChaosSchedule.scripted_default(), "scripted"
        )
        recov = [r["recovery_ms"] for r in report["scripted"]["recoveries"]]
        for seed in seeds:
            rep = one_run(ChaosSchedule.seeded(seed, n_steps), f"seed{seed}")
            report[f"seed{seed}"] = rep
            recov += [r["recovery_ms"] for r in rep["recoveries"]]
        if recov:
            report["recovery_p50_ms"] = round(float(np.percentile(recov, 50)), 3)
            report["recovery_p99_ms"] = round(float(np.percentile(recov, 99)), 3)
            report["recovery_samples"] = len(recov)
        report["goodput_floor"] = SMOKE_GOODPUT_FLOOR
        if serving:
            report["serving"] = _serving_smoke(model, cfg, rng)
    finally:
        if created:
            shutil.rmtree(base, ignore_errors=True)
    return report


def _serving_smoke(model, cfg, rng) -> dict:
    """Replica-loss smoke: a 2-replica decode fleet loses a replica
    mid-drain; every request re-runs on a survivor and the final tokens
    must equal the single-batcher reference (greedy ⇒ pure function of
    the prompt)."""
    from dsml_tpu.runtime.controller import DecodeFleet
    from dsml_tpu.serving import ContinuousBatcher

    params = model.init(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, rng.integers(3, 9)).astype(np.int32)
        for _ in range(6)
    ]
    max_new = 6
    ref = ContinuousBatcher(model, params, n_slots=2)
    ref_rids = [ref.submit(p, max_new) for p in prompts]
    ref_tokens = ref.run()

    fleet = DecodeFleet(
        lambda: ContinuousBatcher(model, params, n_slots=2, max_queue=8),
        min_replicas=2, max_replicas=3, scale_up_queue_depth=2,
        scale_down_idle_ticks=4,
    )
    out = run_chaos_serving(fleet, prompts, max_new, kill_ticks={3: None})
    token_loss = sum(
        1 for frid, rrid in zip(sorted(out["results"]), ref_rids)
        if out["results"][frid] != ref_tokens[rrid]
    )
    return {
        "requests": len(prompts),
        "token_mismatches": token_loss,
        "ticks": out["ticks"],
        "scale_events": len(fleet.scale_events),
    }


def verify(report: dict) -> list[str]:
    """The invariants, as a list of violations (empty = pass)."""
    bad: list[str] = []
    runs = [(k, v) for k, v in report.items()
            if isinstance(v, dict) and "steps_completed" in v]
    for name, rep in runs:
        if rep["steps_completed"] != report["n_steps"]:
            bad.append(f"{name}: lost steps — completed "
                       f"{rep['steps_completed']}/{report['n_steps']}")
        if rep["kills"] and not rep["recoveries"]:
            bad.append(f"{name}: {rep['kills']} kills but zero recoveries")
        if not rep.get("bit_identical"):
            bad.append(f"{name}: final params NOT bit-identical to the "
                       f"uninterrupted run")
        if rep["goodput"] < report["goodput_floor"]:
            bad.append(f"{name}: goodput {rep['goodput']} below the "
                       f"documented floor {report['goodput_floor']}")
    if not runs:
        bad.append("no chaos runs in the report")
    srv = report.get("serving")
    if srv is not None and srv.get("token_mismatches", 0) > 0:
        bad.append(f"serving: {srv['token_mismatches']} request(s) lost or "
                   "changed tokens across a replica kill")
    return bad


def _main(argv=None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="chaos smoke: scripted kill/restore schedule on the "
        "virtual-8 mesh; exits nonzero if any survival invariant fails"
    )
    parser.add_argument("--steps", type=int, default=24)
    parser.add_argument("--seeds", type=int, nargs="*", default=[],
                        help="extra seeded-random schedules")
    parser.add_argument("--report", default="",
                        help="write the JSON report here")
    parser.add_argument("--cluster-snapshot", default="",
                        help="write this process's cluster-obs snapshot "
                        "(registry + trace, identity-stamped) here so an "
                        "aggregator can merge the chaos run into the fleet "
                        "view offline (docs/OBSERVABILITY.md § Cluster)")
    parser.add_argument("--push", default="",
                        help="push the snapshot to a running aggregator at "
                        "this host:port over the comm/ ObsPlane instead of "
                        "(or in addition to) writing a file")
    args = parser.parse_args(argv)

    # force the virtual-8 CPU mesh BEFORE jax initializes a backend
    from dsml_tpu.utils.platform import configure_platform

    configure_platform("cpu", 8)

    want_obs = bool(args.cluster_snapshot or args.push)
    if want_obs:
        # the snapshot is only worth merging if the run recorded itself
        from dsml_tpu import obs as _obs

        _obs.enable(forensics=False)

    env_schedule = config_from_env()
    if env_schedule is not None:
        log.info("DSML_CHAOS schedule: %d events", len(env_schedule.events))
    report = run_smoke(n_steps=args.steps, seeds=tuple(args.seeds),
                       schedule=env_schedule)
    violations = verify(report)
    report["violations"] = violations
    line = json.dumps(report, default=str)
    print(line)
    if args.report:
        with open(args.report, "w") as f:
            f.write(line + "\n")
    if want_obs:
        from dsml_tpu.obs import cluster as _cluster

        if args.cluster_snapshot:
            with open(args.cluster_snapshot, "w") as f:
                json.dump(_cluster.snapshot(role="chaos"), f)
        if args.push:
            try:
                _cluster.push_snapshot(args.push, role="chaos")
            except Exception as e:  # noqa: BLE001 — obs must not fail chaos
                log.warning("cluster push to %s failed: %r", args.push, e)
    for v in violations:
        log.error("chaos invariant violated: %s", v)
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(_main())
