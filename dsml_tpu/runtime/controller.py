"""Elastic chaos-survival controller: ride preemptions end-to-end.

The reference marks a communicator permanently dead on first failure
(recovery "none", SURVEY.md §5.3). Every ingredient of the missing half
already exists in this repo — elastic re-plan (``parallel.elastic``),
bit-identical async checkpointing (``checkpoint``), hang/straggler/goodput
signals (``obs``) — but nothing closed the loop: a preemption still killed
the run. This module is the loop:

    detect ──► shrink ──► resume ──► grow
      │          │           │         │
      │          │           │         └─ capacity returns: re-shard live
      │          │           │            state back onto the full fleet at
      │          │           │            the next checkpoint boundary
      │          │           │            ("keep"), or restore the last
      │          │           │            pure-lineage checkpoint and replay
      │          │           │            at full width ("replay" — final
      │          │           │            params bit-identical to a run
      │          │           │            that never failed)
      │          │           └─ rebuild the step function for the new mesh
      │          │              and continue mid-run, data-loader position
      │          │              intact (``batch_provider`` is a pure
      │          │              function of the step index)
      │          └─ ``elastic.reconfigure`` onto the survivors; when the
      │             audit reports torn leaves (an entire tp shard / pp
      │             stage / ZeRO shard died), fall back to
      │             ``elastic.restore_from_checkpoint`` and replay the
      │             steps since the last commit (the "lost work" metric)
      └─ three independent sources: fleet probes (the coordinator health
         verdict), injected ``DeviceLost`` signals (chaos harness, or a
         step raising), and hangwatch deadline expiries (a wedged-but-
         alive device)

Recovery time and lost work land in the obs registry
(``controller_recovery_ms{stage}``, ``controller_lost_steps_total``,
``controller_redone_steps_total``) and the flight recorder throughout, so
a 3am preemption leaves a story, not a mystery. The guarantee is TESTED,
not asserted: ``runtime.chaos`` drives scripted and seeded-random
kill/restore schedules against this loop (and against the serving
``DecodeFleet`` below) — see ``docs/ELASTIC.md`` and ``bench.py
--section chaos``.

On a single host, device loss is simulated by meshes shrinking between
steps (the model multi-host JAX presents when a host drops) — the same
simulation ``parallel.elastic``'s tests use.
"""

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Callable

import numpy as np

from dsml_tpu.obs import (
    GoodputTracker,
    flight_recorder,
    get_registry,
    hangwatch,
    observe_recovery_ms,
)
from dsml_tpu.parallel import elastic
from dsml_tpu.parallel.elastic import ElasticPolicy
from dsml_tpu.parallel.hybrid import init_hybrid, make_hybrid_train_step
from dsml_tpu.parallel.mesh import MeshSpec, build_mesh
from dsml_tpu.utils.config import Config, field
from dsml_tpu.utils.logging import get_logger

__all__ = [
    "DeviceLost",
    "Unrecoverable",
    "StaticFleet",
    "ControllerConfig",
    "ElasticController",
    "DecodeFleet",
]

log = get_logger("controller")


class DeviceLost(RuntimeError):
    """Failure signal: these devices are gone. Raised by a training step on
    a real loss (XLA surfaces device failure as an error from the step),
    injected by the chaos harness, or synthesized from a coordinator
    health verdict."""

    def __init__(self, devices, message: str = ""):
        self.devices = tuple(devices)
        super().__init__(
            message or f"lost {len(self.devices)} device(s): "
            f"{[getattr(d, 'id', d) for d in self.devices]}"
        )


class Unrecoverable(RuntimeError):
    """The job cannot continue: no survivors, or recovery itself failed."""


class StaticFleet:
    """The no-failure fleet view: a fixed device list. Real deployments
    plug in a view backed by ``jax.devices()`` re-resolution or coordinator
    health probes; the chaos harness plugs in ``chaos.VirtualFleet``."""

    def __init__(self, devices):
        self._devices = list(devices)

    def available(self) -> list:
        return list(self._devices)


@dataclasses.dataclass
class ControllerConfig(Config):
    checkpoint_every: int = field(
        8, help="async checkpoint cadence in steps; also the grow-back "
        "boundary — restored capacity is adopted right after a save commits"
    )
    keep_checkpoints: int = field(
        0, help="max checkpoints retained (0 = keep all; replay grow-back "
        "needs the last pure-lineage checkpoint to outlive the outage)"
    )
    growback: str = field(
        "replay", help="grow-back mode: 'replay' restores the last "
        "pure-lineage checkpoint and re-runs the outage window at full "
        "width (final params bit-identical to a never-failed run); 'keep' "
        "re-shards the survivor-width state onto the restored fleet (zero "
        "recompute, mixed-width lineage)"
    )
    detect_every: int = field(
        1, help="probe the fleet view every N steps (injected DeviceLost "
        "signals and hangwatch verdicts are checked every step regardless)"
    )
    recovery_deadline_s: float = field(
        0.0, help="recoveries slower than this warn + dump a postmortem "
        "bundle (0 = DSML_RECOVERY_DEADLINE_S, default 120)"
    )
    batch_per_device: int = field(1, help="forwarded to the elastic re-plan")
    attn_impl: str = field("", help="attention impl for rebuilt steps ('' = "
                           "per-mesh auto: ring2 on cp meshes, ring otherwise "
                           "— a pinned 'ring' on a cp mesh would lose ring2's "
                           "O(S/cp) residual property on every reconfigure)")

    def resolved_recovery_deadline_s(self) -> float:
        if self.recovery_deadline_s > 0:
            return self.recovery_deadline_s
        try:
            return float(os.environ.get("DSML_RECOVERY_DEADLINE_S", 120.0))
        except ValueError:
            return 120.0


class ElasticController:
    """Supervision loop over a hybrid-parallel training run.

    ``batch_provider(step) -> (x, y)`` must be a deterministic function of
    the 1-based step index (``utils.data.shard_batches`` seeded by step is
    exactly this) — that is what makes the data-loader position a single
    integer that rides in every checkpoint manifest, and replay after a
    fallback bit-identical.

    ``step_factory(model, optimizer, mesh) -> step_fn`` defaults to
    ``make_hybrid_train_step``; step functions are cached per topology, so
    growing back onto the original fleet reuses the original compile.
    """

    def __init__(
        self,
        model,
        optimizer,
        batch_provider: Callable[[int], tuple],
        checkpoint_dir: str,
        fleet=None,
        mesh=None,
        spec: MeshSpec | None = None,
        config: ControllerConfig | None = None,
        policy: ElasticPolicy = ElasticPolicy(),
        global_batch: int | None = None,
        seed: int = 0,
        step_factory: Callable | None = None,
        failure_feed: Callable[[], list] | None = None,
        planner_overrides: dict | None = None,
        migrator=None,
        non_addressable=(),
    ):
        from dsml_tpu.checkpoint import CheckpointManager

        self.model = model
        self.optimizer = optimizer
        self.batch_provider = batch_provider
        self.config = config or ControllerConfig()
        self.policy = policy
        self.global_batch = global_batch
        self.seed = seed
        self.planner_overrides = planner_overrides
        # cross-host state motion (docs/ELASTIC.md § Multi-host recovery):
        # with a ShardMigrator wired, a shrink whose pieces survive only on
        # another host pulls them over the P2P streams instead of falling
        # back to a checkpoint; `non_addressable` marks device ids that
        # belong to other hosts (the single-process sim lists local ids)
        self.migrator = migrator
        self.non_addressable = tuple(non_addressable)
        self._step_factory = step_factory or (
            lambda mdl, opt, m: make_hybrid_train_step(
                mdl, opt, m, attn_impl=self.config.attn_impl or None
            )
        )
        self._failure_feed = failure_feed
        self._ckpt = CheckpointManager(
            checkpoint_dir,
            max_to_keep=self.config.keep_checkpoints or None,
        )
        self._registry = get_registry()
        self._recorder = flight_recorder.get_flight_recorder()
        hw_cfg = hangwatch.config_from_env()
        self._hw = hangwatch.get_hangwatch() if hw_cfg is not None else None
        self._hw_deadline = (
            hangwatch.TrailingDeadline.from_config(hw_cfg)
            if hw_cfg is not None else None
        )
        self._hw_fired_seen = len(self._hw.fired) if self._hw is not None else 0

        if fleet is None:
            import jax

            fleet = StaticFleet(jax.devices())
        self.fleet = fleet

        # the FULL topology — the grow-back target. Caller-provided mesh
        # wins (tests pin exact layouts); otherwise the capacity planner
        # picks, exactly as a shrink re-plan would for the same fleet.
        devices = list(fleet.available())
        if not devices:
            raise Unrecoverable("fleet has no available devices")
        if mesh is not None:
            self._full_mesh = mesh
            self._full_spec = spec or self._spec_of(mesh)
        else:
            if spec is not None:
                self._full_mesh = build_mesh(spec, devices)
                self._full_spec = spec.resolved(len(devices))
            else:
                import jax

                # allocation-free count: materializing a full host init
                # just to size the planner would be a transient whole-model
                # allocation at exactly the scale this controller targets
                abstract = jax.eval_shape(lambda: model.init(seed))
                plan, used = elastic._plan_for_survivors(
                    model, model.n_params(abstract), devices,
                    self.config.batch_per_device, global_batch,
                    planner_overrides,
                )
                self._full_mesh = build_mesh(plan.spec, used)
                self._full_spec = plan.spec
        self._full_ids = frozenset(d.id for d in self._full_mesh.devices.flat)

        self.mesh = self._full_mesh
        self.spec = self._full_spec
        self.params, self.opt_state = init_hybrid(
            model, optimizer, self.mesh, seed=seed
        )
        self._n_params = model.n_params(self.params)
        self._step_cache: dict = {}
        self._step_fn = self._get_step_fn(self.mesh, self.spec)

        # bookkeeping: 1-based index of the NEXT step to run; walls of the
        # steps in the CURRENT lineage (a rewind pops the discarded suffix
        # into lost-work); pure = every step since init ran at full width
        self._step = 1
        self._pure = True
        self._lineage_walls: dict[int, float] = {}
        self._lost_work_s = 0.0
        self._redone_steps = 0
        self._injected: deque[DeviceLost] = deque()
        # ids reported lost by a SIGNAL (injected / step-raised DeviceLost)
        # that the fleet view still lists as available: a StaticFleet never
        # stops listing a dead device, so without this quarantine the next
        # grow boundary would re-adopt it and hang the recovery loop. A
        # health-aware fleet clears the quarantine by dropping the device
        # from available() at least once — after that, its reappearance is
        # a genuine restore.
        self._quarantined: set = set()
        self.recoveries: list[dict] = []
        self.losses: dict[int, float] = {}
        self._goodput = GoodputTracker(registry=self._registry)
        self._t0 = time.monotonic()
        self._registry.gauge(
            "controller_fleet_size", "devices in the controller's mesh"
        ).set(len(devices))

    # ---- public surface --------------------------------------------------

    def inject(self, signal: DeviceLost) -> None:
        """Queue a failure signal (the chaos harness's hook; a coordinator
        adapter pushes health verdicts through the same door)."""
        self._injected.append(signal)

    def run(self, n_steps: int,
            on_step: Callable[[int], None] | None = None) -> dict:
        """Drive training to ``n_steps`` completed steps, riding every
        failure the fleet/chaos throws. ``on_step(step)`` fires before each
        step's detection pass (the chaos harness's injection point).
        Returns :meth:`report`."""
        while self._step <= n_steps:
            step = self._step
            if on_step is not None:
                on_step(step)
            self._detect(step)
            x, y = self.batch_provider(step)
            hw_token = None
            if self._hw is not None:
                deadline = self._hw_deadline.timeout_s()
                if deadline is not None:
                    hw_token = self._hw.arm("controller_step", deadline,
                                            step=step)
            t0 = time.perf_counter()
            try:
                self.params, self.opt_state, loss = self._step_fn(
                    self.params, self.opt_state, x, y
                )
                loss.block_until_ready()
            except DeviceLost as e:
                # a real loss surfaces as an error from the step; recover
                # and RETRY the same step index — nothing is skipped
                self._recover(e.devices)
                continue
            finally:
                if hw_token is not None:
                    self._hw.disarm(hw_token)
            wall = time.perf_counter() - t0
            if self._hw is not None:
                self._hw_deadline.observe(wall)
            self._lineage_walls[step] = wall
            self._goodput.add_productive(wall)
            self.losses[step] = float(loss)
            self._recorder.record("controller_step", step=step,
                                  wall_ms=round(wall * 1e3, 3),
                                  width=self.spec.n_devices)
            self._step += 1
            if step % max(self.config.checkpoint_every, 1) == 0:
                self._save(step)
                self._maybe_grow(step)
        return self.report()

    def close(self) -> None:
        self._ckpt.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def report(self) -> dict:
        wall = time.monotonic() - self._t0
        productive = sum(self._lineage_walls.values())
        recov_ms = [r["recovery_ms"] for r in self.recoveries]
        out = {
            "steps_completed": self._step - 1,
            "width": self.spec.n_devices,
            "pure_lineage": self._pure,
            "recoveries": list(self.recoveries),
            "n_recoveries": len(self.recoveries),
            "redone_steps": self._redone_steps,
            "lost_work_s": round(self._lost_work_s, 6),
            "wall_s": round(wall, 6),
            "productive_s": round(productive, 6),
            "goodput": round(min(productive / max(wall, 1e-9), 1.0), 4),
        }
        if recov_ms:
            out["recovery_p50_ms"] = round(float(np.percentile(recov_ms, 50)), 3)
            out["recovery_p99_ms"] = round(float(np.percentile(recov_ms, 99)), 3)
        return out

    # ---- internals -------------------------------------------------------

    @staticmethod
    def _spec_of(mesh) -> MeshSpec:
        return MeshSpec.from_mesh(mesh)

    def _get_step_fn(self, mesh, spec: MeshSpec):
        key = (tuple(d.id for d in mesh.devices.flat),
               tuple(sorted(spec.sizes_dict().items())))
        hit = self._step_cache.get(key)
        if hit is not None:
            return hit
        fn = self._step_factory(self.model, self.optimizer, mesh)
        self._step_cache[key] = fn
        return fn

    def _save(self, step: int) -> None:
        t0 = time.perf_counter()
        self._ckpt.save(
            step,
            {"params": self.params, "opt_state": self.opt_state,
             "meta": {"step": step}},
            meta={"step": step,
                  "lineage": "pure" if self._pure else "mixed",
                  "width": self.spec.n_devices,
                  "spec": self.spec.sizes_dict()},
            iterator_state={"step": step},
            wait=False,
        )
        self._recorder.record(
            "controller_checkpoint", step=step,
            lineage="pure" if self._pure else "mixed",
            stall_ms=round((time.perf_counter() - t0) * 1e3, 3),
        )

    def _detect(self, step: int) -> None:
        """Run all three detection sources; recover if any fires."""
        lost: list = []
        seen_ids: set = set()

        def note(devs):
            for d in devs:
                if getattr(d, "id", d) not in seen_ids:
                    seen_ids.add(getattr(d, "id", d))
                    lost.append(d)

        while self._injected:
            note(self._injected.popleft().devices)
        if self._failure_feed is not None:
            feed = self._failure_feed() or []
            # the feed speaks device IDS (coordinator verdicts); match them
            # against the live mesh
            by_id = {d.id: d for d in self.mesh.devices.flat}
            note([by_id[i] for i in feed
                  if isinstance(i, int) and i in by_id]
                 + [d for d in feed if not isinstance(d, int)])
        probe = bool(lost) or step % max(self.config.detect_every, 1) == 0
        if self._hw is not None:
            fired = len(self._hw.fired)
            if fired > self._hw_fired_seen:
                # a deadline expiry is a VERDICT to verify, not a failure by
                # itself: probe the fleet now; a wedged device shows up as
                # unavailable there (a slow-but-healthy step is a false
                # alarm the probe clears)
                self._hw_fired_seen = fired
                self._recorder.record("controller_hang_verdict", step=step)
                probe = True
        if probe:
            avail_ids = {d.id for d in self.fleet.available()}
            # a quarantined id the fleet has stopped reporting is released:
            # the fleet is health-aware, so its NEXT appearance means a
            # genuine restore rather than a stale static listing
            self._quarantined -= {i for i in self._quarantined
                                  if i not in avail_ids}
            note([d for d in self.mesh.devices.flat if d.id not in avail_ids])
        if lost:
            self._recover(lost)

    def _recover(self, lost_devices) -> None:
        """shrink (or checkpoint-fallback) onto the survivors."""
        t0 = time.perf_counter()
        lost_ids = {d.id for d in lost_devices}
        self._quarantined |= lost_ids
        width_before = self.spec.n_devices
        self._goodput.mark("preemption", step=self._step,
                           lost=sorted(lost_ids))
        self._recorder.record("controller_detect", step=self._step,
                              lost=sorted(lost_ids), width=width_before)
        survivors = [d for d in self.fleet.available()
                     if d.id not in lost_ids and d.id not in self._quarantined]
        if not survivors:
            raise Unrecoverable(
                f"no surviving devices after losing {sorted(lost_ids)}"
            )
        lost_in_mesh = [d for d in self.mesh.devices.flat if d.id in lost_ids]
        lost_steps = 0
        extra: dict = {}
        mig_before = None
        if self.migrator is not None:
            # donor death verdicts and cached plans are scoped to ONE
            # recovery: a donor that flaked last outage may be healthy now
            if hasattr(self.migrator, "reset_donors"):
                self.migrator.reset_donors()
            mig_before = dict(self.migrator.stats)
        try:
            state = elastic.reconfigure(
                self.model, self.optimizer, self.params, self.opt_state,
                surviving_devices=survivors, lost_devices=lost_in_mesh,
                policy=self.policy,
                batch_per_device=self.config.batch_per_device,
                global_batch=self.global_batch,
                planner_overrides=self.planner_overrides,
                migrator=self.migrator,
                non_addressable=self.non_addressable,
            )
            kind = "reconfigure"
        except RuntimeError as e:
            if "allow_shrink=False" in str(e):
                raise  # fail-fast policy: the reference's semantics, chosen
            # torn state (or P2P migration undeliverable): the Varuna-style
            # COORDINATED fallback — flush in-flight saves, restore the
            # latest commit onto the survivor plan, and rewind the step
            # counter to it (the replayed steps are the lost work). In a
            # real multi-host fleet every host takes this leg on the step
            # CheckpointManager.newest_common_step agrees on.
            log.warning("live state not recoverable (%s); falling back to "
                        "checkpoint", e)
            extra["fallback_reason"] = str(e)[:200]
            self._ckpt.wait_until_finished()
            try:
                state = elastic.restore_from_checkpoint(
                    self._ckpt, self.model, self.optimizer, survivors,
                    seed=self.seed,
                    batch_per_device=self.config.batch_per_device,
                    global_batch=self.global_batch,
                    planner_overrides=self.planner_overrides,
                )
            except FileNotFoundError as fe:
                raise Unrecoverable(
                    f"state torn and no checkpoint to fall back to: {fe}"
                ) from e
            lost_steps = max((self._step - 1) - state.step, 0)
            self._rewind(state.step)
            kind = "checkpoint_fallback"
        if mig_before is not None:
            stats = self.migrator.stats
            delta = {k: stats[k] - mig_before[k] for k in mig_before}
            if delta.get("pieces") or delta.get("bytes") or \
                    delta.get("integrity_failures") or delta.get("retries"):
                extra.update({
                    "migrated_pieces": delta["pieces"],
                    "migrated_bytes": delta["bytes"],
                    "migration_resumed": delta["resumed"],
                    "migration_integrity_failures": delta["integrity_failures"],
                })
        self._adopt(state)
        self._pure = False
        recovery_ms = (time.perf_counter() - t0) * 1e3
        self._finish_recovery(kind, recovery_ms, width_before, lost_steps,
                              sorted(lost_ids), extra=extra)

    def _adopt(self, state) -> None:
        self.params, self.opt_state = state.params, state.opt_state
        self.mesh, self.spec = state.mesh, state.spec
        self._step_fn = self._get_step_fn(self.mesh, self.spec)
        self._registry.gauge(
            "controller_fleet_size", "devices in the controller's mesh"
        ).set(self.spec.n_devices)

    def _rewind(self, to_step: int) -> None:
        """Discard the lineage suffix past ``to_step`` (it will be redone):
        its walls move from productive to lost work, and the step counter
        returns to the step after the restored one."""
        discarded = [s for s in self._lineage_walls if s > to_step]
        lost_s = sum(self._lineage_walls.pop(s) for s in discarded)
        self._lost_work_s += lost_s
        self._redone_steps += len(discarded)
        self._goodput.add_productive(-lost_s)  # no longer useful work
        for s in discarded:
            self.losses.pop(s, None)
        self._step = to_step + 1

    def _finish_recovery(self, kind: str, recovery_ms: float,
                         width_before: int, lost_steps: int,
                         lost_ids: list, extra: dict | None = None) -> None:
        observe_recovery_ms(kind, recovery_ms)
        self._registry.counter(
            "controller_recoveries_total", "controller recovery actions",
            labels=("kind",),
        ).inc(kind=kind)
        # two DISTINCT counters (docs/OBSERVABILITY.md): lost = work the
        # FAILURE destroyed (fallback rewound past the last commit);
        # redone = work the replay grow-back deliberately discards for a
        # pure lineage. A grow_replay must not inflate the former.
        if lost_steps and kind == "checkpoint_fallback":
            self._registry.counter(
                "controller_lost_steps_total",
                "steps rewound to a checkpoint and replayed",
            ).inc(lost_steps)
        if lost_steps and kind == "grow_replay":
            self._registry.counter(
                "controller_redone_steps_total",
                "steps discarded by a replay grow-back and re-run",
            ).inc(lost_steps)
        self._goodput.mark("restore", kind=kind)
        # ledger watermark at the recovery boundary: the re-sharded state
        # was just re-placed — a postmortem's watermark timeline shows
        # whether a shrink doubled residency (the _place_state
        # double-allocation class) or came back to baseline
        from dsml_tpu.obs.memory import get_memory_ledger

        get_memory_ledger(self._registry).note_step_peak(
            self._step, label=f"recovery:{kind}")
        rec = {
            "kind": kind, "recovery_ms": round(recovery_ms, 3),
            "from_width": width_before, "to_width": self.spec.n_devices,
            "lost_steps": lost_steps, "lost_devices": lost_ids,
            "resume_step": self._step,
        }
        rec.update(extra or {})
        self.recoveries.append(rec)
        self._recorder.record(
            "controller_recovered",
            **{("recovery_kind" if k == "kind" else k): v for k, v in rec.items()},
        )
        log.warning(
            "recovered (%s) in %.0f ms: width %d -> %d, resume at step %d"
            "%s", kind, recovery_ms, width_before, self.spec.n_devices,
            self._step, f", {lost_steps} step(s) to replay" if lost_steps else "",
        )
        deadline_s = self.config.resolved_recovery_deadline_s()
        if recovery_ms > deadline_s * 1e3:
            log.error("recovery exceeded its %.0fs deadline (%.0f ms) — "
                      "dumping postmortem bundle", deadline_s, recovery_ms)
            try:
                self._recorder.dump("slow_recovery", extra=rec)
            except Exception:  # noqa: BLE001 — never mask the recovery
                pass

    def _maybe_grow(self, step: int) -> None:
        """At a checkpoint boundary, adopt restored capacity."""
        avail = [d for d in self.fleet.available()
                 if d.id not in self._quarantined]
        cur_ids = {d.id for d in self.mesh.devices.flat}
        fresh = [d for d in avail if d.id not in cur_ids]
        if not fresh or len(avail) <= self.spec.n_devices:
            return
        back_to_full = {d.id for d in avail} == self._full_ids
        if not back_to_full:
            # would the extra capacity actually be USED? a survivor count
            # whose plan instantiates no wider than today's (batch
            # divisibility idles the extras) must not trigger a state move
            # + recompile per boundary for a zero-chip gain
            plan, _ = elastic._plan_for_survivors(
                self.model, self._n_params, avail,
                self.config.batch_per_device, self.global_batch,
                self.planner_overrides,
            )
            if plan.spec.n_devices <= self.spec.n_devices:
                return
        t0 = time.perf_counter()
        width_before = self.spec.n_devices
        self._goodput.mark("grow", step=step, to=len(avail))
        kind = None
        redone = 0
        # replay grow-back is only meaningful back onto the FULL topology:
        # its whole point is a lineage indistinguishable from a never-failed
        # run, and a partial fleet can't produce full-width bits — partial
        # growth rides the keep path below instead
        if (self.config.growback == "replay" and not self._pure
                and back_to_full):
            # deterministic grow-back: flush + prune the mixed-width
            # lineage (a later fallback must not mix lineages), restore
            # the pure commit onto the full topology, and replay the
            # outage window at full width — the final params carry no
            # trace the outage ever happened. With no pure checkpoint on
            # disk (the failure beat the first save), the deterministic
            # INIT is the pure state at step 0: re-derive it and replay
            # everything.
            # barrier BEFORE the lineage query: an in-flight pure save
            # would otherwise be invisible to latest_step (it scans only
            # committed dirs), then deleted as "mixed" once it lands —
            # replaying the whole run for nothing
            self._ckpt.wait_until_finished()
            pure_step = self._ckpt.latest_step(
                where=lambda m: m.get("lineage") == "pure"
            ) or 0
            self._ckpt.delete_steps(
                [s for s in self._ckpt.all_steps() if s > pure_step]
            )
            t_params, t_opt = init_hybrid(
                self.model, self.optimizer, self._full_mesh, seed=self.seed,
            )
            if pure_step == 0:
                state = elastic.ElasticState(
                    params=t_params, opt_state=t_opt,
                    mesh=self._full_mesh, spec=self._full_spec,
                    reasons=("replay grow-back from the deterministic init "
                             "(no pure checkpoint survived the outage)",),
                    step=0,
                )
            else:
                restored = self._ckpt.restore(
                    pure_step,
                    template={"params": t_params, "opt_state": t_opt},
                    partial=True,
                )
                state = elastic.ElasticState(
                    params=restored["params"],
                    opt_state=restored["opt_state"],
                    mesh=self._full_mesh, spec=self._full_spec,
                    reasons=("replay grow-back onto the original mesh",),
                    step=pure_step,
                )
            redone = (self._step - 1) - pure_step
            self._rewind(pure_step)
            self._adopt(state)
            self._pure = True
            kind = "grow_replay"
        if kind is None:
            # keep mode: live survivor-width state re-shards onto the
            # restored fleet — zero recompute, lineage stays mixed-width
            if back_to_full:
                state = elastic.reshard_onto(
                    self.model, self.optimizer, self.params, self.opt_state,
                    self._full_mesh, self._full_spec,
                    migrator=self.migrator,
                    non_addressable=self.non_addressable,
                )
            else:
                state = elastic.reconfigure(
                    self.model, self.optimizer, self.params, self.opt_state,
                    surviving_devices=avail, lost_devices=(),
                    policy=self.policy,
                    batch_per_device=self.config.batch_per_device,
                    global_batch=self.global_batch,
                    planner_overrides=self.planner_overrides,
                    migrator=self.migrator,
                    non_addressable=self.non_addressable,
                )
            self._adopt(state)
            kind = "grow_keep"
        recovery_ms = (time.perf_counter() - t0) * 1e3
        self._finish_recovery(kind, recovery_ms, width_before, redone,
                              [d.id for d in fresh])


# ---------------------------------------------------------------------------
# Serving: decode-replica fleet with queue-depth autoscaling + chaos survival
# ---------------------------------------------------------------------------


class DecodeFleet:
    """Horizontal decode replicas behind one queue — the serving half of
    the chaos-survival story.

    ``make_replica()`` builds a ``serving.ContinuousBatcher`` (each replica
    owns its own slots/cache; on real hardware each would own a chip).
    Requests enter a fleet-level backlog and dispatch to the least-loaded
    replica each tick; autoscaling is QUEUE-DEPTH-DRIVEN:

    - scale UP: total waiting depth > ``scale_up_queue_depth`` × replicas
      and the fleet is below ``max_replicas``;
    - scale DOWN: a replica has been idle ``scale_down_idle_ticks``
      consecutive ticks and the fleet is above ``min_replicas``.

    :meth:`kill_replica` is the chaos hook: the dead replica's unfinished
    requests (queued, mid-admission, mid-decode) re-enter the backlog and
    re-run from their prompts on the survivors — with greedy decoding the
    retried tokens are identical, so a replica loss costs latency, never
    tokens (pinned in tests). Scale events land in
    ``serving_replica_scale_total{direction}`` /
    ``serving_replica_failures_total`` and the flight recorder."""

    def __init__(
        self,
        make_replica: Callable[[], object],
        min_replicas: int = 1,
        max_replicas: int = 4,
        scale_up_queue_depth: int = 4,
        scale_down_idle_ticks: int = 16,
        devices=None,
        devices_per_replica: int = 1,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas; got "
                f"{min_replicas}, {max_replicas}"
            )
        # device pool: with `devices` set, each replica SPANS
        # `devices_per_replica` chips — `make_replica(devices_tuple)` builds
        # it (serving.ContinuousBatcher.for_devices is the canonical
        # factory). A killed replica's chips return to the pool, so its
        # respawn — and the requeued work's failover onto survivors —
        # exercises the same multi-device state motion training recovery
        # does. Without `devices`, `make_replica()` keeps the historical
        # zero-arg contract.
        if devices_per_replica < 1:
            raise ValueError(
                f"devices_per_replica must be >= 1, got {devices_per_replica}"
            )
        self._device_pool: list | None = list(devices) if devices is not None else None
        self.devices_per_replica = devices_per_replica
        if self._device_pool is not None:
            capacity = len(self._device_pool) // devices_per_replica
            if capacity < min_replicas:
                raise ValueError(
                    f"{len(self._device_pool)} pooled device(s) cannot back "
                    f"min_replicas={min_replicas} at {devices_per_replica} "
                    "device(s) per replica"
                )
            max_replicas = min(max_replicas, capacity)
        self._replica_devices: dict[int, tuple] = {}
        self._make = make_replica
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_up_queue_depth = scale_up_queue_depth
        self.scale_down_idle_ticks = scale_down_idle_ticks
        self._obs = get_registry()
        self._replicas: dict[int, object] = {}
        self._idle_ticks: dict[int, int] = {}
        self._next_replica = 0
        self._next_frid = 0
        self._backlog: deque[int] = deque()
        self._spec: dict[int, tuple] = {}       # frid -> (prompt, max_new)
        self._local: dict[tuple, int] = {}      # (replica, local rid) -> frid
        self._placed: dict[int, tuple] = {}     # frid -> (replica, local rid)
        self._results: dict[int, list] = {}
        self.scale_events: list[dict] = []
        for _ in range(min_replicas):
            self._spawn("initial")

    # ---- capacity --------------------------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def _spawn(self, reason: str) -> int:
        rid = self._next_replica
        self._next_replica += 1
        if self._device_pool is not None:
            if len(self._device_pool) < self.devices_per_replica:
                self._next_replica -= 1
                raise RuntimeError(
                    f"device pool exhausted: {len(self._device_pool)} free, "
                    f"{self.devices_per_replica} needed per replica"
                )
            span = tuple(self._device_pool[: self.devices_per_replica])
            del self._device_pool[: self.devices_per_replica]
            self._replica_devices[rid] = span
            try:
                replica = self._replicas[rid] = self._make(span)
            except BaseException:
                # a failed factory must return its chips: nothing will ever
                # retire/kill this rid, so leaking here would permanently
                # shrink fleet capacity one replica-span per failure
                self._release_devices(rid)
                self._next_replica -= 1
                raise
        else:
            replica = self._replicas[rid] = self._make()
        # stamp the replica id into the batcher's serving metrics
        # (admissions / occupancy / queue depth / tokens / sheds) so the
        # cluster aggregator sees per-replica series, not one blended
        # stream; fleet ids are never reused, so a respawn is a NEW series
        if hasattr(replica, "obs_replica"):
            replica.obs_replica = str(rid)
        self._idle_ticks[rid] = 0
        self._note_scale("up", rid, reason)
        return rid

    def _release_devices(self, rid: int) -> None:
        span = self._replica_devices.pop(rid, None)
        if span is not None and self._device_pool is not None:
            self._device_pool.extend(span)

    def _retire(self, rid: int, reason: str) -> None:
        self._replicas.pop(rid)
        self._idle_ticks.pop(rid, None)
        self._release_devices(rid)
        self._note_scale("down", rid, reason)

    def _note_scale(self, direction: str, rid: int, reason: str) -> None:
        self.scale_events.append(
            {"direction": direction, "replica": rid, "reason": reason,
             "n_replicas": len(self._replicas)}
        )
        if self._obs.enabled:
            self._obs.counter(
                "serving_replica_scale_total", "decode replica scale events",
                labels=("direction",),
            ).inc(direction=direction)
            self._obs.gauge(
                "serving_replicas", "live decode replicas",
            ).set(len(self._replicas))
            flight_recorder.record(
                "serving_scale", direction=direction, replica=rid,
                reason=reason, n_replicas=len(self._replicas),
            )

    def kill_replica(self, rid: int | None = None) -> int:
        """Chaos hook: drop a replica (default: the newest). Finished-but-
        uncollected results are harvested first; everything unfinished
        re-enters the backlog at the FRONT (it has waited longest)."""
        if not self._replicas:
            raise RuntimeError("no replicas to kill")
        if rid is None:
            rid = max(self._replicas)
        replica = self._replicas.pop(rid)
        self._idle_ticks.pop(rid, None)
        self._release_devices(rid)
        self._harvest(rid, replica.collect())
        requeued = 0
        for req in reversed(replica.abandon()):
            frid = self._local.pop((rid, req.rid), None)
            if frid is None:
                continue
            self._placed.pop(frid, None)
            self._backlog.appendleft(frid)
            requeued += 1
        if self._obs.enabled:
            self._obs.counter(
                "serving_replica_failures_total", "decode replicas lost",
            ).inc()
            self._obs.counter(
                "serving_requeued_total",
                "requests resubmitted after a replica loss",
            ).inc(requeued)
            self._obs.gauge(
                "serving_replicas", "live decode replicas",
            ).set(len(self._replicas))
            flight_recorder.record(
                "serving_replica_lost", replica=rid, requeued=requeued,
                n_replicas=len(self._replicas),
            )
        self.scale_events.append(
            {"direction": "down", "replica": rid, "reason": "killed",
             "n_replicas": len(self._replicas), "requeued": requeued}
        )
        if not self._replicas and (self._backlog or self._placed):
            # zero capacity with work outstanding: re-arm the minimum fleet
            # now rather than waiting for a tick (the grow-back half)
            for _ in range(self.min_replicas):
                self._spawn("respawn_after_total_loss")
        return requeued

    # ---- requests --------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int) -> int:
        frid = self._next_frid
        self._next_frid += 1
        self._spec[frid] = (np.asarray(prompt, np.int32).reshape(-1),
                            int(max_new_tokens))
        self._backlog.append(frid)
        return frid

    @property
    def outstanding(self) -> int:
        return len(self._backlog) + len(self._placed)

    def queue_depth(self) -> int:
        return len(self._backlog) + sum(
            b.n_queued for b in self._replicas.values()
        )

    def _load(self, replica) -> int:
        return replica.n_queued + replica.n_active + replica.n_pending

    def _harvest(self, rid: int, collected: dict) -> None:
        for lrid, toks in collected.items():
            frid = self._local.pop((rid, lrid), None)
            if frid is not None:
                self._placed.pop(frid, None)
                # the spec (prompt array) exists for requeue-on-failure;
                # once the result is in, keeping it would leak one prompt
                # per lifetime request in a long-lived fleet
                self._spec.pop(frid, None)
                self._results[frid] = toks

    def tick(self) -> None:
        """One fleet scheduler pass: dispatch → autoscale → step replicas
        → harvest."""
        from dsml_tpu.serving import QueueFull

        # dispatch backlog to the least-loaded replica with headroom; a
        # replica at its max_queue cap is only excluded for THIS tick —
        # another replica with room must still receive work (one full
        # queue must not stall the whole backlog)
        capped: set = set()
        while self._backlog and self._replicas:
            open_replicas = [(r, b) for r, b in self._replicas.items()
                             if r not in capped]
            if not open_replicas:
                break
            rid, replica = min(open_replicas, key=lambda kv: self._load(kv[1]))
            if self._load(replica) >= 2 * replica.n_slots:
                break  # the least-loaded is saturated → everyone open is
            frid = self._backlog.popleft()
            prompt, max_new = self._spec[frid]
            try:
                lrid = replica.submit(prompt, max_new)
            except QueueFull:
                self._backlog.appendleft(frid)
                capped.add(rid)
                continue
            self._local[(rid, lrid)] = frid
            self._placed[frid] = (rid, lrid)
        # queue-depth-driven scale-up (one replica per tick)
        if (
            len(self._replicas) < self.max_replicas
            and self.queue_depth()
            > self.scale_up_queue_depth * max(len(self._replicas), 1)
        ):
            self._spawn("queue_depth")
        # drive every replica and harvest retirements
        for rid, replica in list(self._replicas.items()):
            busy = (replica.n_active or replica.n_queued
                    or replica.n_pending)
            if busy:
                self._idle_ticks[rid] = 0
                replica.step()
                self._harvest(rid, replica.collect())
            else:
                self._idle_ticks[rid] += 1
        # idle scale-down (one per tick, never below the floor)
        if len(self._replicas) > self.min_replicas:
            idle = [r for r, t in self._idle_ticks.items()
                    if t >= self.scale_down_idle_ticks]
            if idle:
                self._retire(max(idle), "idle")

    def run(self, max_ticks: int = 100_000) -> dict[int, list]:
        """Drain everything; returns {fleet rid: [tokens]}."""
        for _ in range(max_ticks):
            if not self.outstanding:
                break
            self.tick()
        else:
            raise RuntimeError(f"fleet did not drain within {max_ticks} ticks")
        out = dict(self._results)
        self._results.clear()
        return out
