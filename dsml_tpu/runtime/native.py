"""ctypes bindings for libdsml_runtime.so (see native/dsml_runtime.cc).

The library auto-builds on first import when a compiler is present
(``make -C dsml_tpu/runtime/native``); every consumer has a pure-Python/numpy
fallback, so :func:`available` gates usage rather than imports failing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from dsml_tpu.utils.logging import get_logger

log = get_logger("native")

_DIR = os.path.join(os.path.dirname(__file__), "native")
_SO = os.path.join(_DIR, "libdsml_runtime.so")
_lib = None
_lock = threading.Lock()

DS_OK = 0
DS_IN_PROGRESS = 5


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        # incremental make BEFORE the first dlopen: a no-op when the .so is
        # fresh, a relink when the source is newer. Rebuild-then-reload
        # inside one process cannot work (ctypes caches the mapping by
        # path and never dlcloses), so a stale library must never be
        # loaded in the first place.
        built = True
        try:
            subprocess.run(
                ["make", "-C", _DIR], check=True, capture_output=True, timeout=120
            )
        except (subprocess.SubprocessError, FileNotFoundError) as e:
            built = False
            if not os.path.exists(_SO):
                log.warning("native runtime build failed (%s); using Python fallbacks", e)
                _lib = False
                return False
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warning("native runtime load failed (%s); using Python fallbacks", e)
            _lib = False
            return False
        if not hasattr(lib, "ds_crc32c"):
            # only reachable when make was unavailable and an old .so was
            # the best we had — degrade for this process; the next process
            # with a toolchain rebuilds
            log.warning(
                "native runtime .so is stale%s; using Python fallbacks",
                "" if built else " and no compiler is available",
            )
            _lib = False
            return False
        lib.ds_arena_new.restype = ctypes.c_void_p
        lib.ds_arena_new.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.ds_arena_free.argtypes = [ctypes.c_void_p]
        lib.ds_arena_write.restype = ctypes.c_int32
        lib.ds_arena_write.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64]
        lib.ds_arena_read.restype = ctypes.c_int64
        lib.ds_arena_read.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64]
        lib.ds_arena_logical_size.restype = ctypes.c_int64
        lib.ds_arena_logical_size.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ds_streams_new.restype = ctypes.c_void_p
        lib.ds_streams_free.argtypes = [ctypes.c_void_p]
        lib.ds_stream_arm.restype = ctypes.c_int32
        lib.ds_stream_arm.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
        lib.ds_stream_push.restype = ctypes.c_int32
        lib.ds_stream_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int32]
        lib.ds_stream_status.restype = ctypes.c_int32
        lib.ds_stream_status.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ds_ring_plan.restype = ctypes.c_int32
        lib.ds_ring_plan.argtypes = [ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p]
        lib.ds_reduce_f32.restype = ctypes.c_int32
        lib.ds_reduce_f32.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p]
        lib.ds_idx_parse.restype = ctypes.c_int64
        lib.ds_idx_parse.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p]
        lib.ds_prefetch_new.restype = ctypes.c_void_p
        lib.ds_prefetch_new.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.ds_prefetch_next.restype = ctypes.c_int64
        lib.ds_prefetch_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.ds_prefetch_free.argtypes = [ctypes.c_void_p]
        lib.ds_crc32c.restype = ctypes.c_uint32
        lib.ds_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
        _lib = lib
        return lib


def available() -> bool:
    return bool(_load())


class NativeArena:
    """Bounds-checked flat-address host buffer registry (C++)."""

    def __init__(self, min_addr: int, size: int):
        lib = _load()
        if not lib:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._ptr = lib.ds_arena_new(min_addr, size)

    def write(self, addr: int, data: bytes) -> int:
        return self._lib.ds_arena_write(self._ptr, addr, data, len(data))

    def read(self, addr: int, n: int | None = None) -> bytes:
        if n is None:
            n = self._lib.ds_arena_read(self._ptr, addr, None, 0)
            if n < 0:
                raise KeyError(f"arena read failed: status {-n}")
        out = ctypes.create_string_buffer(n)
        rc = self._lib.ds_arena_read(self._ptr, addr, out, n)
        if rc < 0:
            raise KeyError(f"arena read failed: status {-rc}")
        return out.raw[:rc]

    def logical_size(self, addr: int) -> int:
        n = self._lib.ds_arena_logical_size(self._ptr, addr)
        if n < 0:
            raise KeyError(f"no buffer at {addr:#x}")
        return n

    def __del__(self):
        if getattr(self, "_ptr", None):
            self._lib.ds_arena_free(self._ptr)
            self._ptr = None


class NativeStreams:
    """Chunked-stream reassembly engine writing into a NativeArena."""

    def __init__(self, arena: NativeArena):
        self._lib = arena._lib
        self._arena = arena
        self._ptr = self._lib.ds_streams_new()

    def arm(self, stream_id: int, recv_addr: int, expected: int) -> int:
        return self._lib.ds_stream_arm(self._ptr, self._arena._ptr, stream_id, recv_addr, expected)

    def push(self, stream_id: int, chunk: bytes, final: bool = False) -> int:
        return self._lib.ds_stream_push(self._ptr, self._arena._ptr, stream_id, chunk, len(chunk), int(final))

    def status(self, stream_id: int) -> int:
        return self._lib.ds_stream_status(self._ptr, stream_id)

    def __del__(self):
        if getattr(self, "_ptr", None):
            self._lib.ds_streams_free(self._ptr)
            self._ptr = None


def ring_plan(n: int, rank: int) -> tuple[np.ndarray, np.ndarray]:
    """The 2(n-1)-step ring segment schedule for ``rank`` (C++ planner)."""
    lib = _load()
    steps = 2 * (n - 1)
    send = np.zeros(steps, np.int32)
    recv = np.zeros(steps, np.int32)
    if lib:
        rc = lib.ds_ring_plan(n, rank,
                              send.ctypes.data_as(ctypes.c_void_p),
                              recv.ctypes.data_as(ctypes.c_void_p))
        if rc != DS_OK:
            raise ValueError(f"ring_plan({n}, {rank}) failed: {rc}")
        return send, recv
    for step in range(n - 1):  # Python fallback
        send[step] = (rank - step) % n
        recv[step] = (rank - step - 1) % n
        send[n - 1 + step] = (rank - step + 1) % n
        recv[n - 1 + step] = (rank - step) % n
    return send, recv


def reduce_f32(rows: np.ndarray, op: int) -> np.ndarray:
    """Reduce [n_rows, n] float32 rows elementwise with the C++ kernel
    (numpy fallback when the library is unavailable)."""
    rows = np.ascontiguousarray(rows, np.float32)
    lib = _load()
    if lib:
        out = np.empty(rows.shape[1], np.float32)
        rc = lib.ds_reduce_f32(rows.ctypes.data_as(ctypes.c_void_p), rows.shape[0], rows.shape[1],
                               int(op), out.ctypes.data_as(ctypes.c_void_p))
        if rc == DS_OK:
            return out
    combine = {0: np.add.reduce, 1: np.multiply.reduce, 2: np.minimum.reduce,
               3: np.maximum.reduce, 4: lambda a: np.add.reduce(a) / a.shape[0]}[int(op)]
    return combine(rows).astype(np.float32)


_CRC32C_TABLE: list | None = None


def _crc32c_table() -> list:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            tbl.append(c)
        _CRC32C_TABLE = tbl
    return _CRC32C_TABLE


def _crc32c_py(data: bytes, crc: int = 0) -> int:
    """Pure-Python CRC32C (Castagnoli) — the bit-identical fallback for
    :func:`crc32c` when the native library is unavailable."""
    tbl = _crc32c_table()
    c = ~crc & 0xFFFFFFFF
    for b in data:
        c = tbl[(c ^ b) & 0xFF] ^ (c >> 8)
    return ~c & 0xFFFFFFFF


def crc32c(data, crc: int = 0) -> int:
    """CRC32C (Castagnoli) of ``data``; pass the previous return value as
    ``crc`` to roll the checksum across chunks. The frame checksum of the
    P2P shard-migration path (``comm.migration``): the C kernel when the
    library is built, the table-driven Python fallback otherwise."""
    data = bytes(data) if not isinstance(data, (bytes, bytearray)) else data
    lib = _load()
    if lib:
        return int(lib.ds_crc32c(bytes(data), len(data), crc))
    return _crc32c_py(bytes(data), crc)


class NativePrefetcher:
    """Background-thread batch pipeline over the C++ loader: a producer
    thread gathers each batch's rows from ``dataset`` into a ring of
    ``depth`` slots while the consumer is inside its device step, so the
    host-side gather/copy overlaps device compute (the double-buffering a
    real input pipeline provides — the reference's loader is a synchronous
    loop, ``client.go:579-653``).

    Iterate to receive ``[batch, *row_shape]`` arrays in index order:

        for xb in NativePrefetcher(train_x, perm_indices):
            step(params, jnp.asarray(xb))

    ``indices`` is [n_batches, batch] int32 row ids (an epoch's
    permutation reshaped). The dataset and index arrays are BORROWED by
    the C++ thread — the prefetcher keeps references so they outlive it.
    """

    def __init__(self, dataset: np.ndarray, indices: np.ndarray, depth: int = 2):
        lib = _load()
        if not lib:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        # keep the borrowed buffers alive for the producer thread
        self._data = np.ascontiguousarray(dataset)
        self._idx = np.ascontiguousarray(indices, np.int32)
        if self._idx.ndim != 2:
            raise ValueError(f"indices must be [n_batches, batch], got {self._idx.shape}")
        self._row_shape = self._data.shape[1:]
        self._row_bytes = int(np.prod(self._row_shape, dtype=np.int64)) * self._data.dtype.itemsize
        if not 1 <= int(depth) <= 1024:
            raise ValueError(f"depth must be in [1, 1024], got {depth}")
        self.n_batches, self.batch = map(int, self._idx.shape)
        self._consumed = False
        self._ptr = lib.ds_prefetch_new(
            self._data.ctypes.data_as(ctypes.c_void_p), self._data.shape[0],
            self._row_bytes,
            self._idx.ctypes.data_as(ctypes.c_void_p), self.n_batches,
            self.batch, int(depth),
        )
        if not self._ptr:
            raise ValueError("bad prefetcher arguments (zero batch/depth/row)")

    def __iter__(self):
        # the C++ ring drains once; a second epoch silently yielding zero
        # batches would halve a training run with no signal — be loud
        if self._consumed:
            raise RuntimeError(
                "NativePrefetcher is single-use: construct a new one per "
                "epoch (each carries its own permutation indices anyway)"
            )
        self._consumed = True
        while True:
            # a fresh array per batch: ds_prefetch_next's memcpy is the ONE
            # consumer-side copy, and the caller owns the result outright
            out = np.empty((self.batch, *self._row_shape), self._data.dtype)
            rc = self._lib.ds_prefetch_next(
                self._ptr, out.ctypes.data_as(ctypes.c_void_p)
            )
            if rc == -1:
                return
            if rc < 0:
                raise IndexError("prefetcher row index out of range")
            yield out

    def __del__(self):
        if getattr(self, "_ptr", None):
            self._lib.ds_prefetch_free(self._ptr)
            self._ptr = None


def idx_parse(blob: bytes) -> tuple[np.ndarray, tuple[int, ...]]:
    """Parse an un-gzipped IDX blob via the C++ parser; returns
    (uint8 payload array, dims)."""
    lib = _load()
    if lib:
        dims = np.zeros(3, np.int32)
        off = lib.ds_idx_parse(blob, len(blob), dims.ctypes.data_as(ctypes.c_void_p))
        if off < 0:
            raise ValueError(f"invalid IDX blob: status {-off}")
        shape = tuple(int(d) for d in dims if d > 0)
        data = np.frombuffer(blob, np.uint8, count=int(np.prod(shape)), offset=int(off))
        return data.reshape(shape), shape
    raise RuntimeError("native runtime unavailable")
