"""Native (C++) host runtime bindings."""

from dsml_tpu.runtime.native import (  # noqa: F401
    NativeArena,
    NativeStreams,
    available,
    idx_parse,
    reduce_f32,
    ring_plan,
)
