// dsml native host runtime.
//
// The compiled-language systems layer of the framework (the reference's
// equivalent layer is Go: device memory map + stream state machine in
// DSML/gpu_device_service/gpu_device_server.go, byte-wise reduction in
// DSML/gpu_coordinator_service/gpu_coordinator_server.go:540-543,681-686,
// ring schedule in :379-419, IDX parsing in DSML/client/client.go:270-350).
// TPU compute stays in XLA; this library owns the host-side runtime pieces:
//
//   * arena        — bounds-checked flat-address buffer registry with the
//                    framework's splice/logical-size semantics (host staging
//                    for the gRPC data plane).
//   * stream       — chunked P2P reassembly + length validation state machine.
//   * ring planner — the 2(n-1)-step scatter-reduce/all-gather segment
//                    schedule (send/recv indices per rank per step).
//   * reduce       — dtype-aware elementwise reductions (SUM/PROD/MIN/MAX/AVG)
//                    for the coordinator's cross-host fallback path.
//   * idx parser   — IDX (MNIST) header/payload decoding.
//   * prefetch     — background-thread batch gather into a slot ring (the
//                    double-buffered input pipeline; host copy overlaps the
//                    device step).
//
// C ABI throughout; Python binds via ctypes (dsml_tpu/runtime/native.py).
// Build: make -C dsml_tpu/runtime/native   ->  libdsml_runtime.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// error codes (mirror the gRPC codes the Python layer maps them to)
// ---------------------------------------------------------------------------
enum DsStatus : int32_t {
  DS_OK = 0,
  DS_OUT_OF_RANGE = 1,
  DS_NOT_FOUND = 2,
  DS_INVALID = 3,
  DS_FAILED = 4,
  DS_IN_PROGRESS = 5,
};

// ---------------------------------------------------------------------------
// arena
// ---------------------------------------------------------------------------

struct DsBuffer {
  std::vector<uint8_t> data;
  uint64_t logical = 0;  // bytes of the most recent write
};

struct DsArena {
  uint64_t min_addr;
  uint64_t max_addr;
  std::map<uint64_t, DsBuffer> buffers;
  std::mutex mu;
};

void* ds_arena_new(uint64_t min_addr, uint64_t size) {
  auto* a = new DsArena();
  a->min_addr = min_addr;
  a->max_addr = min_addr + size;
  return a;
}

void ds_arena_free(void* arena) { delete static_cast<DsArena*>(arena); }

int32_t ds_arena_write(void* arena, uint64_t addr, const uint8_t* data, uint64_t len) {
  auto* a = static_cast<DsArena*>(arena);
  // `addr + len` could wrap uint64 for a corrupt wire address; compare the
  // remaining window instead
  if (addr < a->min_addr || addr > a->max_addr || len > a->max_addr - addr)
    return DS_OUT_OF_RANGE;
  std::lock_guard<std::mutex> lock(a->mu);
  DsBuffer& buf = a->buffers[addr];
  if (buf.data.size() > len) {
    // splice: shorter write lands in the prefix, tail survives
    std::memcpy(buf.data.data(), data, len);
  } else {
    buf.data.assign(data, data + len);
  }
  buf.logical = len;
  return DS_OK;
}

int64_t ds_arena_read(void* arena, uint64_t addr, uint8_t* out, uint64_t len) {
  // returns bytes copied, or -status on error; len==0 => full buffer size query
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->buffers.find(addr);
  if (it == a->buffers.end()) return -DS_NOT_FOUND;
  const DsBuffer& buf = it->second;
  if (len == 0) return static_cast<int64_t>(buf.data.size());
  if (len > buf.data.size()) return -DS_OUT_OF_RANGE;
  std::memcpy(out, buf.data.data(), len);
  return static_cast<int64_t>(len);
}

int64_t ds_arena_logical_size(void* arena, uint64_t addr) {
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->buffers.find(addr);
  if (it == a->buffers.end()) return -DS_NOT_FOUND;
  return static_cast<int64_t>(it->second.logical);
}

// ---------------------------------------------------------------------------
// stream reassembly
// ---------------------------------------------------------------------------

struct DsStream {
  std::vector<uint8_t> chunks;
  uint64_t expected = 0;
  uint64_t recv_addr = 0;
  bool armed = false;
  int32_t status = DS_IN_PROGRESS;
};

struct DsStreamEngine {
  std::map<uint64_t, DsStream> streams;
  std::mutex mu;
};

void* ds_streams_new() { return new DsStreamEngine(); }
void ds_streams_free(void* eng) { delete static_cast<DsStreamEngine*>(eng); }

static void ds_stream_try_complete(DsArena* arena, DsStream& st) {
  if (!st.armed) return;
  if (st.chunks.size() == st.expected && st.expected > 0) {
    int32_t rc = ds_arena_write(arena, st.recv_addr, st.chunks.data(), st.chunks.size());
    st.status = (rc == DS_OK) ? DS_OK : DS_FAILED;
    st.chunks.clear();
    st.chunks.shrink_to_fit();
  } else if (st.chunks.size() > st.expected) {
    st.status = DS_FAILED;
  }
}

int32_t ds_stream_arm(void* eng, void* arena, uint64_t stream_id, uint64_t recv_addr,
                      uint64_t expected) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  auto* a = static_cast<DsArena*>(arena);
  if (recv_addr < a->min_addr || recv_addr > a->max_addr || expected > a->max_addr - recv_addr)
    return DS_OUT_OF_RANGE;
  std::lock_guard<std::mutex> lock(e->mu);
  DsStream& st = e->streams[stream_id];
  st.recv_addr = recv_addr;
  st.expected = expected;
  st.armed = true;
  ds_stream_try_complete(a, st);
  return DS_OK;
}

int32_t ds_stream_push(void* eng, void* arena, uint64_t stream_id, const uint8_t* chunk,
                       uint64_t len, int32_t final_chunk) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(e->mu);
  DsStream& st = e->streams[stream_id];
  st.chunks.insert(st.chunks.end(), chunk, chunk + len);
  ds_stream_try_complete(a, st);
  if (final_chunk && st.armed && st.status == DS_IN_PROGRESS) st.status = DS_FAILED;
  return st.status == DS_FAILED ? DS_FAILED : DS_OK;
}

int32_t ds_stream_status(void* eng, uint64_t stream_id) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  std::lock_guard<std::mutex> lock(e->mu);
  auto it = e->streams.find(stream_id);
  if (it == e->streams.end()) return -DS_NOT_FOUND;
  return it->second.status;
}

// ---------------------------------------------------------------------------
// ring schedule planner (gpu_coordinator_server.go:393-419 semantics)
// ---------------------------------------------------------------------------

// Fills send_idx/recv_idx, each [2*(n-1)] entries for `rank`: first n-1
// scatter-reduce steps, then n-1 all-gather steps.
int32_t ds_ring_plan(int32_t n, int32_t rank, int32_t* send_idx, int32_t* recv_idx) {
  if (n < 2 || rank < 0 || rank >= n) return DS_INVALID;
  auto mod = [n](int32_t v) { return ((v % n) + n) % n; };
  for (int32_t step = 0; step < n - 1; ++step) {
    send_idx[step] = mod(rank - step);
    recv_idx[step] = mod(rank - step - 1);
  }
  for (int32_t step = 0; step < n - 1; ++step) {
    send_idx[n - 1 + step] = mod(rank - step + 1);
    recv_idx[n - 1 + step] = mod(rank - step);
  }
  return DS_OK;
}

// ---------------------------------------------------------------------------
// dtype-aware reduction (coordinator host fallback path)
// ---------------------------------------------------------------------------

enum DsOp : int32_t { DS_SUM = 0, DS_PROD = 1, DS_MIN = 2, DS_MAX = 3, DS_AVG = 4 };

// rows: n_rows contiguous f32 rows of n elems each; out: n elems
int32_t ds_reduce_f32(const float* rows, int64_t n_rows, int64_t n, int32_t op, float* out) {
  if (n_rows < 1) return DS_INVALID;
  std::memcpy(out, rows, n * sizeof(float));
  for (int64_t r = 1; r < n_rows; ++r) {
    const float* row = rows + r * n;
    switch (op) {
      case DS_SUM:
      case DS_AVG:
        for (int64_t i = 0; i < n; ++i) out[i] += row[i];
        break;
      case DS_PROD:
        for (int64_t i = 0; i < n; ++i) out[i] *= row[i];
        break;
      case DS_MIN:
        for (int64_t i = 0; i < n; ++i) out[i] = row[i] < out[i] ? row[i] : out[i];
        break;
      case DS_MAX:
        for (int64_t i = 0; i < n; ++i) out[i] = row[i] > out[i] ? row[i] : out[i];
        break;
      default:
        return DS_INVALID;
    }
  }
  if (op == DS_AVG) {
    const float inv = 1.0f / static_cast<float>(n_rows);
    for (int64_t i = 0; i < n; ++i) out[i] *= inv;
  }
  return DS_OK;
}

// ---------------------------------------------------------------------------
// IDX (MNIST) parsing
// ---------------------------------------------------------------------------

static uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) | (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// Parses an (un-gzipped) IDX blob. dims_out must hold 3 entries:
// images -> [count, rows, cols]; labels -> [count, 0, 0].
// Returns the payload byte offset, or -status.
int64_t ds_idx_parse(const uint8_t* buf, uint64_t len, int32_t* dims_out) {
  if (len < 8) return -DS_INVALID;
  uint32_t magic = be32(buf);
  if (magic == 2051) {  // images
    if (len < 16) return -DS_INVALID;
    dims_out[0] = static_cast<int32_t>(be32(buf + 4));
    dims_out[1] = static_cast<int32_t>(be32(buf + 8));
    dims_out[2] = static_cast<int32_t>(be32(buf + 12));
    uint64_t need = 16ull + uint64_t(dims_out[0]) * dims_out[1] * dims_out[2];
    if (len < need) return -DS_INVALID;
    return 16;
  }
  if (magic == 2049) {  // labels
    dims_out[0] = static_cast<int32_t>(be32(buf + 4));
    dims_out[1] = 0;
    dims_out[2] = 0;
    if (len < 8ull + uint64_t(dims_out[0])) return -DS_INVALID;
    return 8;
  }
  return -DS_INVALID;
}

// ---------------------------------------------------------------------------
// prefetching batch loader
// ---------------------------------------------------------------------------
// Background producer thread gathers batch rows from a borrowed dataset
// blob into a ring of `depth` slots while the consumer (the training loop)
// is inside its device step — the host-side gather/copy overlaps device
// compute instead of serializing with it (the double-buffered input
// pipeline a real data loader provides; the reference's loader is a
// synchronous Go loop, client.go:270-350 + :579-653).

struct DsPrefetch {
  const uint8_t* data;    // borrowed — caller keeps the dataset alive
  uint64_t n_rows = 0, row_bytes = 0;
  const int32_t* idx;     // borrowed [n_batches * batch] row indices
  uint64_t n_batches = 0, batch = 0, depth = 0;
  std::vector<std::vector<uint8_t>> slots;
  uint64_t head = 0;      // next batch the producer fills
  uint64_t tail = 0;      // next batch the consumer takes
  std::atomic<bool> stop{false};
  int32_t error = DS_OK;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::thread worker;
};

static void ds_prefetch_run(DsPrefetch* p) {
  for (;;) {
    std::unique_lock<std::mutex> lock(p->mu);
    p->cv_prod.wait(lock, [p] {
      return p->stop.load() || p->head - p->tail < p->depth;
    });
    if (p->stop.load() || p->head >= p->n_batches || p->error != DS_OK) return;
    uint64_t b = p->head;
    lock.unlock();  // gather outside the lock: the consumer may drain slots

    std::vector<uint8_t>& slot = p->slots[b % p->depth];
    int32_t err = DS_OK;
    for (uint64_t j = 0; j < p->batch; ++j) {
      int64_t row = p->idx[b * p->batch + j];
      if (row < 0 || uint64_t(row) >= p->n_rows) {
        err = DS_OUT_OF_RANGE;
        break;
      }
      std::memcpy(slot.data() + j * p->row_bytes,
                  p->data + uint64_t(row) * p->row_bytes, p->row_bytes);
    }

    lock.lock();
    if (err != DS_OK) {
      p->error = err;
      p->cv_cons.notify_all();
      return;
    }
    p->head = b + 1;
    bool done = p->head >= p->n_batches;
    p->cv_cons.notify_all();
    if (done) return;
  }
}

void* ds_prefetch_new(const uint8_t* data, uint64_t n_rows, uint64_t row_bytes,
                      const int32_t* idx, uint64_t n_batches, uint64_t batch,
                      uint64_t depth) {
  // depth is a small ring (2-4 in practice); a huge value — e.g. Python's
  // -1 wrapped through uint64 — would make the slot allocation throw
  // bad_alloc straight through the C ABI and abort the process
  if (depth == 0 || depth > 1024 || batch == 0 || row_bytes == 0) return nullptr;
  auto* p = new DsPrefetch();
  p->data = data;
  p->n_rows = n_rows;
  p->row_bytes = row_bytes;
  p->idx = idx;
  p->n_batches = n_batches;
  p->batch = batch;
  p->depth = depth;
  p->slots.assign(depth, std::vector<uint8_t>(batch * row_bytes));
  p->worker = std::thread(ds_prefetch_run, p);
  return p;
}

// Blocks until the next batch is ready and copies it into `out`
// ([batch * row_bytes] bytes). Returns the batch index, -1 once all
// batches were delivered, or -2 on a producer error (bad row index).
int64_t ds_prefetch_next(void* handle, uint8_t* out) {
  auto* p = static_cast<DsPrefetch*>(handle);
  std::unique_lock<std::mutex> lock(p->mu);
  p->cv_cons.wait(lock, [p] {
    return p->tail < p->head || p->error != DS_OK || p->tail >= p->n_batches;
  });
  // drain already-produced batches BEFORE surfacing a producer error, so
  // delivery up to the bad batch is deterministic regardless of how far
  // ahead the producer ran
  if (p->tail >= p->n_batches) return -1;
  if (p->tail >= p->head && p->error != DS_OK) return -2;
  uint64_t b = p->tail;
  std::memcpy(out, p->slots[b % p->depth].data(), p->batch * p->row_bytes);
  p->tail = b + 1;
  p->cv_prod.notify_one();
  return static_cast<int64_t>(b);
}

void ds_prefetch_free(void* handle) {
  auto* p = static_cast<DsPrefetch*>(handle);
  {
    std::lock_guard<std::mutex> lock(p->mu);
    p->stop.store(true);
  }
  p->cv_prod.notify_all();
  p->cv_cons.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

// ---------------------------------------------------------------------------
// crc32c — Castagnoli CRC (poly 0x1EDC6F41, reflected 0x82F63B78), the
// frame checksum of the P2P shard-migration path (comm/migration.py).
// Table-driven byte-at-a-time: sequential-dependency CRCs cannot be
// vectorized in numpy, so the hot loop lives here; the Python fallback in
// runtime/native.py is bit-identical but ~100x slower.
// ---------------------------------------------------------------------------

static uint32_t g_crc32c_tbl[256];
static std::once_flag g_crc32c_once;

static void ds_crc32c_build_table() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1u) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
    g_crc32c_tbl[i] = c;
  }
}

// Rolling API: pass the previous return value as `crc` to extend a running
// checksum across chunks (start with 0); one-shot callers pass crc=0.
uint32_t ds_crc32c(const uint8_t* data, uint64_t n, uint32_t crc) {
  std::call_once(g_crc32c_once, ds_crc32c_build_table);
  crc = ~crc;
  for (uint64_t i = 0; i < n; ++i)
    crc = g_crc32c_tbl[(crc ^ data[i]) & 0xffu] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
