// dsml native host runtime.
//
// The compiled-language systems layer of the framework (the reference's
// equivalent layer is Go: device memory map + stream state machine in
// DSML/gpu_device_service/gpu_device_server.go, byte-wise reduction in
// DSML/gpu_coordinator_service/gpu_coordinator_server.go:540-543,681-686,
// ring schedule in :379-419, IDX parsing in DSML/client/client.go:270-350).
// TPU compute stays in XLA; this library owns the host-side runtime pieces:
//
//   * arena        — bounds-checked flat-address buffer registry with the
//                    framework's splice/logical-size semantics (host staging
//                    for the gRPC data plane).
//   * stream       — chunked P2P reassembly + length validation state machine.
//   * ring planner — the 2(n-1)-step scatter-reduce/all-gather segment
//                    schedule (send/recv indices per rank per step).
//   * reduce       — dtype-aware elementwise reductions (SUM/PROD/MIN/MAX/AVG)
//                    for the coordinator's cross-host fallback path.
//   * idx parser   — IDX (MNIST) header/payload decoding.
//
// C ABI throughout; Python binds via ctypes (dsml_tpu/runtime/native.py).
// Build: make -C dsml_tpu/runtime/native   ->  libdsml_runtime.so

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// error codes (mirror the gRPC codes the Python layer maps them to)
// ---------------------------------------------------------------------------
enum DsStatus : int32_t {
  DS_OK = 0,
  DS_OUT_OF_RANGE = 1,
  DS_NOT_FOUND = 2,
  DS_INVALID = 3,
  DS_FAILED = 4,
  DS_IN_PROGRESS = 5,
};

// ---------------------------------------------------------------------------
// arena
// ---------------------------------------------------------------------------

struct DsBuffer {
  std::vector<uint8_t> data;
  uint64_t logical = 0;  // bytes of the most recent write
};

struct DsArena {
  uint64_t min_addr;
  uint64_t max_addr;
  std::map<uint64_t, DsBuffer> buffers;
  std::mutex mu;
};

void* ds_arena_new(uint64_t min_addr, uint64_t size) {
  auto* a = new DsArena();
  a->min_addr = min_addr;
  a->max_addr = min_addr + size;
  return a;
}

void ds_arena_free(void* arena) { delete static_cast<DsArena*>(arena); }

int32_t ds_arena_write(void* arena, uint64_t addr, const uint8_t* data, uint64_t len) {
  auto* a = static_cast<DsArena*>(arena);
  // `addr + len` could wrap uint64 for a corrupt wire address; compare the
  // remaining window instead
  if (addr < a->min_addr || addr > a->max_addr || len > a->max_addr - addr)
    return DS_OUT_OF_RANGE;
  std::lock_guard<std::mutex> lock(a->mu);
  DsBuffer& buf = a->buffers[addr];
  if (buf.data.size() > len) {
    // splice: shorter write lands in the prefix, tail survives
    std::memcpy(buf.data.data(), data, len);
  } else {
    buf.data.assign(data, data + len);
  }
  buf.logical = len;
  return DS_OK;
}

int64_t ds_arena_read(void* arena, uint64_t addr, uint8_t* out, uint64_t len) {
  // returns bytes copied, or -status on error; len==0 => full buffer size query
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->buffers.find(addr);
  if (it == a->buffers.end()) return -DS_NOT_FOUND;
  const DsBuffer& buf = it->second;
  if (len == 0) return static_cast<int64_t>(buf.data.size());
  if (len > buf.data.size()) return -DS_OUT_OF_RANGE;
  std::memcpy(out, buf.data.data(), len);
  return static_cast<int64_t>(len);
}

int64_t ds_arena_logical_size(void* arena, uint64_t addr) {
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->buffers.find(addr);
  if (it == a->buffers.end()) return -DS_NOT_FOUND;
  return static_cast<int64_t>(it->second.logical);
}

// ---------------------------------------------------------------------------
// stream reassembly
// ---------------------------------------------------------------------------

struct DsStream {
  std::vector<uint8_t> chunks;
  uint64_t expected = 0;
  uint64_t recv_addr = 0;
  bool armed = false;
  int32_t status = DS_IN_PROGRESS;
};

struct DsStreamEngine {
  std::map<uint64_t, DsStream> streams;
  std::mutex mu;
};

void* ds_streams_new() { return new DsStreamEngine(); }
void ds_streams_free(void* eng) { delete static_cast<DsStreamEngine*>(eng); }

static void ds_stream_try_complete(DsArena* arena, DsStream& st) {
  if (!st.armed) return;
  if (st.chunks.size() == st.expected && st.expected > 0) {
    int32_t rc = ds_arena_write(arena, st.recv_addr, st.chunks.data(), st.chunks.size());
    st.status = (rc == DS_OK) ? DS_OK : DS_FAILED;
    st.chunks.clear();
    st.chunks.shrink_to_fit();
  } else if (st.chunks.size() > st.expected) {
    st.status = DS_FAILED;
  }
}

int32_t ds_stream_arm(void* eng, void* arena, uint64_t stream_id, uint64_t recv_addr,
                      uint64_t expected) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  auto* a = static_cast<DsArena*>(arena);
  if (recv_addr < a->min_addr || recv_addr > a->max_addr || expected > a->max_addr - recv_addr)
    return DS_OUT_OF_RANGE;
  std::lock_guard<std::mutex> lock(e->mu);
  DsStream& st = e->streams[stream_id];
  st.recv_addr = recv_addr;
  st.expected = expected;
  st.armed = true;
  ds_stream_try_complete(a, st);
  return DS_OK;
}

int32_t ds_stream_push(void* eng, void* arena, uint64_t stream_id, const uint8_t* chunk,
                       uint64_t len, int32_t final_chunk) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  auto* a = static_cast<DsArena*>(arena);
  std::lock_guard<std::mutex> lock(e->mu);
  DsStream& st = e->streams[stream_id];
  st.chunks.insert(st.chunks.end(), chunk, chunk + len);
  ds_stream_try_complete(a, st);
  if (final_chunk && st.armed && st.status == DS_IN_PROGRESS) st.status = DS_FAILED;
  return st.status == DS_FAILED ? DS_FAILED : DS_OK;
}

int32_t ds_stream_status(void* eng, uint64_t stream_id) {
  auto* e = static_cast<DsStreamEngine*>(eng);
  std::lock_guard<std::mutex> lock(e->mu);
  auto it = e->streams.find(stream_id);
  if (it == e->streams.end()) return -DS_NOT_FOUND;
  return it->second.status;
}

// ---------------------------------------------------------------------------
// ring schedule planner (gpu_coordinator_server.go:393-419 semantics)
// ---------------------------------------------------------------------------

// Fills send_idx/recv_idx, each [2*(n-1)] entries for `rank`: first n-1
// scatter-reduce steps, then n-1 all-gather steps.
int32_t ds_ring_plan(int32_t n, int32_t rank, int32_t* send_idx, int32_t* recv_idx) {
  if (n < 2 || rank < 0 || rank >= n) return DS_INVALID;
  auto mod = [n](int32_t v) { return ((v % n) + n) % n; };
  for (int32_t step = 0; step < n - 1; ++step) {
    send_idx[step] = mod(rank - step);
    recv_idx[step] = mod(rank - step - 1);
  }
  for (int32_t step = 0; step < n - 1; ++step) {
    send_idx[n - 1 + step] = mod(rank - step + 1);
    recv_idx[n - 1 + step] = mod(rank - step);
  }
  return DS_OK;
}

// ---------------------------------------------------------------------------
// dtype-aware reduction (coordinator host fallback path)
// ---------------------------------------------------------------------------

enum DsOp : int32_t { DS_SUM = 0, DS_PROD = 1, DS_MIN = 2, DS_MAX = 3, DS_AVG = 4 };

// rows: n_rows contiguous f32 rows of n elems each; out: n elems
int32_t ds_reduce_f32(const float* rows, int64_t n_rows, int64_t n, int32_t op, float* out) {
  if (n_rows < 1) return DS_INVALID;
  std::memcpy(out, rows, n * sizeof(float));
  for (int64_t r = 1; r < n_rows; ++r) {
    const float* row = rows + r * n;
    switch (op) {
      case DS_SUM:
      case DS_AVG:
        for (int64_t i = 0; i < n; ++i) out[i] += row[i];
        break;
      case DS_PROD:
        for (int64_t i = 0; i < n; ++i) out[i] *= row[i];
        break;
      case DS_MIN:
        for (int64_t i = 0; i < n; ++i) out[i] = row[i] < out[i] ? row[i] : out[i];
        break;
      case DS_MAX:
        for (int64_t i = 0; i < n; ++i) out[i] = row[i] > out[i] ? row[i] : out[i];
        break;
      default:
        return DS_INVALID;
    }
  }
  if (op == DS_AVG) {
    const float inv = 1.0f / static_cast<float>(n_rows);
    for (int64_t i = 0; i < n; ++i) out[i] *= inv;
  }
  return DS_OK;
}

// ---------------------------------------------------------------------------
// IDX (MNIST) parsing
// ---------------------------------------------------------------------------

static uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) | (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// Parses an (un-gzipped) IDX blob. dims_out must hold 3 entries:
// images -> [count, rows, cols]; labels -> [count, 0, 0].
// Returns the payload byte offset, or -status.
int64_t ds_idx_parse(const uint8_t* buf, uint64_t len, int32_t* dims_out) {
  if (len < 8) return -DS_INVALID;
  uint32_t magic = be32(buf);
  if (magic == 2051) {  // images
    if (len < 16) return -DS_INVALID;
    dims_out[0] = static_cast<int32_t>(be32(buf + 4));
    dims_out[1] = static_cast<int32_t>(be32(buf + 8));
    dims_out[2] = static_cast<int32_t>(be32(buf + 12));
    uint64_t need = 16ull + uint64_t(dims_out[0]) * dims_out[1] * dims_out[2];
    if (len < need) return -DS_INVALID;
    return 16;
  }
  if (magic == 2049) {  // labels
    dims_out[0] = static_cast<int32_t>(be32(buf + 4));
    dims_out[1] = 0;
    dims_out[2] = 0;
    if (len < 8ull + uint64_t(dims_out[0])) return -DS_INVALID;
    return 8;
  }
  return -DS_INVALID;
}

}  // extern "C"
