"""Native checkpoint format: per-leaf binary piece files + a JSON manifest.

Dependency-free (numpy + json + ``os.replace``) persistence for arbitrary
jax/numpy pytrees, designed around three facts of pod-scale training:

- **Sharding-aware**: every leaf is stored as its set of UNIQUE pieces
  (one file per distinct shard index, replicas deduplicated), so an n-way
  ZeRO-2-sharded optimizer leaf writes exactly its 1/n of the bytes — the
  per-dp-rank shard files the manifest indexes. The manifest records tree
  paths, shapes, dtypes, and the saved ``NamedSharding`` (mesh axis names/
  sizes + ``PartitionSpec``), so a restore can re-lay the state onto ANY
  compatible mesh: the template's shardings drive placement, not the
  checkpoint's.
- **Atomic**: all files are written into a hidden temp directory
  (``.tmp.step_N``), fsynced, and the finished directory is committed with
  one ``os.replace`` rename — the manifest is written last inside the temp
  dir, so a crash at ANY point leaves either the previous committed steps
  untouched or a stale temp dir that the next writer clears. A step
  directory is visible iff it is complete.
- **Async-friendly**: :func:`snapshot` materializes every piece to host
  memory (a real copy — immune to later donation/in-place reuse of the
  device buffers) and returns a plain host object; :func:`commit` does the
  disk I/O and can run on a background thread (``checkpoint.async_writer``).

Restore resizing rule (the cross-mesh ZeRO-2 path): a 1-D leaf that was
saved sharded over a mesh axis may restore into a template of a DIFFERENT
1-D size — the tail is zero-padding added by the bucket partitioner
(``parallel.bucketing`` identity-pads each flat bucket to a multiple of the
axis size), so going to a smaller padded size trims verified zeros and a
larger one appends zeros. Any other shape mismatch is an error.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any

import jax
import numpy as np

from dsml_tpu.utils.logging import get_logger

log = get_logger("checkpoint.native")

FORMAT_VERSION = 1
MANIFEST = "manifest.json"
_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp."


def step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{int(step):08d}"


def parse_step_dirname(name: str) -> int | None:
    if not name.startswith(_STEP_PREFIX):
        return None
    try:
        return int(name[len(_STEP_PREFIX):])
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# tree paths / dtypes / shardings <-> JSON
# ---------------------------------------------------------------------------


def _path_str(path) -> str:
    """Canonical '/'-joined string for a tree_flatten_with_path key path
    (DictKey.key / SequenceKey.idx / GetAttrKey.name / FlattenedIndexKey.key
    all reduce to their printable value)."""
    parts = []
    for entry in path:
        for attr in ("key", "idx", "name"):
            if hasattr(entry, attr):
                parts.append(str(getattr(entry, attr)))
                break
        else:
            parts.append(str(entry))
    return "/".join(parts)


def _dtype_name(dt) -> str:
    return np.dtype(dt).name


def _dtype_from_name(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat8/bfloat16/float8_* live in ml_dtypes (a jax dependency)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _sharding_entry(leaf) -> dict | None:
    """JSON description of a NamedSharding (None for anything else — the
    restore template decides placement anyway; the saved spec is metadata
    for audits and the 1-D resize rule)."""
    from jax.sharding import NamedSharding

    sharding = getattr(leaf, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return None
    spec = []
    for part in sharding.spec:
        if part is None:
            spec.append(None)
        elif isinstance(part, (tuple, list)):
            spec.append([str(a) for a in part])
        else:
            spec.append([str(part)])
    mesh = sharding.mesh
    return {
        "spec": spec,
        "mesh_axes": [str(a) for a in mesh.axis_names],
        "mesh_shape": [int(mesh.shape[a]) for a in mesh.axis_names],
    }


def _piece_key(index, shape) -> tuple:
    """Normalized ((start, stop), ...) for a shard index — ``slice.indices``
    makes ``slice(None)`` and ``slice(0, n)`` agree across sources."""
    return tuple(
        s.indices(dim)[:2] for s, dim in zip(index, shape) if isinstance(s, slice)
    )


# ---------------------------------------------------------------------------
# snapshot (host copy) — the synchronous half of an async save
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Snapshot:
    """Host-resident image of one checkpoint: manifest dict + named blobs.
    Safe to write from another thread — every array is a fresh host copy."""

    manifest: dict
    blobs: list  # [(relative filename, np.ndarray)]


def snapshot(state: Any, step: int, extra: dict | None = None) -> Snapshot:
    """Copy ``state`` to host memory and lay out the manifest. Returns
    before any disk I/O; the copies are independent of the source arrays,
    so donated/overwritten device buffers cannot corrupt the checkpoint."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    entries: list[dict] = []
    blobs: list[tuple[str, np.ndarray]] = []
    local_pid = jax.process_index()
    for li, (path, leaf) in enumerate(leaves):
        p = _path_str(path)
        if leaf is None or isinstance(leaf, (bool, str)) or (
            isinstance(leaf, (int, float)) and not isinstance(leaf, np.generic)
        ):
            entries.append({"path": p, "inline": leaf,
                            "kind": type(leaf).__name__})
            continue
        if isinstance(leaf, np.generic):  # numpy scalar → inline
            entries.append({"path": p, "inline": leaf.item(),
                            "kind": type(leaf.item()).__name__})
            continue
        if isinstance(leaf, jax.Array):
            entry, leaf_blobs = _snapshot_jax_leaf(leaf, p, li, local_pid)
        else:
            arr = np.array(leaf)  # host copy (python lists, np arrays)
            fn = f"L{li:05d}_P000.bin"
            entry = {
                "path": p, "shape": list(arr.shape),
                "dtype": _dtype_name(arr.dtype), "sharding": None,
                "pieces": [{"file": fn, "index": [[0, n] for n in arr.shape]}],
            }
            leaf_blobs = [(fn, arr)]
        entries.append(entry)
        blobs.extend(leaf_blobs)
    manifest = {
        "format": FORMAT_VERSION,
        "step": int(step),
        "leaves": entries,
        "extra": dict(extra or {}),
    }
    return Snapshot(manifest=manifest, blobs=blobs)


def _snapshot_jax_leaf(leaf, path: str, li: int, local_pid: int):
    """Manifest entry (ALL pieces, computed from the global sharding) plus
    the blob list for the pieces THIS process owns. A piece's owner is the
    process of its lowest-id holder device, so replicas write once and a
    multi-host save partitions the bytes without coordination."""
    sharding = leaf.sharding
    holders: dict[tuple, list] = {}
    for dev, idx in sharding.devices_indices_map(leaf.shape).items():
        holders.setdefault(_piece_key(idx, leaf.shape), []).append(dev)
    addressable = {
        _piece_key(s.index, leaf.shape): s for s in leaf.addressable_shards
    }
    pieces, blobs = [], []
    for pi, (key, devs) in enumerate(sorted(holders.items())):
        fn = f"L{li:05d}_P{pi:03d}.bin"
        pieces.append({"file": fn, "index": [[int(a), int(b)] for a, b in key]})
        owner = min(devs, key=lambda d: d.id)
        if owner.process_index == local_pid:
            shard = addressable.get(key)
            if shard is None:  # replica owned here but lowest-id copy remote
                shard = next(s for s in leaf.addressable_shards
                             if _piece_key(s.index, leaf.shape) == key)
            blobs.append((fn, np.array(shard.data, copy=True)))
    entry = {
        "path": path,
        "shape": [int(n) for n in leaf.shape],
        "dtype": _dtype_name(leaf.dtype),
        "sharding": _sharding_entry(leaf),
        "pieces": pieces,
    }
    return entry, blobs


# ---------------------------------------------------------------------------
# commit (disk) — runs on the async writer thread
# ---------------------------------------------------------------------------


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # platforms without directory fsync
        pass


def commit(directory: str, snap: Snapshot) -> str:
    """Write ``snap`` under ``directory`` and atomically publish it as
    ``step_<N>``. Crash-safe: everything lands in ``.tmp.step_<N>`` first
    (manifest last), and only the final ``os.replace`` rename makes the
    step visible — readers never observe a partial checkpoint."""
    step = snap.manifest["step"]
    final = os.path.join(directory, step_dirname(step))
    tmp = os.path.join(directory, _TMP_PREFIX + step_dirname(step))
    multi = jax.process_count() > 1
    if os.path.isdir(tmp) and not multi:
        shutil.rmtree(tmp)  # stale leftover from a crashed writer
    os.makedirs(tmp, exist_ok=True)
    for fn, arr in snap.blobs:
        fpath = os.path.join(tmp, fn)
        with open(fpath, "wb") as f:
            f.write(np.ascontiguousarray(arr).tobytes())
            f.flush()
            os.fsync(f.fileno())
    if multi:
        # every process must finish its pieces before process 0 publishes
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_commit_{step}")
        if jax.process_index() != 0:
            multihost_utils.sync_global_devices(f"ckpt_done_{step}")
            return final
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(snap.manifest, f)
        f.flush()
        os.fsync(f.fileno())
    if os.path.isdir(final):
        shutil.rmtree(final)  # re-save of the same step
    os.replace(tmp, final)
    _fsync_dir(directory)
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_done_{step}")
    log.info("committed checkpoint step %d -> %s", step, final)
    return final


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------


def read_manifest(ckpt_dir: str) -> dict:
    with open(os.path.join(ckpt_dir, MANIFEST)) as f:
        return json.load(f)


def _assemble(ckpt_dir: str, entry: dict) -> np.ndarray:
    """Full host array for one manifest entry, reassembled from its pieces."""
    shape = tuple(entry["shape"])
    dtype = _dtype_from_name(entry["dtype"])
    out = np.empty(shape, dtype)
    for piece in entry["pieces"]:
        idx = tuple(slice(a, b) for a, b in piece["index"])
        sub_shape = tuple(b - a for a, b in piece["index"])
        raw = np.fromfile(os.path.join(ckpt_dir, piece["file"]), dtype=dtype)
        expect = int(np.prod(sub_shape)) if sub_shape else 1
        if raw.size != expect:
            raise ValueError(
                f"checkpoint piece {piece['file']} for {entry['path']!r} has "
                f"{raw.size} elements, expected {expect} — truncated file?"
            )
        out[idx] = raw.reshape(sub_shape)
    return out


def _saved_dim0_sharded(entry: dict) -> bool:
    sh = entry.get("sharding")
    return bool(sh and sh["spec"] and sh["spec"][0])


def _resize_flat(arr: np.ndarray, target: int, path: str) -> np.ndarray:
    """Trim (verified-zero tail) or zero-pad a flat 1-D leaf — the ZeRO-2
    bucket-padding invariant (see module docstring)."""
    if target < arr.shape[0]:
        tail = arr[target:]
        if np.any(tail != 0):
            raise ValueError(
                f"cannot restore {path!r}: shrinking {arr.shape[0]} -> {target} "
                "would drop non-zero data (not bucket padding)"
            )
        return np.ascontiguousarray(arr[:target])
    return np.concatenate([arr, np.zeros(target - arr.shape[0], arr.dtype)])


def _materialize(ckpt_dir: str, entry: dict, tleaf) -> Any:
    """Restore one leaf into the shape/dtype/placement the template asks
    for. Accepts jax.Array / ShapeDtypeStruct (sharding-carrying), numpy
    arrays, and plain scalars as template leaves."""
    if "inline" in entry or entry.get("kind"):
        value = entry.get("inline")
        return value
    arr = _assemble(ckpt_dir, entry)
    t_shape = getattr(tleaf, "shape", None)
    if t_shape is not None and tuple(t_shape) != arr.shape:
        if arr.ndim == 1 and len(t_shape) == 1 and _saved_dim0_sharded(entry):
            arr = _resize_flat(arr, int(t_shape[0]), entry["path"])
        else:
            raise ValueError(
                f"template shape {tuple(t_shape)} != saved shape {arr.shape} "
                f"for {entry['path']!r}"
            )
    t_dtype = getattr(tleaf, "dtype", None)
    if t_dtype is not None and np.dtype(t_dtype) != arr.dtype:
        arr = arr.astype(t_dtype)
    if isinstance(tleaf, (bool, int, float, np.generic)):
        return type(tleaf)(arr.item()) if not isinstance(tleaf, np.generic) else arr[()]
    if isinstance(tleaf, np.ndarray):
        return arr
    sharding = getattr(tleaf, "sharding", None)
    if sharding is not None:
        return jax.device_put(arr, sharding)
    if isinstance(tleaf, jax.ShapeDtypeStruct):
        # an abstract template leaf with NO placement request stays a host
        # array — committing it to the default device would materialize
        # whole-state trees on one chip (the elastic-failover OOM hazard);
        # callers that want device residency put a sharding on the struct
        return arr
    import jax.numpy as jnp

    return jnp.asarray(arr)


def restore_tree(ckpt_dir: str, template: Any = None, partial: bool = False) -> Any:
    """Rebuild the saved pytree.

    With a ``template``, each template leaf is matched to its saved entry by
    tree path and restored with the TEMPLATE's shape/dtype/sharding (the
    relayout path: topology changes between save and restore need no
    conversion step). ``partial=True`` allows the template to name a subtree
    of what was saved (the params-only serving load); with ``partial=False``
    a template that silently drops saved state is an error.

    Without a template, returns plain nested dicts/lists of numpy arrays
    (tuples and namedtuple containers come back as lists — a structural
    template is required to revive those types).
    """
    manifest = read_manifest(ckpt_dir)
    entries = {e["path"]: e for e in manifest["leaves"]}
    if template is None:
        root: dict = {}
        for e in manifest["leaves"]:
            value = e["inline"] if "inline" in e else _assemble(ckpt_dir, e)
            _insert(root, e["path"].split("/"), value)
        return _listify(root)
    leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    matched = set()
    out = []
    for path, tleaf in leaves:
        p = _path_str(path)
        if p not in entries:
            raise KeyError(
                f"template leaf {p!r} not found in checkpoint "
                f"{ckpt_dir} (saved paths: {sorted(entries)[:8]}...)"
            )
        matched.add(p)
        out.append(_materialize(ckpt_dir, entries[p], tleaf))
    if not partial and len(matched) != len(entries):
        missing = sorted(set(entries) - matched)
        raise ValueError(
            f"restore template covers {len(matched)}/{len(entries)} saved "
            f"leaves (first missing: {missing[:5]}); pass partial=True for a "
            "weights-only/subtree restore"
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def _insert(root: dict, keys: list, value) -> None:
    node = root
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


def _listify(node):
    """Dict levels whose keys are exactly 0..n-1 were sequences; rebuild as
    lists so layer stacks round-trip without a template."""
    if not isinstance(node, dict):
        return node
    out = {k: _listify(v) for k, v in node.items()}
    keys = list(out)
    if keys and all(k.isdigit() for k in keys):
        idx = sorted(int(k) for k in keys)
        if idx == list(range(len(idx))):
            return [out[str(i)] for i in idx]
    return out
