"""Preemption-safe sharded checkpointing (``docs/CHECKPOINT.md``).

The subsystem the reference lacked entirely (SURVEY.md §5.4: a crash loses
the run) and pod-scale TPU training treats as a first-order throughput term
(preemption-driven scheduling): sharding-aware save/restore with no
dependency beyond numpy.

- :mod:`native` — the on-disk format: per-leaf binary piece files (unique
  shards only — ZeRO-2 state writes 1/n of the bytes) + a JSON manifest
  (tree paths, shapes, dtypes, sharding specs), committed atomically via
  write-to-temp + ``os.replace``.
- :mod:`async_writer` — background commit thread; the step loop pays only
  the device→host snapshot, never the disk.
- :mod:`manager` — :class:`CheckpointManager`: ``save``/``restore``/
  ``latest_step``/``max_to_keep`` GC/partial (weights-only) restore, plus
  the manifest-side ``iterator_state`` hook.
- :mod:`iterator` — :class:`ResumableIterator`: persists the data-loader
  position for bit-identical resume.

``utils.checkpoint.Checkpointer`` remains as a thin compat front-end
(orbax optional, selected explicitly).
"""

from dsml_tpu.checkpoint.async_writer import AsyncWriter
from dsml_tpu.checkpoint.iterator import ResumableIterator
from dsml_tpu.checkpoint.manager import CheckpointManager

__all__ = ["AsyncWriter", "CheckpointManager", "ResumableIterator"]
