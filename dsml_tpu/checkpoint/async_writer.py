"""Background commit thread for async checkpoint saves.

The save path splits in two: the SNAPSHOT (device→host copy,
``native.snapshot``) happens synchronously on the caller's thread — after it
returns, the training step is free to donate/overwrite every source buffer —
and the COMMIT (file writes + atomic rename + garbage collection) runs here,
overlapping the next steps' compute. One worker thread, FIFO order, so saves
commit in submission order and ``max_to_keep`` GC never races a commit.

Errors from a background commit don't vanish: the first failure is held and
re-raised on the next :meth:`submit`, :meth:`wait`, or :meth:`close` — a
training loop that keeps calling ``save`` finds out about a full disk on the
very next save, not at shutdown.
"""

from __future__ import annotations

import collections
import threading
import time

from dsml_tpu.obs import get_registry


class AsyncWriter:
    """Single-threaded FIFO job runner with sticky first-error propagation.

    Observability (``docs/OBSERVABILITY.md``; no-op unless the registry is
    enabled): ``checkpoint_queue_depth`` gauge (jobs waiting + running),
    ``checkpoint_commit_ms`` histogram (per-job wall), and
    ``checkpoint_errors_total`` counter (background failures held for the
    caller — the sticky-error path is otherwise invisible until the next
    ``save``)."""

    def __init__(self, name: str = "ckpt-writer"):
        self._name = name
        self._jobs: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._busy = False
        self._error: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._closed = False
        self._obs = get_registry()

    def _note_depth(self) -> None:
        # caller holds self._lock
        self._obs.gauge(
            "checkpoint_queue_depth", "async checkpoint jobs pending",
            labels=("writer",),
        ).set(len(self._jobs) + (1 if self._busy else 0), writer=self._name)

    def submit(self, fn) -> None:
        """Queue ``fn()`` for background execution; raises any held error
        from a previous job first."""
        self.check_error()
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncWriter is closed")
            self._jobs.append(fn)
            self._note_depth()
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._idle.notify_all()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._closed:
                    self._idle.wait(timeout=1.0)
                if not self._jobs:
                    return  # closed and drained
                fn = self._jobs.popleft()
                self._busy = True
                self._note_depth()
            t0 = time.perf_counter()
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — held for the caller
                self._obs.counter(
                    "checkpoint_errors_total",
                    "background checkpoint commit failures (held sticky)",
                    labels=("writer",),
                ).inc(writer=self._name)
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                self._obs.histogram(
                    "checkpoint_commit_ms", "background commit wall time",
                    labels=("writer",),
                ).observe((time.perf_counter() - t0) * 1e3, writer=self._name)
                with self._lock:
                    self._busy = False
                    self._note_depth()
                    self._idle.notify_all()

    def check_error(self) -> None:
        """Re-raise (and clear) the held first error, non-blocking."""
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def wait(self) -> None:
        """Block until every submitted job has finished; re-raise the first
        failure."""
        with self._lock:
            while self._jobs or self._busy:
                self._idle.wait()
        self.check_error()

    def pending(self) -> int:
        with self._lock:
            return len(self._jobs) + (1 if self._busy else 0)

    def close(self) -> None:
        """Drain the queue, surface any held error, and stop the thread."""
        try:
            self.wait()
        finally:
            with self._lock:
                self._closed = True
                self._idle.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
