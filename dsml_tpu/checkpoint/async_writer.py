"""Background commit thread for async checkpoint saves.

The save path splits in two: the SNAPSHOT (device→host copy,
``native.snapshot``) happens synchronously on the caller's thread — after it
returns, the training step is free to donate/overwrite every source buffer —
and the COMMIT (file writes + atomic rename + garbage collection) runs here,
overlapping the next steps' compute. One worker thread, FIFO order, so saves
commit in submission order and ``max_to_keep`` GC never races a commit.

Errors from a background commit don't vanish: the first failure is held and
re-raised on the next :meth:`submit`, :meth:`wait`, or :meth:`close` — a
training loop that keeps calling ``save`` finds out about a full disk on the
very next save, not at shutdown.
"""

from __future__ import annotations

import collections
import os
import threading
import time

from dsml_tpu.obs import get_registry
from dsml_tpu.obs import flight_recorder, hangwatch
from dsml_tpu.utils.logging import get_logger

log = get_logger("ckpt-writer")

# a background commit outliving this many seconds is suspect: wait() warns
# with queue depth + the in-flight label instead of blocking silently, and
# with DSML_HANGWATCH set the worker's armed deadline dumps stacks + bundle
DEFAULT_COMMIT_DEADLINE_S = 120.0


def _commit_deadline_s() -> float:
    try:
        v = float(os.environ.get("DSML_CKPT_COMMIT_DEADLINE_S",
                                 DEFAULT_COMMIT_DEADLINE_S))
    except ValueError:
        return DEFAULT_COMMIT_DEADLINE_S
    return v if v > 0 else DEFAULT_COMMIT_DEADLINE_S


class AsyncWriter:
    """Single-threaded FIFO job runner with sticky first-error propagation.

    Observability (``docs/OBSERVABILITY.md``; no-op unless the registry is
    enabled): ``checkpoint_queue_depth`` gauge (jobs waiting + running),
    ``checkpoint_commit_ms`` histogram (per-job wall), and
    ``checkpoint_errors_total`` counter (background failures held for the
    caller — the sticky-error path is otherwise invisible until the next
    ``save``). Each commit lands a ``checkpoint_commit`` flight-recorder
    event, and a commit (or a ``wait()``) exceeding ``deadline_s`` logs a
    warning carrying the queue depth and the in-flight job's label — a
    full NFS mount blocks loudly instead of forever."""

    def __init__(self, name: str = "ckpt-writer",
                 deadline_s: float | None = None):
        self._name = name
        self.deadline_s = (deadline_s if deadline_s is not None
                           else _commit_deadline_s())
        # host bytes of snapshots queued-or-committing (the memory the
        # async path STAGES between the device→host copy and the rename);
        # a ledger source + gauge so leaked staging shows up, not just
        # queue depth
        self._staged_nbytes = 0.0
        self._jobs: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._busy = False
        self._busy_since: float | None = None
        self._overdue_warned = False
        self._current_label: str | None = None
        self._error: BaseException | None = None
        self._thread: threading.Thread | None = None
        self._closed = False
        self._obs = get_registry()
        self._recorder = flight_recorder.get_flight_recorder()
        hw_cfg = hangwatch.config_from_env()
        self._hangwatch = hangwatch.get_hangwatch() if hw_cfg is not None else None
        # memory-ledger source (docs/OBSERVABILITY.md § Memory ledger):
        # weakly held, dies with this writer
        from dsml_tpu.obs.memory import get_memory_ledger

        get_memory_ledger(self._obs).register_source(
            "checkpoint_staging", self.staged_bytes,
            name=f"{self._name}/{id(self):x}",
        )

    def staged_bytes(self) -> float:
        """Host bytes of snapshots not yet committed (queued + in-flight)."""
        with self._lock:
            return self._staged_nbytes

    def _note_depth(self) -> None:
        # caller holds self._lock
        self._obs.gauge(
            "checkpoint_queue_depth", "async checkpoint jobs pending",
            labels=("writer",),
        ).set(len(self._jobs) + (1 if self._busy else 0), writer=self._name)
        self._obs.gauge(
            "checkpoint_staging_bytes",
            "host snapshot bytes awaiting background commit",
            labels=("writer",),
        ).set(self._staged_nbytes, writer=self._name)

    def submit(self, fn, label: str | None = None,
               nbytes: float = 0.0) -> None:
        """Queue ``fn()`` for background execution; raises any held error
        from a previous job first. ``label`` (e.g. ``"step 42"``) names the
        job in deadline warnings and flight-recorder events; ``nbytes`` is
        the staged payload the job holds until it completes (the ledger's
        ``checkpoint_staging`` accounting — released success or fail)."""
        self.check_error()
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncWriter is closed")
            # a commit wedged PAST its deadline would otherwise be silent
            # until wait(): the next save is the natural place to shout
            if (self._busy and self._busy_since is not None
                    and not self._overdue_warned
                    and time.monotonic() - self._busy_since > self.deadline_s):
                self._overdue_warned = True
                log.warning(
                    "commit %s still running after %.0fs (deadline %.0fs, "
                    "%d queued behind it) — storage may be wedged",
                    self._current_label or "?",
                    time.monotonic() - self._busy_since, self.deadline_s,
                    len(self._jobs),
                )
            self._jobs.append((fn, label, float(max(nbytes, 0.0))))
            self._staged_nbytes += float(max(nbytes, 0.0))
            self._note_depth()
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True
                )
                self._thread.start()
            self._idle.notify_all()

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._jobs and not self._closed:
                    self._idle.wait(timeout=1.0)
                if not self._jobs:
                    return  # closed and drained
                fn, label, job_nbytes = self._jobs.popleft()
                self._busy = True
                self._busy_since = time.monotonic()
                self._overdue_warned = False
                self._current_label = label
                depth = len(self._jobs) + 1
                self._note_depth()
            hw_token = (
                self._hangwatch.arm(
                    "checkpoint_commit", self.deadline_s,
                    label=label or "?", queue_depth=depth, writer=self._name,
                )
                if self._hangwatch is not None else None
            )
            t0 = time.perf_counter()
            ok = True
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — held for the caller
                ok = False
                self._obs.counter(
                    "checkpoint_errors_total",
                    "background checkpoint commit failures (held sticky)",
                    labels=("writer",),
                ).inc(writer=self._name)
                with self._lock:
                    if self._error is None:
                        self._error = e
            finally:
                if hw_token is not None:
                    self._hangwatch.disarm(hw_token)
                wall_ms = (time.perf_counter() - t0) * 1e3
                self._obs.histogram(
                    "checkpoint_commit_ms", "background commit wall time",
                    labels=("writer",),
                ).observe(wall_ms, writer=self._name)
                self._recorder.record(
                    "checkpoint_commit", writer=self._name,
                    label=label or "?", ms=round(wall_ms, 3), ok=ok,
                )
                with self._lock:
                    queued_behind = len(self._jobs)
                    self._busy = False
                    self._busy_since = None
                    self._current_label = None
                    # the snapshot is durable (or dead) either way — its
                    # host bytes are no longer staged
                    self._staged_nbytes = max(
                        self._staged_nbytes - job_nbytes, 0.0)
                    self._note_depth()
                    self._idle.notify_all()
                if wall_ms > self.deadline_s * 1e3:
                    log.warning(
                        "commit %s took %.1fs (deadline %.0fs, %d queued "
                        "behind it) — storage is falling behind the save "
                        "cadence", label or "?", wall_ms / 1e3,
                        self.deadline_s, queued_behind,
                    )

    def check_error(self) -> None:
        """Re-raise (and clear) the held first error, non-blocking."""
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def wait(self) -> None:
        """Block until every submitted job has finished; re-raise the first
        failure. A wait outliving ``deadline_s`` is never silent: each
        elapsed deadline logs a warning naming the in-flight job and the
        queue depth (the commit-deadline sentinel — ISSUE 5), so an
        operator tailing the log sees WHAT the shutdown is stuck on."""
        t0 = time.monotonic()
        warned = 0
        with self._lock:
            while self._jobs or self._busy:
                self._idle.wait(timeout=self.deadline_s)
                elapsed = time.monotonic() - t0
                if ((self._jobs or self._busy)
                        and elapsed >= self.deadline_s * (warned + 1)):
                    warned += 1
                    label = self._current_label
                    depth = len(self._jobs) + (1 if self._busy else 0)
                    log.warning(
                        "wait(): still blocked after %.0fs on commit %s "
                        "(%d job(s) outstanding; deadline %.0fs)",
                        elapsed, label or "?", depth, self.deadline_s,
                    )
        self.check_error()

    def pending(self) -> int:
        with self._lock:
            return len(self._jobs) + (1 if self._busy else 0)

    def close(self) -> None:
        """Drain the queue, surface any held error, and stop the thread."""
        try:
            self.wait()
        finally:
            with self._lock:
                self._closed = True
                self._idle.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=5.0)
