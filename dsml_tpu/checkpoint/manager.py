"""CheckpointManager — the front-end of the native checkpoint subsystem.

Directory layout (one run directory, many steps)::

    <dir>/step_00000042/manifest.json   # tree paths, shapes, dtypes,
    <dir>/step_00000042/L00003_P001.bin #   shardings, piece index
    <dir>/.tmp.step_00000043/...        # in-flight write (invisible to
                                        #   latest_step until renamed)

``save(..., wait=False)`` snapshots device arrays to host BEFORE returning
(donation-safe) and commits on a background thread — the step loop never
stalls on disk. ``max_to_keep`` garbage-collects old steps after each
commit. ``restore`` is sharding-aware: the template's shardings drive the
relayout, so a checkpoint saved on one mesh restores onto another (see
``native.restore_tree``). ``iterator_state`` rides in the manifest so a
resume can put the data loader back at the exact batch it stopped at
(``checkpoint.iterator.ResumableIterator``).
"""

from __future__ import annotations

import os
import shutil
from typing import Any

from dsml_tpu.checkpoint import native
from dsml_tpu.checkpoint.async_writer import AsyncWriter
from dsml_tpu.obs import get_registry
from dsml_tpu.utils.logging import get_logger

log = get_logger("checkpoint")


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int | None = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._writer = AsyncWriter()
        # reclaim any .trash.* debris a prior delete renamed but could not
        # remove (busy NFS handles at deletion time)
        for name in os.listdir(self.directory):
            if name.startswith(".trash."):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- write ------------------------------------------------------------

    def save(
        self,
        step: int,
        state: Any,
        *,
        iterator_state: dict | None = None,
        meta: dict | None = None,
        wait: bool = True,
    ) -> None:
        """Persist ``state`` as step ``step``. With ``wait=False`` only the
        host snapshot happens here; the disk commit overlaps training and is
        made durable by the next ``wait_until_finished``/``close`` (or
        absorbed by a later save's barrier). ``iterator_state`` /``meta``
        must be JSON-serializable; they land in the manifest, not as
        leaves."""
        extra = {}
        if iterator_state is not None:
            extra["iterator"] = iterator_state
        if meta:
            extra["meta"] = dict(meta)
        snap = native.snapshot(state, step=step, extra=extra)
        directory = self.directory

        def job():
            native.commit(directory, snap)
            self._gc()

        # the label surfaces in commit-deadline warnings and flight events,
        # so a stuck wait() names the step it is blocked on; the blob
        # bytes ride along as the ledger's checkpoint_staging claim
        # (held until the commit lands, success or fail)
        self._writer.submit(
            job, label=f"step {step}",
            nbytes=sum(int(b.nbytes) for _, b in snap.blobs),
        )
        get_registry().counter(
            "checkpoint_saves_total", "checkpoint save submissions",
            labels=("mode",),
        ).inc(mode="sync" if wait else "async")
        if wait:
            self._writer.wait()
            log.info("saved checkpoint step %d -> %s", step, directory)
        else:
            log.info("scheduled async checkpoint save step %d -> %s", step, directory)

    def wait_until_finished(self) -> None:
        """Block until every in-flight async save has committed (re-raising
        any background write failure)."""
        self._writer.wait()

    def _delete_step(self, step: int, why: str) -> bool:
        path = os.path.join(self.directory, native.step_dirname(step))
        # rename-then-delete: a reader listing steps mid-GC never sees a
        # half-deleted directory as a valid checkpoint
        trash = os.path.join(self.directory, f".trash.{native.step_dirname(step)}")
        try:
            os.replace(path, trash)
        except OSError:  # already gone (concurrent GC) — fine
            return False
        # the RENAME is the deletion (the step is out of every listing);
        # a reclaim failure (NFS .nfsXXXX busy files, open fds) must still
        # count + log the deletion — silence exactly when deletion
        # misbehaves is how trash dirs quietly eat a disk
        try:
            shutil.rmtree(trash)
        except OSError as e:
            log.warning("checkpoint step %d removed but %s not yet "
                        "reclaimed (%s); swept at the next manager open",
                        step, trash, e)
        # a silent deletion is how a "lost" checkpoint becomes a
        # mystery: every deletion names the step AND the path it removed,
        # through both the logger and the registry
        get_registry().counter(
            "checkpoint_gc_total", "checkpoint deletions",
        ).inc()
        log.info("deleted checkpoint step %d (%s), reason=%s", step, path, why)
        return True

    def _gc(self) -> None:
        if not self.max_to_keep or self.max_to_keep < 1:
            return
        steps = self.all_steps()
        for step in steps[: -self.max_to_keep]:
            self._delete_step(step, f"max_to_keep={self.max_to_keep}")

    def delete_steps(self, steps) -> int:
        """Explicitly drop committed steps — the elastic controller's
        lineage-pruning hook (a replay grow-back discards the mixed-width
        checkpoints so a later fallback cannot mix lineages). Returns how
        many were actually removed."""
        return sum(self._delete_step(int(s), "explicit") for s in steps)

    # -- read -------------------------------------------------------------

    def all_steps(self) -> list[int]:
        """Committed steps, ascending. Only directories with a manifest
        count — an interrupted write (temp dir) is invisible."""
        out = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        for name in names:
            step = native.parse_step_dirname(name)
            if step is None:
                continue
            if os.path.exists(os.path.join(self.directory, name, native.MANIFEST)):
                out.append(step)
        return sorted(out)

    @staticmethod
    def newest_common_step(per_host_steps) -> int | None:
        """The newest step EVERY host has committed — the step a
        COORDINATED all-hosts checkpoint fallback must restore when P2P
        shard migration cannot deliver (docs/ELASTIC.md § Multi-host
        recovery). A host restoring a step its peers never committed would
        desync the fleet; the intersection is the only safe set. ``None``
        when any host has nothing (or the intersection is empty): the
        outage predates the first fleet-wide commit."""
        sets = [set(int(s) for s in steps) for steps in per_host_steps]
        if not sets:
            return None
        common = set.intersection(*sets)
        return max(common) if common else None

    def latest_step(self, where=None) -> int | None:
        """Newest committed step; with ``where`` (a predicate over the
        step's manifest ``meta`` dict), the newest step whose meta
        satisfies it — how the elastic controller finds the last
        pure-lineage checkpoint for a replay grow-back."""
        steps = self.all_steps()
        if where is None:
            return steps[-1] if steps else None
        for step in reversed(steps):
            try:
                meta = self.meta(step)
            except (OSError, KeyError, ValueError):
                continue
            if where(meta):
                return step
        return None

    def _step_dir(self, step: int | None) -> str:
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        path = os.path.join(self.directory, native.step_dirname(step))
        if not os.path.exists(os.path.join(path, native.MANIFEST)):
            raise FileNotFoundError(f"no committed checkpoint for step {step} under {self.directory}")
        return path

    def restore(self, step: int | None = None, template: Any = None,
                partial: bool = False) -> Any:
        """Restore state (latest step when ``step`` is None). With a
        ``template`` (arrays or ShapeDtypeStructs), leaves come back with
        the template's dtypes and shardings; ``partial=True`` restores only
        the subtree the template names (the weights-only inference path)."""
        return native.restore_tree(self._step_dir(step), template, partial)

    def iterator_state(self, step: int | None = None) -> dict | None:
        """The data-loader position saved with this step (None if absent)."""
        return native.read_manifest(self._step_dir(step))["extra"].get("iterator")

    def meta(self, step: int | None = None) -> dict:
        return native.read_manifest(self._step_dir(step))["extra"].get("meta", {})

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        self._writer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
