"""Resumable data iterators — the loader-position half of a bit-identical
resume.

Restoring (params, opt_state) alone resumes the MODEL but not the RUN: the
data loader would start over and replay batches the optimizer has already
consumed, so the post-resume loss trajectory diverges from the
uninterrupted one. The framework's batch iterators
(``utils.data.shard_batches`` / ``lm_window_batches``) are deterministic
functions of their seed, which makes position a single integer: wrap the
iterator in :class:`ResumableIterator`, persist ``state()`` with each
checkpoint (``CheckpointManager.save(..., iterator_state=...)``), and
resume by rebuilding the same factory and fast-forwarding — every batch
after the resume point is bit-identical to the batch the uninterrupted run
would have seen.

Composes with ``utils.data.prefetch_batches``: put the prefetcher INSIDE
the factory (``lambda: prefetch_batches(lm_window_batches(...))``) — the
wrapper counts batches the CONSUMER pulled, so prefetch depth never
over-advances the recorded position.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator


class ResumableIterator:
    """Iterator wrapper that tracks consumption and replays to a position.

    ``factory`` must return a fresh, deterministic iterator each call (same
    batches in the same order). ``state()`` is JSON-serializable;
    ``ResumableIterator(factory, state=saved)`` rebuilds the stream and
    skips exactly the consumed prefix.
    """

    def __init__(self, factory: Callable[[], Iterator], state: dict | None = None):
        self._factory = factory
        self._it = iter(factory())
        self.consumed = 0
        if state:
            skip = int(state.get("consumed", 0))
            for _ in range(skip):
                next(self._it)
            self.consumed = skip

    def __iter__(self):
        return self

    def __next__(self) -> Any:
        item = next(self._it)
        self.consumed += 1
        return item

    def state(self) -> dict:
        """Position snapshot to persist alongside the model state."""
        return {"consumed": self.consumed}

    def reset(self) -> None:
        """Restart the underlying stream from the beginning (e.g. a new
        epoch with a new factory seed: build a new ResumableIterator)."""
        self._it = iter(self._factory())
        self.consumed = 0
