"""SLO-aware router over a disaggregated prefill/decode serving fleet.

The front door of the fleet (docs/SERVING.md): N prefill workers and M
decode workers behind one admission surface. Responsibilities:

- **Admission + shedding** — every request names an :class:`SLOClass`;
  a class sheds with the batcher's own :class:`QueueFull` when its router
  backlog hits the class cap or the measured-TTFT estimate exceeds the
  class budget. Overload is an EXPLICIT signal (``serving_shed_total``
  with ``role="router"``) raised BEFORE queues collapse — decode p99
  stays flat while the router turns excess load away (pinned in tests).
- **Load-aware dispatch** — prompts go to the prefill worker with the
  cheapest measured backlog (queue tokens priced at the per-chunk wall
  EWMA); completed handoffs go to the decode worker with the smallest
  (queue depth, measured TPOT) — queue depth and measured TTFT/TPOT, not
  round-robin.
- **Prefix replication** — ``register_prefix`` fans out to every prefill
  worker, so the system-prompt O(L−P) admission win holds wherever a
  request lands.
- **Handoff transport** — in-process object handover by default;
  ``transport=`` a callable (e.g. ``handoff.frame_transport``) routes
  every handoff through the CRC-framed wire codec; real cross-host pulls
  use the donor/migrator stream path (``serving.handoff``).
- **Failure** — ``kill_prefill_worker`` / ``kill_decode_worker`` are the
  chaos hooks: unfinished work re-enters the backlog and RE-PREFILLS on
  survivors. Prefill is a pure function of the prompt and the sampler
  folds the fleet-wide rid, so a worker loss costs latency, never tokens
  (``runtime.chaos.run_chaos_serving_fleet`` pins it).
- **Request tracing + SLO accounting** — ``submit`` mints a
  :class:`~dsml_tpu.obs.TraceContext` that rides every stage (prefill
  dispatch, the handoff wire, decode injection, retire/requeue — the
  SAME trace across retries), the TTFT/TPOT histograms carry trace_id
  exemplars, and each class's measured TTFT/TPOT/e2e feeds
  ``obs/slo.py`` SLI windows → burn-rate status + p99 tail attribution
  (``Router.slo``; docs/OBSERVABILITY.md § Request tracing & SLO
  budgets).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import numpy as np

from dsml_tpu.obs import TraceContext, flight_recorder, get_registry, get_tracer
from dsml_tpu.obs.slo import SLOSpec, SLOTracker
from dsml_tpu.serving.batcher import ContinuousBatcher, QueueFull
from dsml_tpu.serving.prefill import PrefillWorker
from dsml_tpu.utils.config import env_int
from dsml_tpu.utils.logging import get_logger

__all__ = ["Router", "SLOClass", "build_fleet"]

log = get_logger("serving.router")

# raw per-request sample/record retention (offline percentiles, SLO tail
# attribution, chaos verdicts): bounded so a long-lived fleet's host
# memory stays flat — overflow counts into ``dropped_samples`` +
# ``serving_samples_dropped_total`` instead of growing silently
_SAMPLE_CAP_ENV = "DSML_SERVING_SAMPLES"
_SAMPLE_CAP_DEFAULT = 4096


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One admission class. ``max_queue`` caps this class's ROUTER backlog
    (0 = unbounded); ``ttft_budget_ms`` sheds when the measured-load TTFT
    estimate exceeds it (None = no budget) AND doubles as the class's
    measured TTFT SLI budget; lower ``priority`` dispatches first when
    classes compete for prefill capacity.

    The SLO-accounting fields (``obs/slo.py``): ``tpot_budget_ms`` /
    ``e2e_budget_ms`` budget the other two SLIs, ``objective`` is the
    target good fraction each budgeted SLI must meet before its error
    budget starts burning (docs/OBSERVABILITY.md § Request tracing &
    SLO budgets)."""

    name: str
    max_queue: int = 0
    ttft_budget_ms: float | None = None
    priority: int = 0
    tpot_budget_ms: float | None = None
    e2e_budget_ms: float | None = None
    objective: float = 0.99


@dataclasses.dataclass
class _Spec:
    prompt: object
    max_new_tokens: int
    slo: str
    submitted_at: float
    trace: TraceContext | None = None


class Router:
    """See module docstring. ``prefill_workers`` is a list of
    :class:`PrefillWorker`, ``decode_workers`` a list of
    :class:`ContinuousBatcher` (the decode role: admission happens via
    ``inject``, their own submit path stays unused). All workers must
    share the model config and — for sampled serving — the same
    ``seed``/``temperature`` as the reference deployment, since the
    sampler folds (seed, fleet rid, step)."""

    def __init__(self, prefill_workers, decode_workers,
                 slo_classes=None, transport=None):
        if not prefill_workers or not decode_workers:
            raise ValueError("need at least one prefill and one decode worker")
        self.prefill_workers = list(prefill_workers)
        self.decode_workers = list(decode_workers)
        for i, pw in enumerate(self.prefill_workers):
            pw.obs_replica = str(i)
        for i, dw in enumerate(self.decode_workers):
            dw.obs_replica = str(i)
            dw.obs_role = "decode"
        # paged fleets are all-or-nothing: a dense handoff cannot land in
        # a page pool (and vice versa), so a mixed fleet is a deployment
        # bug caught HERE, not inside a later tick's inject
        paged = {bool(getattr(w, "paged", False))
                 for w in self.prefill_workers + self.decode_workers}
        if len(paged) > 1:
            raise ValueError(
                "mixed fleet: every prefill AND decode worker must agree "
                "on paged_kv"
            )
        self.paged = paged.pop()
        if self.paged:
            shapes = {(w.page_size, w.page_quant)
                      for w in self.prefill_workers + self.decode_workers}
            if len(shapes) > 1:
                raise ValueError(
                    f"paged fleet disagrees on (page_size, quant): {shapes}"
                )
        classes = list(slo_classes) if slo_classes else [SLOClass("default")]
        self._classes = {c.name: c for c in classes}
        if len(self._classes) != len(classes):
            raise ValueError("duplicate SLO class names")
        self.transport = transport
        self._obs = get_registry()
        self.obs_replica = "router"
        self.obs_role = "router"
        self._backlog: dict[str, deque[int]] = {
            c.name: deque() for c in classes
        }
        self._spec: dict[int, _Spec] = {}
        self._next_frid = 0
        self._prefill_at: dict[int, PrefillWorker] = {}
        self._ready: deque = deque()  # handoffs awaiting decode capacity
        self._local: dict[tuple, int] = {}   # (id(worker), local rid) -> frid
        self._decode_at: dict[int, tuple] = {}
        self._prefill_done_at: dict[int, float] = {}
        self._results: dict[int, list] = {}
        # measured fleet latencies (seconds; EWMA alpha 0.2): TTFT end to
        # end, per-token decode latency, and the handoff→first-token wait
        # that prices the decode half of the admission estimate
        self.ttft_ewma_s: float | None = None
        self.tpot_ewma_s: float | None = None
        self.decode_wait_ewma_s: float | None = None
        # raw per-request samples (ttft_s, tpot_s or None, e2e_s) for
        # offline percentiles — the bench/SLO-report path; cleared by
        # :meth:`reset_latency_stats`. BOUNDED (maxlen deque): a
        # long-lived fleet must not grow host memory one tuple per
        # lifetime request — overflow is counted, never silent
        self._sample_cap = max(env_int(_SAMPLE_CAP_ENV, _SAMPLE_CAP_DEFAULT), 1)
        self.latency_samples: deque[tuple] = deque(maxlen=self._sample_cap)
        self.dropped_samples = 0
        self._tpot_by_worker: dict[int, float] = {}
        self.shed_counts: dict[str, int] = {c.name: 0 for c in classes}
        self.requeued_prefill = 0
        self.requeued_decode = 0
        self.transport_failures = 0
        self.n_handoffs_routed = 0
        # ---- request tracing + SLO accounting (the PR 13 layer) ----
        # trace context per in-flight request; stage marks (monotonic
        # seconds) split TTFT into queue/prefill/handoff/first-decode;
        # request_records is the bounded retired-request ledger the chaos
        # verdicts and the tail-attribution bench read
        self._trace: dict[int, TraceContext] = {}
        self._stage_marks: dict[int, dict] = {}
        self._retries: dict[int, int] = {}
        self.requeue_log: list[tuple] = []  # (frid, monotonic) — bounded below
        self.request_records: dict[int, dict] = {}
        self._record_order: deque[int] = deque()
        self.slo = SLOTracker([
            SLOSpec(
                name=c.name, objective=c.objective,
                ttft_budget_ms=c.ttft_budget_ms,
                tpot_budget_ms=c.tpot_budget_ms,
                e2e_budget_ms=c.e2e_budget_ms,
            )
            for c in classes
        ], registry=self._obs)

    # ---- admission -------------------------------------------------------

    def register_prefix(self, tokens) -> None:
        """Replicate a shared prompt head across EVERY prefill worker (the
        fleet-wide system-prompt pattern): any worker the router picks
        admits a matching prompt at O(L − P). Blocking setup call.

        On a PAGED fleet the registration also lands on every DECODE
        worker (its page pool holds the prefix pages once, refcounted),
        and prefill workers then ELIDE the prefix's full pages from
        every matching handoff (``ship_prefix_pages``): the decode side
        shares its local pages for those rows — the fleet-level CoW that
        cuts both the handoff wire bytes and the decode-side HBM per
        matching request."""
        for pw in self.prefill_workers:
            pw.register_prefix(tokens)
        if self.paged:
            for dw in self.decode_workers:
                dw.register_prefix(tokens)
            # every decode worker can now serve the shared rows locally —
            # safe to stop shipping them (replication happens before any
            # matching handoff exists: this is a blocking setup call)
            for pw in self.prefill_workers:
                pw.ship_prefix_pages = True

    def estimate_ttft_ms(self, prompt_len: int) -> float:
        """Measured-load TTFT estimate for a hypothetical new prompt:
        un-prefilled tokens ahead of it — router backlog plus the cheapest
        worker's own queue — priced at the measured per-chunk wall EWMA
        (spread across the prefill pool), plus the measured
        handoff→first-token decode wait. Zero until the first measurements
        land — the class cap (queue depth) carries admission control
        before the cost model is warm."""
        worker_ms = min(
            pw.estimate_ms(prompt_len) for pw in self.prefill_workers
        )
        ewmas = [pw.chunk_s_ewma for pw in self.prefill_workers
                 if pw.chunk_s_ewma]
        backlog_ms = 0.0
        if ewmas:
            backlog_tokens = sum(
                len(self._spec[f].prompt)
                for b in self._backlog.values() for f in b
            )
            chunk = self.prefill_workers[0].prefill_chunk
            chunks = -(-backlog_tokens // chunk)
            backlog_ms = (chunks * (sum(ewmas) / len(ewmas)) * 1e3
                          / len(self.prefill_workers))
        decode_ms = (self.decode_wait_ewma_s or 0.0) * 1e3
        return worker_ms + backlog_ms + decode_ms

    def _shed(self, cls: SLOClass, reason: str) -> None:
        self.shed_counts[cls.name] += 1
        self._obs.counter(
            "serving_shed_total", "requests rejected by the queue cap",
            labels=("replica", "role"),
        ).inc(replica=self.obs_replica, role=self.obs_role)
        if self._obs.enabled:
            flight_recorder.record(
                "serving_router_shed", slo=cls.name, reason=reason,
            )
        raise QueueFull(
            f"SLO class {cls.name!r} shed ({reason}); back off or retry a "
            "lower class"
        )

    def submit(self, prompt, max_new_tokens: int, slo: str = "default") -> int:
        cls = self._classes.get(slo)
        if cls is None:
            raise ValueError(
                f"unknown SLO class {slo!r}; declared: {sorted(self._classes)}"
            )
        # validate at the fleet edge: a malformed request must fail HERE
        # (the caller's bug, ValueError) — not inside a later tick's
        # dispatch, where it would crash unrelated requests' scheduling
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        pw0 = self.prefill_workers[0]
        pw0.model._check_generate_args(len(prompt), max_new_tokens, 0.0, 0, 0)
        if not pw0._fits(len(prompt)):
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the chunk grid for "
                f"max_seq={pw0.model.config.max_seq}"
            )
        if cls.max_queue and len(self._backlog[cls.name]) >= cls.max_queue:
            self._shed(cls, f"backlog at cap {cls.max_queue}")
        if cls.ttft_budget_ms is not None:
            est = self.estimate_ttft_ms(len(prompt))
            if est > cls.ttft_budget_ms:
                self._shed(
                    cls, f"estimated TTFT {est:.0f}ms > budget "
                    f"{cls.ttft_budget_ms:.0f}ms"
                )
        frid = self._next_frid
        self._next_frid += 1
        # mint the request's trace identity HERE — the fleet edge is the
        # one point every request passes exactly once. The context then
        # rides prefill dispatch, the handoff wire, and decode injection;
        # a requeue keeps the SAME trace (the retry is the same request)
        ctx = TraceContext.mint(span_id="router_submit")
        self._trace[frid] = ctx
        self._retries[frid] = 0
        with get_tracer().request_span(
            "router_submit", ctx, flow="start", frid=frid, slo=cls.name,
            prompt_len=len(prompt),
        ):
            self._spec[frid] = _Spec(
                prompt=prompt, max_new_tokens=int(max_new_tokens),
                slo=cls.name, submitted_at=time.monotonic(), trace=ctx,
            )
            self._stage_marks[frid] = {}
            self._backlog[cls.name].append(frid)
        return frid

    @property
    def outstanding(self) -> int:
        return len(self._spec)

    def trace_of(self, frid: int) -> TraceContext | None:
        """The trace context minted for ``frid`` at submit (None once the
        request retired — its trace_id then lives in
        ``request_records[frid]``)."""
        return self._trace.get(frid)

    # ---- dispatch --------------------------------------------------------

    def _dispatch_prefill(self) -> None:
        """Drain backlogs (priority order) onto the cheapest prefill
        worker. A worker at its queue cap is excluded for this tick only;
        dispatching stops when every worker is capped."""
        for cls in sorted(self._classes.values(), key=lambda c: c.priority):
            backlog = self._backlog[cls.name]
            while backlog:
                # capacity-check BEFORE submitting: the worker's own
                # QueueFull path counts a SHED, and a routed request that
                # merely waits another tick was never shed (single-threaded
                # scheduler, so the check cannot race the submit)
                open_pws = [
                    pw for pw in self.prefill_workers
                    if not (pw.max_queue and pw.n_queued >= pw.max_queue)
                ]
                if not open_pws:
                    return
                frid = backlog[0]
                spec = self._spec[frid]
                pw = min(
                    open_pws,
                    key=lambda w: (w.estimate_ms(len(spec.prompt)),
                                   w.queue_tokens, w.n_queued),
                )
                pw.submit(
                    spec.prompt, spec.max_new_tokens, frid=frid,
                    key_rid=frid, submitted_at=spec.submitted_at,
                    trace=(spec.trace.child("prefill_dispatch")
                           if spec.trace else None),
                )
                # queue stage ends here: the LAST dispatch wins after a
                # requeue, so a retry's stage split reflects the run that
                # actually finished (e2e always counts from first submit)
                self._stage_marks.setdefault(frid, {})["dispatched"] = (
                    time.monotonic()
                )
                backlog.popleft()
                self._prefill_at[frid] = pw

    def decode_cost_s(self, dw) -> float:
        """Per-token cost estimate for one decode worker — the TPOT cost
        model the dispatch order uses. An acceptance-aware prediction
        wins when the worker speculates and its EWMAs are warm
        (``ContinuousBatcher.predicted_tpot_s``: measured verify-tick
        wall over measured committed-tokens-per-tick — a worker whose
        drafts stop landing gets expensive BEFORE harvested TPOT catches
        up); otherwise the harvested per-worker TPOT EWMA."""
        predict = getattr(dw, "predicted_tpot_s", None)
        p = predict() if callable(predict) else None
        if p is not None:
            return p
        return self._tpot_by_worker.get(id(dw), 0.0)

    def _route_handoff(self, h) -> bool:
        """Place one (already-transported) handoff on the decode worker
        with the smallest (load, TPOT cost estimate); returns False when
        every worker is at its inject cap (the handoff waits in
        ``_ready``). Caps are checked before injecting — the worker's own
        QueueFull path counts a SHED, and a handoff that merely waits
        another tick was never shed."""
        order = sorted(
            self.decode_workers,
            key=lambda w: (
                w.n_active + w.n_queued + w.n_pending + w.n_injected,
                self.decode_cost_s(w),
            ),
        )
        for dw in order:
            if dw.max_queue and dw.n_injected >= dw.max_queue:
                continue
            if h.page_size is not None:
                lrid = dw.inject(
                    h.prompt, h.max_new_tokens, logits_row=h.logits,
                    key_rid=h.key_rid, submitted_at=h.submitted_at,
                    kv_pages=h.cache1, page_size=h.page_size,
                    prefix_rows=h.prefix_rows, trace_id=h.trace_id,
                )
            else:
                lrid = dw.inject(
                    h.prompt, h.max_new_tokens, h.cache1, h.logits,
                    key_rid=h.key_rid, submitted_at=h.submitted_at,
                    trace_id=h.trace_id,
                )
            self._local[(id(dw), lrid)] = h.frid
            self._decode_at[h.frid] = (dw, lrid)
            self._prefill_done_at[h.frid] = h.prefill_done_at
            marks = self._stage_marks.setdefault(h.frid, {})
            marks["prefill_done"] = h.prefill_done_at
            marks["injected"] = time.monotonic()
            self.n_handoffs_routed += 1
            return True
        return False

    def _harvest(self, dw) -> None:
        for lrid, req in dw.collect_requests().items():
            frid = self._local.pop((id(dw), lrid), None)
            if frid is None:
                continue
            self._decode_at.pop(frid, None)
            spec = self._spec.pop(frid, None)
            self._results[frid] = req.tokens
            done_at = self._prefill_done_at.pop(frid, None)
            ctx = self._trace.pop(frid, None)
            marks = self._stage_marks.pop(frid, {})
            retries = self._retries.pop(frid, 0)
            if req.first_token_at is None:
                continue
            ttft = req.first_token_at - req.submitted_at
            self.ttft_ewma_s = (
                ttft if self.ttft_ewma_s is None
                else 0.8 * self.ttft_ewma_s + 0.2 * ttft
            )
            tpot = None
            e2e = None
            if len(req.tokens) > 1 and req.finished_at is not None:
                tpot = (req.finished_at - req.first_token_at) / (
                    len(req.tokens) - 1
                )
            if req.finished_at is not None:
                e2e = req.finished_at - req.submitted_at
                if len(self.latency_samples) == self._sample_cap:
                    self.dropped_samples += 1
                    if self._obs.enabled:
                        self._obs.counter(
                            "serving_samples_dropped_total",
                            "per-request samples evicted by the bounded "
                            "buffer", labels=("replica", "role"),
                        ).inc(replica=self.obs_replica, role=self.obs_role)
                self.latency_samples.append((ttft, tpot, e2e))
            if done_at is not None:
                wait = max(req.first_token_at - done_at, 0.0)
                self.decode_wait_ewma_s = (
                    wait if self.decode_wait_ewma_s is None
                    else 0.8 * self.decode_wait_ewma_s + 0.2 * wait
                )
            if tpot is not None:
                self.tpot_ewma_s = (
                    tpot if self.tpot_ewma_s is None
                    else 0.8 * self.tpot_ewma_s + 0.2 * tpot
                )
                prev = self._tpot_by_worker.get(id(dw))
                self._tpot_by_worker[id(dw)] = (
                    tpot if prev is None else 0.8 * prev + 0.2 * tpot
                )
                if self._obs.enabled:
                    self._obs.histogram(
                        "serving_tpot_ms", "per-token decode latency",
                        labels=("replica", "role"),
                    ).observe(tpot * 1e3,
                              exemplar=ctx.trace_id if ctx else None,
                              replica=dw.obs_replica, role=dw.obs_role)
            if self._obs.enabled:
                self._obs.histogram(
                    "serving_ttft_ms", "end-to-end time to first token",
                    labels=("replica", "role"),
                ).observe(ttft * 1e3,
                          exemplar=ctx.trace_id if ctx else None,
                          replica=self.obs_replica, role=self.obs_role)
            self._account_retired(frid, req, spec, ctx, marks, retries,
                                  ttft, tpot, e2e)

    def _account_retired(self, frid, req, spec, ctx, marks, retries,
                         ttft, tpot, e2e) -> None:
        """SLO + stage accounting for one retired request: split TTFT into
        queue / prefill / handoff / first-decode from the stage marks,
        feed the class's SLI windows (``obs/slo.py``), and append the
        bounded request record the chaos verdicts and tail-attribution
        report read."""
        slo_name = spec.slo if spec is not None else "default"
        stages = {}
        t_sub = req.submitted_at
        dispatched = marks.get("dispatched")
        prefill_done = marks.get("prefill_done")
        injected = marks.get("injected")
        if dispatched is not None:
            stages["queue"] = max(dispatched - t_sub, 0.0)
        if prefill_done is not None and dispatched is not None:
            stages["prefill"] = max(prefill_done - dispatched, 0.0)
        if injected is not None and prefill_done is not None:
            stages["handoff"] = max(injected - prefill_done, 0.0)
        if injected is not None and req.first_token_at is not None:
            stages["first_decode"] = max(req.first_token_at - injected, 0.0)
        if req.finished_at is not None and req.first_token_at is not None:
            stages["decode"] = req.finished_at - req.first_token_at
        if slo_name in self.slo.specs:
            self.slo.record(
                slo_name,
                ttft_ms=ttft * 1e3,
                tpot_ms=None if tpot is None else tpot * 1e3,
                e2e_ms=None if e2e is None else e2e * 1e3,
                trace_id=ctx.trace_id if ctx else None,
                stages=stages,
            )
        record = {
            "frid": frid,
            "slo": slo_name,
            "trace_id": ctx.trace_id if ctx else None,
            "retries": retries,
            "ttft_s": ttft,
            "tpot_s": tpot,
            "e2e_s": e2e,
            "finished_mono": req.finished_at,
            "stages_s": stages,
        }
        self.request_records[frid] = record
        self._record_order.append(frid)
        while len(self._record_order) > self._sample_cap:
            self.request_records.pop(self._record_order.popleft(), None)

    def tick(self) -> None:
        """One fleet pass: retry waiting handoffs → dispatch backlog →
        step prefill workers (routing fresh handoffs) → step decode
        workers → harvest."""
        while self._ready:
            if not self._route_handoff(self._ready[0]):
                break
            self._ready.popleft()
        self._dispatch_prefill()
        for pw in self.prefill_workers:
            for h in pw.step():
                self._prefill_at.pop(h.frid, None)
                if self.transport is not None:
                    # the wire hop runs ONCE per handoff, here — a handoff
                    # parked in _ready must not re-pay encode+CRC+decode
                    # on every placement retry. A FAILED hop (CRC abort,
                    # dead stream, donor loss) is the documented
                    # re-prefill case: the handoff is reproducible from
                    # the prompt, so the request goes back to the backlog
                    # front instead of crashing the fleet or stranding
                    try:
                        h = self.transport(h)
                    except Exception as e:  # noqa: BLE001 — wire boundary
                        self.transport_failures += 1
                        self._respool(h.frid)
                        log.warning(
                            "handoff transport failed for frid %d; "
                            "re-prefilling: %r", h.frid, e,
                        )
                        if self._obs.enabled:
                            flight_recorder.record(
                                "serving_handoff_transport_failure",
                                frid=h.frid, error=repr(e)[:120],
                            )
                        continue
                if not self._route_handoff(h):
                    self._ready.append(h)
        for dw in self.decode_workers:
            if (dw.n_active or dw.n_queued or dw.n_pending or dw.n_injected
                    or dw.n_preempted):
                dw.step()
                self._harvest(dw)
        if self._obs.enabled:
            self._obs.gauge(
                "serving_queue_depth", "requests waiting for a slot",
                labels=("replica", "role"),
            ).set(
                sum(len(b) for b in self._backlog.values()) + len(self._ready),
                replica=self.obs_replica, role=self.obs_role,
            )

    def decode_gaps(self) -> list[float]:
        """All decode workers' inter-emission gap samples (seconds),
        pooled — with ``decode_quantum=1`` these ARE per-token decode
        latencies, the burst-isolation headline's raw data: a monolithic
        batcher's gaps stretch while prefill chunks share its ticks; a
        disaggregated decode worker's do not."""
        out: list[float] = []
        for dw in self.decode_workers:
            out.extend(dw._gaps)
        return out

    def reset_latency_stats(self) -> None:
        self.latency_samples.clear()
        for dw in self.decode_workers:
            dw.reset_latency_stats()

    def reset_request_records(self) -> None:
        """Drop the retired-request ledger (and its eviction order —
        clearing only the dict would desync the bound). Warm-up
        isolation alongside :meth:`reset_latency_stats` + ``slo.reset()``."""
        self.request_records.clear()
        self._record_order.clear()

    def run(self, max_ticks: int = 100_000) -> dict[int, list]:
        """Drain everything; returns {frid: [tokens]} for every request
        finished during (or before) this call."""
        for _ in range(max_ticks):
            if not self.outstanding:
                break
            self.tick()
        else:
            raise RuntimeError(f"fleet did not drain within {max_ticks} ticks")
        out = dict(self._results)
        self._results.clear()
        return out

    # ---- chaos hooks -----------------------------------------------------

    def _respool(self, frid: int) -> None:
        spec = self._spec.get(frid)
        if spec is None:
            return
        self._backlog[spec.slo].appendleft(frid)  # it has waited longest
        # the retry keeps the SAME trace (same request, same error-budget
        # clock: submitted_at is untouched, so the eventual SLI burn
        # counts the FULL user-visible latency, kill included) — the
        # retry span marks the requeue on the request's causal chain
        self._retries[frid] = self._retries.get(frid, 0) + 1
        now = time.monotonic()
        self.requeue_log.append((frid, now))
        if len(self.requeue_log) > self._sample_cap:
            del self.requeue_log[: len(self.requeue_log) - self._sample_cap]
        ctx = self._trace.get(frid)
        if ctx is not None and self._obs.enabled:
            tracer = get_tracer()
            with tracer.request_span(
                "serving_request_retry", ctx, frid=frid,
                outcome="requeued", retries=self._retries[frid],
            ):
                tracer.flow("serving_request_retry", ctx, phase="step",
                            outcome="requeued")

    def kill_prefill_worker(self, idx: int | None = None) -> int:
        """Chaos hook: drop a prefill worker (default: the last). Its
        unfinished jobs — queued and MID-CHUNK — re-enter the backlog at
        the front and re-prefill on a survivor; identical rows, identical
        tokens. Returns the requeue count."""
        if len(self.prefill_workers) <= 1:
            raise RuntimeError("cannot kill the last prefill worker")
        pw = self.prefill_workers.pop(
            idx if idx is not None else len(self.prefill_workers) - 1
        )
        requeued = 0
        # abandon() lists oldest first; appendleft-ing in REVERSE keeps
        # the longest-waiting job at the backlog head (the same rule as
        # kill_decode_worker's)
        for spec in reversed(pw.abandon()):
            frid = spec["frid"]
            self._prefill_at.pop(frid, None)
            self._respool(frid)
            requeued += 1
        self.requeued_prefill += requeued
        if self._obs.enabled:
            flight_recorder.record(
                "serving_prefill_worker_lost", requeued=requeued,
                survivors=len(self.prefill_workers),
            )
        return requeued

    def kill_decode_worker(self, idx: int | None = None) -> int:
        """Chaos hook: drop a decode worker. Finished-but-uncollected
        results are harvested first; unfinished requests (injected queue,
        mid-decode) re-enter the backlog and run the FULL pipeline again —
        re-prefill on a prefill worker, handoff, decode on a survivor.
        Greedy decode makes the re-run bit-identical. Returns the requeue
        count."""
        if len(self.decode_workers) <= 1:
            raise RuntimeError("cannot kill the last decode worker")
        dw = self.decode_workers.pop(
            idx if idx is not None else len(self.decode_workers) - 1
        )
        self._harvest(dw)
        requeued = 0
        for req in reversed(dw.abandon()):
            frid = self._local.pop((id(dw), req.rid), None)
            if frid is None:
                continue
            self._decode_at.pop(frid, None)
            self._prefill_done_at.pop(frid, None)
            self._respool(frid)
            requeued += 1
        self.requeued_decode += requeued
        self._tpot_by_worker.pop(id(dw), None)
        if self._obs.enabled:
            flight_recorder.record(
                "serving_decode_worker_lost", requeued=requeued,
                survivors=len(self.decode_workers),
            )
        return requeued


def build_fleet(
    model,
    params,
    n_prefill: int = 1,
    n_decode: int = 1,
    prefill_chunk: int = 64,
    slo_classes=None,
    transport=None,
    devices=None,
    prefill_max_queue: int = 0,
    paged_kv=False,
    page_size: int = 16,
    prefill_n_pages: int = 0,
    **decode_kwargs,
) -> Router:
    """Assemble a disaggregated fleet: ``n_prefill`` chunked prefill
    workers + ``n_decode`` decode batchers behind a :class:`Router`.
    ``devices`` (optional) assigns each decode worker an equal slice via
    ``ContinuousBatcher.for_devices`` — the fleet's chip budget; prefill
    workers run on the default device. ``decode_kwargs`` go to each
    decode batcher (``n_slots``, ``max_queue``, ``temperature``/``seed``,
    ...). Decode workers keep ``prefill_chunk=0`` — admission arrives
    prefilled by construction. ``paged_kv`` builds a PAGED fleet (int4
    page pools everywhere, paged handoffs, decode-side CoW prefixes —
    docs/SERVING.md § Paged KV): ``page_size`` is fleet-wide,
    ``prefill_n_pages`` sizes the prefill pools, and decode pool sizes
    ride ``decode_kwargs['n_pages']``. Paged composes with ``devices``:
    a multi-chip decode worker shards its page pool's HEAD axis over tp
    (``ContinuousBatcher.for_devices``), so every chip carries 1/tp of
    each page — the capacity win lands per chip, tokens identical to a
    single-device paged worker (pinned in tests)."""
    prefill_workers = [
        PrefillWorker(model, params, prefill_chunk,
                      max_queue=prefill_max_queue, paged_kv=paged_kv,
                      page_size=page_size, n_pages=prefill_n_pages)
        for _ in range(n_prefill)
    ]
    if paged_kv:
        decode_kwargs.setdefault("paged_kv", paged_kv)
        decode_kwargs.setdefault("page_size", page_size)
    if devices is not None:
        devices = list(devices)
        per = len(devices) // n_decode
        if per < 1:
            raise ValueError(
                f"{len(devices)} device(s) cannot back {n_decode} decode "
                "worker(s)"
            )
        decode_workers = [
            ContinuousBatcher.for_devices(
                model, params, devices[i * per : (i + 1) * per],
                **decode_kwargs,
            )
            for i in range(n_decode)
        ]
    else:
        decode_workers = [
            ContinuousBatcher(model, params, **decode_kwargs)
            for _ in range(n_decode)
        ]
    return Router(prefill_workers, decode_workers,
                  slo_classes=slo_classes, transport=transport)
