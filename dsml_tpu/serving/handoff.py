"""KV-cache handoff: moving a prefilled request to a decode worker.

The disaggregated fleet's one new data-plane object: a :class:`Handoff`
carries everything a decode worker needs to continue a request whose
prefill ran elsewhere — the prompt's 1-row KV cache, the next-token
logits at the last prompt position, and the request's identity/timing.
Three transports, cheapest first:

- **In-process** (workers share a host): the ``Handoff`` object itself is
  the transfer — the decode worker's insert DONATES the cache buffers
  (``ContinuousBatcher.inject``), so the rows move by ownership, not copy.
- **CRC-framed byte codec** (:func:`encode_handoff`/:func:`decode_handoff`):
  the cache leaves and logits serialize into one contiguous payload framed
  exactly like the migration stream path — ``MIGRATE_CHUNK``-sized frames,
  CRC32C per frame (``comm.migration.payload_chunk_crcs``) — so "one
  corrupt chunk" maps to one failed frame and a mismatch aborts the
  handoff (:class:`HandoffIntegrityError`) before any byte reaches a
  cache. :func:`frame_transport` round-trips a handoff through this codec
  with validation on — the in-process stand-in for a wire hop that tests
  and the bench use to pin bit-identity THROUGH the framing.
- **Hardened P2P streams** (:func:`register_with_donor` /
  :func:`fetch_from_migrator`): cross-host handoff rides the SAME
  machinery as elastic shard migration — the prefill host registers the
  handoff's arrays with its device server's ``StateDonor``; the decode
  host pulls them with a ``ShardMigrator`` (``BeginSend``/``StreamSend``
  under per-frame CRC32C, resumable offsets, bounded-backoff retries,
  donor-death fallback). A failed fetch raises ``MigrationError`` and the
  router re-prefills on a survivor — the handoff is always reproducible
  from the prompt, so stream loss costs latency, never tokens.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = [
    "HANDOFF_SCHEMA",
    "Handoff",
    "HandoffIntegrityError",
    "decode_handoff",
    "encode_handoff",
    "fetch_from_migrator",
    "frame_transport",
    "register_with_donor",
]

HANDOFF_SCHEMA = "dsml.serving.handoff/1"


class HandoffIntegrityError(RuntimeError):
    """The handoff payload failed CRC32C frame validation (or its sizes
    disagree with the header). The contract mirrors the migration path's:
    corrupted rows NEVER land in a decode cache — the caller re-fetches or
    re-prefills from the prompt (which reproduces identical rows)."""


@dataclasses.dataclass
class Handoff:
    """One prefilled request in flight between worker roles.

    ``cache1`` is the per-layer 1-row KV cache (``model.init_cache(1)``
    layout — plain k/v or quantized k/k_s/v/v_s entries ride the same
    field), filled for positions ``[0, prefill_len)``. ``logits`` is the
    last prompt position's next-token row; the decode worker samples the
    first token from it under the (seed, ``key_rid``, step) fold.
    ``submitted_at``/``prefill_done_at`` are ``time.monotonic`` marks the
    router uses for true end-to-end TTFT and for splitting prefill wait
    from decode wait in its load estimates."""

    frid: int
    prompt: np.ndarray          # [L] int32
    max_new_tokens: int
    prefill_len: int
    cache1: list                # per-layer {entry: array [1, H, max_seq, ·]}
    logits: np.ndarray          # [vocab]
    submitted_at: float = 0.0
    prefill_done_at: float = 0.0
    key_rid: int | None = None
    # PAGED handoff (docs/SERVING.md § Paged KV): when ``page_size`` is
    # set, ``cache1`` holds the shipped PAGES instead — per-layer dicts
    # with a leading shipped-page axis [n_ship, H, page_size, ·] in the
    # decode pool's own (quantized) entry layout, so the wire carries
    # int4 pages (~8x fewer bytes than dense f32 rows) and the decode
    # worker installs them verbatim. ``prefix_rows`` leading rows are NOT
    # shipped: the decode worker shares its own registered prefix pages
    # for them (the fleet-level CoW elision; always a page multiple).
    page_size: int | None = None
    prefix_rows: int = 0
    # request-scoped trace identity (obs.TraceContext): minted at
    # Router.submit, stamped by the prefill worker, carried through BOTH
    # wire paths (codec header + donor descriptor header) so the decode
    # host's spans join the same causal chain. ``parent_span`` names the
    # emitting stage's span — the cross-process parent link.
    trace_id: str | None = None
    parent_span: str = ""


def _leaves(cache1) -> list:
    """Deterministic leaf order — (layer index, sorted entry keys) — so
    encoder and decoder (and the donor/migrator key scheme) agree on the
    payload layout without any negotiation."""
    out = []
    for i, layer in enumerate(cache1):
        for key in sorted(layer):
            out.append((i, key, layer[key]))
    return out


def _host(arr) -> np.ndarray:
    # device arrays pull to host once here; numpy passes through
    return np.ascontiguousarray(np.asarray(arr))


def encode_handoff(handoff: Handoff) -> dict:
    """Serialize a handoff into ``{"header", "payload", "chunk_crcs"}``:
    one contiguous byte payload (cache leaves in :func:`_leaves` order,
    logits last) plus the CRC32C frame table at ``MIGRATE_CHUNK``
    granularity. The header is JSON-able — a wire implementation ships it
    over its control channel and the payload over the data plane."""
    # imported here, not at module top: the comm stack (grpc) must not
    # ride along with `from dsml_tpu.serving import ContinuousBatcher`
    from dsml_tpu.comm.migration import payload_chunk_crcs

    parts, leaves = [], []
    for i, key, arr in _leaves(handoff.cache1):
        a = _host(arr)
        parts.append(a.tobytes())
        leaves.append({
            "layer": i, "entry": key, "dtype": str(a.dtype),
            "shape": list(a.shape), "nbytes": len(parts[-1]),
        })
    logits = _host(handoff.logits).astype(np.float32, copy=False)
    parts.append(logits.tobytes())
    payload = b"".join(parts)
    header = {
        "schema": HANDOFF_SCHEMA,
        "frid": int(handoff.frid),
        "key_rid": None if handoff.key_rid is None else int(handoff.key_rid),
        "prompt": [int(t) for t in handoff.prompt],
        "max_new_tokens": int(handoff.max_new_tokens),
        "prefill_len": int(handoff.prefill_len),
        "submitted_at": float(handoff.submitted_at),
        "prefill_done_at": float(handoff.prefill_done_at),
        "n_layers": len(handoff.cache1),
        "page_size": handoff.page_size,
        "prefix_rows": int(handoff.prefix_rows),
        "trace_id": handoff.trace_id,
        "parent_span": handoff.parent_span,
        "leaves": leaves,
        "logits_nbytes": len(parts[-1]),
        "total_nbytes": len(payload),
    }
    return {"header": header, "payload": payload,
            "chunk_crcs": payload_chunk_crcs(payload)}


def decode_handoff(frame: dict, validate: bool = True) -> Handoff:
    """Reconstruct a :class:`Handoff` from :func:`encode_handoff` output,
    validating every CRC32C frame first (``validate=False`` skips only the
    CRC pass — sizes are always checked). Cache leaves come back as host
    numpy; ``ContinuousBatcher.inject`` re-places them on device."""
    from dsml_tpu.comm.migration import payload_chunk_crcs

    header, payload = frame["header"], frame["payload"]
    if header.get("schema") != HANDOFF_SCHEMA:
        raise HandoffIntegrityError(
            f"unknown handoff schema {header.get('schema')!r}"
        )
    if len(payload) != int(header["total_nbytes"]):
        raise HandoffIntegrityError(
            f"payload is {len(payload)} bytes, header says "
            f"{header['total_nbytes']}"
        )
    if validate:
        got = payload_chunk_crcs(payload)
        want = list(frame["chunk_crcs"])
        bad = [i for i, (a, b) in enumerate(zip(got, want)) if a != b]
        if len(got) != len(want) or bad:
            raise HandoffIntegrityError(
                f"CRC32C mismatch on handoff frid={header['frid']}: "
                f"frame(s) {bad[:8]} of {len(got)} failed validation"
            )
    cache1: list = [{} for _ in range(int(header["n_layers"]))]
    off = 0
    for leaf in header["leaves"]:
        n = int(leaf["nbytes"])
        arr = np.frombuffer(
            payload[off : off + n], dtype=np.dtype(leaf["dtype"])
        ).reshape(leaf["shape"])
        cache1[int(leaf["layer"])][leaf["entry"]] = arr
        off += n
    logits = np.frombuffer(
        payload[off : off + int(header["logits_nbytes"])], dtype=np.float32
    )
    return Handoff(
        frid=int(header["frid"]),
        prompt=np.asarray(header["prompt"], np.int32),
        max_new_tokens=int(header["max_new_tokens"]),
        prefill_len=int(header["prefill_len"]),
        cache1=cache1,
        logits=logits,
        submitted_at=float(header["submitted_at"]),
        prefill_done_at=float(header["prefill_done_at"]),
        key_rid=header.get("key_rid"),
        page_size=header.get("page_size"),
        prefix_rows=int(header.get("prefix_rows", 0)),
        trace_id=header.get("trace_id"),
        parent_span=str(header.get("parent_span") or ""),
    )


def frame_transport(handoff: Handoff) -> Handoff:
    """Round-trip a handoff through the CRC-framed codec with validation —
    the transport the router uses to prove (and tests to pin) that the
    wire framing itself never perturbs tokens. A real deployment replaces
    this hop with the donor/migrator stream pull below."""
    return decode_handoff(encode_handoff(handoff))


# ---------------------------------------------------------------------------
# cross-host: the hardened StateDonor / ShardMigrator stream path
# ---------------------------------------------------------------------------


def register_with_donor(donor, handoff: Handoff, prefix: str | None = None) -> dict:
    """Publish a handoff on the prefill host's device server: every cache
    leaf (and the logits row) registers with the server's ``StateDonor``
    under ``<prefix>/<layer>/<entry>``, and the returned DESCRIPTOR — the
    codec header plus the key prefix, no payload — travels to the decode
    host over any control channel. The payload bytes then move via
    ``BeginSend``/``StreamSend`` when the decode host pulls
    (:func:`fetch_from_migrator`). Call ``donor.unregister(prefix)`` once
    the pull completes — handoffs are per-request transients and must not
    grow the donor table."""
    prefix = prefix if prefix is not None else f"handoff/{int(handoff.frid)}"
    # the header is built directly from the leaf metadata — the stream
    # path never needs the codec's contiguous payload (the donor frames +
    # CRCs each leaf itself at BeginSend), so serializing it here would be
    # a wasted full-cache copy + CRC pass per handoff
    leaves, total = [], 0
    for i, key, arr in _leaves(handoff.cache1):
        a = _host(arr)
        donor.register_array(f"{prefix}/{i}/{key}", a,
                             trace_id=handoff.trace_id)
        leaves.append({
            "layer": i, "entry": key, "dtype": str(a.dtype),
            "shape": list(a.shape), "nbytes": int(a.nbytes),
        })
        total += int(a.nbytes)
    logits = _host(handoff.logits).astype(np.float32, copy=False)
    donor.register_array(f"{prefix}/logits", logits,
                         trace_id=handoff.trace_id)
    header = {
        "schema": HANDOFF_SCHEMA,
        "frid": int(handoff.frid),
        "key_rid": None if handoff.key_rid is None else int(handoff.key_rid),
        "prompt": [int(t) for t in handoff.prompt],
        "max_new_tokens": int(handoff.max_new_tokens),
        "prefill_len": int(handoff.prefill_len),
        "submitted_at": float(handoff.submitted_at),
        "prefill_done_at": float(handoff.prefill_done_at),
        "n_layers": len(handoff.cache1),
        "page_size": handoff.page_size,
        "prefix_rows": int(handoff.prefix_rows),
        "trace_id": handoff.trace_id,
        "parent_span": handoff.parent_span,
        "leaves": leaves,
        "logits_nbytes": int(logits.nbytes),
        "total_nbytes": total + int(logits.nbytes),
    }
    return {"prefix": prefix, "header": header}


def fetch_from_migrator(migrator, descriptor: dict) -> Handoff:
    """Pull a published handoff over the hardened P2P streams: one
    ``ShardMigrator.fetch_piece`` per leaf (whole-array pieces), each
    delivery CRC32C-validated frame-by-frame with resumable offsets and
    donor-death retries — the exact machinery elastic shard migration
    rides. Raises ``comm.migration.MigrationError`` when a leaf cannot be
    delivered; the router's contract is then re-prefill on a survivor."""
    from dsml_tpu.obs import TraceContext, get_tracer

    header = descriptor["header"]
    prefix = descriptor["prefix"]
    ctx = TraceContext.from_header(header)
    cache1: list = [{} for _ in range(int(header["n_layers"]))]
    # the pull is the cross-host hop — a trace-tagged span (+ flow step)
    # on the DECODE host's timeline, so the stitched view shows the wire
    # time between the prefill host's handoff span and decode admission
    with get_tracer().request_span(
        "handoff_pull", ctx, flow="step", frid=int(header["frid"]),
        nbytes=int(header["total_nbytes"]),
    ):
        for leaf in header["leaves"]:
            piece = [[0, int(s)] for s in leaf["shape"]]
            arr = migrator.fetch_piece(
                f"{prefix}/{leaf['layer']}/{leaf['entry']}", piece,
                leaf["dtype"], trace_id=header.get("trace_id"),
            )
            cache1[int(leaf["layer"])][leaf["entry"]] = arr
        vocab = int(header["logits_nbytes"]) // np.dtype(np.float32).itemsize
        logits = migrator.fetch_piece(
            f"{prefix}/logits", [[0, vocab]], "float32",
            trace_id=header.get("trace_id"),
        ).reshape(-1)
    return Handoff(
        frid=int(header["frid"]),
        prompt=np.asarray(header["prompt"], np.int32),
        max_new_tokens=int(header["max_new_tokens"]),
        prefill_len=int(header["prefill_len"]),
        cache1=cache1,
        logits=logits,
        submitted_at=float(header["submitted_at"]),
        prefill_done_at=float(header["prefill_done_at"]),
        key_rid=header.get("key_rid"),
        page_size=header.get("page_size"),
        prefix_rows=int(header.get("prefix_rows", 0)),
        trace_id=header.get("trace_id"),
        parent_span=str(header.get("parent_span") or ""),
    )
