"""Continuous-batching serving — slot-based decode with in-flight admission.

The reference has no inference path at all (SURVEY.md §5; its client only
trains, ``client.go:516-659``); the framework's serving stack already does
static batched decode (``GPT2.generate``/``generate_spmd``). This module
adds the throughput layer a real serving deployment needs: requests arrive
at different times with different prompt/output lengths, and a static
batch would idle every slot until the LONGEST request finishes. Continuous
batching (the vLLM/Orca scheduling idea) retires each request the moment
it completes and admits a queued one into the freed slot — realized here
TPU-first:

- ONE jitted decode program for all slots (``model.decode_step_slots``):
  fully static shapes, per-slot depths carried as a ``pos`` vector, cache
  writes as a batched scatter, attention masked to ``s <= pos[b]`` per
  row. No recompilation ever happens at steady state.
- Prefill compiles once per PROMPT BUCKET (next power-of-two length):
  prompts are right-padded to the bucket, the logits read at the true
  last index (``prefill(last_index=L-1)``), and the new request's cache
  rows are scattered into its slot.
- The host-side scheduler is a plain loop: admit → decode → emit/retire.
  Sampling is greedy or temperature-based with a per-request key, so a
  request's tokens are independent of which slot/step served it.

Single-device by design (the TP/DP-sharded decode lives in
``generate_spmd``); slots × continuous admission is the axis this module
adds.

This module is also the DECODE WORKER of the disaggregated serving fleet
(``dsml_tpu.serving.router``): :meth:`ContinuousBatcher.inject` admits a
request whose prefill already ran on a PREFILL worker — the handed-off KV
rows scatter into a slot exactly like a local admission's, and the first
token samples from the handed-off logits with the identical
(seed, key_rid, step) PRNG fold, so disaggregation never changes tokens
(pinned in tests).

``paged_kv`` replaces the dense per-slot cache with a PAGED one: a pool
of fixed-size token pages (int4 block-quantized by default), a per-slot
page table the attention gathers through, and a host-side refcounting
allocator (``serving.paging``) — so a chip's HBM pays for the rows
requests actually hold instead of ``n_slots × max_seq`` dense rows, and
registered prefixes become COPY-ON-WRITE page-table entries shared
read-only across every matching request (docs/SERVING.md § Paged KV).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from dsml_tpu.obs import get_registry

__all__ = ["Request", "ContinuousBatcher", "QueueFull"]


class QueueFull(RuntimeError):
    """``submit`` rejected by the queue cap (``max_queue``): the batcher
    sheds load explicitly instead of letting an unbounded queue grow until
    every request's latency is unbounded too. Counted in
    ``serving_shed_total``; callers (routers, the ``DecodeFleet``) retry
    elsewhere or surface backpressure upstream."""


@dataclasses.dataclass
class Request:
    rid: int
    prompt: np.ndarray  # [L] int32
    max_new_tokens: int
    tokens: list = dataclasses.field(default_factory=list)  # emitted so far
    done: bool = False
    # wall-clock marks for the serving latency metrics (time.monotonic)
    submitted_at: float = 0.0
    first_token_at: float | None = None
    finished_at: float | None = None
    last_emit_at: float | None = None
    # sampler identity override: the PRNG key folds (seed, key_rid, step)
    # instead of the LOCAL rid — how a fleet keeps sampled tokens identical
    # to a reference batcher whose rids differ from this replica's (the
    # router stamps its fleet-wide rid here; None = use ``rid``)
    key_rid: int | None = None
    # request-scoped trace identity (obs.TraceContext.trace_id): stamped
    # by the router at submit and carried through the handoff wire — the
    # decode-side spans/flow events and the admission-histogram exemplar
    # all tag with it, so a tail latency resolves to ONE request's trace
    trace_id: str | None = None
    # preemption priority (paged ``preemption=True`` only): under page
    # pressure the LOWEST-priority active slot is evicted first (ties
    # break youngest-first, so FIFO order degrades last). Pure
    # scheduling — tokens never depend on it.
    priority: int = 0

    def trace_ctx(self):
        """The request's TraceContext (flow id derives from trace_id
        alone, so the decode side rebuilds it without extra wire state);
        None when the request carries no trace."""
        if self.trace_id is None:
            return None
        from dsml_tpu.obs import TraceContext

        return TraceContext(trace_id=self.trace_id)


def _bucket(n: int, buckets: tuple) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest bucket {buckets[-1]}")


# the host-side prompt-lookup draft rule lives with its device twin in
# models/speculative.py — ONE drafting rule for the standalone speculator
# and the batcher's speculative tick (equivalence pinned in tests)
from dsml_tpu.models.speculative import lookup_draft_host as _lookup_draft


class ContinuousBatcher:
    """Slot-based continuous-batching decoder over one model + params.

    ``submit`` enqueues prompts; ``step`` admits queued requests into free
    slots (bucketed prefill), runs one decode QUANTUM, emits new tokens,
    and retires finished requests (EOS or token budget). ``run`` drains
    everything. Greedy by default; ``temperature > 0`` samples with a
    per-(request, step) fold of ``seed`` so results don't depend on slot
    timing.

    ``decode_quantum`` — tokens decoded per scheduler tick, chained inside
    ONE jitted ``lax.scan`` (sampling included). 1 = retire/admit at every
    token (max lane utilization). Each tick costs one host↔device round
    trip, which on a tunneled TPU (~100 ms RTT) or any small model dwarfs
    the step compute — a quantum of k amortizes that k× at the cost of up
    to k−1 wasted lane-ticks when a request finishes mid-quantum
    (iteration-level vs token-level scheduling, the Orca trade-off).
    Tokens are IDENTICAL for any quantum; only throughput changes.

    ``prefill_chunk`` — when > 0, admission prefills prompts in chunks of
    that many tokens via ``model.prefill_chunk``, running at most one
    chunk per scheduler tick once a long admission is in flight: decode
    quanta continue BETWEEN a long prompt's chunks instead of every
    active slot stalling for the whole prefill (the head-of-line problem
    of whole-prompt admission; Orca/vLLM chunked prefill). Tokens are
    identical either way (chunk chaining == whole-prompt prefill — pinned
    in tests; with ``kv_quant`` the chunk path reads int8 cache rows for
    within-prompt attention, the standard chunked-prefill approximation).
    0 (default) keeps whole-prompt bucketed admission.

    ``register_prefix(tokens)`` — PREFIX CACHING for shared prompt heads
    (the system-prompt pattern): the prefix's cache rows and next-token
    logits are computed once; any later prompt starting with a registered
    prefix admits by COPYING those rows and chunk-prefilling only the
    suffix, cutting admission prefill from O(L) to O(L - P) (the TTFT
    win). Requires ``prefill_chunk > 0`` (the suffix rides the chunk
    path); the longest matching prefix is used; tokens are identical with
    or without the cache (prefix rows attend only within the prefix under
    causality, so they equal the full prefill's — pinned in tests).

    ``turbo_factor`` — when >= 2, a SECOND decode program with quantum
    ``decode_quantum * turbo_factor`` is compiled, and a scheduler tick
    escalates to it whenever the batcher is in steady-state decode: the
    queue is empty, no chunked admission is mid-flight, and at least one
    active request has the full turbo quantum's budget remaining (a slot
    that finishes mid-tick would have idled under plain ticks too — the
    queue is empty — so escalation wastes nothing a plain schedule would
    have used; an EOS or budget hit mid-turbo retires the slot and
    discards the tail, exactly as a plain quantum does). Dispatch cost
    drops ``turbo_factor``× in steady state while admission latency keeps
    the BASE quantum's granularity — the adaptive answer to the per-tick
    host RTT that a fixed large quantum would buy only by slowing every
    admission. Tokens are IDENTICAL with turbo on or off (the sampler
    folds (request, absolute step) — pinned in tests). A request submitted
    DURING a turbo tick waits out that tick (the trade-off vs the base
    quantum's admission cadence) — so keep the turbo quantum
    (``decode_quantum * turbo_factor`` tokens × the per-token step time)
    within the deployment's TTFT budget, or use ``adaptive_quantum``,
    whose early exit removes the trade-off entirely.

    ``adaptive_quantum`` — when >= 2, each decode tick runs an EARLY-EXIT
    device loop (``lax.while_loop``) of up to that many steps that stops
    the moment ANY active slot finishes (EOS or token budget). This
    dissolves the fixed-quantum trade-off: a tick never decodes past a
    retirement (zero wasted lane-ticks), a freed slot admits on the very
    next tick (zero admission delay beyond one tick boundary), and in
    steady state one host dispatch carries up to ``adaptive_quantum``
    tokens per slot. Dispatch count collapses from O(tokens/quantum) to
    ~O(retirements + admissions) — the fix for a high per-dispatch host
    RTT (the axon tunnel's ~100 ms) that a fixed large quantum could only
    buy by delaying admissions and over-decoding retired slots. Works with
    greedy and temperature sampling; tokens are IDENTICAL to the plain
    batcher and to ``generate`` (same chain, sampler folds the absolute
    step — pinned in tests). While a chunked admission is mid-flight the
    scheduler drops back to plain ``decode_quantum`` ticks so prefill
    chunks keep interleaving with decode. Exclusive with ``turbo_factor``
    and ``speculative_window`` (each sets its own per-tick budget).

    ``speculative_window`` — when >= 2, each decode tick runs PROMPT-LOOKUP
    SPECULATIVE decoding across all slots: every active slot drafts
    window−1 tokens from the most recent n-gram match in its own history
    (host-side numpy — no device round trip), ONE ``model.verify_step``
    call scores every slot's window at its own depth, and each slot
    commits the longest draft prefix matching the model's greedy chain
    plus the model's own next token — 1..window tokens per tick per slot.
    Greedy only (``temperature`` must be 0) and exclusive with
    ``decode_quantum > 1`` (the window IS the quantum). Tokens are
    identical to the plain batcher and to ``generate`` (pinned in tests);
    rejected drafts leave garbage cache rows that the next verify window
    always overwrites before any query attends to them
    (``verify_step``'s invariant).

    ``speculative_adaptive`` — the verify-window width adapts per tick to
    the measured draft-acceptance EWMA (2..``speculative_window``), so a
    workload whose drafts stop landing stops paying wide-window verify
    FLOPs; greedy tokens are identical at any width (pinned in tests).
    The same EWMAs drive :meth:`predicted_tpot_s`, the router's
    acceptance-aware TPOT cost model.

    ``paged_kv`` — replace the dense per-slot cache with a page POOL:
    ``n_pages`` physical pages of ``page_size`` token rows (int4
    block-quantized with per-row scales by default; ``"int8"``/``False``
    for the wider codecs), a per-slot page table the attention gathers
    through, and a host-side refcounting allocator. Admission reserves
    every page a request can ever touch up front (no mid-flight
    preemption), ``register_prefix`` becomes a COPY-ON-WRITE page-table
    entry (matching requests share the prefix's full pages read-only; a
    straddling tail page is materialized privately only because the slot
    writes into it), and ``inject`` lands shipped PAGES plus local
    shared-prefix references. Requires chunked admission
    (``prefill_chunk > 0``) for local submits; single-device; greedy
    tokens are bit-identical to a dense batcher running the same KV
    codec (``kv_quant``), and the pool holds ~8× more rows per HBM byte
    than the dense f32 cache (docs/SERVING.md § Paged KV,
    docs/TUNING.md for sizing).
    """

    def __init__(
        self,
        model,
        params,
        n_slots: int = 8,
        eos_id: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
        prompt_buckets: tuple = (32, 64, 128, 256, 512, 1024),
        decode_quantum: int = 1,
        turbo_factor: int = 0,
        prefill_chunk: int = 0,
        speculative_window: int = 0,
        speculative_ngram: int = 2,
        speculative_adaptive: bool = False,
        adaptive_quantum: int = 0,
        max_queue: int = 0,
        mesh=None,
        paged_kv=False,
        page_size: int = 16,
        n_pages: int = 0,
        preemption: bool = False,
        preempt_policy: str = "auto",
        weight_quant: str | None = "env",
    ):
        """``mesh`` — a framework mesh (``parallel.mesh.build_mesh``) makes
        serving TENSOR-PARALLEL: params are Megatron-sharded
        (``model.param_specs()``), the slot cache's (or page pool's) head
        axis shards over 'tp', and prefill/decode run head-parallel under
        shard_map with the full logits row reconstructed for sampling —
        same tokens as the single-device batcher (tests pin it).

        ``weight_quant`` — serving weight codec for the dequant-fused
        matmul path: ``"env"`` (default) reads ``DSML_WEIGHT_QUANT``
        (off unless set), ``"int8"``/``"int4"`` block-quantize the
        transformer matmul weights (``models.common.
        quantize_weights_blocked``) so they sit in HBM at ~4×/~8×
        compression and dequantize one VMEM tile at a time inside the
        Pallas matmul; ``None``/"off" serves the params as given. The
        compressed bytes are claimed in the memory ledger under
        ``weights_quant``. Single-device replicas only (the TP shard_map
        path expects plain leaves matching ``param_specs``).

        ``preemption`` (paged only) — replace up-front worst-case page
        reservation with an eviction tier: admission reserves only the
        prompt chunk grid, decode GROWS the allocation page-by-page, and
        when growth finds the pool dry the lowest-priority active slot is
        preempted — its private pages swap to host (the handoff page
        payload layout) or drop for recompute-from-prompt per
        ``preempt_policy`` ("swap" | "recompute" | "auto") — and the
        request resumes, tokens identical, once pages free. CoW-shared
        prefix pages are never evicted while shared (the refcount keeps
        the master alive; the victim only drops its reference).
        docs/SERVING.md § Paged KV has the policy rule."""
        cfg = model.config
        self.model = model
        self.mesh = mesh
        self.n_slots = n_slots
        self.eos_id = eos_id
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed
        # sorted + deduped: _bucket picks the FIRST bucket >= len(prompt),
        # so an unsorted tuple would silently admit short prompts into the
        # largest bucket, wasting prefill compiles/compute
        self.prompt_buckets = tuple(sorted({b for b in prompt_buckets if b <= cfg.max_seq}))
        if not self.prompt_buckets:
            raise ValueError(f"no prompt bucket fits max_seq={cfg.max_seq}")
        if prefill_chunk < 0 or prefill_chunk > cfg.max_seq:
            raise ValueError(
                f"prefill_chunk must be in [0, max_seq={cfg.max_seq}], got {prefill_chunk}"
            )
        self.prefill_chunk = int(prefill_chunk)
        # the in-flight chunked admission: (request, reserved slot,
        # accumulating 1-row cache, next chunk's start position) — at most
        # one at a time; its reserved slot holds rid -2 so neither the
        # decode mask (>= 0) nor the free-slot scan (== -1) touches it.
        # (Paged mode drops the cache1 element: chunks write straight into
        # the slot's reserved pool pages — (request, slot, next start).)
        self._pending = None

        # ---- paged KV cache (docs/SERVING.md § Paged KV) ----
        # "fp" = unquantized pages (full-precision gather parity — the
        # page-table machinery alone, no codec): mode None, paged True
        self.page_quant = (None if paged_kv == "fp"
                           else model._page_mode(paged_kv))  # None|int8|int4
        self.paged = bool(paged_kv)
        self.page_size = int(page_size)
        if preemption and not self.paged:
            raise ValueError("preemption is a paged_kv eviction tier; "
                             "set paged_kv=")
        if preempt_policy not in ("swap", "recompute", "auto"):
            raise ValueError(
                f"preempt_policy must be 'swap', 'recompute', or 'auto', "
                f"got {preempt_policy!r}"
            )
        self.preemption = bool(preemption)
        self.preempt_policy = preempt_policy
        if self.paged:
            if turbo_factor or adaptive_quantum:
                raise ValueError(
                    "paged_kv composes with plain decode quanta and "
                    "speculative windows; turbo_factor/adaptive_quantum are "
                    "dense-cache escalations"
                )
            if cfg.max_seq % self.page_size:
                raise ValueError(
                    f"page_size must divide max_seq={cfg.max_seq}, got "
                    f"{self.page_size}"
                )
            self._n_pt = cfg.max_seq // self.page_size  # table entries/slot
            # 0 = parity sizing: every slot can hold max_seq rows, like the
            # dense cache — the capacity win comes from sizing it DOWN to
            # the workload (docs/TUNING.md has the accounting)
            self.n_pages = int(n_pages) or n_slots * self._n_pt + 1
            from dsml_tpu.serving.paging import PagePool

            self._pages = PagePool(self.n_pages)
            # host page table: row per slot, entry 0 (the scratch page) for
            # everything unallocated; device copy rides along per dispatch
            self._page_table = np.zeros((n_slots, self._n_pt), np.int32)
            self._slot_pages: list[list] = [[] for _ in range(n_slots)]
            # per-slot CoW accounting + preemption priority: the first
            # _slot_shared[s] entries of a slot's page list are read-only
            # shared prefix pages (never swapped — only the reference is
            # dropped on eviction); _slot_prio orders eviction victims
            self._slot_shared = np.zeros(n_slots, np.int32)
            self._slot_prio = np.zeros(n_slots, np.int64)
            # preempted-but-unfinished requests awaiting resume (FIFO;
            # resumes take precedence over fresh admissions)
            self._preempted: deque = deque()
            self.n_preemptions = 0
            self.n_swap_evictions = 0
            self.n_recompute_evictions = 0
            # flow marks dedupe per wait EPISODE (rid of the last blocked
            # head per queue) — the counter stays per-tick, but marking
            # every blocked tick would flood a stuck request's trace chain
            # and churn the bounded span buffer
            self._page_wait_rid_inject: int | None = None
            self._page_wait_rid_queue: int | None = None
            self.n_cow_copies = 0
            # pages the prefix registry holds FOREVER — the never-fits
            # checks subtract these from the reservable ceiling (a pool
            # mostly eaten by registrations must reject, not livelock)
            self._registry_pages = 0
        else:
            self.n_pages = 0

        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0 (0 = unbounded), got {max_queue}")
        # queue cap: an unbounded admission queue under overload grows
        # without limit — memory, and every queued request's latency, with
        # it. A cap makes overload an EXPLICIT signal (QueueFull +
        # serving_shed_total) the caller can act on (shed, retry elsewhere,
        # backpressure) instead of a slow collapse. 0 keeps the historical
        # unbounded behavior.
        self.max_queue = int(max_queue)
        self._obs = get_registry()  # no-op unless observability is enabled
        # serving metrics are labeled per replica so a DecodeFleet's N
        # batchers produce N distinguishable series for the cluster
        # aggregator instead of one blended stream; a standalone batcher
        # is replica "0". DecodeFleet restamps this at spawn time.
        self.obs_replica = "0"
        # worker-kind label on every serving metric: fleet merges split
        # TTFT (prefill-bound) from TPOT (decode-bound) by role. A batcher
        # is the fleet's decode worker — a standalone batcher does both
        # jobs but reports as "decode" (docs/OBSERVABILITY.md)
        self.obs_role = "decode"
        # ---- dequant-fused serving weights (docs/TUNING.md § Kernel
        # fusion) — resolve the knob, compress the params BEFORE any
        # decode program closes over them, and claim the compressed
        # bytes so the ledger's params row reconciles
        if weight_quant == "env":
            from dsml_tpu.ops.quantization import weight_quant_mode

            weight_quant = weight_quant_mode()
        if weight_quant in ("off", "none", "0", False):
            weight_quant = None
        if weight_quant is not None:
            if weight_quant not in ("int8", "int4"):
                raise ValueError(
                    f"weight_quant must be 'int8', 'int4', or None, got "
                    f"{weight_quant!r}"
                )
            if mesh is not None:
                raise ValueError(
                    "weight_quant serves single-device replicas; the TP "
                    "shard_map path expects plain param leaves matching "
                    "param_specs"
                )
            from dsml_tpu.models.common import quantize_weights_blocked
            from dsml_tpu.ops.quantization import QuantizedWeight

            params = quantize_weights_blocked(params, weight_quant)
            packed = scales = 0
            for leaf in jax.tree.leaves(
                params, is_leaf=lambda l: isinstance(l, QuantizedWeight)
            ):
                if isinstance(leaf, QuantizedWeight):
                    packed += int(leaf.qw.nbytes)
                    scales += int(leaf.qs.nbytes)
            self._wq_bytes = {"packed": packed, "scales": scales}
            from dsml_tpu.obs.memory import get_memory_ledger

            get_memory_ledger(self._obs).register_source(
                "weights_quant", self._ledger_weight_quant_bytes,
                name=f"{self.obs_replica}/{self.obs_role}/{id(self):x}",
            )
        else:
            self._wq_bytes = {}
        self.weight_quant = weight_quant
        # handed-off admissions awaiting a free slot: (Request, cache1,
        # logits row) — prefilled elsewhere, so admission is insert-only
        self._inject: deque = deque()
        self._queue: deque[Request] = deque()
        self._live: dict[int, Request] = {}  # queued or in a slot
        self._done: dict[int, Request] = {}  # retired, awaiting collect()
        self._latency: list = []  # (ttft_s, e2e_s) per retired request
        self._gaps: list = []  # consumer-visible inter-emission gap samples
        self._prefixes: list = []  # (tokens, cache1, last_logits) len-desc
        self._next_rid = 0
        # slot state (host-side numpy; device state is the cache)
        self._slot_rid = np.full(n_slots, -1, np.int64)  # -1 = free
        self._pos = np.zeros(n_slots, np.int32)  # next cache write index
        self._last_tok = np.zeros(n_slots, np.int32)
        self._slot_key = np.zeros((n_slots, 2), np.uint32)  # rid-derived PRNG keys

        if decode_quantum < 1:
            raise ValueError(f"decode_quantum must be >= 1, got {decode_quantum}")
        self.decode_quantum = decode_quantum
        if turbo_factor < 0 or turbo_factor == 1:
            raise ValueError(
                f"turbo_factor must be 0 (off) or >= 2, got {turbo_factor}"
            )
        if turbo_factor and speculative_window:
            raise ValueError(
                "turbo_factor composes with plain quanta only; the speculative "
                "window sets its own per-tick budget"
            )
        if turbo_factor and decode_quantum * turbo_factor >= cfg.max_seq:
            # submit() enforces len(prompt) + max_new <= max_seq with a
            # nonempty prompt, so remaining budget tops out at max_seq - 1:
            # a turbo quantum at or past max_seq could never engage and the
            # second program's compile would be pure waste
            raise ValueError(
                f"turbo quantum {decode_quantum * turbo_factor} >= "
                f"max_seq={cfg.max_seq} — no request could ever have that much "
                "budget remaining"
            )
        self.turbo_factor = int(turbo_factor)
        if adaptive_quantum:
            if adaptive_quantum < 2 or adaptive_quantum > cfg.max_seq:
                raise ValueError(
                    f"adaptive_quantum must be in [2, max_seq={cfg.max_seq}] "
                    f"or 0 (off), got {adaptive_quantum}"
                )
            if turbo_factor or speculative_window:
                raise ValueError(
                    "adaptive_quantum sets its own early-exit per-tick budget; "
                    "exclusive with turbo_factor and speculative_window"
                )
        self.adaptive_quantum = int(adaptive_quantum)
        # dispatch counters: observability for tests and servers (how often
        # the turbo/adaptive escalations actually engage, and what a
        # workload's host-dispatch bill actually was)
        self.n_plain_ticks = 0
        self.n_turbo_ticks = 0
        self.n_adaptive_ticks = 0
        self.n_prefill_dispatches = 0
        self.n_insert_dispatches = 0
        if speculative_window:
            if speculative_window < 2 or speculative_ngram < 1:
                raise ValueError(
                    f"speculative_window must be >= 2 (1 committed + >=1 draft) "
                    f"and speculative_ngram >= 1; got {speculative_window}, "
                    f"{speculative_ngram}"
                )
            if self.temperature > 0.0:
                raise ValueError(
                    "speculative decoding is greedy-only (verify-by-argmax); "
                    "temperature must be 0"
                )
            if decode_quantum != 1:
                raise ValueError(
                    "speculative_window replaces decode_quantum (the window IS "
                    "the per-tick token budget); set decode_quantum=1"
                )
        self.speculative_window = int(speculative_window)
        self.speculative_ngram = int(speculative_ngram)
        if speculative_adaptive and not speculative_window:
            raise ValueError(
                "speculative_adaptive adapts the speculative window; set "
                "speculative_window >= 2"
            )
        self.speculative_adaptive = bool(speculative_adaptive)
        # speculative acceptance telemetry: per-slot EWMAs of the draft
        # acceptance rate plus a batcher-level EWMA, the measured verify
        # tick wall, and the committed-tokens-per-slot-tick EWMA — the
        # inputs to the adaptive window choice here and to the router's
        # acceptance-aware TPOT cost model (predicted_tpot_s)
        self._slot_accept = np.full(n_slots, np.nan)
        self.accept_ewma: float | None = None
        self.spec_tick_s_ewma: float | None = None
        self.commit_ewma: float | None = None
        self.n_spec_ticks = 0
        self.spec_window_used: dict[int, int] = {}  # width -> tick count
        max_seq = cfg.max_seq
        temperature = self.temperature
        top_k, top_p = self.top_k, self.top_p
        tp_axis = "tp" if mesh is not None else None
        from jax import lax

        from dsml_tpu.models.gpt2 import sample_token_logits

        def make_decode_k(k):
            """Build the k-chained slot-decode + sampling program (ONE
            dispatch). ``base_keys`` [B, 2] per-slot PRNG keys
            (rid-derived), ``steps_done`` [B] tokens already emitted per
            request (the sampler's step index — folding the ABSOLUTE step
            keeps the sampled stream identical for any k, including the
            turbo escalation). Positions clamp at max_seq-1: slots that
            retire mid-quantum keep writing their (dead) last row, which
            the next prefill overwrites."""

            def decode_k(p, c, t, pos, base_keys, steps_done):
                def body(carry, i):
                    c, t, pos = carry
                    logits, c = model.decode_step_slots(p, c, t, pos, tp_axis)
                    if temperature <= 0.0:
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    else:
                        def one(row, key, n_done):
                            k2 = jax.random.fold_in(key, n_done + i)
                            return sample_token_logits(row, k2, temperature, top_k, top_p)

                        nxt = jax.vmap(one)(logits, base_keys, steps_done)
                    return (c, nxt, jnp.minimum(pos + 1, max_seq - 1)), nxt

                (c, _, _), toks = lax.scan(body, (c, t, pos), jnp.arange(k))
                return toks, c  # toks [k, B]

            return decode_k

        decode_k = make_decode_k(decode_quantum)
        decode_turbo = (
            make_decode_k(decode_quantum * turbo_factor) if turbo_factor else None
        )

        def make_decode_k_paged(k):
            """``make_decode_k`` against the page pool: same k-chained
            scan + sampling (identical (seed, rid, step) folds — paged vs
            dense never changes WHICH token is sampled, only where its
            K/V row lives), cache writes/reads routed through the page
            table."""
            pq = self.page_quant

            def decode_k_paged(p, pool, table, t, pos, base_keys, steps_done):
                def body(carry, i):
                    pool, t, pos = carry
                    logits, pool = model.decode_step_slots_paged(
                        p, pool, table, t, pos, tp_axis, pq
                    )
                    if temperature <= 0.0:
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    else:
                        def one(row, key, n_done):
                            k2 = jax.random.fold_in(key, n_done + i)
                            return sample_token_logits(row, k2, temperature, top_k, top_p)

                        nxt = jax.vmap(one)(logits, base_keys, steps_done)
                    return (pool, nxt, jnp.minimum(pos + 1, max_seq - 1)), nxt

                (pool, _, _), toks = lax.scan(body, (pool, t, pos), jnp.arange(k))
                return toks, pool  # toks [k, B]

            return decode_k_paged

        def make_decode_until(k_max):
            """Early-exit decode loop: up to ``k_max`` chained slot-decode
            steps in ONE dispatch, stopping after the step where any ACTIVE
            slot finishes (budget reached, or EOS when configured). Returns
            (toks [k_max, B], n_steps, cache) — the host applies
            ``toks[:n_steps]``. Same token chain as ``make_decode_k``
            (sampler folds the absolute step), so tokens are identical."""
            eos = eos_id

            def decode_until(p, c, t, pos, base_keys, steps_done, remaining,
                             active):
                def cond(state):
                    _, _, _, i, stop, _ = state
                    return (i < k_max) & ~stop

                def body(state):
                    c, t, pos, i, stop, toks = state
                    logits, c = model.decode_step_slots(p, c, t, pos, tp_axis)
                    if temperature <= 0.0:
                        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    else:
                        def one(row, key, n_done):
                            k2 = jax.random.fold_in(key, n_done + i)
                            return sample_token_logits(
                                row, k2, temperature, top_k, top_p
                            )

                        nxt = jax.vmap(one)(logits, base_keys, steps_done)
                    toks = lax.dynamic_update_index_in_dim(toks, nxt, i, 0)
                    done = active & (i + 1 >= remaining)
                    if eos is not None:
                        done = done | (active & (nxt == eos))
                    return (c, nxt, jnp.minimum(pos + 1, max_seq - 1),
                            i + 1, jnp.any(done), toks)

                toks0 = jnp.zeros((k_max, t.shape[0]), jnp.int32)
                c, _, _, n, _, toks = lax.while_loop(
                    cond, body,
                    (c, t, pos, jnp.asarray(0, jnp.int32),
                     jnp.asarray(False), toks0),
                )
                return toks, n, c

            return decode_until

        decode_adaptive = (
            make_decode_until(adaptive_quantum) if adaptive_quantum else None
        )

        def prefill_chunk_fn(p, c, toks, start, last):
            return model.prefill_chunk(p, c, toks, start, tp_axis, last_index=last)

        # FUSED admission programs: prefill + scatter-into-slot in ONE
        # dispatch (slot is traced, so one compile serves every slot).
        # Admission cost halves: each whole-prompt admit and each chunked
        # admission's final chunk save a host round trip vs the separate
        # _insert call (which remains for the prefix-cache copy path, where
        # the stored master rows must NOT be donated)
        def prefill_insert_fn(p, cache, toks, last, slot):
            logits, c1 = model.prefill(p, toks, tp_axis, last_index=last)
            return logits, ContinuousBatcher._insert_fn(cache, c1, slot)

        def prefill_chunk_insert_fn(p, cache, c1, toks, start, last, slot):
            logits, c1 = model.prefill_chunk(
                p, c1, toks, start, tp_axis, last_index=last
            )
            return logits, ContinuousBatcher._insert_fn(cache, c1, slot)

        def verify_fn(p, c, toks, pos):  # toks [B, W], pos [B] per-slot depth
            return model.verify_step(p, c, toks, pos, tp_axis)

        if self.paged:
            pq = self.page_quant

            def chunk_paged_fn(p, pool, table, toks, start, last):
                return model.prefill_chunk_paged(
                    p, pool, table, toks, start, tp_axis, last_index=last,
                    quant=pq,
                )

            def verify_paged_fn(p, pool, table, toks, pos):
                return model.verify_step_paged(
                    p, pool, table, toks, pos, tp_axis, quant=pq
                )

            if mesh is None:
                self.params = params
                self._pool = model.init_page_pool(
                    self.n_pages, self.page_size, quant=pq
                )
                # the pool is donated every dispatch, exactly like the
                # dense cache: XLA updates the page buffers in place
                self._decode_paged = jax.jit(
                    make_decode_k_paged(decode_quantum), donate_argnums=(1,)
                )
                self._prefill_chunk_paged = jax.jit(
                    chunk_paged_fn, donate_argnums=(1,)
                )
                # jit retraces per window width, so ONE program object
                # serves the adaptive ladder (each width compiles once)
                self._verify_paged = jax.jit(
                    verify_paged_fn, donate_argnums=(1,)
                )
            else:
                # TP paged serving: the pool's HEAD axis shards over 'tp'
                # (the dense cache's sharding rule, applied to pages);
                # the page/row axes replicate their index math across
                # shards, so the page table, allocator, and host
                # scheduler are untouched — a multi-chip decode replica
                # gets the paged capacity win per chip
                from jax.sharding import NamedSharding, PartitionSpec as P

                from dsml_tpu.parallel.hybrid import shard_params

                tp_size = mesh.shape.get("tp", 1)
                n_kv = getattr(cfg, "n_kv_head", cfg.n_head)
                if n_kv % tp_size:
                    raise ValueError(
                        f"pool head count {n_kv} not divisible by tp={tp_size}"
                    )
                pspecs = model.param_specs()
                self.params = shard_params(params, mesh, pspecs)
                pool_global = model.init_page_pool(
                    self.n_pages, self.page_size, quant=pq
                )
                head_sh = NamedSharding(mesh, P(None, "tp"))
                self._pool = jax.tree.map(
                    lambda a: jax.device_put(a, head_sh), pool_global
                )
                pool_spec = jax.tree.map(lambda _: P(None, "tp"), pool_global)

                def _tp_paged_jit(fn, n_rep):
                    return jax.jit(
                        jax.shard_map(
                            fn, mesh=mesh,
                            in_specs=(pspecs, pool_spec) + (P(),) * n_rep,
                            out_specs=(P(), pool_spec),
                            check_vma=False,
                        ),
                        donate_argnums=(1,),
                    )

                self._decode_paged = _tp_paged_jit(
                    make_decode_k_paged(decode_quantum), 5
                )
                self._prefill_chunk_paged = _tp_paged_jit(chunk_paged_fn, 4)
                self._verify_paged = _tp_paged_jit(verify_paged_fn, 3)

            from dsml_tpu.serving.paging import copy_page

            # page copy / handoff install stay PLAIN jits: index-space
            # ops along the page axis, which GSPMD shards per-head for
            # free when the pool carries a tp sharding
            self._copy_page = jax.jit(copy_page, donate_argnums=(0,))

            def install_pages_fn(pool, payload, phys):
                # paged KV handoff install: shipped page payloads land
                # verbatim at the allocated physical pages
                return [
                    {key: c[key].at[phys].set(pl[key]) for key in c}
                    for c, pl in zip(pool, payload)
                ]

            self._install_pages = jax.jit(
                install_pages_fn, donate_argnums=(0,)
            )
            # pool occupancy gauges refresh at SCRAPE time (the collect
            # hook), not per tick: an idle batcher's /metrics must show
            # the pool's CURRENT state, not freeze at the last tick's
            # (the frozen-SLO-burn-gauge bug class; weakly held — the
            # hook dies with this batcher)
            self._obs.add_collect_hook(self._export_pool_gauges)
            # memory-ledger source: the pool's device bytes with the
            # live/shared/free/scratch split, re-read at every scrape and
            # postmortem (docs/OBSERVABILITY.md § Memory ledger) — weakly
            # held, so a retired batcher drops out of the ledger
            self._page_nbytes: float | None = None
            from dsml_tpu.obs.memory import get_memory_ledger

            get_memory_ledger(self._obs).register_source(
                "kv_pages", self._ledger_page_bytes,
                name=f"{self.obs_replica}/{self.obs_role}/{id(self):x}",
            )
        elif mesh is None:
            self.params = params
            self._cache = model.init_cache(n_slots)
            # the cache is donated: XLA updates it in place each tick
            # instead of allocating + copying the full [slots, H, max_seq,
            # hd] buffers per token (params are NOT donated — they serve
            # every step)
            self._decode = jax.jit(decode_k, donate_argnums=(1,))
            self._decode_turbo = (
                jax.jit(decode_turbo, donate_argnums=(1,))
                if decode_turbo else None
            )
            self._decode_adaptive = (
                jax.jit(decode_adaptive, donate_argnums=(1,))
                if decode_adaptive else None
            )
            # ONE compile serves every chunk: start/last_index stay traced
            self._prefill_chunk = jax.jit(prefill_chunk_fn, donate_argnums=(1,))
            self._prefill_insert = jax.jit(prefill_insert_fn, donate_argnums=(1,))
            self._prefill_chunk_insert = jax.jit(
                prefill_chunk_insert_fn, donate_argnums=(1, 2)
            )
            self._verify = jax.jit(verify_fn, donate_argnums=(1,))
            self._fresh_cache1 = lambda: model.init_cache(1)
            self._place_cache1 = lambda tree: jax.tree.map(jnp.asarray, tree)
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dsml_tpu.parallel.hybrid import shard_params

            tp_size = mesh.shape.get("tp", 1)
            n_heads = getattr(cfg, "n_kv_head", cfg.n_head)
            if n_heads % tp_size:
                raise ValueError(
                    f"cache head count {n_heads} not divisible by tp={tp_size}"
                )
            pspecs = model.param_specs()
            self.params = shard_params(params, mesh, pspecs)
            # global cache (full heads), head axis sharded over tp; every
            # other mesh axis replicates it
            cache_global = model.init_cache(n_slots)
            head_sh = NamedSharding(mesh, P(None, "tp"))
            self._cache = jax.tree.map(
                lambda a: jax.device_put(a, head_sh), cache_global
            )
            cache_spec = jax.tree.map(lambda _: P(None, "tp"), cache_global)
            def _tp_decode_jit(fn):
                return jax.jit(
                    jax.shard_map(
                        fn, mesh=mesh,
                        in_specs=(pspecs, cache_spec, P(), P(), P(), P()),
                        out_specs=(P(), cache_spec),
                        check_vma=False,
                    ),
                    donate_argnums=(1,),
                )

            self._decode = _tp_decode_jit(decode_k)
            self._decode_turbo = (
                _tp_decode_jit(decode_turbo) if decode_turbo else None
            )
            self._decode_adaptive = (
                jax.jit(
                    jax.shard_map(
                        decode_adaptive, mesh=mesh,
                        in_specs=(pspecs, cache_spec, P(), P(), P(), P(),
                                  P(), P()),
                        out_specs=(P(), P(), cache_spec),
                        check_vma=False,
                    ),
                    donate_argnums=(1,),
                )
                if decode_adaptive else None
            )
            self._prefill_chunk = jax.jit(
                jax.shard_map(
                    prefill_chunk_fn, mesh=mesh,
                    in_specs=(pspecs, cache_spec, P(), P(), P()),
                    out_specs=(P(), cache_spec),
                    check_vma=False,
                ),
                donate_argnums=(1,),
            )
            self._prefill_insert = jax.jit(
                jax.shard_map(
                    prefill_insert_fn, mesh=mesh,
                    in_specs=(pspecs, cache_spec, P(), P(), P()),
                    out_specs=(P(), cache_spec),
                    check_vma=False,
                ),
                donate_argnums=(1,),
            )
            self._prefill_chunk_insert = jax.jit(
                jax.shard_map(
                    prefill_chunk_insert_fn, mesh=mesh,
                    in_specs=(pspecs, cache_spec, cache_spec, P(), P(), P(), P()),
                    out_specs=(P(), cache_spec),
                    check_vma=False,
                ),
                donate_argnums=(1, 2),
            )
            self._verify = jax.jit(
                jax.shard_map(
                    verify_fn, mesh=mesh,
                    in_specs=(pspecs, cache_spec, P(), P()),
                    out_specs=(P(), cache_spec),
                    check_vma=False,
                ),
                donate_argnums=(1,),
            )
            self._fresh_cache1 = lambda: jax.tree.map(
                lambda a: jax.device_put(a, head_sh), model.init_cache(1)
            )
            self._place_cache1 = lambda tree: jax.tree.map(
                lambda a: jax.device_put(jnp.asarray(a), head_sh), tree
            )
        self._insert = jax.jit(self._insert_fn, donate_argnums=(0,))

    @classmethod
    def for_devices(cls, model, params, devices, **kwargs):
        """Build a batcher whose replica SPANS ``devices``: more than one
        device makes the replica tensor-parallel over a ``tp=len(devices)``
        mesh (Megatron params, head-sharded cache — same tokens as the
        single-device batcher); exactly one keeps the plain single-device
        batcher. The ``DecodeFleet`` device-pool factory target: a fleet
        handing each replica a slice of chips calls this, so replica
        failover moves a MULTI-device replica's work just like a
        single-device one's. ``len(devices)`` must divide the model's head
        count (the tp-sharding rule)."""
        devices = list(devices)
        if len(devices) <= 1:
            return cls(model, params, **kwargs)
        from dsml_tpu.parallel.mesh import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(tp=len(devices)), devices)
        return cls(model, params, mesh=mesh, **kwargs)

    @classmethod
    def from_checkpoint(cls, model, directory, step: int | None = None,
                        mesh=None, param_dtype=None, init_seed: int = 0, **kwargs):
        """Serve straight from a training checkpoint: a WEIGHTS-ONLY partial
        restore of the ``params`` subtree — the (n×-larger, n-way-sharded)
        optimizer state is never read, which is the point of the partial
        restore path (``docs/CHECKPOINT.md``). ``directory`` is a native
        checkpoint run directory (or an open ``CheckpointManager``);
        ``step=None`` loads the latest committed step. ``param_dtype``
        casts on restore (e.g. serve a bf16-trained checkpoint as f32);
        with ``mesh`` the restored weights land Megatron-sharded for the
        TP serving path. Remaining kwargs go to the constructor."""
        import jax as _jax

        from dsml_tpu.checkpoint import CheckpointManager

        manager = (directory if hasattr(directory, "restore")
                   else CheckpointManager(directory))
        template = model.init(init_seed)
        if param_dtype is not None:
            template = _jax.tree.map(
                lambda l: l.astype(param_dtype)
                if jnp.issubdtype(l.dtype, jnp.floating) else l,
                template,
            )
        if mesh is not None:
            from dsml_tpu.parallel.hybrid import shard_params

            template = shard_params(template, mesh, model.param_specs())
        params = manager.restore(
            step, template={"params": template}, partial=True
        )["params"]
        return cls(model, params, mesh=mesh, **kwargs)

    @staticmethod
    def _insert_fn(cache, cache1, slot):
        """Scatter a 1-row prefill cache into slot ``slot`` of the big
        cache (the admission write). Layout-generic over the entry keys so
        quantized caches (k/k_s/v/v_s) ride the same path."""
        return [
            {key: c[key].at[slot].set(c1[key][0]) for key in c}
            for c, c1 in zip(cache, cache1)
        ]

    # ---- request interface -----------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               key_rid: int | None = None,
               trace_id: str | None = None,
               priority: int = 0) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        # the SAME validation generate applies (length budget, max_new >= 1,
        # temperature range) — duplicating it here would let the two paths'
        # contracts drift apart
        self.model._check_generate_args(
            len(prompt), max_new_tokens, self.temperature, self.top_k, self.top_p
        )
        if self.speculative_window:
            # a continuing slot verifies a full window at pos < L + max_new;
            # its last row (pos + W - 1) must stay inside the cache
            w = self.speculative_window
            if len(prompt) + max_new_tokens + w - 1 > self.model.config.max_seq:
                raise ValueError(
                    f"prompt ({len(prompt)}) + max_new ({max_new_tokens}) + "
                    f"speculative_window-1 ({w - 1}) must fit max_seq="
                    f"{self.model.config.max_seq}"
                )
        if self.paged:
            if not self.prefill_chunk:
                raise ValueError(
                    "paged local admission requires prefill_chunk > 0 "
                    "(decode-only paged workers admit via inject)"
                )
            if not self._chunk_grid_fits(len(prompt)):
                raise ValueError(
                    f"prompt length {len(prompt)} exceeds the chunk grid for "
                    f"max_seq={self.model.config.max_seq} (paged admission "
                    "has no bucketed fallback)"
                )
            # never-fits check against the RESERVABLE ceiling: total pages
            # minus scratch minus the registry's permanent holdings, with
            # a matched prefix's shared full pages credited — a request
            # that could only livelock at the FIFO head must fail HERE
            pre = self._prefixes and self._match_prefix(prompt)
            p_len = len(pre[0]) if pre else 0
            need = self._reserve_rows(len(prompt), max_new_tokens, p_len,
                                      worst_case=True)
            n_private = -(-need // self.page_size) - p_len // self.page_size
            ceiling = self.n_pages - 1 - self._registry_pages
            if n_private > ceiling:
                raise ValueError(
                    f"request needs {n_private} private pages but only "
                    f"{ceiling} are ever reservable ({self._registry_pages} "
                    "held by the prefix registry); raise n_pages"
                )
        elif not self._chunk_grid_fits(len(prompt)):
            # whole-prompt bucketed admission → reject at submit, not admit
            _bucket(len(prompt), self.prompt_buckets)
        if self.max_queue and len(self._queue) >= self.max_queue:
            # shed AFTER validation: a malformed request is the caller's
            # bug (ValueError), a full queue is the deployment's state
            self._shed()
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      submitted_at=time.monotonic(), key_rid=key_rid,
                      trace_id=trace_id, priority=int(priority))
        self._queue.append(req)
        self._live[rid] = req
        return rid

    def _shed(self) -> None:
        self._obs.counter(
            "serving_shed_total",
            "requests rejected by the queue cap",
            labels=("replica", "role"),
        ).inc(replica=self.obs_replica, role=self.obs_role)
        raise QueueFull(
            f"admission queue at its cap ({self.max_queue} waiting); "
            "request shed — retry on another replica or back off"
        )

    def inject(self, prompt, max_new_tokens: int, cache1=None,
               logits_row=None, key_rid: int | None = None,
               submitted_at: float | None = None, *,
               kv_pages=None, page_size: int | None = None,
               prefix_rows: int = 0, trace_id: str | None = None) -> int:
        """Admit a request whose PREFILL already ran elsewhere — the
        decode-worker half of the disaggregated fleet's KV handoff
        (``dsml_tpu.serving.handoff``). ``cache1`` is the 1-row KV cache a
        ``PrefillWorker`` (or this class's own chunked-prefill path)
        produced for the whole prompt; ``logits_row`` the next-token
        logits at the prompt's last position. Admission costs ONE insert
        scatter (no prefill compute on this worker); the first token
        samples from ``logits_row`` under the identical
        (seed, ``key_rid``, step) fold a local admission would use, so
        tokens are bit-identical to submitting the prompt here (pinned in
        tests). ``submitted_at`` carries the ORIGINAL submit time so the
        admission-latency histogram reports true TTFT, queue + prefill +
        handoff included. Sheds with :class:`QueueFull` at ``max_queue``
        like :meth:`submit` (the router retries on another replica).

        A PAGED worker admits a paged handoff instead: ``kv_pages`` is
        the shipped page payload (per-layer dicts with a leading
        shipped-page axis, the pool's own entry layout), ``page_size``
        the sender's (must match), and ``prefix_rows`` the leading rows
        NOT shipped because this worker shares its own registered prefix
        pages for them (copy-on-write — validated here against the local
        registry so a mismatch fails at the fleet edge, not inside a
        tick)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        self.model._check_generate_args(
            len(prompt), max_new_tokens, self.temperature, self.top_k, self.top_p
        )
        cfg = self.model.config
        if self.paged:
            if kv_pages is None:
                raise ValueError(
                    "paged decode worker: inject needs kv_pages= (a dense "
                    "cache1 cannot land in a page pool)"
                )
            if page_size != self.page_size:
                raise ValueError(
                    f"handoff pages are {page_size} rows, this pool's are "
                    f"{self.page_size} — prefill and decode workers must "
                    "share the page size"
                )
            if len(kv_pages) != cfg.n_layer:
                raise ValueError(
                    f"handoff has {len(kv_pages)} layers, model has "
                    f"{cfg.n_layer}"
                )
            ref = self._pool[0]
            for key in ref:
                arr = kv_pages[0].get(key)
                if arr is None or tuple(arr.shape[1:]) != tuple(ref[key].shape[1:]):
                    raise ValueError(
                        f"handoff page entry {key!r} is "
                        f"{None if arr is None else tuple(arr.shape)}; pool "
                        f"pages are {tuple(ref[key].shape)} — quant modes "
                        "must match"
                    )
            if prefix_rows < 0 or prefix_rows % self.page_size or \
                    prefix_rows > len(prompt):
                raise ValueError(
                    f"prefix_rows={prefix_rows} must be a multiple of "
                    f"page_size={self.page_size} within the prompt"
                )
            if prefix_rows:
                # fail at the fleet edge if no local registration covers
                # the shared rows (the router replicates registrations, so
                # this is a deployment bug, not a runtime state)
                self._registered_prefix_pages(prompt, prefix_rows)
            n_ship = int(kv_pages[0]["k"].shape[0])
            rows = self._handoff_rows(len(prompt), max_new_tokens,
                                      prefix_rows, n_ship, worst_case=True)
            n_private = (-(-rows // self.page_size)
                         - prefix_rows // self.page_size)
            ceiling = self.n_pages - 1 - self._registry_pages
            if n_private > ceiling:
                raise ValueError(
                    f"handoff needs {n_private} private pages but only "
                    f"{ceiling} are ever reservable ({self._registry_pages} "
                    "held by the prefix registry); raise n_pages"
                )
        else:
            if kv_pages is not None:
                raise ValueError(
                    "dense decode worker: got kv_pages= (paged handoffs "
                    "need a paged_kv batcher)"
                )
            if len(cache1) != cfg.n_layer:
                raise ValueError(
                    f"handoff cache has {len(cache1)} layers, model has "
                    f"{cfg.n_layer}"
                )
            k = cache1[0]["k"]
            if k.shape[0] != 1 or k.shape[2] != cfg.max_seq:
                raise ValueError(
                    f"handoff cache rows are {tuple(k.shape)}; expected "
                    f"(1, heads, max_seq={cfg.max_seq}, ...) — prefill and "
                    "decode workers must share the model config"
                )
        if self.max_queue and len(self._inject) >= self.max_queue:
            self._shed()
        rid = self._next_rid
        self._next_rid += 1
        req = Request(
            rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
            submitted_at=(time.monotonic() if submitted_at is None
                          else submitted_at),
            key_rid=key_rid, trace_id=trace_id,
        )
        self._live[rid] = req
        ctx = req.trace_ctx()
        if ctx is not None and self._obs.enabled:
            from dsml_tpu.obs import get_tracer

            # the handoff landed on this decode worker: a flow step on
            # the decode lane links the prefill host's handoff span to
            # the admission that follows
            get_tracer().flow("decode_inject", ctx, phase="step",
                              rid=rid, replica=self.obs_replica)
        payload = (kv_pages, int(prefix_rows)) if self.paged else cache1
        self._inject.append((req, payload, np.asarray(logits_row).reshape(-1)))
        return rid

    def _admit_injected(self, emitted: dict) -> None:
        """Admit handed-off requests into free slots: insert the prefilled
        rows and run the shared admission epilogue — ONE dispatch, zero
        prefill compute (an in-process handoff's device rows pass through
        ``_place_cache1`` untouched, so the host never copies them).
        Handoffs admit BEFORE queued prompts: they already paid their
        prefill, so waiting behind local prefill work would squander the
        disaggregation win. Paged handoffs reserve + install PAGES
        instead: shared prefix rows resolve to this worker's own
        registered prefix pages (refcount++, zero bytes moved), shipped
        pages land verbatim at freshly allocated physical pages, and the
        decode budget's remaining pages come from the free list — an
        admission that can't reserve waits in the inject queue."""
        from dsml_tpu.serving.paging import pages_for

        while self._inject:
            free = np.flatnonzero(self._slot_rid == -1)
            if len(free) == 0:
                return
            if not self.paged:
                req, cache1, logits_row = self._inject.popleft()
                slot = int(free[0])
                self.n_insert_dispatches += 1
                self._cache = self._insert(
                    self._cache, self._place_cache1(cache1), jnp.int32(slot)
                )
                self._finish_admission(req, slot, logits_row, emitted)
                continue
            req, (payload, prefix_rows), logits_row = self._inject[0]  # peek
            slot = int(free[0])
            n_ship = int(payload[0]["k"].shape[0])
            rows = self._handoff_rows(len(req.prompt), req.max_new_tokens,
                                      prefix_rows, n_ship)
            n_full = prefix_rows // self.page_size
            n_private = pages_for(rows, self.page_size) - n_full
            if not self._pages.can_alloc(n_private):
                from dsml_tpu.serving.paging import note_page_wait

                first = self._page_wait_rid_inject != req.rid
                self._page_wait_rid_inject = req.rid
                note_page_wait(self._obs, self.obs_replica, self.obs_role,
                               trace=req.trace_ctx() if first else None)
                return  # pool full: the handoff waits for retirements
            shared = (self._registered_prefix_pages(req.prompt, prefix_rows)
                      if prefix_rows else [])
            self._pages.share(shared)
            private = self._pages.alloc(n_private)
            self._inject.popleft()
            self._slot_pages[slot] = shared + private
            # the CoW boundary must ride along: eviction treats the first
            # _slot_shared entries as reference-only (never swapped), so an
            # injected slot without it would swap out REGISTRY pages and
            # resume as if they were its own private allocation
            self._slot_shared[slot] = len(shared)
            self._page_table[slot, :] = 0
            self._page_table[slot, : len(shared) + len(private)] = shared + private
            if n_ship:
                payload_dev = [
                    {key: jnp.asarray(arr) for key, arr in layer.items()}
                    for layer in payload
                ]
                self.n_insert_dispatches += 1
                self._pool = self._install_pages(
                    self._pool, payload_dev,
                    jnp.asarray(private[:n_ship], jnp.int32),
                )
            self._finish_admission(req, slot, logits_row, emitted)

    def _registered_prefix_pages(self, prompt: np.ndarray,
                                 prefix_rows: int) -> list:
        """The first ``prefix_rows // page_size`` pages of a registered
        prefix agreeing with ``prompt`` on its first ``prefix_rows``
        tokens. ANY agreeing registration serves: a page's bytes depend
        only on the tokens at and before its rows (causality) and the
        codec is deterministic, so every agreeing prefix holds identical
        bytes there. ``inject`` validated a match exists."""
        n_full = prefix_rows // self.page_size
        for ptoks, ppages, _ in self._prefixes:
            if len(ptoks) >= prefix_rows and len(ppages) >= n_full and \
                    np.array_equal(ptoks[:prefix_rows], prompt[:prefix_rows]):
                return [int(p) for p in ppages[:n_full]]
        raise RuntimeError(
            f"no registered prefix covers the handoff's {prefix_rows} shared "
            "rows — inject validation should have rejected it"
        )

    def register_prefix(self, tokens) -> None:
        """Precompute and retain the KV rows + next-token logits for a
        shared prompt head (a system prompt). Later ``submit``s whose
        prompt starts with the longest registered prefix admit by copying
        these rows and chunk-prefilling only the suffix. Registration is
        a blocking setup call (it runs the prefix's chunked prefill).

        On a PAGED batcher the registration IS a page-table entry: the
        prefix chunk-prefills into pool pages held by the registry
        (refcount 1, forever), and matching admissions SHARE those pages
        read-only instead of copying rows — copy-on-write materializes
        at most the one page a straddling prefix tail makes the slot
        write into. A paged decode-only worker (``prefill_chunk=0``) may
        register too — that is how the fleet's decode side holds the
        prefix pages its paged handoffs reference."""
        if self.paged:
            self._register_prefix_paged(tokens)
            return
        if not self.prefill_chunk:
            raise ValueError("prefix caching requires prefill_chunk > 0")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prefix")
        if not self._chunk_grid_fits(n):
            raise ValueError(
                f"prefix length {n} exceeds the chunk grid for max_seq="
                f"{self.model.config.max_seq}"
            )
        c = self.prefill_chunk
        cache1 = self._fresh_cache1()
        logits = None
        for start in range(0, n, c):
            end = min(start + c, n)
            padded = np.zeros((1, c), np.int32)
            padded[0, : end - start] = tokens[start:end]
            last_local = (n - 1) - start if end >= n else c - 1
            logits, cache1 = self._prefill_chunk(
                self.params, cache1, jnp.asarray(padded),
                jnp.int32(start), jnp.int32(last_local),
            )
        self._prefixes.append((tokens, cache1, np.asarray(logits[0])))
        self._prefixes.sort(key=lambda p: -len(p[0]))  # longest match wins

    def _register_prefix_paged(self, tokens) -> None:
        """Chunk-prefill a prefix into registry-held pool pages. The
        chunk size is ``prefill_chunk`` when local admission runs here,
        else ``page_size`` — a quantized pool makes chunk chaining
        CHUNK-SIZE-INVARIANT (every query reads every key quantized), so
        pages registered with one chunk size are bit-identical to a
        prefill worker's at another (pinned in tests). Pages the padded
        final chunk touches beyond the prefix (pad garbage) are released
        right back — the registry retains exactly ⌈n/page_size⌉ pages."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prefix")
        c = self.prefill_chunk or self.page_size
        if -(-n // c) * c > self.model.config.max_seq:
            raise ValueError(
                f"prefix length {n} exceeds the chunk grid for max_seq="
                f"{self.model.config.max_seq}"
            )
        from dsml_tpu.serving.paging import prefill_prefix_into_pages

        pages, logits, self._pool = prefill_prefix_into_pages(
            self._prefill_chunk_paged, self.params, self._pool, self._pages,
            tokens, c, self.page_size, self._n_pt,
        )
        self._registry_pages += len(pages)
        self._prefixes.append((tokens, pages, logits))
        self._prefixes.sort(key=lambda p: -len(p[0]))  # longest match wins

    def _match_prefix(self, prompt: np.ndarray):
        """Longest registered prefix that heads ``prompt`` AND whose
        suffix chunk grid stays inside the cache; None otherwise."""
        L = len(prompt)
        c = self.prefill_chunk
        max_seq = self.model.config.max_seq
        for ptoks, pcache, plogits in self._prefixes:
            p = len(ptoks)
            if p > L or not np.array_equal(prompt[:p], ptoks):
                continue
            if p < L and p + (-(-(L - p) // c)) * c > max_seq:
                continue  # padded suffix grid would overrun the cache
            return ptoks, pcache, plogits
        return None

    @property
    def n_active(self) -> int:
        return int((self._slot_rid >= 0).sum())

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def n_pending(self) -> int:
        """Chunked admissions currently mid-prefill (0 or 1) — queued in
        neither ``n_queued`` nor ``n_active``; drain loops must check all
        three (``run`` does)."""
        return 0 if self._pending is None else 1

    @property
    def n_injected(self) -> int:
        """Handed-off admissions waiting for a free slot (:meth:`inject`)
        — a fourth drain-loop term alongside queued/active/pending."""
        return len(self._inject)

    @property
    def n_preempted(self) -> int:
        """Evicted-but-unfinished requests awaiting resume (the paged
        ``preemption`` tier) — the fifth drain-loop term; 0 elsewhere."""
        return len(self._preempted) if (self.paged and self.preemption) else 0

    # ---- scheduling ------------------------------------------------------------

    def _request_key(self, rid: int):
        """The rid-derived base PRNG key — THE one derivation shared by the
        host sampler, the slot-key table, and (folded with the step index)
        the in-scan sampler; the quantum/slot-independence guarantees rest
        on all samplers folding the identical (seed, rid, step) sequence."""
        return jax.random.fold_in(jax.random.PRNGKey(self.seed), rid)

    def _req_key(self, req: Request):
        """:meth:`_request_key` under the request's SAMPLER identity —
        ``key_rid`` when the router stamped one (fleet-wide rid), else the
        local rid. Every sampler site derives through here so a handed-off
        request's token stream matches the reference batcher's exactly."""
        return self._request_key(req.rid if req.key_rid is None else req.key_rid)

    def _sample(self, logits: np.ndarray, req: Request) -> int:
        if self.temperature <= 0.0:
            return int(np.argmax(logits))
        from dsml_tpu.models.gpt2 import sample_token_logits

        key = jax.random.fold_in(self._req_key(req), len(req.tokens))
        return int(sample_token_logits(
            jnp.asarray(logits), key, self.temperature, self.top_k, self.top_p
        ))

    def _chunk_grid_fits(self, prompt_len: int) -> bool:
        """True when the chunked path serves this prompt: chunking is on
        and the padded chunk grid ceil(L/C)·C stays inside max_seq (the
        final chunk is right-padded to C, and its padded K/V rows must not
        wrap past the cache end). With C dividing max_seq — every default —
        this is simply L <= max_seq."""
        c = self.prefill_chunk
        if c <= 0:
            return False
        return -(-prompt_len // c) * c <= self.model.config.max_seq

    def _handoff_rows(self, prompt_len: int, max_new: int, prefix_rows: int,
                      n_ship: int, worst_case: bool = False) -> int:
        """Rows a paged HANDOFF admission must reserve pages for: the
        decode budget (+ speculative overhang) or the shipped+shared page
        grid, whichever is larger — THE one formula, shared by inject's
        capacity validation and the actual admission reservation so the
        two can never disagree. With ``preemption`` the admission
        reserves only the landing grid (shipped + shared pages) and the
        decode budget grows page-by-page."""
        base = prompt_len + max_new
        if self.speculative_window:
            base += self.speculative_window - 1
        landing = prefix_rows + n_ship * self.page_size
        if self.preemption and not worst_case:
            return max(prompt_len, landing)
        return max(base, landing)

    def _reserve_rows(self, prompt_len: int, max_new: int,
                      prefix_len: int, worst_case: bool = False) -> int:
        """Rows a paged admission must reserve pages for — everything the
        request can EVER write: the padded prefill chunk grid (pad rows of
        the final chunk land in pages too), the decode budget, and the
        speculative verify window's overhang. Reserving up front is what
        makes decode/verify allocation-free mid-flight (docs/SERVING.md).

        With ``preemption`` only the CHUNK GRID reserves (what prefill
        itself writes); the decode budget and verify overhang grow
        page-by-page under ``_ensure_decode_pages``, and pressure evicts
        instead of deadlocking — admission tracks current demand, not the
        worst case. ``worst_case=True`` (submit's never-fits check)
        always returns the full footprint: eviction cannot shrink ONE
        request's own eventual live set, so a request whose footprint
        exceeds the reservable ceiling must still fail at submit."""
        base = prompt_len + max_new
        if self.speculative_window:
            base += self.speculative_window - 1
        c = self.prefill_chunk or self.page_size
        grid_end = prefix_len + -(-(prompt_len - prefix_len) // c) * c \
            if prompt_len > prefix_len else prompt_len
        if self.preemption and not worst_case:
            return min(self.model.config.max_seq, grid_end)
        return min(self.model.config.max_seq, max(base, grid_end))

    def _assign_slot_pages(self, slot: int, plan) -> None:
        """Install an admission plan's pages as ``slot``'s page table (and
        run its CoW straddle copy, counting it)."""
        self._slot_pages[slot] = list(plan.pages)
        self._slot_shared[slot] = plan.n_shared
        self._page_table[slot, :] = 0
        self._page_table[slot, : len(plan.pages)] = plan.pages
        if plan.copy is not None:
            src, dst = plan.copy
            self._pool = self._copy_page(
                self._pool, jnp.int32(src), jnp.int32(dst)
            )
            self.n_cow_copies += 1
            self._obs.counter(
                "serving_cow_copies_total",
                "prefix pages materialized privately on first write",
                labels=("replica", "role"),
            ).inc(replica=self.obs_replica, role=self.obs_role)

    def _decode_table(self) -> np.ndarray:
        """The page table a decode/verify dispatch may see: ACTIVE slots'
        rows only. A pending chunked admission's slot already owns its
        reserved pages (the chunk program writes them), but the decode
        program also writes a (masked, never-read) garbage row for every
        non-active slot — routed to the scratch page here, so a decode
        tick interleaving with a mid-flight admission can never clobber
        its freshly prefilled rows (the paged twin of the dense path's
        separate accumulating cache1; regression-pinned)."""
        return np.where((self._slot_rid >= 0)[:, None], self._page_table, 0)

    def _free_slot_pages(self, slot: int) -> None:
        """Release a slot's pages back to the pool (retire/abandon path);
        its table row points back at the scratch page so the decode
        program's dead-slot writes stay harmless. No-op for dense."""
        if not self.paged:
            return
        pages = self._slot_pages[slot]
        if pages:
            self._pages.release(pages)
        self._slot_pages[slot] = []
        self._slot_shared[slot] = 0
        self._page_table[slot, :] = 0

    # ---- eviction-based preemption (paged preemption=True) ---------------------

    def _pick_victim(self, exclude: int | None = None) -> int | None:
        """The eviction order: lowest priority first, youngest (highest
        rid) within a priority — FIFO fairness degrades last. ``exclude``
        shields the slot whose growth triggered the pressure (it preempts
        itself only when nothing else is left)."""
        best = None
        for slot in np.flatnonzero(self._slot_rid >= 0):
            s = int(slot)
            if s == exclude:
                continue
            key = (self._slot_prio[s], -self._slot_rid[s])
            if best is None or key < best[0]:
                best = (key, s)
        return None if best is None else best[1]

    def _preempt_kind(self, req) -> str:
        """The swap-vs-recompute rule (docs/SERVING.md § Paged KV).
        "auto": a victim still at its first token holds only prompt-grid
        pages that chunked prefill reproduces at full throughput (and may
        re-hit the prefix cache) — RECOMPUTE, skip the host round trip;
        past that, swapping the live bytes beats re-running prefill over
        prompt + emitted rows. Both paths resume with identical tokens
        (quantized chunk chaining is chunk-size-invariant, so recomputed
        rows are bit-identical to the evicted ones — the PR 11 property
        the recompute path rests on)."""
        if self.preempt_policy != "auto":
            return self.preempt_policy
        return "recompute" if len(req.tokens) <= 1 else "swap"

    def _evict_slot(self, slot: int) -> None:
        """Preempt ``slot``: private pages swap to host (the handoff page
        payload layout — ``paging.gather_pages``) or drop for recompute,
        ALL page references release (a CoW-shared prefix page just loses
        this reference; the refcount keeps the registry master alive —
        shared pages are NEVER evicted while shared), and the request
        joins the resume queue. The consumer sees a longer inter-emission
        gap, never different tokens."""
        from dsml_tpu.serving.paging import gather_pages

        req = self._live[int(self._slot_rid[slot])]
        pages = self._slot_pages[slot]
        n_shared = int(self._slot_shared[slot])
        private = pages[n_shared:]
        kind = self._preempt_kind(req)
        entry = {
            "req": req,
            "pos": int(self._pos[slot]),
            "last_tok": int(self._last_tok[slot]),
            "shared_rows": n_shared * self.page_size,
            "kind": kind,
        }
        if kind == "swap":
            entry["pages_host"] = gather_pages(self._pool, private)
            self.n_swap_evictions += 1
        else:
            self.n_recompute_evictions += 1
        self._slot_rid[slot] = -1
        self._free_slot_pages(slot)
        self._preempted.append(entry)
        self.n_preemptions += 1
        if self._obs.enabled:
            from dsml_tpu.obs import flight_recorder

            self._obs.counter(
                "serving_preemptions_total",
                "slots evicted under page-pool pressure",
                labels=("kind", "replica", "role"),
            ).inc(kind=kind, replica=self.obs_replica, role=self.obs_role)
            extra = {"trace_id": req.trace_id} if req.trace_id else {}
            # the pressure that forced this eviction, measured-headroom
            # first (memory_pressure) — a postmortem shows whether the
            # chip or merely the pool sizing was the constraint
            flight_recorder.record(
                "serving_preempt", rid=req.rid, kind=kind,
                pos=entry["pos"],
                pressure=round(self.memory_pressure(), 4), **extra,
            )

    def _ensure_decode_pages(self, active, width: int):
        """Preemption-mode page GROWTH: before a decode/verify dispatch,
        every participating slot must own pages covering its next
        ``width`` write rows. When the pool is dry, evict (lowest
        priority, youngest first) until the growth fits — the growing
        slot itself is preempted only when no other victim remains.
        Returns the slots still active (victims drop out); non-preemption
        batchers pass through untouched (their reservation covered
        everything up front)."""
        if not (self.paged and self.preemption):
            return active
        max_seq = self.model.config.max_seq
        kept = []
        for slot in active:
            s = int(slot)
            if self._slot_rid[s] < 0:
                continue  # already evicted as a victim this pass
            last_row = min(int(self._pos[s]) + width - 1, max_seq - 1)
            n_entries = last_row // self.page_size + 1
            while len(self._slot_pages[s]) < n_entries:
                want = n_entries - len(self._slot_pages[s])
                if self._pages.can_alloc(want):
                    start_i = len(self._slot_pages[s])
                    new = self._pages.alloc(want)
                    self._slot_pages[s].extend(new)
                    self._page_table[s, start_i : start_i + want] = new
                    continue
                victim = self._pick_victim(exclude=s)
                if victim is None:
                    # nothing else holds pages: this slot yields and
                    # resumes when retirements free the pool (submit's
                    # worst-case never-fits check guarantees it CAN)
                    self._evict_slot(s)
                    break
                self._evict_slot(int(victim))
            if self._slot_rid[s] >= 0:
                kept.append(s)
        return [s for s in kept if self._slot_rid[s] >= 0]

    def _try_resume(self, entry: dict, slot: int) -> bool:
        """Re-admit one preempted request into ``slot``. Swap: re-share
        the registered prefix pages, allocate fresh private pages, land
        the host copy verbatim (the handoff install scatter), restore the
        decode state — bit-identical rows, zero recompute. Recompute:
        reserve the re-prefill grid and stage a pending chunked admission
        over prompt + emitted tokens (all but the last, which is the next
        decode input) — chunk-size invariance makes the rebuilt rows
        bit-identical to the evicted ones. Returns False when the pool
        cannot serve the resume yet (it keeps its queue spot; resumes
        precede fresh admissions)."""
        from dsml_tpu.serving.paging import pages_for

        req = entry["req"]
        pos = entry["pos"]
        shared_rows = entry["shared_rows"]
        n_full = shared_rows // self.page_size
        if entry["kind"] == "swap":
            payload = entry["pages_host"]
            n_private = int(payload[0]["k"].shape[0])
            if not self._pages.can_alloc(n_private):
                return False
            shared = (self._registered_prefix_pages(req.prompt, shared_rows)
                      if shared_rows else [])
            self._pages.share(shared)
            private = self._pages.alloc(n_private)
            pages = shared + private
            self._slot_pages[slot] = pages
            self._slot_shared[slot] = n_full
            self._page_table[slot, :] = 0
            self._page_table[slot, : len(pages)] = pages
            if n_private:
                payload_dev = [
                    {key: jnp.asarray(arr) for key, arr in layer.items()}
                    for layer in payload
                ]
                self.n_insert_dispatches += 1
                self._pool = self._install_pages(
                    self._pool, payload_dev,
                    jnp.asarray(private, jnp.int32),
                )
            self._restore_slot(req, slot, pos, entry["last_tok"])
            return True
        # recompute: re-prefill prompt + tokens[:-1] (rows [0, pos)) from
        # the shared prefix boundary; the final chunk's logits are
        # discarded — the request already emitted its next input token
        c = self.prefill_chunk or self.page_size
        grid_end = shared_rows + -(-(pos - shared_rows) // c) * c
        grid_end = min(grid_end, self.model.config.max_seq)
        n_private = pages_for(grid_end, self.page_size) - n_full
        if not self._pages.can_alloc(n_private):
            return False
        shared = (self._registered_prefix_pages(req.prompt, shared_rows)
                  if shared_rows else [])
        self._pages.share(shared)
        private = self._pages.alloc(n_private)
        pages = shared + private
        self._slot_pages[slot] = pages
        self._slot_shared[slot] = n_full
        self._page_table[slot, :] = 0
        self._page_table[slot, : len(pages)] = pages
        if pos == shared_rows:
            # every written row lives in shared registry pages (an
            # exact-hit admission evicted before writing): nothing to
            # recompute — reoccupy directly
            self._restore_slot(req, slot, pos, entry["last_tok"])
            return True
        seq = np.concatenate(
            [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
        )
        assert len(seq) == pos, (len(seq), pos)
        self._slot_rid[slot] = -2  # reserved: not free, not decoding
        self._pending = (req, slot, shared_rows, seq,
                         {"pos": pos, "last_tok": entry["last_tok"]})
        return True

    def _restore_slot(self, req, slot: int, pos: int, last_tok: int) -> None:
        """Reoccupy ``slot`` with a resumed request's decode state (no
        emission, no first-token sample — those already happened)."""
        self._slot_rid[slot] = req.rid
        self._pos[slot] = pos
        self._last_tok[slot] = last_tok
        self._slot_key[slot] = np.asarray(self._req_key(req))
        self._slot_accept[slot] = np.nan
        self._slot_prio[slot] = req.priority
        if self._obs.enabled:
            from dsml_tpu.obs import flight_recorder

            extra = {"trace_id": req.trace_id} if req.trace_id else {}
            flight_recorder.record("serving_resume", rid=req.rid, pos=pos,
                                   **extra)

    @property
    def free_pages(self) -> int:
        return self._pages.free_pages if self.paged else 0

    @property
    def used_pages(self) -> int:
        return self._pages.used_pages if self.paged else 0

    @property
    def shared_pages(self) -> int:
        return self._pages.shared_pages if self.paged else 0

    def _occupy(self, req: Request, slot: int, tok: int) -> None:
        """Install an admitted (not-yet-finished) request into its slot."""
        self._slot_rid[slot] = req.rid
        self._pos[slot] = len(req.prompt)
        self._last_tok[slot] = tok
        self._slot_key[slot] = np.asarray(self._req_key(req))
        self._slot_accept[slot] = np.nan  # a fresh request, a fresh EWMA
        if self.paged:
            self._slot_prio[slot] = req.priority

    def _finish_admission(self, req: Request, slot: int, logits_row, emitted: dict) -> None:
        """THE admission epilogue — shared by whole-prompt, chunked, and
        exact-prefix admissions so the bookkeeping cannot drift: sample the
        first token, stamp TTFT, emit, then retire (slot stays free) or
        occupy."""
        tok = self._sample(np.asarray(logits_row), req)
        req.tokens.append(tok)
        req.first_token_at = time.monotonic()
        if self._obs.enabled:
            # admission latency = queue wait + prefill: the serving-side
            # TTFT, as a histogram the /metrics endpoint can expose live.
            # The sample carries the request's trace_id as an EXEMPLAR, so
            # a tail bucket resolves to the trace that landed in it
            admission_ms = (req.first_token_at - req.submitted_at) * 1e3
            self._obs.histogram(
                "serving_admission_ms", "submit→first-token latency",
                labels=("replica", "role"),
            ).observe(admission_ms, exemplar=req.trace_id,
                      replica=self.obs_replica, role=self.obs_role)
            from dsml_tpu.obs import flight_recorder, get_tracer

            extra = {"trace_id": req.trace_id} if req.trace_id else {}
            flight_recorder.record(
                "serving_admit", rid=req.rid, prompt_len=len(req.prompt),
                admission_ms=round(admission_ms, 3), **extra,
            )
            ctx = req.trace_ctx()
            if ctx is not None:
                get_tracer().instant(
                    "serving_first_token", trace_id=req.trace_id,
                    rid=req.rid, admission_ms=round(admission_ms, 3),
                    replica=self.obs_replica,
                )
        emitted[req.rid] = [tok]
        if self._finished(req, tok):
            self._retire(req)
            self._slot_rid[slot] = -1  # release any reservation
            self._free_slot_pages(slot)
            return
        self._occupy(req, slot, tok)

    def _admit_full(self, req: Request, slot: int, emitted: dict) -> None:
        """Whole-prompt bucketed prefill + cache insert + first sampled
        token. A request that finishes AT prefill (budget 1 or immediate
        EOS) never occupies the slot."""
        L = len(req.prompt)
        bucket = _bucket(L, self.prompt_buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = req.prompt
        # fused prefill+insert: one dispatch per admission
        logits, self._cache = self._prefill_insert(
            self.params, self._cache, jnp.asarray(padded), jnp.int32(L - 1),
            jnp.int32(slot),
        )
        self.n_prefill_dispatches += 1
        self._finish_admission(req, slot, logits[0], emitted)

    def _admit(self) -> dict[int, list]:
        """Fill free slots from the queue (whole-prompt admission path).
        Returns {rid: [first token]} for every admission — step() merges it
        so streaming consumers see token 1 too."""
        emitted: dict[int, list] = {}
        for slot in np.flatnonzero(self._slot_rid == -1):
            while self._queue and self._slot_rid[slot] == -1:
                self._admit_full(self._queue.popleft(), int(slot), emitted)
        return emitted

    def _advance_pending(self, emitted: dict) -> bool:
        """Run ONE chunk of the in-flight chunked admission. On the final
        chunk: sample the first token, insert the accumulated cache into
        the reserved slot, and occupy (or retire) it. Returns True when the
        admission completed this call."""
        req, slot, cache1, start = self._pending
        c = self.prefill_chunk
        L = len(req.prompt)
        end = min(start + c, L)
        padded = np.zeros((1, c), np.int32)
        padded[0, : end - start] = req.prompt[start:end]
        is_last = end >= L
        last_local = (L - 1) - start if is_last else c - 1
        if not is_last:
            logits, cache1 = self._prefill_chunk(
                self.params, cache1, jnp.asarray(padded),
                jnp.int32(start), jnp.int32(last_local),
            )
            self.n_prefill_dispatches += 1
            self._pending = (req, slot, cache1, start + c)
            return False
        # final chunk: fused chunk-prefill + insert — one dispatch
        logits, self._cache = self._prefill_chunk_insert(
            self.params, self._cache, cache1, jnp.asarray(padded),
            jnp.int32(start), jnp.int32(last_local), jnp.int32(slot),
        )
        self.n_prefill_dispatches += 1
        self._pending = None
        self._finish_admission(req, slot, logits[0], emitted)
        return True

    def _admit_chunked(self) -> dict[int, list]:
        """Chunked admission pass: advance the in-flight admission by ONE
        chunk; when an admission completes (short prompts complete in one
        chunk), keep admitting from the queue, so cold-start still fills
        every free slot in a single tick. The moment a LONG prompt's chunk
        finishes without completing the admission, the pass yields — decode
        quanta run between its remaining chunks (no head-of-line stall)."""
        emitted: dict[int, list] = {}
        while True:
            if self._pending is not None:
                if not self._advance_pending(emitted):
                    return emitted  # long admission mid-flight: decode now
                continue  # completed → maybe start the next admission
            free = np.flatnonzero(self._slot_rid == -1)
            if len(free) == 0 or not self._queue:
                return emitted
            req = self._queue.popleft()
            if not self._chunk_grid_fits(len(req.prompt)):
                # odd max_seq where the padded grid would overrun the cache:
                # this request rides the bucketed whole-prompt path
                self._admit_full(req, int(free[0]), emitted)
                continue
            slot = int(free[0])
            pre = self._prefixes and self._match_prefix(req.prompt)
            if pre:
                ptoks, pcache, plogits = pre
                if len(ptoks) == len(req.prompt):
                    # the whole prompt is the stored prefix: admission
                    # completes with zero prefill work (_insert does not
                    # donate its source, so the master rows stay intact)
                    self.n_insert_dispatches += 1
                    self._cache = self._insert(self._cache, pcache, slot)
                    self._finish_admission(req, slot, plogits, emitted)
                    continue
                # suffix-only prefill: the pending cache starts as a COPY of
                # the prefix rows (the chunk program donates its cache arg —
                # the stored master must survive for the next match)
                self._slot_rid[slot] = -2
                self._pending = (
                    req, slot, jax.tree.map(jnp.copy, pcache), len(ptoks)
                )
                continue
            self._slot_rid[slot] = -2  # reserve: not free, not decoding
            self._pending = (req, slot, self._fresh_cache1(), 0)

    def _admit_paged(self) -> dict[int, list]:
        """Paged admission pass — ``_admit_chunked`` with pages: advance
        the in-flight admission by ONE chunk; otherwise reserve a page
        plan for the queue head (shared prefix pages + CoW straddle +
        fresh pages for the whole prompt-grid/decode/window footprint)
        and start it. A head that cannot reserve WAITS — retirements free
        pages, and FIFO order keeps the wait fair. Exact-prefix hits
        admit with zero prefill dispatches: the shared page-table entry
        (plus at most one CoW page copy) IS the admission."""
        from dsml_tpu.serving.paging import plan_admission

        emitted: dict[int, list] = {}
        while True:
            if self._pending is not None:
                if not self._advance_pending_paged(emitted):
                    return emitted  # long admission mid-flight: decode now
                continue
            free = np.flatnonzero(self._slot_rid == -1)
            if len(free) == 0:
                return emitted
            if self.preemption and self._preempted:
                # resumes precede fresh admissions: a preempted request
                # already paid its prefill (and its queue wait) — parking
                # it behind new work would turn one eviction into
                # unbounded starvation. A resume that cannot reserve yet
                # holds the line (FIFO; retirements free pages).
                if not self._try_resume(self._preempted[0], int(free[0])):
                    from dsml_tpu.serving.paging import note_page_wait

                    rid = self._preempted[0]["req"].rid
                    first = self._page_wait_rid_queue != rid
                    self._page_wait_rid_queue = rid
                    note_page_wait(
                        self._obs, self.obs_replica, self.obs_role,
                        trace=(self._preempted[0]["req"].trace_ctx()
                               if first else None),
                    )
                    return emitted
                self._preempted.popleft()
                continue
            if not self._queue:
                return emitted
            req = self._queue[0]  # peek: pop only once pages are reserved
            L = len(req.prompt)
            slot = int(free[0])
            pre = self._prefixes and self._match_prefix(req.prompt)
            ptoks, ppages, plogits = pre if pre else (None, None, None)
            p_len = len(ptoks) if pre else 0
            plan = plan_admission(
                self._pages, self.page_size,
                self._reserve_rows(L, req.max_new_tokens, p_len),
                prefix_pages=ppages, prefix_len=p_len,
            )
            if plan is None:
                if (self._pages.used_pages == self._registry_pages
                        and self.n_active == 0 and not self._inject):
                    # the pool is as empty as it will ever get and the
                    # head still can't reserve — a prefix registered
                    # AFTER this submit shrank the ceiling past it. Fail
                    # loudly instead of livelocking the FIFO (submit()'s
                    # never-fits check guards the normal order).
                    raise RuntimeError(
                        f"request {req.rid} can never reserve its pages "
                        f"({self._registry_pages} held by the prefix "
                        "registry); register prefixes before accepting "
                        "traffic, or raise n_pages"
                    )
                from dsml_tpu.serving.paging import note_page_wait

                first = self._page_wait_rid_queue != req.rid
                self._page_wait_rid_queue = req.rid
                note_page_wait(self._obs, self.obs_replica, self.obs_role,
                               trace=req.trace_ctx() if first else None)
                return emitted  # pool full: wait for retirements
            self._queue.popleft()
            self._assign_slot_pages(slot, plan)
            if pre and p_len == L:
                # the whole prompt is the registered prefix: admission
                # completes with zero prefill work and zero row copies
                self._finish_admission(req, slot, plogits, emitted)
                continue
            self._slot_rid[slot] = -2  # reserve: not free, not decoding
            self._pending = (req, slot, p_len, req.prompt, None)

    def _advance_pending_paged(self, emitted: dict) -> bool:
        """Run ONE chunk of the in-flight paged admission — the chunk
        writes straight into the slot's reserved pool pages (no side
        cache, no final insert dispatch). Returns True when the admission
        completed this call. ``seq`` is the row stream being prefilled —
        the prompt for a fresh admission, prompt + emitted tokens for a
        recompute RESUME (``resume`` then carries the decode state to
        restore; the final chunk's logits are discarded — the resumed
        request already sampled its next input)."""
        req, slot, start, seq, resume = self._pending
        c = self.prefill_chunk or self.page_size
        L = len(seq)
        end = min(start + c, L)
        padded = np.zeros((1, c), np.int32)
        padded[0, : end - start] = seq[start:end]
        is_last = end >= L
        last_local = (L - 1) - start if is_last else c - 1
        table_row = jnp.asarray(self._page_table[slot : slot + 1])
        logits, self._pool = self._prefill_chunk_paged(
            self.params, self._pool, table_row, jnp.asarray(padded),
            jnp.int32(start), jnp.int32(last_local),
        )
        self.n_prefill_dispatches += 1
        if not is_last:
            self._pending = (req, slot, start + c, seq, resume)
            return False
        self._pending = None
        if resume is not None:
            self._restore_slot(req, slot, resume["pos"], resume["last_tok"])
            return True
        self._finish_admission(req, slot, logits[0], emitted)
        return True

    def _finished(self, req: Request, tok: int) -> bool:
        return (self.eos_id is not None and tok == self.eos_id) or (
            len(req.tokens) >= req.max_new_tokens
        )

    def _retire(self, req: Request) -> None:
        req.done = True
        req.finished_at = time.monotonic()
        self._latency.append((
            (req.first_token_at or req.finished_at) - req.submitted_at,  # TTFT
            req.finished_at - req.submitted_at,  # e2e
        ))
        if self._obs.enabled:
            from dsml_tpu.obs import flight_recorder, get_tracer

            # per-request lifecycle in the flight ring: a serving postmortem
            # shows which requests were in flight and their tail latencies
            extra = {"trace_id": req.trace_id} if req.trace_id else {}
            flight_recorder.record(
                "serving_retire", rid=req.rid, tokens=len(req.tokens),
                e2e_ms=round((req.finished_at - req.submitted_at) * 1e3, 3),
                **extra,
            )
            ctx = req.trace_ctx()
            if ctx is not None:
                # flow END: the request's causal chain terminates on this
                # decode worker's lane (retire is the one stage that knows)
                get_tracer().flow("serving_retire", ctx, phase="end",
                                  rid=req.rid, outcome="retired",
                                  replica=self.obs_replica)
        # move out of the live table so a long-running server doesn't
        # accumulate one Request per lifetime request; collect() drains
        self._done[req.rid] = self._live.pop(req.rid)

    def _note_emissions(self, emitted: dict) -> None:
        """Record per-request inter-emission GAPS — the consumer-visible
        latency samples. A quantum/window of k tokens arrives as ONE
        emission, so a gap spans one scheduler tick; a tick stalled behind
        another request's admission shows up as a genuinely long gap (the
        head-of-line signal per-request averages would smooth away)."""
        now = time.monotonic()
        for rid, toks in emitted.items():
            if not toks:
                continue
            req = self._live.get(rid) or self._done.get(rid)
            if req is None:
                continue
            if req.last_emit_at is not None:
                self._gaps.append(now - req.last_emit_at)
            req.last_emit_at = now

    def latency_stats(self) -> dict:
        """p50/p99 TTFT, inter-emission gap, and end-to-end seconds since
        construction (or the last ``reset_latency_stats``) — the standard
        online-serving metrics; throughput alone hides queueing and
        head-of-line behavior. ``gap_*`` percentiles are over PER-EMISSION
        gap samples pooled across requests (with ``decode_quantum=k`` one
        emission carries up to k tokens — up to ``k * turbo_factor`` on a
        turbo tick — so divide by the emission's token count for a
        per-token figure)."""
        out = {"n_requests": len(self._latency)}
        if not self._latency:
            return out

        def pct(vals, q):
            return round(float(np.percentile(np.asarray(vals), q)), 6)

        ttft, e2e = zip(*self._latency)
        out.update(
            ttft_p50_s=pct(ttft, 50), ttft_p99_s=pct(ttft, 99),
            e2e_p50_s=pct(e2e, 50), e2e_p99_s=pct(e2e, 99),
        )
        if self._gaps:
            out["gap_p50_s"] = pct(self._gaps, 50)
            out["gap_p99_s"] = pct(self._gaps, 99)
        return out

    def reset_latency_stats(self) -> None:
        self._latency.clear()
        self._gaps.clear()

    def step(self) -> dict[int, list]:
        """One scheduler tick: admit, one decode QUANTUM over ALL slots,
        emit. Returns {rid: [new tokens]} for every request that produced
        tokens this tick — including each admission's prefill-sampled first
        token (a request finishing mid-quantum gets its truncated tail; the
        over-decoded lane-ticks are the quantum's scheduling cost)."""
        if not self._obs.enabled:
            emitted = self._step_inner()
            self._note_emissions(emitted)
            return emitted
        from dsml_tpu.obs import get_tracer

        # one span per scheduler tick (decode quantum + admissions): the
        # decode leg of request tracing — a request's inter-token stalls
        # land inside these spans on the worker's own timeline lane
        with get_tracer().span("decode_tick", replica=self.obs_replica,
                               n_active=self.n_active):
            emitted = self._step_inner()
        self._note_emissions(emitted)
        if self._obs.enabled:
            # batch occupancy per tick: the utilization signal behind
            # "should this deployment raise n_slots"
            self._obs.histogram(
                "serving_slot_occupancy", "active slots / n_slots per tick",
                labels=("replica", "role"),
                buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
            ).observe(self.n_active / self.n_slots,
                      replica=self.obs_replica, role=self.obs_role)
            self._obs.gauge(
                "serving_queue_depth", "requests waiting for a slot",
                labels=("replica", "role"),
            ).set(self.n_queued + self.n_injected,
                  replica=self.obs_replica, role=self.obs_role)
            self._obs.counter(
                "serving_tokens_total", "tokens emitted",
                labels=("replica", "role"),
            ).inc(sum(len(t) for t in emitted.values()),
                  replica=self.obs_replica, role=self.obs_role)
            # pool occupancy gauges are NOT exported here: they refresh
            # at scrape time via the collect hook registered at
            # construction (_export_pool_gauges) — a per-tick export
            # would freeze an idle batcher's pool metrics at the last
            # tick's values (the frozen-SLO-burn-gauge bug class)
        return emitted

    def _export_pool_gauges(self) -> None:
        """Collect-hook body: the (replica, role)-labeled pool
        occupancy/free-list/CoW gauges, computed from the pool's CURRENT
        state at every exposition (``Registry.add_collect_hook``) —
        /metrics between ticks shows live occupancy, and an idle
        batcher's gauges can never freeze. Reads ``obs_replica`` at call
        time, so a fleet's restamp after spawn is reflected."""
        if not self._obs.enabled:
            # collect hooks run even on a disabled registry; every set()
            # below would no-op anyway — skip the pool reads and the
            # memory_pressure() device poll outright
            return
        from dsml_tpu.serving.paging import export_pool_gauges

        export_pool_gauges(self._obs, self._pages,
                           self.obs_replica, self.obs_role)
        self._obs.gauge(
            "serving_memory_pressure",
            "device-memory pressure in [0,1]: measured bytes_in_use / "
            "bytes_limit when the backend reports memory_stats, else the "
            "pool's allocated-page fraction",
            labels=("replica", "role"),
        ).set(self.memory_pressure(), replica=self.obs_replica,
              role=self.obs_role)

    def _bytes_per_page(self) -> float:
        """PER-DEVICE bytes of ONE physical page — computed once from the
        live pool arrays via their addressable shards (so int4 rows, GQA
        head counts, and tp sharding are all reflected: a tp=2 pool's
        head-sharded arrays claim what ONE chip holds, not the global
        nbytes — never re-derived analytically)."""
        if self._page_nbytes is None:
            from dsml_tpu.obs.memory import tree_nbytes

            total = tree_nbytes(self._pool, per_device=True)
            self._page_nbytes = total / max(self.n_pages, 1)
        return self._page_nbytes

    def _ledger_page_bytes(self) -> dict:
        """Ledger source body: the pool's device bytes as a disjoint
        live/shared/free/scratch split (sums to the full pool allocation —
        the pool buffers are resident whatever the occupancy)."""
        if not self.paged or self._pool is None:
            return {}
        bpp = self._bytes_per_page()
        shared = self._pages.shared_pages
        return {
            "live": (self._pages.used_pages - shared) * bpp,
            "shared": shared * bpp,
            "free": self._pages.free_pages * bpp,
            "scratch": bpp,
        }

    def _ledger_weight_quant_bytes(self) -> dict:
        """Ledger source body: the compressed serving weights' resident
        device bytes, packed codes and scales split — the acceptance pin
        that quantized weights never ride HBM at full width (the ratio of
        the params row to this one is the codec's compression)."""
        return dict(self._wq_bytes)

    def memory_pressure(self) -> float:
        """Device-memory pressure in [0, 1] — the preemption tier's and
        the autoscaler's signal. MEASURED when the backend reports
        ``memory_stats`` (bytes_in_use / bytes_limit: the whole chip,
        params and XLA temps included — the number an eviction decision
        actually competes against), falling back to the pool's
        allocated-page fraction on statless backends (virtual-CPU tests:
        identical behavior to the page-count era)."""
        if not self.paged:
            return 0.0
        from dsml_tpu.obs.memory import get_memory_ledger

        measured = get_memory_ledger(self._obs).measure()
        if measured["available"] and measured.get("bytes_limit"):
            return min(max(
                measured["bytes_in_use"] / measured["bytes_limit"], 0.0), 1.0)
        allocatable = max(self.n_pages - 1, 1)
        return (allocatable - self._pages.free_pages) / allocatable

    def _step_inner(self) -> dict[int, list]:
        emitted: dict[int, list] = {}
        if self._inject:
            self._admit_injected(emitted)
        # handed-off and local admissions touch disjoint rids, so a plain
        # merge cannot clobber an emission list
        if self.paged:
            emitted.update(self._admit_paged())
        else:
            emitted.update(
                self._admit_chunked() if self.prefill_chunk else self._admit()
            )
        active = np.flatnonzero(self._slot_rid >= 0)
        if len(active) == 0:
            return emitted
        if self.speculative_window:
            return self._step_speculative(emitted, active)
        if self.paged and self.preemption:
            # lazy growth: every decoding slot must own pages for this
            # tick's writes; pressure evicts (the slots list shrinks)
            active = self._ensure_decode_pages(active, self.decode_quantum)
            if not active:
                return emitted
        steps_done = np.asarray(
            [len(self._live[rid].tokens) if rid >= 0 else 0 for rid in self._slot_rid],
            np.int32,
        )
        if self.paged:
            # paged decode tick: the page table rides along; writes scatter
            # into each slot's reserved pages (free slots' into scratch)
            toks, self._pool = self._decode_paged(
                self.params, self._pool, jnp.asarray(self._decode_table()),
                jnp.asarray(self._last_tok), jnp.asarray(self._pos),
                jnp.asarray(self._slot_key), jnp.asarray(steps_done),
            )
            self.n_plain_ticks += 1
            return self._apply_decoded(
                emitted, active, np.asarray(toks), self.decode_quantum
            )
        # adaptive early-exit tick: one dispatch decodes until any active
        # slot finishes (or k_max) — engaged whenever no chunked admission
        # is mid-flight (those need the plain quantum's chunk interleave).
        # A retirement ends the tick, so a queued request admits on the
        # very next tick: large k_max costs no admission latency
        if self._decode_adaptive is not None and self._pending is None:
            remaining = np.full(self.n_slots, self.model.config.max_seq, np.int32)
            for slot in active:
                req = self._live[int(self._slot_rid[slot])]
                remaining[slot] = req.max_new_tokens - len(req.tokens)
            toks, n_steps, self._cache = self._decode_adaptive(
                self.params,
                self._cache,
                jnp.asarray(self._last_tok),
                jnp.asarray(self._pos),
                jnp.asarray(self._slot_key),
                jnp.asarray(steps_done),
                jnp.asarray(remaining),
                jnp.asarray(self._slot_rid >= 0),
            )
            self.n_adaptive_ticks += 1
            quantum = int(n_steps)
            toks = np.asarray(toks)[:quantum]  # rows past the stop are zeros
            return self._apply_decoded(emitted, active, toks, quantum)
        # turbo escalation: in steady-state decode (nothing waiting to
        # admit) the escalated program amortizes the per-dispatch host round
        # trip turbo_factor x. Gate on the LARGEST remaining budget: with an
        # empty queue a slot freed mid-tick would sit idle under plain ticks
        # too, so turbo wastes nothing a plain schedule would have used — it
        # just needs one slot that consumes the whole tick to pay for it.
        # (A mid-tick EOS/budget finish retires exactly as under plain
        # ticks; the continuing-slot position invariant is budget-derived
        # and holds for any quantum.)
        quantum = self.decode_quantum
        decode = self._decode
        if (
            self._decode_turbo is not None
            and not self._queue
            and self._pending is None
        ):
            turbo_q = self.decode_quantum * self.turbo_factor
            remaining = max(
                self._live[int(self._slot_rid[s])].max_new_tokens
                - len(self._live[int(self._slot_rid[s])].tokens)
                for s in active
            )
            if remaining >= turbo_q:
                quantum, decode = turbo_q, self._decode_turbo
        if quantum == self.decode_quantum:
            self.n_plain_ticks += 1
        else:
            self.n_turbo_ticks += 1
        toks, self._cache = decode(
            self.params,
            self._cache,
            jnp.asarray(self._last_tok),
            jnp.asarray(self._pos),
            jnp.asarray(self._slot_key),
            jnp.asarray(steps_done),
        )
        toks = np.asarray(toks)  # [quantum, n_slots]
        return self._apply_decoded(emitted, active, toks, quantum)

    def _apply_decoded(self, emitted: dict, active, toks, quantum: int) -> dict:
        """Apply one tick's decoded tokens ``toks [quantum, n_slots]`` to
        the per-slot requests: emit, retire on EOS/budget (truncating a
        finished slot's tail), and advance continuing slots' positions."""
        for slot in active:
            req = self._live[int(self._slot_rid[slot])]
            new = emitted.setdefault(req.rid, [])
            for i in range(quantum):
                tok = int(toks[i, slot])
                req.tokens.append(tok)
                new.append(tok)
                if self._finished(req, tok):
                    self._retire(req)
                    self._slot_rid[slot] = -1  # freed → next admit reuses it
                    self._free_slot_pages(slot)
                    break
            if self._slot_rid[slot] >= 0:  # request continues
                self._pos[slot] += quantum
                # the jitted scan clamps its cache writes at max_seq-1; a
                # CONTINUING request must never need that clamp (submit()'s
                # L + max_new <= max_seq budget guarantees the next write
                # index is in range). Surface the invariant here rather
                # than silently diverge from the device-side positions.
                assert self._pos[slot] < self.model.config.max_seq, (
                    f"slot {slot} position {self._pos[slot]} escaped max_seq="
                    f"{self.model.config.max_seq}; host/device cache positions"
                    " have diverged"
                )
                self._last_tok[slot] = int(toks[-1, slot])
        return emitted

    def _active_accept_ewma(self) -> float | None:
        """The adaptive window's acceptance signal: the mean of ACTIVE
        slots' per-slot EWMAs — the requests actually in flight set the
        width, not a retired request's stale rate — falling back to the
        batcher-level EWMA while no active slot has a measurement yet
        (fresh admissions), and None before any measurement at all."""
        active = self._slot_accept[self._slot_rid >= 0]
        vals = active[~np.isnan(active)]
        if len(vals):
            return float(vals.mean())
        return self.accept_ewma

    def _spec_window_for_tick(self) -> int:
        """This tick's verify-window width. Fixed at ``speculative_window``
        unless ``speculative_adaptive``: then the width tracks the
        measured acceptance (:meth:`_active_accept_ewma`) — one draft
        beyond the expected accepted count, floored at 2, capped at the
        configured max — so a workload whose drafts stop landing stops
        paying for wide verify windows (each window column is verify
        FLOPs + cache-read bandwidth), and one whose drafts land climbs
        back to the full window. Greedy tokens are IDENTICAL at any width
        (each tick commits the model's own greedy chain), so adapting is
        pure scheduling — pinned in tests. Starts at the max width
        (optimistic) until the first acceptance measurement lands."""
        w_max = self.speculative_window
        if not self.speculative_adaptive:
            return w_max
        acc = self._active_accept_ewma()
        if acc is None:
            return w_max
        expected = 1.0 + acc * (w_max - 1)
        return max(2, min(w_max, int(np.ceil(expected)) + 1))

    def predicted_tpot_s(self) -> float | None:
        """Acceptance-aware per-token decode latency prediction: the
        measured verify-tick wall EWMA over the measured
        committed-tokens-per-slot-tick EWMA. None until both are warm (or
        when not speculating) — the router then falls back to its
        harvested TPOT EWMA. This is how per-slot acceptance feeds the
        SLO router's cost model: a worker whose drafts stop landing gets
        expensive BEFORE its harvested TPOT catches up."""
        if (not self.speculative_window or self.spec_tick_s_ewma is None
                or not self.commit_ewma):
            return None
        return self.spec_tick_s_ewma / max(self.commit_ewma, 1.0)

    def _step_speculative(self, emitted: dict, active) -> dict[int, list]:
        """One speculative tick: per-slot prompt-lookup drafts (host-side,
        the shared ``models.speculative`` rule), ONE verify call over all
        slots at their own depths (``verify_step`` dense /
        ``verify_step_paged`` through the page table), then per-slot
        greedy-chain acceptance — each active slot commits 1..w tokens.
        Inactive slots carry a dummy window at position 0 whose garbage
        rows land in their own dead cache rows (dense) or the scratch
        page (paged) and are never read. Acceptance-rate EWMAs update
        per slot here — the adaptive window and the router's TPOT cost
        model both feed on them."""
        w = self._spec_window_for_tick()
        if self.paged and self.preemption:
            # the verify window writes rows pos..pos+w-1 — grow first
            active = self._ensure_decode_pages(active, w)
            if len(active) == 0:
                return emitted
        toks = np.zeros((self.n_slots, w), np.int32)
        pos = np.zeros(self.n_slots, np.int32)
        for slot in active:
            req = self._live[int(self._slot_rid[slot])]
            history = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)]
            )
            toks[slot, 0] = self._last_tok[slot]
            toks[slot, 1:] = _lookup_draft(history, self.speculative_ngram, w - 1)
            pos[slot] = self._pos[slot]
        t0 = time.monotonic()
        if self.paged:
            logits, self._pool = self._verify_paged(
                self.params, self._pool, jnp.asarray(self._decode_table()),
                jnp.asarray(toks), jnp.asarray(pos),
            )
        else:
            logits, self._cache = self._verify(
                self.params, self._cache, jnp.asarray(toks), jnp.asarray(pos)
            )
        greedy = np.asarray(jnp.argmax(logits, axis=-1))  # [n_slots, W]
        wall = time.monotonic() - t0  # greedy pull forced the dispatch
        self.n_spec_ticks += 1
        self.spec_window_used[w] = self.spec_window_used.get(w, 0) + 1
        self.spec_tick_s_ewma = (
            wall if self.spec_tick_s_ewma is None
            else 0.8 * self.spec_tick_s_ewma + 0.2 * wall
        )
        committed_total = 0
        for slot in active:
            req = self._live[int(self._slot_rid[slot])]
            new = emitted.setdefault(req.rid, [])
            drafts = toks[slot, 1:]
            committed = 0
            measured = True  # False when retirement censors the window
            for i in range(w):
                # greedy[i] is the model's next token after consuming window
                # position i — valid iff every draft before it matched the
                # chain, which is exactly how far this loop gets
                tok = int(greedy[slot, i])
                req.tokens.append(tok)
                new.append(tok)
                self._last_tok[slot] = tok
                committed += 1
                if self._finished(req, tok):
                    self._retire(req)
                    self._slot_rid[slot] = -1  # freed → next admit reuses it
                    self._free_slot_pages(slot)
                    # EOS/budget cut the window short: the unconsumed
                    # drafts were never judged, so this tick is not an
                    # acceptance sample (unless the window was already
                    # fully accepted)
                    measured = committed == w
                    break
                if i == w - 1 or int(drafts[i]) != tok:
                    break  # draft diverged (or window exhausted): stop here
            committed_total += committed
            if measured and w > 1:
                rate = (committed - 1) / (w - 1)
                prev = self._slot_accept[slot]
                self._slot_accept[slot] = (
                    rate if np.isnan(prev) else 0.8 * prev + 0.2 * rate
                )
                self.accept_ewma = (
                    rate if self.accept_ewma is None
                    else 0.8 * self.accept_ewma + 0.2 * rate
                )
            if self._slot_rid[slot] >= 0:  # request continues
                self._pos[slot] += committed
                # the next verify window writes rows pos..pos+W-1; submit()'s
                # L + max_new + W - 1 <= max_seq budget keeps it in range
                assert self._pos[slot] + w <= self.model.config.max_seq, (
                    f"slot {slot} verify window would escape max_seq="
                    f"{self.model.config.max_seq}"
                )
        mean_commit = committed_total / len(active)
        self.commit_ewma = (
            mean_commit if self.commit_ewma is None
            else 0.8 * self.commit_ewma + 0.2 * mean_commit
        )
        if self._obs.enabled and self.accept_ewma is not None:
            self._obs.gauge(
                "serving_spec_accept_rate",
                "speculative draft acceptance rate (EWMA)",
                labels=("replica", "role"),
            ).set(self.accept_ewma, replica=self.obs_replica,
                  role=self.obs_role)
        return emitted

    def abandon(self) -> list[Request]:
        """Evacuate every UNFINISHED request — queued, mid-chunked-
        admission, and mid-decode — and reset the scheduler state (the
        replica-failure path: a ``DecodeFleet`` resubmits the returned
        requests' prompts on surviving replicas; with greedy decoding the
        re-run emits identical tokens, so a replica loss costs latency,
        never tokens). Already-retired results stay collectable via
        :meth:`collect`. Cache contents become garbage that the next
        admissions fully overwrite (the same invariant a fresh batcher
        starts with)."""
        live = [self._live[rid] for rid in sorted(self._live)]
        self._queue.clear()
        self._inject.clear()  # handed-off rows die with the replica; the
        #                       router re-prefills from the prompt
        self._live.clear()
        self._pending = None
        if self.paged and self.preemption:
            # preempted requests' pages released at eviction; their host
            # swap copies die with the replica — re-prefill reproduces
            self._preempted.clear()
        self._slot_rid[:] = -1
        self._pos[:] = 0
        self._last_tok[:] = 0
        self._slot_accept[:] = np.nan
        if self.paged:
            # every slot's pages return to the pool (registered prefix
            # pages keep the registry's reference and SURVIVE — they are
            # this worker's setup state, not a request's) — the no-leak
            # invariant the chaos smoke asserts after a replica kill
            for slot in range(self.n_slots):
                self._free_slot_pages(slot)
        if self._obs.enabled:
            from dsml_tpu.obs import flight_recorder, get_tracer

            flight_recorder.record("serving_abandon", n_requests=len(live))
            tracer = get_tracer()
            for req in live:
                if req.trace_id is not None:
                    # NOT a flow end: the router requeues these under the
                    # SAME trace — the chain continues on a survivor
                    tracer.instant(
                        "serving_abandon", trace_id=req.trace_id,
                        rid=req.rid, outcome="abandoned",
                        replica=self.obs_replica,
                    )
        return live

    def collect(self) -> dict[int, list]:
        """{rid: [tokens]} for every request retired since the last collect
        (drained — repeated calls don't re-report, and the batcher holds no
        per-request state afterwards)."""
        done = {rid: req.tokens for rid, req in self._done.items()}
        self._done.clear()
        return done

    def collect_requests(self) -> dict[int, Request]:
        """Like :meth:`collect` but returns the full :class:`Request`
        objects (tokens AND timing marks) — the router's harvest path: it
        needs per-request TTFT/TPOT samples for load-aware dispatch, which
        the token-only view discards. Drained the same way."""
        done = dict(self._done)
        self._done.clear()
        return done

    def run(self, max_steps: int = 100_000) -> dict[int, list]:
        """Drain queue + slots; returns {rid: [tokens]} for every request
        retired during (or before) this call."""
        for _ in range(max_steps):
            if (not self._queue and not self._inject
                    and self.n_active == 0 and self.n_pending == 0
                    and self.n_preempted == 0):
                break
            self.step()
        else:
            raise RuntimeError(f"serving did not drain within {max_steps} steps")
        return self.collect()
