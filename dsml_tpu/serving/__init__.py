"""Serving subsystem: continuous batching, disaggregated prefill/decode.

Grown from the single-module continuous batcher (``serving.py``, now
:mod:`dsml_tpu.serving.batcher` — every historical import keeps working)
into the fleet shape production traffic wants (docs/SERVING.md):

- :mod:`batcher`  — ``ContinuousBatcher``: slot-based continuous batching
  on one replica (chunked prefill, prefix cache, turbo/adaptive quanta,
  speculative windows), now also the DECODE-worker role: ``inject()``
  admits a request whose KV rows + first logits were prefilled elsewhere.
- :mod:`prefill`  — ``PrefillWorker``: chunked prefill to completion with
  a replicated prefix registry, producing ``Handoff`` objects.
- :mod:`handoff`  — the KV-cache handoff: in-process object handover on a
  shared host, CRC32C-framed byte codec (the ``comm/migration.py``
  framing) and ``StateDonor``/``ShardMigrator`` integration for the
  cross-host stream path.
- :mod:`router`   — ``Router``: SLO-class admission with explicit
  shedding, load-aware dispatch over N prefill + M decode workers using
  queue depth and an acceptance-aware TPOT cost model, prefix
  replication, chaos hooks.
- :mod:`paging`   — the paged-KV host side: refcounting page-pool
  allocator + copy-on-write admission planning shared by the batcher
  (decode role) and the prefill worker (docs/SERVING.md § Paged KV).

The interference problem this removes: one batcher interleaves prefill
chunks with decode quanta, so a burst of long prompts inflates every
in-flight request's per-token latency. Splitting the roles keeps decode
ticks pure decode — the burst lands on the prefill pool (the
Gemma-on-TPU disaggregation result; ``bench.py --section serving_fleet``
measures the isolation A/B at equal chip count).
"""

from dsml_tpu.serving.batcher import ContinuousBatcher, QueueFull, Request

# Fleet-layer exports resolve lazily (PEP 562, the dsml_tpu/__init__
# pattern): `from dsml_tpu.serving import ContinuousBatcher` — every
# historical import — must not drag the fleet modules (and through them
# the comm/grpc stack) into the process.
_LAZY = {
    "Handoff": "handoff",
    "HandoffIntegrityError": "handoff",
    "decode_handoff": "handoff",
    "encode_handoff": "handoff",
    "fetch_from_migrator": "handoff",
    "frame_transport": "handoff",
    "register_with_donor": "handoff",
    "PrefillWorker": "prefill",
    "Router": "router",
    "SLOClass": "router",
    "build_fleet": "router",
}

__all__ = ["ContinuousBatcher", "QueueFull", "Request", *sorted(_LAZY)]


def __getattr__(name):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(
        importlib.import_module(f"{__name__}.{module}"), name
    )
    globals()[name] = value
    return value
