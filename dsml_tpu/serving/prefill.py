"""Prefill worker: chunked prompt prefill to completion, then hand off.

One half of the disaggregated serving split (docs/SERVING.md). A
``PrefillWorker`` owns params and ONE jitted chunk program (the same
``model.prefill_chunk`` path the continuous batcher's chunked admission
uses — chunk chaining is pinned bit-identical to whole-prompt prefill),
runs at most one chunk dispatch per scheduler tick (so the router's tick
time stays bounded — pipelining across workers, not within one), and
emits a :class:`~dsml_tpu.serving.handoff.Handoff` when a prompt
completes. It never decodes: a burst of long prompts saturates prefill
workers while decode workers keep emitting tokens at their steady cadence
— the interference isolation the fleet A/B measures.

The prefix registry (``register_prefix``) is the batcher's system-prompt
pattern at the fleet level: the router replicates each registration
across every prefill worker, so any worker admits a matching prompt by
copying the master rows and chunk-prefilling only the suffix — admission
drops from O(L) to O(L − P) fleet-wide.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from dsml_tpu.obs import get_registry, get_tracer
from dsml_tpu.serving.batcher import QueueFull
from dsml_tpu.serving.handoff import Handoff

__all__ = ["PrefillWorker"]


@dataclasses.dataclass
class _Job:
    frid: int
    prompt: np.ndarray
    max_new_tokens: int
    key_rid: int | None
    submitted_at: float
    # prompt tokens this job will actually prefill (longest matching
    # prefix subtracted at submit time) — summed into the worker's O(1)
    # running load counter; re-stamped when a new prefix registers
    eff_tokens: int = 0
    # request trace context (obs.TraceContext or None): every chunk span
    # and the emitted handoff carry it — the prefill leg of the request's
    # cross-process causal chain
    trace: object = None


class PrefillWorker:
    """Chunked prefill to completion; emits handoffs, never decodes.

    ``submit`` enqueues a prompt (``frid`` is the fleet-wide id the
    handoff and the sampler identity carry; ``max_queue`` sheds with
    :class:`QueueFull` like the batcher). ``step()`` runs AT MOST one
    chunk dispatch and returns every handoff completed this tick
    (exact-prefix hits complete with zero dispatch and ride along).
    ``abandon()`` evacuates unfinished jobs for re-prefill on a survivor
    — a worker loss costs latency, never tokens, because prefill is a
    pure function of the prompt.

    Load signals for the router: :attr:`queue_tokens` (prompt tokens
    waiting or mid-flight, prefix savings already subtracted) and
    :meth:`estimate_ms` (that backlog priced at the measured per-chunk
    wall EWMA)."""

    def __init__(self, model, params, prefill_chunk: int,
                 max_queue: int = 0, paged_kv=False, page_size: int = 16,
                 n_pages: int = 0):
        cfg = model.config
        if not 0 < prefill_chunk <= cfg.max_seq:
            raise ValueError(
                f"prefill_chunk must be in [1, max_seq={cfg.max_seq}], "
                f"got {prefill_chunk}"
            )
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.model = model
        self.params = params
        self.prefill_chunk = int(prefill_chunk)
        self.max_queue = int(max_queue)
        self.obs_replica = "0"
        self.obs_role = "prefill"
        self._obs = get_registry()
        self._queue: deque[_Job] = deque()
        self._queued_tokens = 0  # running sum of queued jobs' eff_tokens
        # the in-flight job: (job, accumulating 1-row cache, next start) —
        # paged: (job, AdmissionPlan, next start)
        self._pending: tuple | None = None
        self._prefixes: list = []  # (tokens, cache1|pages, last_logits) len-desc
        self._next_frid = 0
        # page-wait flow marks dedupe per wait EPISODE (frid of the last
        # blocked queue head): the counter is per-tick, the trace mark is
        # once per episode
        self._page_wait_frid: int | None = None
        # measured per-chunk wall EWMA (seconds) — the router's prefill
        # cost model; seeded by the first real chunk
        self.chunk_s_ewma: float | None = None
        self.n_chunk_dispatches = 0
        self.n_handoffs = 0

        # ---- paged mode: prefill INTO pool pages, hand off the pages ----
        # (the paged fleet's prefill half: the handoff ships int4 pages —
        # ~8x fewer wire bytes than dense f32 rows — and a matched prefix
        # can be elided entirely when the decode side shares its own
        # registered prefix pages; the Router flips ship_prefix_pages on
        # once it has replicated every registration fleet-wide)
        self.page_quant = (None if paged_kv == "fp"
                           else model._page_mode(paged_kv))
        self.paged = bool(paged_kv)
        self.page_size = int(page_size)
        self.ship_prefix_pages = False
        if self.paged:
            if cfg.max_seq % self.page_size:
                raise ValueError(
                    f"page_size must divide max_seq={cfg.max_seq}, got "
                    f"{self.page_size}"
                )
            self._n_pt = cfg.max_seq // self.page_size
            # auto-size: one full-length job in flight + one more + scratch;
            # registrations eat into this — size n_pages for the prefix set
            self.n_pages = int(n_pages) or 2 * self._n_pt + 1
            from dsml_tpu.serving.paging import PagePool

            self._pages = PagePool(self.n_pages)
            self.n_cow_copies = 0
            pq = self.page_quant
            self._pool = model.init_page_pool(
                self.n_pages, self.page_size, quant=pq
            )

            def chunk_paged_fn(p, pool, table, toks, start, last):
                return model.prefill_chunk_paged(
                    p, pool, table, toks, start, None, last_index=last,
                    quant=pq,
                )

            self._chunk_paged = jax.jit(chunk_paged_fn, donate_argnums=(1,))
            from dsml_tpu.serving.paging import copy_page

            self._copy_page = jax.jit(copy_page, donate_argnums=(0,))
            # pages the prefix registry holds forever (the never-fits
            # checks subtract these from the reservable ceiling)
            self._registry_pages = 0
            # pool gauges refresh at SCRAPE time (weakly-held collect
            # hook): an idle worker's /metrics must show current
            # occupancy, not freeze at the last tick's export
            self._obs.add_collect_hook(self._export_pool_gauges)
        else:
            self.n_pages = 0

        def chunk_fn(p, c, toks, start, last):
            return model.prefill_chunk(p, c, toks, start, None, last_index=last)

        # one compile serves every chunk (start/last stay traced); the
        # accumulating cache is donated exactly as the batcher's chunk path
        self._chunk = jax.jit(chunk_fn, donate_argnums=(1,))
        self._fresh_cache1 = lambda: model.init_cache(1)

    # ---- request interface -----------------------------------------------

    def _fits(self, prompt_len: int) -> bool:
        c = self.prefill_chunk
        return -(-prompt_len // c) * c <= self.model.config.max_seq

    def submit(self, prompt, max_new_tokens: int, frid: int | None = None,
               key_rid: int | None = None,
               submitted_at: float | None = None, trace=None) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        # the decode worker re-validates at inject; checking here too fails
        # at the FLEET edge instead of after prefill compute was spent
        self.model._check_generate_args(len(prompt), max_new_tokens, 0.0, 0, 0)
        if not self._fits(len(prompt)):
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the chunk grid for "
                f"max_seq={self.model.config.max_seq}"
            )
        if self.paged:
            # never-fits check against the reservable ceiling (pool minus
            # scratch minus registry holdings, matched prefix's shared
            # pages credited) — a job that could only park at the queue
            # head forever must fail at submit, not wedge the worker
            from dsml_tpu.serving.paging import pages_for

            pre0 = self._match_prefix(prompt) if self._prefixes else None
            p0 = len(pre0[0]) if pre0 else 0
            grid = (p0 + -(-(len(prompt) - p0) // self.prefill_chunk)
                    * self.prefill_chunk) if len(prompt) > p0 else len(prompt)
            n_private = pages_for(grid, self.page_size) - p0 // self.page_size
            ceiling = self.n_pages - 1 - self._registry_pages
            if n_private > ceiling:
                raise ValueError(
                    f"prefill job needs {n_private} private pages but only "
                    f"{ceiling} are ever reservable ({self._registry_pages} "
                    "held by the prefix registry); raise n_pages"
                )
        if self.max_queue and len(self._queue) >= self.max_queue:
            self._obs.counter(
                "serving_shed_total", "requests rejected by the queue cap",
                labels=("replica", "role"),
            ).inc(replica=self.obs_replica, role=self.obs_role)
            raise QueueFull(
                f"prefill queue at its cap ({self.max_queue} waiting)"
            )
        if frid is None:
            frid = self._next_frid
        self._next_frid = max(self._next_frid, frid + 1)
        pre = self._match_prefix(prompt) if self._prefixes else None
        eff = len(prompt) - (len(pre[0]) if pre else 0)
        self._queue.append(_Job(
            frid=frid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            key_rid=key_rid,
            submitted_at=(time.monotonic() if submitted_at is None
                          else submitted_at),
            eff_tokens=eff,
            trace=trace,
        ))
        self._queued_tokens += eff
        return frid

    def register_prefix(self, tokens) -> None:
        """Precompute + retain KV rows and next-token logits for a shared
        prompt head — the batcher's ``register_prefix``, prefill-side.
        Blocking setup call (runs the prefix's chunked prefill now). On a
        paged worker the registration is a page-table entry: the prefix
        lands in registry-held pool pages that matching jobs SHARE during
        their suffix prefill (CoW — only a straddling tail page is ever
        copied), and that paged handoffs elide when the decode side
        shares its own registration (``ship_prefix_pages``)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prefix")
        if not self._fits(n):
            raise ValueError(
                f"prefix length {n} exceeds the chunk grid for max_seq="
                f"{self.model.config.max_seq}"
            )
        c = self.prefill_chunk
        if self.paged:
            from dsml_tpu.serving.paging import prefill_prefix_into_pages

            pages, logits, self._pool = prefill_prefix_into_pages(
                self._chunk_paged, self.params, self._pool, self._pages,
                tokens, c, self.page_size, self._n_pt,
            )
            self._registry_pages += len(pages)
            self._prefixes.append((tokens, pages, logits))
        else:
            cache1 = self._fresh_cache1()
            logits = None
            for start in range(0, n, c):
                end = min(start + c, n)
                padded = np.zeros((1, c), np.int32)
                padded[0, : end - start] = tokens[start:end]
                last_local = (n - 1) - start if end >= n else c - 1
                logits, cache1 = self._chunk(
                    self.params, cache1, jnp.asarray(padded),
                    jnp.int32(start), jnp.int32(last_local),
                )
            self._prefixes.append((tokens, cache1, np.asarray(logits[0])))
        self._prefixes.sort(key=lambda p: -len(p[0]))  # longest match wins
        # re-stamp queued jobs' effective tokens: the new prefix may cover
        # prompts submitted before it registered (setup-time cost only)
        self._queued_tokens = 0
        for job in self._queue:
            pre = self._match_prefix(job.prompt)
            job.eff_tokens = len(job.prompt) - (len(pre[0]) if pre else 0)
            self._queued_tokens += job.eff_tokens

    def _match_prefix(self, prompt: np.ndarray):
        L = len(prompt)
        c = self.prefill_chunk
        max_seq = self.model.config.max_seq
        for ptoks, pcache, plogits in self._prefixes:
            p = len(ptoks)
            if p > L or not np.array_equal(prompt[:p], ptoks):
                continue
            if p < L and p + (-(-(L - p) // c)) * c > max_seq:
                continue  # padded suffix grid would overrun the cache
            return ptoks, pcache, plogits
        return None

    # ---- load signals ----------------------------------------------------

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def n_pending(self) -> int:
        return 0 if self._pending is None else 1

    @property
    def queue_tokens(self) -> int:
        """Prompt tokens this worker still has to prefill: queued prompts
        (longest matching prefix already subtracted — registered prefixes
        cost zero) plus the in-flight job's remaining tokens. O(1): the
        queued sum is a running counter (the router's dispatch loop reads
        this per worker per backlog item per tick)."""
        total = self._queued_tokens
        if self._pending is not None:
            job, _, start = self._pending
            total += max(len(job.prompt) - start, 0)
        return total

    def estimate_ms(self, prompt_len: int = 0) -> float:
        """Estimated wall to drain the current backlog plus a hypothetical
        ``prompt_len`` prompt — queue depth priced at the measured
        per-chunk EWMA (one chunk dispatch per tick). Pre-measurement the
        estimate is 0: the router then spreads by queue depth alone."""
        if not self.chunk_s_ewma:
            return 0.0
        chunks = -(-(self.queue_tokens + prompt_len) // self.prefill_chunk)
        return chunks * self.chunk_s_ewma * 1e3

    # ---- scheduling ------------------------------------------------------

    def _gather_pages(self, page_ids) -> list:
        """Pull physical pages to host as the handoff payload — the
        shared ``paging.gather_pages`` layout (per-layer dicts with a
        leading shipped-page axis; the preemption tier's swap-out uses
        the same format). A read: master/registry pages stay intact."""
        from dsml_tpu.serving.paging import gather_pages

        return gather_pages(self._pool, page_ids)

    def _paged_handoff(self, job: _Job, pages, n_full_prefix: int) -> Handoff:
        """Assemble a paged handoff from a job's pages: with
        ``ship_prefix_pages`` the matched prefix's FULL pages are elided
        (the decode worker shares its own registration for those rows —
        ``prefix_rows`` says how many); otherwise every page ships. The
        straddling prefix page always ships — the suffix wrote into it."""
        n_skip = n_full_prefix if self.ship_prefix_pages else 0
        self.n_handoffs += 1
        self._note_handoff(job)
        return Handoff(
            frid=job.frid, prompt=job.prompt,
            max_new_tokens=job.max_new_tokens,
            prefill_len=len(job.prompt),
            cache1=self._gather_pages(pages[n_skip:]),
            logits=None,  # caller fills (registry hit vs fresh chunk)
            submitted_at=job.submitted_at,
            prefill_done_at=time.monotonic(),
            key_rid=job.key_rid,
            page_size=self.page_size,
            prefix_rows=n_skip * self.page_size,
            trace_id=(job.trace.trace_id if job.trace else None),
            parent_span="prefill_chunk",
        )

    def _note_handoff(self, job: _Job) -> None:
        """Trace the handoff emission: a flow STEP on this worker's lane
        (the prefill→decode hop the stitched timeline links through)."""
        if job.trace is not None:
            get_tracer().flow("prefill_handoff", job.trace, phase="step",
                              frid=job.frid, replica=self.obs_replica)

    def _start(self, job: _Job):
        """Begin ``job``: an exact prefix hit completes immediately (COPIED
        master rows — the stored cache must survive for the next match);
        otherwise stage the pending chunk state (prefix rows copied in as
        the starting cache when a partial hit applies). Paged: reserve the
        job's page plan first — returns the sentinel ``"wait"`` when the
        pool cannot serve it yet (the job stays queued); an exact hit
        ships straight from the registry pages, zero allocation."""
        pre = self._match_prefix(job.prompt) if self._prefixes else None
        if self.paged:
            from dsml_tpu.serving.paging import pages_for, plan_admission

            L = len(job.prompt)
            if pre is not None and len(pre[0]) == L:
                ptoks, ppages, plogits = pre
                n_full = (L // self.page_size if self.ship_prefix_pages
                          else 0)
                h = self._paged_handoff(job, list(ppages), n_full)
                h.logits = np.asarray(plogits)
                return h
            p_len = len(pre[0]) if pre else 0
            c = self.prefill_chunk
            grid_end = p_len + -(-(L - p_len) // c) * c
            plan = plan_admission(
                self._pages, self.page_size, grid_end,
                prefix_pages=pre[1] if pre else None, prefix_len=p_len,
            )
            if plan is None:
                if self._pages.used_pages == self._registry_pages:
                    # nothing in flight will ever free a page and the job
                    # still can't reserve — a prefix registered AFTER this
                    # submit shrank the ceiling past it (submit()'s
                    # never-fits check guards the normal order)
                    raise RuntimeError(
                        f"prefill job {job.frid} can never reserve its "
                        f"pages ({self._registry_pages} held by the prefix "
                        "registry); register prefixes before accepting "
                        "traffic, or raise n_pages"
                    )
                return "wait"  # pool full: the job keeps its queue spot
            if plan.copy is not None:
                src, dst = plan.copy
                self._pool = self._copy_page(
                    self._pool, jnp.int32(src), jnp.int32(dst)
                )
                self.n_cow_copies += 1
            self._pending = (job, plan, p_len)  # suffix starts at the prefix
            return None
        if pre is not None:
            ptoks, pcache, plogits = pre
            if len(ptoks) == len(job.prompt):
                self.n_handoffs += 1
                self._note_handoff(job)
                return Handoff(
                    frid=job.frid, prompt=job.prompt,
                    max_new_tokens=job.max_new_tokens,
                    prefill_len=len(job.prompt),
                    cache1=jax.tree.map(jnp.copy, pcache),
                    logits=np.asarray(plogits),
                    submitted_at=job.submitted_at,
                    prefill_done_at=time.monotonic(),
                    key_rid=job.key_rid,
                    trace_id=(job.trace.trace_id if job.trace else None),
                    parent_span="prefix_hit",
                )
            self._pending = (job, jax.tree.map(jnp.copy, pcache), len(ptoks))
            return None
        self._pending = (job, self._fresh_cache1(), 0)
        return None

    def _advance(self) -> Handoff | None:
        """Run ONE chunk of the in-flight job; returns its handoff when
        this chunk completed the prompt. Paged: the chunk writes straight
        into the job's reserved pool pages; on completion the shipped
        pages gather to host and EVERY page releases (shared prefix
        references included — the allocator's refcounts keep the registry
        masters alive)."""
        job, state, start = self._pending
        c = self.prefill_chunk
        L = len(job.prompt)
        end = min(start + c, L)
        padded = np.zeros((1, c), np.int32)
        padded[0, : end - start] = job.prompt[start:end]
        is_last = end >= L
        last_local = (L - 1) - start if is_last else c - 1
        t0 = time.monotonic()
        # one span per chunk dispatch, tagged with the request's trace —
        # the prefill leg a p99 TTFT outlier resolves to on the timeline
        with get_tracer().request_span(
            "prefill_chunk", job.trace, frid=job.frid, start=int(start),
            replica=self.obs_replica,
        ):
            if self.paged:
                plan = state
                table = np.zeros((1, self._n_pt), np.int32)
                table[0, : len(plan.pages)] = plan.pages
                logits, self._pool = self._chunk_paged(
                    self.params, self._pool, jnp.asarray(table),
                    jnp.asarray(padded), jnp.int32(start),
                    jnp.int32(last_local),
                )
            else:
                logits, state = self._chunk(
                    self.params, state, jnp.asarray(padded),
                    jnp.int32(start), jnp.int32(last_local),
                )
            logits_host = np.asarray(logits[0])  # forces the dispatch
        wall = time.monotonic() - t0
        self.n_chunk_dispatches += 1
        self.chunk_s_ewma = (
            wall if self.chunk_s_ewma is None
            else 0.8 * self.chunk_s_ewma + 0.2 * wall
        )
        if self._obs.enabled:
            self._obs.histogram(
                "serving_prefill_chunk_ms", "one prefill chunk dispatch",
                labels=("replica", "role"),
            ).observe(wall * 1e3, replica=self.obs_replica,
                      role=self.obs_role)
        if not is_last:
            self._pending = (job, state, start + c)
            return None
        self._pending = None
        if self.paged:
            h = self._paged_handoff(job, plan.pages, plan.n_shared)
            h.logits = logits_host
            self._pages.release(plan.pages)
            return h
        self.n_handoffs += 1
        self._note_handoff(job)
        return Handoff(
            frid=job.frid, prompt=job.prompt,
            max_new_tokens=job.max_new_tokens, prefill_len=L,
            cache1=state, logits=logits_host,
            submitted_at=job.submitted_at,
            prefill_done_at=time.monotonic(),
            key_rid=job.key_rid,
            trace_id=(job.trace.trace_id if job.trace else None),
            parent_span="prefill_chunk",
        )

    def step(self) -> list[Handoff]:
        """One scheduler tick: at most ONE chunk dispatch, plus any
        zero-cost exact-prefix completions reached along the way. Returns
        the handoffs completed this tick."""
        out: list[Handoff] = []
        while True:
            if self._pending is None:
                if not self._queue:
                    break
                job = self._queue[0]  # peek: a paged job that cannot
                #                       reserve pages keeps its queue spot
                h = self._start(job)
                if h == "wait":
                    from dsml_tpu.serving.paging import note_page_wait

                    first = self._page_wait_frid != job.frid
                    self._page_wait_frid = job.frid
                    note_page_wait(self._obs, self.obs_replica,
                                   self.obs_role,
                                   trace=job.trace if first else None)
                    break
                self._queue.popleft()
                self._queued_tokens -= job.eff_tokens
                if h is not None:
                    out.append(h)  # exact prefix hit: no dispatch spent
                continue
            h = self._advance()
            if h is not None:
                out.append(h)
            break  # one chunk dispatch per tick — bounded tick time
        if self._obs.enabled:
            self._obs.gauge(
                "serving_queue_depth", "requests waiting for a slot",
                labels=("replica", "role"),
            ).set(self.n_queued + self.n_pending,
                  replica=self.obs_replica, role=self.obs_role)
            self._obs.counter(
                "serving_handoffs_total",
                "prefilled requests handed to decode workers",
                labels=("replica", "role"),
            ).inc(len(out), replica=self.obs_replica, role=self.obs_role)
            # pool gauges are scrape-time (collect hook), not per-tick
        return out

    def _export_pool_gauges(self) -> None:
        """Collect-hook body: current pool occupancy/free-list/CoW
        gauges at every exposition (``Registry.add_collect_hook``)."""
        from dsml_tpu.serving.paging import export_pool_gauges

        export_pool_gauges(self._obs, self._pages,
                           self.obs_replica, self.obs_role)

    def abandon(self) -> list[dict]:
        """Evacuate every unfinished job — queued and mid-chunk — as
        resubmittable specs (the worker-loss path; a partial cache is
        dropped, re-prefill reproduces it bit-identically). The worker is
        reusable afterwards."""
        jobs = list(self._queue)
        self._queue.clear()
        self._queued_tokens = 0
        if self._pending is not None:
            jobs.insert(0, self._pending[0])  # it has waited longest
            if self.paged:  # the dead job's page reservation returns too
                self._pages.release(self._pending[1].pages)
            self._pending = None
        return [
            {"frid": j.frid, "prompt": j.prompt,
             "max_new_tokens": j.max_new_tokens, "key_rid": j.key_rid,
             "submitted_at": j.submitted_at}
            for j in jobs
        ]
