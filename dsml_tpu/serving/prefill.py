"""Prefill worker: chunked prompt prefill to completion, then hand off.

One half of the disaggregated serving split (docs/SERVING.md). A
``PrefillWorker`` owns params and ONE jitted chunk program (the same
``model.prefill_chunk`` path the continuous batcher's chunked admission
uses — chunk chaining is pinned bit-identical to whole-prompt prefill),
runs at most one chunk dispatch per scheduler tick (so the router's tick
time stays bounded — pipelining across workers, not within one), and
emits a :class:`~dsml_tpu.serving.handoff.Handoff` when a prompt
completes. It never decodes: a burst of long prompts saturates prefill
workers while decode workers keep emitting tokens at their steady cadence
— the interference isolation the fleet A/B measures.

The prefix registry (``register_prefix``) is the batcher's system-prompt
pattern at the fleet level: the router replicates each registration
across every prefill worker, so any worker admits a matching prompt by
copying the master rows and chunk-prefilling only the suffix — admission
drops from O(L) to O(L − P) fleet-wide.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from dsml_tpu.obs import get_registry
from dsml_tpu.serving.batcher import QueueFull
from dsml_tpu.serving.handoff import Handoff

__all__ = ["PrefillWorker"]


@dataclasses.dataclass
class _Job:
    frid: int
    prompt: np.ndarray
    max_new_tokens: int
    key_rid: int | None
    submitted_at: float
    # prompt tokens this job will actually prefill (longest matching
    # prefix subtracted at submit time) — summed into the worker's O(1)
    # running load counter; re-stamped when a new prefix registers
    eff_tokens: int = 0


class PrefillWorker:
    """Chunked prefill to completion; emits handoffs, never decodes.

    ``submit`` enqueues a prompt (``frid`` is the fleet-wide id the
    handoff and the sampler identity carry; ``max_queue`` sheds with
    :class:`QueueFull` like the batcher). ``step()`` runs AT MOST one
    chunk dispatch and returns every handoff completed this tick
    (exact-prefix hits complete with zero dispatch and ride along).
    ``abandon()`` evacuates unfinished jobs for re-prefill on a survivor
    — a worker loss costs latency, never tokens, because prefill is a
    pure function of the prompt.

    Load signals for the router: :attr:`queue_tokens` (prompt tokens
    waiting or mid-flight, prefix savings already subtracted) and
    :meth:`estimate_ms` (that backlog priced at the measured per-chunk
    wall EWMA)."""

    def __init__(self, model, params, prefill_chunk: int,
                 max_queue: int = 0):
        cfg = model.config
        if not 0 < prefill_chunk <= cfg.max_seq:
            raise ValueError(
                f"prefill_chunk must be in [1, max_seq={cfg.max_seq}], "
                f"got {prefill_chunk}"
            )
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.model = model
        self.params = params
        self.prefill_chunk = int(prefill_chunk)
        self.max_queue = int(max_queue)
        self.obs_replica = "0"
        self.obs_role = "prefill"
        self._obs = get_registry()
        self._queue: deque[_Job] = deque()
        self._queued_tokens = 0  # running sum of queued jobs' eff_tokens
        # the in-flight job: (job, accumulating 1-row cache, next start)
        self._pending: tuple | None = None
        self._prefixes: list = []  # (tokens, cache1, last_logits) len-desc
        self._next_frid = 0
        # measured per-chunk wall EWMA (seconds) — the router's prefill
        # cost model; seeded by the first real chunk
        self.chunk_s_ewma: float | None = None
        self.n_chunk_dispatches = 0
        self.n_handoffs = 0

        def chunk_fn(p, c, toks, start, last):
            return model.prefill_chunk(p, c, toks, start, None, last_index=last)

        # one compile serves every chunk (start/last stay traced); the
        # accumulating cache is donated exactly as the batcher's chunk path
        self._chunk = jax.jit(chunk_fn, donate_argnums=(1,))
        self._fresh_cache1 = lambda: model.init_cache(1)

    # ---- request interface -----------------------------------------------

    def _fits(self, prompt_len: int) -> bool:
        c = self.prefill_chunk
        return -(-prompt_len // c) * c <= self.model.config.max_seq

    def submit(self, prompt, max_new_tokens: int, frid: int | None = None,
               key_rid: int | None = None,
               submitted_at: float | None = None) -> int:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("empty prompt")
        # the decode worker re-validates at inject; checking here too fails
        # at the FLEET edge instead of after prefill compute was spent
        self.model._check_generate_args(len(prompt), max_new_tokens, 0.0, 0, 0)
        if not self._fits(len(prompt)):
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the chunk grid for "
                f"max_seq={self.model.config.max_seq}"
            )
        if self.max_queue and len(self._queue) >= self.max_queue:
            self._obs.counter(
                "serving_shed_total", "requests rejected by the queue cap",
                labels=("replica", "role"),
            ).inc(replica=self.obs_replica, role=self.obs_role)
            raise QueueFull(
                f"prefill queue at its cap ({self.max_queue} waiting)"
            )
        if frid is None:
            frid = self._next_frid
        self._next_frid = max(self._next_frid, frid + 1)
        pre = self._match_prefix(prompt) if self._prefixes else None
        eff = len(prompt) - (len(pre[0]) if pre else 0)
        self._queue.append(_Job(
            frid=frid, prompt=prompt, max_new_tokens=int(max_new_tokens),
            key_rid=key_rid,
            submitted_at=(time.monotonic() if submitted_at is None
                          else submitted_at),
            eff_tokens=eff,
        ))
        self._queued_tokens += eff
        return frid

    def register_prefix(self, tokens) -> None:
        """Precompute + retain KV rows and next-token logits for a shared
        prompt head — the batcher's ``register_prefix``, prefill-side.
        Blocking setup call (runs the prefix's chunked prefill now)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        n = len(tokens)
        if n < 1:
            raise ValueError("empty prefix")
        if not self._fits(n):
            raise ValueError(
                f"prefix length {n} exceeds the chunk grid for max_seq="
                f"{self.model.config.max_seq}"
            )
        c = self.prefill_chunk
        cache1 = self._fresh_cache1()
        logits = None
        for start in range(0, n, c):
            end = min(start + c, n)
            padded = np.zeros((1, c), np.int32)
            padded[0, : end - start] = tokens[start:end]
            last_local = (n - 1) - start if end >= n else c - 1
            logits, cache1 = self._chunk(
                self.params, cache1, jnp.asarray(padded),
                jnp.int32(start), jnp.int32(last_local),
            )
        self._prefixes.append((tokens, cache1, np.asarray(logits[0])))
        self._prefixes.sort(key=lambda p: -len(p[0]))  # longest match wins
        # re-stamp queued jobs' effective tokens: the new prefix may cover
        # prompts submitted before it registered (setup-time cost only)
        self._queued_tokens = 0
        for job in self._queue:
            pre = self._match_prefix(job.prompt)
            job.eff_tokens = len(job.prompt) - (len(pre[0]) if pre else 0)
            self._queued_tokens += job.eff_tokens

    def _match_prefix(self, prompt: np.ndarray):
        L = len(prompt)
        c = self.prefill_chunk
        max_seq = self.model.config.max_seq
        for ptoks, pcache, plogits in self._prefixes:
            p = len(ptoks)
            if p > L or not np.array_equal(prompt[:p], ptoks):
                continue
            if p < L and p + (-(-(L - p) // c)) * c > max_seq:
                continue  # padded suffix grid would overrun the cache
            return ptoks, pcache, plogits
        return None

    # ---- load signals ----------------------------------------------------

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def n_pending(self) -> int:
        return 0 if self._pending is None else 1

    @property
    def queue_tokens(self) -> int:
        """Prompt tokens this worker still has to prefill: queued prompts
        (longest matching prefix already subtracted — registered prefixes
        cost zero) plus the in-flight job's remaining tokens. O(1): the
        queued sum is a running counter (the router's dispatch loop reads
        this per worker per backlog item per tick)."""
        total = self._queued_tokens
        if self._pending is not None:
            job, _, start = self._pending
            total += max(len(job.prompt) - start, 0)
        return total

    def estimate_ms(self, prompt_len: int = 0) -> float:
        """Estimated wall to drain the current backlog plus a hypothetical
        ``prompt_len`` prompt — queue depth priced at the measured
        per-chunk EWMA (one chunk dispatch per tick). Pre-measurement the
        estimate is 0: the router then spreads by queue depth alone."""
        if not self.chunk_s_ewma:
            return 0.0
        chunks = -(-(self.queue_tokens + prompt_len) // self.prefill_chunk)
        return chunks * self.chunk_s_ewma * 1e3

    # ---- scheduling ------------------------------------------------------

    def _start(self, job: _Job) -> Handoff | None:
        """Begin ``job``: an exact prefix hit completes immediately (COPIED
        master rows — the stored cache must survive for the next match);
        otherwise stage the pending chunk state (prefix rows copied in as
        the starting cache when a partial hit applies)."""
        pre = self._match_prefix(job.prompt) if self._prefixes else None
        if pre is not None:
            ptoks, pcache, plogits = pre
            if len(ptoks) == len(job.prompt):
                self.n_handoffs += 1
                return Handoff(
                    frid=job.frid, prompt=job.prompt,
                    max_new_tokens=job.max_new_tokens,
                    prefill_len=len(job.prompt),
                    cache1=jax.tree.map(jnp.copy, pcache),
                    logits=np.asarray(plogits),
                    submitted_at=job.submitted_at,
                    prefill_done_at=time.monotonic(),
                    key_rid=job.key_rid,
                )
            self._pending = (job, jax.tree.map(jnp.copy, pcache), len(ptoks))
            return None
        self._pending = (job, self._fresh_cache1(), 0)
        return None

    def _advance(self) -> Handoff | None:
        """Run ONE chunk of the in-flight job; returns its handoff when
        this chunk completed the prompt."""
        job, cache1, start = self._pending
        c = self.prefill_chunk
        L = len(job.prompt)
        end = min(start + c, L)
        padded = np.zeros((1, c), np.int32)
        padded[0, : end - start] = job.prompt[start:end]
        is_last = end >= L
        last_local = (L - 1) - start if is_last else c - 1
        t0 = time.monotonic()
        logits, cache1 = self._chunk(
            self.params, cache1, jnp.asarray(padded),
            jnp.int32(start), jnp.int32(last_local),
        )
        logits_host = np.asarray(logits[0])  # forces the dispatch to finish
        wall = time.monotonic() - t0
        self.n_chunk_dispatches += 1
        self.chunk_s_ewma = (
            wall if self.chunk_s_ewma is None
            else 0.8 * self.chunk_s_ewma + 0.2 * wall
        )
        if self._obs.enabled:
            self._obs.histogram(
                "serving_prefill_chunk_ms", "one prefill chunk dispatch",
                labels=("replica", "role"),
            ).observe(wall * 1e3, replica=self.obs_replica,
                      role=self.obs_role)
        if not is_last:
            self._pending = (job, cache1, start + c)
            return None
        self._pending = None
        self.n_handoffs += 1
        return Handoff(
            frid=job.frid, prompt=job.prompt,
            max_new_tokens=job.max_new_tokens, prefill_len=L,
            cache1=cache1, logits=logits_host,
            submitted_at=job.submitted_at,
            prefill_done_at=time.monotonic(),
            key_rid=job.key_rid,
        )

    def step(self) -> list[Handoff]:
        """One scheduler tick: at most ONE chunk dispatch, plus any
        zero-cost exact-prefix completions reached along the way. Returns
        the handoffs completed this tick."""
        out: list[Handoff] = []
        while True:
            if self._pending is None:
                if not self._queue:
                    break
                job = self._queue.popleft()
                self._queued_tokens -= job.eff_tokens
                h = self._start(job)
                if h is not None:
                    out.append(h)  # exact prefix hit: no dispatch spent
                continue
            h = self._advance()
            if h is not None:
                out.append(h)
            break  # one chunk dispatch per tick — bounded tick time
        if self._obs.enabled:
            self._obs.gauge(
                "serving_queue_depth", "requests waiting for a slot",
                labels=("replica", "role"),
            ).set(self.n_queued + self.n_pending,
                  replica=self.obs_replica, role=self.obs_role)
            self._obs.counter(
                "serving_handoffs_total",
                "prefilled requests handed to decode workers",
                labels=("replica", "role"),
            ).inc(len(out), replica=self.obs_replica, role=self.obs_role)
        return out

    def abandon(self) -> list[dict]:
        """Evacuate every unfinished job — queued and mid-chunk — as
        resubmittable specs (the worker-loss path; a partial cache is
        dropped, re-prefill reproduces it bit-identically). The worker is
        reusable afterwards."""
        jobs = list(self._queue)
        self._queue.clear()
        self._queued_tokens = 0
        if self._pending is not None:
            jobs.insert(0, self._pending[0])  # it has waited longest
            self._pending = None
        return [
            {"frid": j.frid, "prompt": j.prompt,
             "max_new_tokens": j.max_new_tokens, "key_rid": j.key_rid,
             "submitted_at": j.submitted_at}
            for j in jobs
        ]
