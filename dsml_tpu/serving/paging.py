"""Host-side page-pool accounting for the paged KV cache.

The device side of paged serving is three model programs
(``decode_step_slots_paged`` / ``prefill_chunk_paged`` /
``verify_step_paged`` — gathered attention through a page table, scatter
writes at (physical page, row)). This module is the HOST side those
programs trust: a refcounting allocator over the physical pages and the
copy-on-write admission planner. Two invariants carry the whole design:

- **Writes only land in private pages.** The planner shares only the
  FULL pages of a matched prefix (rows ``[0, ⌊P/page⌋·page)``); a prefix
  whose tail straddles a page boundary gets that one page materialized
  privately (``copy``), because the suffix prefill — and later decode —
  writes into it. Everything past the prefix is freshly allocated. So a
  shared page is read-only by construction, and refcounts only ever
  gate RECLAMATION, never correctness.
- **Reservation up front, zero mid-flight preemption** (the default).
  Admission reserves every page the request can EVER touch (prompt grid
  + decode budget + speculative window) before the first chunk runs; a
  request that can't reserve waits in the queue. Decode therefore never
  runs out of pages mid-flight — the simple scheduler stays simple, and
  the capacity story is still 4-8× (int4 rows + right-sized reservation
  vs a dense max_seq slot; docs/TUNING.md has the accounting).
  ``ContinuousBatcher(preemption=True)`` replaces the worst-case
  reservation with an EVICTION tier: admission reserves only the prompt
  grid, decode grows page-by-page, and under pressure the
  lowest-priority slot's private pages swap to host (the handoff page
  payload layout — :func:`gather_pages`) or drop for
  recompute-from-prompt; refcounted CoW prefix pages are never evicted
  while shared (releasing a reference never frees a page another owner
  holds). Tokens are identical either way — preemption is pure
  scheduling (docs/SERVING.md § Paged KV).

Page 0 is the SCRATCH page: never allocated, named by every free/retired
slot's table entries, so a dead slot's (masked, never-read) writes can't
corrupt a live slot's pages.

Shared by ``ContinuousBatcher`` (decode role) and ``PrefillWorker`` so
the two ends of a paged KV handoff cannot drift on allocation rules.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

__all__ = [
    "PagePool",
    "AdmissionPlan",
    "pages_for",
    "plan_admission",
    "copy_page",
    "gather_pages",
    "prefill_prefix_into_pages",
    "export_pool_gauges",
    "note_page_wait",
]


def pages_for(rows: int, page_size: int) -> int:
    """Physical pages needed to hold ``rows`` token rows."""
    return -(-int(rows) // int(page_size))


class PagePool:
    """Refcounting free-list allocator over ``n_pages`` physical pages.

    Page 0 is reserved as the scratch page and never handed out. ``alloc``
    gives fresh pages at refcount 1; ``share`` bumps an already-owned
    page (the CoW prefix path); ``release`` drops one reference and
    returns the page to the free list when the count hits zero. The pool
    raises on double-free/over-release — an allocator bug must crash the
    test that found it, not silently corrupt a neighbor's cache rows.
    """

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError(
                f"need n_pages >= 2 (page 0 is the scratch page), got {n_pages}"
            )
        self.n_pages = int(n_pages)
        self._free: deque[int] = deque(range(1, self.n_pages))
        self._ref = np.zeros(self.n_pages, np.int32)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.n_pages - 1 - len(self._free)

    @property
    def shared_pages(self) -> int:
        """Pages currently referenced by MORE than one owner — the live
        CoW sharing the occupancy gauges report."""
        return int((self._ref > 1).sum())

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def can_alloc(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, n: int) -> list[int]:
        if n < 0:
            raise ValueError(f"cannot alloc {n} pages")
        if len(self._free) < n:
            raise RuntimeError(
                f"page pool exhausted: {n} requested, {len(self._free)} free "
                f"of {self.n_pages} — callers must check can_alloc and wait"
            )
        pages = [self._free.popleft() for _ in range(n)]
        self._ref[pages] = 1
        return pages

    def share(self, pages) -> None:
        pages = list(pages)
        if any(self._ref[p] < 1 for p in pages):
            raise RuntimeError(f"sharing unowned page(s) in {pages}")
        for p in pages:
            self._ref[p] += 1

    def release(self, pages) -> None:
        for p in pages:
            if p == 0 or self._ref[p] < 1:
                raise RuntimeError(f"releasing free/scratch page {p}")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                self._free.append(int(p))


@dataclasses.dataclass
class AdmissionPlan:
    """One admission's page assignment. ``pages`` is the slot's table
    prefix in order (shared prefix pages first, then private); the first
    ``n_shared`` entries are read-only shared pages; ``copy`` is the one
    (src, dst) CoW materialization when the prefix tail straddles a page
    boundary (dst is ``pages[n_shared]``), else None."""

    pages: list
    n_shared: int
    copy: tuple | None


def plan_admission(pool: PagePool, page_size: int, total_rows: int,
                   prefix_pages=None, prefix_len: int = 0,
                   share_prefix: bool = True) -> AdmissionPlan | None:
    """Plan a request's page reservation: share the matched prefix's full
    pages, privately materialize a straddling prefix tail page, allocate
    the rest fresh. ``total_rows`` must cover everything the request can
    ever write (prompt chunk grid, decode budget, speculative window —
    the caller computes it). Returns None when the pool cannot serve the
    reservation right now (the request waits); the plan is applied
    atomically — on None, no counts changed. ``share_prefix=False``
    plans the same page count without sharing (the A/B baseline the
    CoW win is measured against)."""
    n_need = pages_for(total_rows, page_size)
    n_full = 0
    straddle = None
    if prefix_pages is not None and prefix_len > 0 and share_prefix:
        n_full = min(int(prefix_len) // int(page_size), len(prefix_pages))
        if prefix_len % page_size and n_full < len(prefix_pages):
            straddle = int(prefix_pages[n_full])
    n_private = n_need - n_full
    if n_private < 0:
        # a prefix longer than the reservation can't happen (the caller's
        # total_rows includes the whole prompt + decode budget, and the
        # matched prefix is a prompt prefix) — fail loudly if it does
        raise ValueError(
            f"prefix covers {n_full} pages but the request reserves only "
            f"{n_need}"
        )
    if not pool.can_alloc(n_private):
        return None
    shared = [int(p) for p in (prefix_pages[:n_full] if n_full else [])]
    private = pool.alloc(n_private)
    pool.share(shared)
    copy = (straddle, private[0]) if (straddle is not None and n_private) else None
    return AdmissionPlan(pages=shared + private, n_shared=n_full, copy=copy)


def copy_page(pool, src, dst):
    """Duplicate one physical page across every layer/entry — the CoW
    materialization of a straddling prefix tail. THE one copy kernel:
    batcher and prefill worker both jit this (``donate_argnums=(0,)``),
    so the two ends of a paged fleet cannot drift on copy semantics."""
    return [
        {key: a.at[dst].set(a[src]) for key, a in c.items()}
        for c in pool
    ]


def gather_pages(pool, page_ids) -> list:
    """Pull physical pages to host — per-layer dicts with a leading
    shipped-page axis, the decode pool's own entry layout. THE one paged
    row-payload format: prefill workers assemble handoffs from it
    (``serving.handoff`` frames/CRCs it for the wire), and the
    preemption tier's swap-out rides the SAME layout, so a swapped
    request's host copy installs back through the identical
    ``_install_pages`` scatter a handoff uses. A read: master/registry
    pages stay intact."""
    import jax.numpy as jnp

    idx = jnp.asarray(list(page_ids), jnp.int32)
    return [
        {key: np.asarray(arr[idx]) for key, arr in c.items()}
        for c in pool
    ]


def prefill_prefix_into_pages(chunk, params, pool, allocator, tokens,
                              chunk_size: int, page_size: int, n_pt: int):
    """Chunk-prefill a PREFIX into freshly allocated registry pages — THE
    one paged-registration algorithm (batcher decode role and prefill
    worker both register through here; fleet-level CoW elision rests on
    both ends' registry pages being byte-identical). ``chunk`` is the
    caller's jitted paged-chunk program ``(params, pool, table, padded,
    start, last) -> (logits, pool)``. Pages the padded final chunk
    touches beyond the prefix (pad garbage) are released right back —
    the registry keeps exactly ⌈len(tokens)/page_size⌉ pages. Returns
    ``(kept_pages, last_logits, new_pool)``; raises RuntimeError when
    the pool cannot stage the chunk grid."""
    n = len(tokens)
    grid_end = -(-n // chunk_size) * chunk_size
    n_keep = pages_for(n, page_size)
    n_grid = pages_for(grid_end, page_size)
    if not allocator.can_alloc(n_grid):
        raise RuntimeError(
            f"page pool too full to register a {n}-token prefix "
            f"({n_grid} pages needed, {allocator.free_pages} free)"
        )
    pages = allocator.alloc(n_grid)
    table = np.zeros((1, n_pt), np.int32)
    table[0, :n_grid] = pages
    logits = None
    for start in range(0, n, chunk_size):
        end = min(start + chunk_size, n)
        padded = np.zeros((1, chunk_size), np.int32)
        padded[0, : end - start] = tokens[start:end]
        last_local = (n - 1) - start if end >= n else chunk_size - 1
        logits, pool = chunk(params, pool, table, padded,
                             np.int32(start), np.int32(last_local))
    if n_grid > n_keep:  # pad-only pages hold nothing shareable
        allocator.release(pages[n_keep:])
    return pages[:n_keep], np.asarray(logits[0]), pool


def export_pool_gauges(obs, pool: PagePool, replica: str, role: str) -> None:
    """The (replica, role)-labeled occupancy/free-list/CoW gauges every
    paged worker exports per tick (docs/OBSERVABILITY.md)."""
    for name, help_, value in (
        ("serving_page_pool_used", "pool pages in use", pool.used_pages),
        ("serving_page_pool_free", "pool pages on the free list",
         pool.free_pages),
        ("serving_page_pool_shared", "pages shared by >1 owner (CoW)",
         pool.shared_pages),
    ):
        obs.gauge(name, help_, labels=("replica", "role")).set(
            value, replica=replica, role=role
        )


def note_page_wait(obs, replica: str, role: str, trace=None) -> None:
    """Count one pool-pressure wait tick (an admission that could not
    reserve its page plan and stayed queued) and, when ``trace`` is
    passed, mark the wait on the request's flow — page-pool pressure is
    a real TTFT stage and must be attributable per request, not just
    visible as a gauge dip (docs/OBSERVABILITY.md § Request tracing &
    SLO budgets). Callers pass ``trace`` only on the FIRST blocked tick
    of a wait episode: a request stuck for thousands of ticks must not
    flood its causal chain with arrows or churn the bounded span buffer
    out of the events a postmortem needs (the counter stays per-tick)."""
    if not obs.enabled:
        return
    obs.counter(
        "serving_page_wait_total",
        "admission ticks spent waiting for pool pages",
        labels=("replica", "role"),
    ).inc(replica=replica, role=role)
    if trace is not None:
        from dsml_tpu.obs import get_tracer

        get_tracer().flow("page_wait", trace, phase="step", replica=replica)
