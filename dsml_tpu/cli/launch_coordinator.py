"""Launch the GPUCoordinator server.

Reference counterpart: ``DSML/cmd/gpu_coordinator_server/main.go`` (hard-coded
:50051). Health-loop cadence, dial retries, and the collective algorithm are
flags here.

Usage:
    python -m dsml_tpu.cli.launch_coordinator --port 50051
"""

from __future__ import annotations

import dataclasses
import time

from dsml_tpu.comm.coordinator import CoordinatorConfig
from dsml_tpu.utils.config import Config, field


@dataclasses.dataclass
class CoordinatorCLIConfig(Config):
    port: int = field(50051, help="bind port (reference default)")
    host: str = field("127.0.0.1", help="bind address")
    coordinator: CoordinatorConfig = field(default_factory=CoordinatorConfig)


def main(argv=None) -> None:
    cfg = CoordinatorCLIConfig.parse_args(argv)
    from dsml_tpu.comm.coordinator import serve_coordinator
    from dsml_tpu.utils.logging import get_logger

    handle = serve_coordinator(port=cfg.port, config=cfg.coordinator, host=cfg.host)
    get_logger("launch").info("coordinator on %s", handle.address)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        handle.stop()


if __name__ == "__main__":
    main()
