"""L3 — process launchers (device host, coordinator, trainer)."""
