"""Launch GPUDevice servers, one per local accelerator chip.

Reference counterpart: ``DSML/cmd/gpu_device_server/main.go`` (3 servers on
hard-coded ports 5003-5005). Here everything is configurable (SURVEY.md §5.6)
and each server fronts a real ``jax.Device``.

Usage:
    python -m dsml_tpu.cli.launch_devices --num_devices 3 --base_port 5003
"""

from __future__ import annotations

import dataclasses
import time

from dsml_tpu.utils.config import Config, field


@dataclasses.dataclass
class DeviceHostConfig(Config):
    num_devices: int = field(0, help="number of device servers (0 = one per local chip)")
    base_port: int = field(5003, help="first port; server i binds base_port+i (0 = ephemeral)")
    base_device_id: int = field(1, help="deviceId of the first server (reference uses 1..3)")
    # Large enough by default for the MLP weight/grad buffers (~437 KB each)
    # the on-device compute path serves; the reference's 12 KB (0x3000) only
    # fit its streamed test payloads.
    mem_size: int = field(0x400000, help="per-device address-space size in bytes")
    host: str = field("127.0.0.1", help="bind address")
    mlp_sizes: tuple[int, ...] = field(default_factory=lambda: (784, 128, 64, 10),
                                       help="layer sizes for the on-device MLP (RunForward/RunBackward)")
    platform: str = field("", help="jax platform override: cpu|tpu ('' = container default)")
    cpu_devices: int = field(0, help="virtual CPU device count when --platform cpu")


def main(argv=None) -> None:
    cfg = DeviceHostConfig.parse_args(argv)
    from dsml_tpu.utils.platform import configure_platform

    configure_platform(cfg.platform, cfg.cpu_devices)
    import jax

    from dsml_tpu.comm.device_server import serve_local_devices
    from dsml_tpu.models.mlp import MLP
    from dsml_tpu.utils.logging import get_logger

    log = get_logger("launch")
    n = cfg.num_devices or len(jax.devices())
    ports = None if cfg.base_port == 0 else [cfg.base_port + i for i in range(n)]
    handles = serve_local_devices(
        n,
        base_device_id=cfg.base_device_id,
        mem_size=cfg.mem_size,
        ports=ports,
        model=MLP(cfg.mlp_sizes),
    )
    for h in handles:
        log.info(
            "device %d on %s (jax device: %s)", h.runtime.device_id, h.address, h.runtime.jax_device
        )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        for h in handles:
            h.stop()


if __name__ == "__main__":
    main()
