"""Device-mesh construction — the substrate every parallelism axis rides on.

The reference's "communicator" is a rank-ordered list of dialed devices
(``gpu_coordinator_server.go:121-192``); scaling strategies beyond DP exist
only in its literature corpus (SURVEY.md §2.3). Here the communicator's
TPU-native generalization is a named ``jax.sharding.Mesh`` with one axis per
strategy:

    pp   pipeline stages          (outermost: least traffic, coarsest grain)
    dp   data parallelism / ZeRO  (gradient psum)
    fsdp param sharding           (all-gather weights, reduce-scatter grads)
    sp   sequence ring (legacy)   (XLA ring attention ppermute neighbors)
    cp   context parallelism      (flash ring attention: per-layer KV block
                                   streaming — heavy traffic, near-innermost)
    tp   tensor parallelism       (innermost: highest-bandwidth collectives)

Axis order is laid out so the highest-traffic axes map to adjacent chips on
the ICI torus (XLA assigns the innermost mesh axis the fastest locality); EP
(expert parallel) aliases onto the ``tp`` axis at MoE layers — experts are
sharded over tp and token payloads ride ``all_to_all`` across it
(``models/gpt2.py:_moe_block``) — rather than occupying a dedicated mesh
axis, keeping the expert exchange on the fastest interconnect (the
LoongTrain/DeepSpeed-style fast/slow split, SURVEY.md §5.7).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np
from jax.sharding import Mesh

from dsml_tpu.utils.config import Config, field

AXES = ("pp", "dp", "fsdp", "sp", "cp", "tp")


@dataclasses.dataclass
class MeshSpec(Config):
    pp: int = field(1, help="pipeline-parallel stages")
    dp: int = field(0, help="data-parallel size (0 = absorb remaining devices)")
    fsdp: int = field(1, help="fully-sharded data-parallel (param sharding) size")
    sp: int = field(1, help="sequence-parallel ring size (XLA online-softmax ring)")
    cp: int = field(1, help="context-parallel ring size (flash ring attention)")
    tp: int = field(1, help="tensor-parallel size")

    def resolved(self, n_devices: int) -> "MeshSpec":
        """Fill dp=0 with whatever devices remain after the fixed axes."""
        fixed = self.pp * self.fsdp * self.sp * self.cp * self.tp
        dp = self.dp
        if dp == 0:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by pp*fsdp*sp*cp*tp={fixed}")
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {self.sizes_dict() | {'dp': dp}} needs {dp * fixed} devices, have {n_devices}"
            )
        return dataclasses.replace(self, dp=dp)

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "MeshSpec":
        """The spec a live mesh realizes (absent axes = size 1) — the one
        conversion the hybrid step and the elastic controller both use."""
        return cls(**{a: mesh.shape.get(a, 1) for a in AXES})

    def seq_axis(self) -> str:
        """The mesh axis the SEQUENCE dimension shards over — ``cp`` (flash
        ring attention) when cp > 1, else the legacy ``sp`` ring. At most one
        may exceed 1: composing two sequence rings needs the 2D attention
        grid, which rides ``tp × sp`` (``ops.attention.attention_2d``)."""
        if self.sp > 1 and self.cp > 1:
            raise ValueError(
                f"sp={self.sp} and cp={self.cp} both >1: pick ONE sequence "
                "ring (2D sequence grids ride tp × sp via attention_2d)"
            )
        return "cp" if self.cp > 1 else "sp"

    def sizes_dict(self) -> dict:
        return {a: getattr(self, a) for a in AXES}

    @property
    def n_devices(self) -> int:
        return math.prod(self.sizes_dict().values())


def build_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    """Build the named mesh over ``devices`` (default: all local devices)."""
    import jax

    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolved(len(devices))
    shape = tuple(getattr(spec, a) for a in AXES)
    return Mesh(np.asarray(devices).reshape(shape), AXES)


def data_mesh(n: int | None = None, devices=None) -> Mesh:
    """Pure-DP mesh over n (default all) devices."""
    import jax

    devices = list(devices if devices is not None else jax.devices())[: n or None]
    return build_mesh(MeshSpec(dp=len(devices)), devices)


def multislice_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    """Multi-slice mesh: ``dp`` (and only dp) spans the DCN between slices,
    every other axis stays inside a slice's ICI — the LoongTrain fast/slow
    split (SURVEY.md §5.7) at pod scale, and the layout
    ``hierarchical_all_reduce('ici_axes', 'dp')`` assumes.

    Devices are grouped by their ``slice_index`` attribute (real multi-slice
    TPU runtimes expose it; hosts without one — CPU meshes, single slices —
    fall back to one virtual slice, making this a drop-in ``build_mesh``).
    Requirements: equal devices per slice; spec.dp must equal
    ``n_slices × per-slice dp remainder`` — i.e. the non-dp axes must fit
    inside ONE slice, which is exactly the property that keeps tp/sp/fsdp
    collectives off the DCN.
    """
    import jax

    devices = list(devices if devices is not None else jax.devices())
    spec = (spec or MeshSpec()).resolved(len(devices))
    return Mesh(_multislice_layout(devices, spec), AXES)


def _multislice_layout(devices, spec: MeshSpec) -> np.ndarray:
    """The device array for :func:`multislice_mesh` (separable for tests:
    works on any objects carrying ``slice_index``)."""
    slices: dict = {}
    for d in devices:
        slices.setdefault(getattr(d, "slice_index", 0), []).append(d)
    n_slices = len(slices)
    per_slice = [len(v) for v in slices.values()]
    if len(set(per_slice)) != 1:
        raise ValueError(f"unequal slice sizes {per_slice}; a mesh needs a rectangle")
    inner = spec.pp * spec.fsdp * spec.sp * spec.cp * spec.tp
    if spec.dp % n_slices:
        raise ValueError(f"dp={spec.dp} not divisible by n_slices={n_slices}")
    if inner * (spec.dp // n_slices) != per_slice[0]:
        raise ValueError(
            f"non-dp axes (pp*fsdp*sp*cp*tp={inner}) x per-slice dp "
            f"({spec.dp // n_slices}) must fill one slice ({per_slice[0]} devices); "
            "shrink tp/sp/pp so they fit inside a slice — crossing the DCN with "
            "them defeats the point of the multislice layout"
        )
    # device order: slice-major on the dp axis → dp index = slice * dp_per + i,
    # so every non-dp axis (and the intra-slice part of dp) stays on ICI and
    # only the outer dp hops ride the DCN
    ordered = [d for k in sorted(slices) for d in slices[k]]
    shape = tuple(getattr(spec, a) for a in AXES)
    arr = np.empty(len(ordered), dtype=object)
    arr[:] = ordered
    arr = arr.reshape(
        n_slices, spec.dp // n_slices, spec.pp, spec.fsdp, spec.sp, spec.cp, spec.tp
    )
    return arr.transpose(2, 0, 1, 3, 4, 5, 6).reshape(shape)
