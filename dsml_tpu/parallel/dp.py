"""Data parallelism: sharded-batch train steps with real gradient sync.

The reference's "data parallelism" computed one full-batch forward/backward
on the client CPU and shipped *identical* gradients to every device, so its
all-reduce was a functional no-op (SURVEY.md §2.3, §8.4). Here the global
batch is sharded across the ``dp`` mesh axis and gradients genuinely sync:

- ``algorithm="xla"``  — batch carries ``P('dp')`` sharding into ``jit``; XLA
  propagates shardings and inserts the topology-optimal all-reduce for the
  mean-loss gradient. The default for training.
- ``algorithm="ring"`` — explicit ``shard_map``: per-shard grads sync through
  the 2(n-1)-step ``ppermute`` ring (``dsml_tpu.ops.collectives``) — the
  reference's AllReduceRing schedule with honest semantics, usable
  end-to-end in training (BASELINE.md config: "MNIST MLP, 4 TPU devices,
  ring AllReduce"). ``"ring2"`` is the bidirectional variant, ``"auto"``
  picks ring-vs-naive per payload.
- ``algorithm="naive"`` — gather-everything baseline, for benchmarks.
- ``algorithm="q8"``   — v1 8-bit compressed sync: per-rank gradients
  quantize to blockwise int8 with stochastic rounding, then ALL-GATHER
  (O(n) wire bytes per rank; unbiased — ``dsml_tpu.ops.quantization``).
- ``algorithm="q8_ring" / "q8_ring2" / "q4_ring" / "q4_ring2"`` — v2
  block-quantized ring schedules (EQuARX-style): int8/int4 quantization
  INSIDE the 2(n−1)-step ring — quantize each scatter-reduce hop's chunk,
  dequantize-accumulate, re-quantize for the next hop; bandwidth-optimal
  volume at 8/4 bits per element. ``"quant"`` picks the scheme per
  gradient dtype from ``DSML_QUANT``.
- ``error_feedback=True`` (quantized ring algorithms only): per-leaf
  per-rank residual buffers fold the compression error into the next
  step's gradients (EF-SGD), so repeated quantized syncs don't drift. The
  step then carries the residual tree as explicit state —
  ``step(params, opt_state, ef, x, y) -> (params, opt_state, ef, loss)``
  — initialized by ``parallel.bucketing.init_error_feedback`` and
  checkpointable like params (the trainer rides it in the manifest).

Every explicit algorithm syncs through ``parallel.bucketing``: the gradient
pytree partitions into ~``bucket_size_mb``-MiB buckets and each bucket's
reduction is an INDEPENDENT collective inside the jitted step, so XLA's
latency-hiding scheduler can overlap early buckets' exchange with the rest
of the backward (and quantized syncs quantize per bucket instead of
serializing one full-vector ravel→quantize). ``bucket_size_mb=None``
restores the old single-buffer sync bit-for-bit, for A/B measurement.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsml_tpu.obs import (
    flight_recorder,
    record_collective_plan,
    record_quant_sync_bytes,
)
from dsml_tpu.ops.collectives import ReduceOp
from dsml_tpu.parallel.bucketing import (
    bucketed_all_reduce,
    default_bucket_mb,
    is_quantized_algorithm,
    plan_buckets,
    plan_quant_wire_bytes,
    supports_error_feedback,
)

__all__ = ["make_dp_train_step", "make_eval_step"]


def make_dp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    algorithm: str = "xla",
    axis: str = "dp",
    donate: bool = True,
    bucket_size_mb: float | None | str = "auto",
    error_feedback: bool = False,
):
    """Build ``step(params, opt_state, x, y) -> (params, opt_state, loss)``.

    ``loss_fn(params, x, y)`` must return the mean loss over its (shard of
    the) batch. Params/opt-state are replicated; x/y enter sharded along
    ``axis``. The returned step is jitted over ``mesh``.

    ``bucket_size_mb`` (explicit algorithms only): ``"auto"`` = the
    ``DSML_BUCKET_MB`` env default (4 MiB — docs/TUNING.md), a number = that
    many MiB per bucket, ``None`` = the pre-bucketing single-buffer sync.

    ``error_feedback=True`` (quantized ring algorithms only) changes the
    signature to ``step(params, opt_state, ef, x, y) -> (params, opt_state,
    ef, loss)`` with ``ef`` the per-rank residual state from
    ``parallel.bucketing.init_error_feedback(params, mesh, axis)`` —
    sharded over ``axis`` (each device stores only its own residual) and
    donated like the optimizer state.
    """
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(axis))
    if bucket_size_mb == "auto":
        bucket_size_mb = default_bucket_mb()
    if error_feedback and not supports_error_feedback(algorithm):
        raise ValueError(
            f"error_feedback=True requires a quantized ring algorithm "
            f"(q8_ring/q8_ring2/q4_ring/q4_ring2/quant), got {algorithm!r}"
        )
    # build-time breadcrumb: a postmortem names the sync configuration the
    # dying run was built with, even before the first compile records a plan
    flight_recorder.record(
        "train_step_build", algorithm=algorithm, axis=axis,
        bucket_mb=bucket_size_mb, devices=mesh.devices.size,
        error_feedback=error_feedback,
    )
    # Loss-reactive transforms (adaptive_plateau) consume the loss via
    # ``value=``; the wrapper lets every optimizer accept the extra arg.
    optimizer = optax.with_extra_args_support(optimizer)
    n_ranks = mesh.shape[axis]
    # filled at trace time (static shapes); read by the per-step dispatch
    # wrapper below to bump the cumulative wire-byte counter
    quant_bytes_cell: dict = {}

    def _note_quant_bytes(grads):
        if is_quantized_algorithm(algorithm) and not quant_bytes_cell:
            plan = plan_buckets(
                grads,
                bucket_size_mb if bucket_size_mb is not None else float("inf"),
            )
            quant_bytes_cell.update(plan_quant_wire_bytes(plan, n_ranks, algorithm))

    if algorithm == "xla":

        def compute_grads(params, x, y):
            return jax.value_and_grad(loss_fn)(params, x, y)

    elif error_feedback:

        def compute_grads(params, ef, x, y):
            def shard_fn(params, ef, x, y):
                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                # EF syncs are plan-shaped even at None (per-dtype buckets,
                # the zero2 convention) — resolve so the recorder models
                # what actually runs, per its documented contract
                record_collective_plan(
                    algorithm, grads,
                    bucket_size_mb if bucket_size_mb is not None else float("inf"),
                    axis,
                )
                _note_quant_bytes(grads)
                ef_local = jax.tree.map(lambda l: l[0], ef)
                grads, new_ef = bucketed_all_reduce(
                    grads, axis, ReduceOp.AVG, algorithm, bucket_size_mb,
                    error_feedback=ef_local,
                )
                return (
                    jax.lax.pmean(loss, axis),
                    grads,
                    jax.tree.map(lambda l: l[None], new_ef),
                )

            return jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis), P(axis)),
                out_specs=(P(), P(), P(axis)),
                check_vma=False,
            )(params, ef, x, y)

    else:

        def compute_grads(params, x, y):
            def shard_fn(params, x, y):
                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                # trace-time (static shapes): records bucket count/bytes
                # once per compile, labeled by algorithm — zero cost per step
                record_collective_plan(algorithm, grads, bucket_size_mb, axis)
                _note_quant_bytes(grads)
                grads = bucketed_all_reduce(
                    grads, axis, ReduceOp.AVG, algorithm, bucket_size_mb
                )
                return jax.lax.pmean(loss, axis), grads

            return jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(), P()),
                check_vma=False,
            )(params, x, y)

    ef_sh = NamedSharding(mesh, P(axis))

    if error_feedback:

        def step(params, opt_state, ef, x, y):
            loss, grads, ef = compute_grads(params, ef, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
            params = optax.apply_updates(params, updates)
            return params, opt_state, ef, loss

        jitted = jax.jit(
            step,
            in_shardings=(repl, repl, ef_sh, batch_sh, batch_sh),
            out_shardings=(repl, repl, ef_sh, repl),
            donate_argnums=(0, 1, 2) if donate else (),
        )
    else:

        def step(params, opt_state, x, y):
            loss, grads = compute_grads(params, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        jitted = jax.jit(
            step,
            in_shardings=(repl, repl, batch_sh, batch_sh),
            out_shardings=(repl, repl, repl),
            donate_argnums=(0, 1) if donate else (),
        )

    if not is_quantized_algorithm(algorithm):
        return jitted

    def run(*args):
        out = jitted(*args)
        # first call traced above, so the cell is filled by now; one dict
        # walk + a no-op-able counter write per step (obs discipline)
        record_quant_sync_bytes(quant_bytes_cell, algorithm, axis)
        return out

    return run


def make_eval_step(model, mesh: Mesh, axis: str = "dp"):
    """Jitted ``(params, x, y) -> correct_count`` with the batch sharded."""
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(axis))

    def correct(params, x, y):
        return jnp.sum(jnp.argmax(model.apply(params, x), axis=-1) == y)

    return jax.jit(correct, in_shardings=(repl, batch_sh, batch_sh), out_shardings=repl)
