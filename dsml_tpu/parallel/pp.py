"""Pipeline parallelism: microbatched stage schedule over the ``pp`` axis.

The reference's literature corpus (GPipe, PipeDream, Chimera, Zero-Bubble —
SURVEY.md §2.3 "PP: literature only") realized TPU-style: the layer stack is
split into S stages, one per ``pp``-axis rank; activations hop stage→stage
via ``ppermute`` (the chip-to-chip send the reference's BeginSend/StreamSend
API *intended*, over ICI); M microbatches stream through a GPipe schedule of
M+S-1 ticks, expressed as one ``lax.scan`` — so the whole pipelined forward
is a single XLA program, and ``jax.grad`` through it yields the mirrored
pipelined backward (synchronous GPipe semantics: bubble fraction
(S-1)/(M+S-1), amortized by more microbatches).

Params arrive layer-stacked (leading layer axis) and sharded ``P('pp', ...)``
so shard_map hands each rank exactly its stage's layers.

On the rest of the reference's PP literature folder: Zero-Bubble's B/W
backward split and Chimera's bidirectional pipelines both win by filling a
rank's IDLE tick slots with other work — but under SPMD lockstep every rank
executes every tick's program anyway (inactive ranks compute-and-discard,
see the ``where`` note in :func:`pipeline_apply`), so there are no idle
slots to fill: ZB would re-run the same ticks with extra bookkeeping, and
Chimera's two directions would double per-tick work for the same makespan.
The schedule that DOES help here is Megatron's interleave
(:func:`pipeline_apply_interleaved`): it shrinks the number of wasted
ticks, not how they're filled.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "pipeline_apply",
    "pipeline_apply_interleaved",
    "pipeline_train_1f1b",
    "stack_layer_params",
    "pipeline_specs",
    "interleave_layer_order",
]


def stack_layer_params(layer_params: list) -> dict:
    """[per-layer pytrees] → one pytree with a leading layer axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *layer_params)


def pipeline_specs(layer_spec, axis: str = "pp"):
    """PartitionSpec pytree for stacked layer params: layer axis → ``axis``."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda s: P(axis, *s), layer_spec, is_leaf=lambda x: isinstance(x, P)
    )


def pipeline_apply(
    layer_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    axis: str = "pp",
    remat: bool | str = False,
) -> jax.Array:
    """Run a layer stack as a pipeline. Call under ``shard_map``.

    ``layer_fn(one_layer_params, x) -> x`` — one layer's transform (activation
    shape preserved, the transformer-block invariant).
    ``stage_params`` — this rank's layers, leading axis = layers-per-stage.
    ``microbatches`` — [M, microbatch, ...], replicated across the axis
    (only stage 0 consumes them).
    ``remat=True`` rematerializes each tick's stage computation in the
    backward pass: activation memory stops scaling with the number of
    microbatches in flight — the memory property 1F1B scheduling
    (PipeDream, SURVEY.md §2.3) buys, achieved compiler-side instead of by
    hand-interleaving forward/backward. ``remat="int8"`` additionally
    compresses each LAYER's stashed input to blockwise int8
    (``ops.quantization.compressed_checkpoint``, the ActNN/GACT capability)
    — per-layer granularity, since the compressed stash is what bounds
    memory rather than the checkpoint cut.

    Returns [M, microbatch, ...] outputs, replicated to every rank.
    """
    if remat not in (False, True, "int8"):
        raise ValueError(f"unknown remat mode {remat!r}; choose False, True, or 'int8'")
    if not isinstance(remat, str):
        remat = bool(remat)  # 1 passes validation (1 == True); normalize so
        # the `remat is True` dispatch below can't silently drop remat
    n_stage = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    n_micro = microbatches.shape[0]

    if remat == "int8":
        from dsml_tpu.ops.quantization import compressed_checkpoint

        layer_fn = compressed_checkpoint(layer_fn)

    def stage_fn(x):
        def body(h, one_layer):
            return layer_fn(one_layer, h), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    if remat is True:
        stage_fn = jax.checkpoint(stage_fn)

    if n_stage == 1:
        return jax.vmap(stage_fn)(microbatches)

    ticks = n_micro + n_stage - 1
    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]  # no wraparound; edge gets zeros

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t; later stages consume what the
        # previous stage handed over on the prior tick
        feed = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(rank == 0, feed, buf)
        active = (t - rank >= 0) & (t - rank < n_micro)
        # `where`, NOT `lax.cond`: the stage contains collectives (tp psums,
        # sp ring attention), and a collective instruction's channel spans
        # every device in the program — ranks whose pp-varying predicate
        # skips the branch would desert the exchange and corrupt it
        # (empirically: wrong forward values, not a deadlock). Bubble ticks
        # therefore compute-and-discard; that waste is inherent to SPMD
        # lockstep, and 1F1B's zero-seed backward shares it.
        y = jnp.where(active, stage_fn(x_in), jnp.zeros_like(x_in))
        # last stage completes microbatch (t - n_stage + 1)
        out_idx = t - (n_stage - 1)
        write = (rank == n_stage - 1) & (out_idx >= 0)
        slot = jnp.clip(out_idx, 0, n_micro - 1)
        outputs = outputs.at[slot].set(jnp.where(write, y, outputs[slot]))
        buf = lax.ppermute(y, axis, fwd_perm)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    # outputs are resident on the last stage only; replicate so every rank
    # (e.g. a colocated loss/unembed) can proceed
    return lax.psum(jnp.where(rank == n_stage - 1, outputs, 0.0), axis)


def interleave_layer_order(n_layer: int, n_stage: int, v: int) -> list[int]:
    """Layer permutation for the interleaved schedule: rank r owns virtual
    chunks r, r+S, …, r+(v−1)S (Megatron PTD-P's round-robin assignment), so
    the stacked layer axis must be reordered before sharding it ``P('pp')``
    — position ``r·(n_layer/S) + j·(n_layer/(vS)) + i`` gets original layer
    ``(r + jS)·(n_layer/(vS)) + i``."""
    if n_layer % (n_stage * v):
        raise ValueError(f"n_layer={n_layer} not divisible by stages×interleave={n_stage * v}")
    per_chunk = n_layer // (n_stage * v)
    order = []
    for r in range(n_stage):
        for j in range(v):
            chunk = r + j * n_stage
            order.extend(range(chunk * per_chunk, (chunk + 1) * per_chunk))
    return order


def pipeline_apply_interleaved(
    layer_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    v: int,
    axis: str = "pp",
    remat: bool | str = False,
) -> jax.Array:
    """Interleaved virtual-stage pipeline (Megatron PTD-P's interleaved
    schedule — the same `2104.04473v5.pdf` in the reference's §3 Hybrid
    literature whose tensor sharding ``models.gpt2`` implements). Each rank
    holds ``v`` non-contiguous layer CHUNKS (chunks r, r+S, …, r+(v−1)S);
    a microbatch hops the ring v times, visiting chunks in order. The fill/
    drain bubble is S−1 ticks of CHUNK work instead of GPipe's S−1 ticks of
    full-stage work — v× smaller, the schedule's whole point.

    SPMD formulation: microbatches are injected in groups of S spaced S·v
    ticks apart. Under that spacing each in-flight work unit (microbatch m,
    chunk k) advances exactly one hop per tick with no rank ever owing two
    units in the same tick — so the whole schedule is one ``lax.scan`` with
    a single carry buffer and a full-ring ``ppermute`` (the S−1→0 edge
    carries chunk k → k+1 wraparound traffic), total ticks M·v + S − 1.
    Closed form per (tick t, rank r): with q = (t−r−((t−r) mod S))/S, the
    active unit is chunk index j = q mod v, microbatch
    m = ((t−r) mod S) + S·(q div v).

    ``stage_params`` — this rank's chunks, leading axes [v, layers_per_chunk]
    (stack with :func:`stack_layer_params` after permuting layers by
    :func:`interleave_layer_order`, shard ``P('pp')``, then reshape the
    local leading axis S·v/S → [v, per_chunk] inside the caller's shard_map
    — :meth:`models.gpt2.GPT2._blocks_spmd` shows the dance).
    ``microbatches`` — [M, micro, ...] with M divisible by S.
    Returns [M, micro, ...], replicated (same contract as
    :func:`pipeline_apply`).
    """
    n_stage = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    n_micro = microbatches.shape[0]
    if n_micro % n_stage:
        raise ValueError(
            f"interleaved schedule needs microbatches divisible by stages: {n_micro} % {n_stage}"
        )
    if remat not in (False, True, "int8"):
        raise ValueError(f"unknown remat mode {remat!r}; choose False, True, or 'int8'")
    if not isinstance(remat, str):
        remat = bool(remat)  # 1 passes validation (1 == True); normalize so
        # the `remat is True` dispatch below can't silently drop remat
    if remat == "int8":
        from dsml_tpu.ops.quantization import compressed_checkpoint

        layer_fn = compressed_checkpoint(layer_fn)

    def chunk_fn(chunk_params, x):
        def body(h, one_layer):
            return layer_fn(one_layer, h), None

        out, _ = lax.scan(body, x, chunk_params)
        return out

    if remat is True:
        chunk_fn = jax.checkpoint(chunk_fn)

    if n_stage == 1:
        # v chunks on one rank = the plain layer stack
        def all_chunks(x):
            def body(h, chunk):
                return chunk_fn(chunk, h), None

            out, _ = lax.scan(body, x, stage_params)
            return out

        return jax.vmap(all_chunks)(microbatches)

    groups = n_micro // n_stage
    ticks = n_micro * v + n_stage - 1
    ring = [(i, (i + 1) % n_stage) for i in range(n_stage)]  # full ring: S−1→0 wraps chunks

    def tick(carry, t):
        buf, outputs = carry
        rel = t - rank
        mmod = jnp.remainder(rel, n_stage)
        q = (rel - mmod) // n_stage
        j = jnp.remainder(q, v)  # which of this rank's v chunks
        g = q // v  # microbatch group
        m = mmod + n_stage * g
        active = (rel >= 0) & (g >= 0) & (g < groups)
        slot = jnp.clip(m, 0, n_micro - 1)

        # rank 0's chunk 0 (j==0) ingests micro m; everything else consumes
        # the ring hop (which already carries chunk k−1's output for unit m)
        feed = microbatches[slot]
        x_in = jnp.where((rank == 0) & (j == 0), feed, buf)
        chunk = jax.tree.map(lambda p: p[j], stage_params)
        y = jnp.where(active, chunk_fn(chunk, x_in), jnp.zeros_like(x_in))

        # last rank's last chunk (j==v−1) completes micro m
        write = (rank == n_stage - 1) & (j == v - 1) & active
        outputs = outputs.at[slot].set(jnp.where(write, y, outputs[slot]))
        buf = lax.ppermute(y, axis, ring)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    return lax.psum(jnp.where(rank == n_stage - 1, outputs, 0.0), axis)


def _lift(x, axes: tuple) -> jax.Array:
    """Mark ``x`` varying over any of ``axes`` it isn't already (identity on
    values) — keeps scan-carry vma types stable across ticks."""
    missing = tuple(a for a in axes if a not in jax.typeof(x).vma)
    return lax.pcast(x, missing, to="varying") if missing else x


def pipeline_train_1f1b(
    stage_fn: Callable,
    head_fn: Callable,
    stage_params,
    head_params,
    micros: jax.Array,
    targets: jax.Array,
    axis: str = "pp",
    vary_axes: tuple = ("pp", "dp", "sp", "tp"),
    loss_seed_scale: float | jax.Array = None,
):
    """Hand-interleaved 1F1B pipeline schedule (PipeDream-flush — the
    reference's ``Literatures/1.1 PP/sosp_pipedream.pdf`` roadmap item, named
    in its Final Report "Future Work"). Call under ``shard_map`` with
    ``check_vma=True`` — the schedule runs per-tick ``jax.vjp`` INSIDE the
    mesh program, and vma tracking is what makes collective transposes
    (tp psums in blocks/head, sp ring-attention ppermutes) exact there.

    Unlike :func:`pipeline_apply` + ``jax.grad`` (synchronous GPipe, which
    stores one residual set per tick — O(M) activations — unless the whole
    stage is rematerialized), 1F1B starts each microbatch's backward as soon
    as its forward completes: in-flight activations are bounded by the
    schedule at ≤ 2(S−1)+1 microbatch inputs per rank regardless of M, and
    the backward recomputes the stage forward from the stashed input
    (activation recomputation, the standard 1F1B+remat memory point). The
    bubble fraction stays (S−1)/(M+S−1) per direction — synchronous-flush
    1F1B trades no compute for GPipe, it trades memory.

    Per rank r at tick t: forward of microbatch ``t − r`` and backward of
    microbatch ``t − 2(S−1) + r`` (on the last stage the two coincide, so
    its head cotangent feeds the backward the same tick — the "1F" and "1B"
    interleave). Activations hop forward and cotangents hop backward via
    ``ppermute`` every tick.

    Arguments:
      ``stage_fn(stage_params, x) -> y`` — this rank's stage.
      ``head_fn(head_params, y, target) -> scalar`` — per-microbatch loss
        (mean over its tokens); executed every tick on every rank for SPMD
        uniformity, contributing only on the last stage.
      ``micros`` — [M, mb, ...] pipeline inputs, replicated over the axis
        (stage 0 consumes them). ``targets`` — [M, ...] per-micro targets.
      ``vary_axes`` — every mesh axis the computation genuinely varies
        over; schedule buffers are vma-lifted to this set so scan carries
        stay type-stable.
      ``loss_seed_scale`` — the head cotangent seed (default ``1/M``). The
        KEY vma fact (empirically pinned by tests): the transpose of an
        auto-lifted replicated input psums its cotangent across the lifted
        axes IMMEDIATELY, inside each per-tick vjp. So param cotangents
        come back already globally reduced, and the seed must carry the
        full normalization — callers whose global loss is a mean over
        batch axes pass ``1/(M · n_dp · n_sp)``. The seed is masked to
        (last stage ∧ active tick), which is also what keeps inactive
        ticks' garbage head compute OUT of those internal psums.

    Returns ``(loss, d_stage, d_head, d_micros)``:
      ``loss`` — Σ per-micro losses / M, nonzero on the last rank only
        (caller: psum over ``axis``, pmean over batch axes).
      ``d_stage`` / ``d_head`` — param grads, ALREADY reduced to each
        leaf's replication (the internal-psum semantics above) under the
        caller's seed scale; use as-is.
      ``d_micros`` — per-rank cotangent of ``micros``, nonzero on rank 0;
        psum over (``axis``, tensor axes) before feeding an embedding VJP.
    """
    n_stage = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    n_micro = micros.shape[0]
    depth = min(n_micro, 2 * (n_stage - 1) + 1)  # max in-flight inputs per rank
    ticks = n_micro + 2 * (n_stage - 1)
    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]
    bwd_perm = [(i + 1, i) for i in range(n_stage - 1)]
    is_last = rank == n_stage - 1
    if loss_seed_scale is None:
        loss_seed_scale = 1.0 / n_micro
    scale = jnp.asarray(loss_seed_scale, jnp.float32)

    def tick(carry, t):
        buf_f, buf_b, stash, g_stage, g_head, loss_acc, d_micros = carry
        m_f = t - rank
        m_b = t - 2 * (n_stage - 1) + rank
        act_f = (m_f >= 0) & (m_f < n_micro)
        act_b = (m_b >= 0) & (m_b < n_micro)
        slot_f = jnp.clip(m_f, 0, n_micro - 1)
        slot_b = jnp.clip(m_b, 0, n_micro - 1)

        # ---- forward slot: stage 0 ingests micro m_f, others consume the
        # previous stage's hop; the input is stashed for the backward's
        # recompute (ring buffer of `depth` slots — never more in flight)
        x_in = _lift(jnp.where(rank == 0, micros[slot_f], buf_f), vary_axes)
        y = _lift(
            jnp.where(act_f, stage_fn(stage_params, x_in), jnp.zeros_like(x_in)), vary_axes
        )
        stash = stash.at[slot_f % depth].set(jnp.where(act_f, x_in, stash[slot_f % depth]))

        # ---- head: on the last stage, micro m_b's forward finished THIS
        # tick (m_f == m_b there) — its loss cotangent starts the backward
        # immediately, which is the 1F1B interleave
        tgt = targets[slot_b]
        l_m, head_vjp = jax.vjp(lambda hp, yy: head_fn(hp, yy, tgt), head_params, y)
        seed = jnp.where(is_last & act_b, scale, 0.0).astype(l_m.dtype)
        seed = _lift(seed, tuple(jax.typeof(l_m).vma))
        d_hp, dy_head = head_vjp(seed)
        dy = jnp.where(is_last, dy_head, buf_b)

        # ---- backward slot: recompute the stage forward from the stashed
        # input and transpose (activation recomputation — no per-tick
        # residuals survive in the scan carry)
        x_saved = stash[slot_b % depth]
        y2, stage_vjp = jax.vjp(stage_fn, stage_params, x_saved)
        dy = _lift(dy, tuple(jax.typeof(y2).vma))
        d_sp, dx = stage_vjp(dy)
        # d_sp / d_hp are zero on inactive ticks (the masked seed zeroes the
        # whole cotangent chain) and already carry their internal cross-rank
        # psums — accumulate UNMASKED, or the replicated values would be
        # destroyed on the ranks the mask rejects
        g_stage = jax.tree.map(jnp.add, g_stage, d_sp)
        g_head = jax.tree.map(jnp.add, g_head, d_hp)
        loss_acc = loss_acc + jnp.where(act_b & is_last, l_m.astype(jnp.float32), 0.0)
        dx_masked = jnp.where(act_b, dx, jnp.zeros_like(dx))
        d_micros = d_micros.at[slot_b].set(
            jnp.where(act_b & (rank == 0), dx_masked, d_micros[slot_b])
        )

        buf_f = _lift(lax.ppermute(y, axis, fwd_perm), vary_axes)
        buf_b = _lift(lax.ppermute(dx_masked, axis, bwd_perm), vary_axes)
        return (buf_f, buf_b, stash, g_stage, g_head, loss_acc, d_micros), None

    carry0 = (
        _lift(jnp.zeros_like(micros[0]), vary_axes),  # buf_f
        _lift(jnp.zeros_like(micros[0]), vary_axes),  # buf_b
        _lift(jnp.zeros((depth, *micros.shape[1:]), micros.dtype), vary_axes),  # stash
        jax.tree.map(jnp.zeros_like, stage_params),
        jax.tree.map(jnp.zeros_like, head_params),
        _lift(jnp.zeros((), jnp.float32), vary_axes),
        _lift(jnp.zeros_like(micros), vary_axes),  # d_micros
    )
    (_, _, _, g_stage, g_head, loss_acc, d_micros), _ = lax.scan(
        tick, carry0, jnp.arange(ticks)
    )
    return loss_acc / n_micro, g_stage, g_head, d_micros
