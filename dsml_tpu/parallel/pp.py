"""Pipeline parallelism: microbatched stage schedule over the ``pp`` axis.

The reference's literature corpus (GPipe, PipeDream, Chimera, Zero-Bubble —
SURVEY.md §2.3 "PP: literature only") realized TPU-style: the layer stack is
split into S stages, one per ``pp``-axis rank; activations hop stage→stage
via ``ppermute`` (the chip-to-chip send the reference's BeginSend/StreamSend
API *intended*, over ICI); M microbatches stream through a GPipe schedule of
M+S-1 ticks, expressed as one ``lax.scan`` — so the whole pipelined forward
is a single XLA program, and ``jax.grad`` through it yields the mirrored
pipelined backward (synchronous GPipe semantics: bubble fraction
(S-1)/(M+S-1), amortized by more microbatches).

Params arrive layer-stacked (leading layer axis) and sharded ``P('pp', ...)``
so shard_map hands each rank exactly its stage's layers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_apply", "stack_layer_params", "pipeline_specs"]


def stack_layer_params(layer_params: list) -> dict:
    """[per-layer pytrees] → one pytree with a leading layer axis."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *layer_params)


def pipeline_specs(layer_spec, axis: str = "pp"):
    """PartitionSpec pytree for stacked layer params: layer axis → ``axis``."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda s: P(axis, *s), layer_spec, is_leaf=lambda x: isinstance(x, P)
    )


def pipeline_apply(
    layer_fn: Callable,
    stage_params,
    microbatches: jax.Array,
    axis: str = "pp",
    remat: bool = False,
) -> jax.Array:
    """Run a layer stack as a pipeline. Call under ``shard_map``.

    ``layer_fn(one_layer_params, x) -> x`` — one layer's transform (activation
    shape preserved, the transformer-block invariant).
    ``stage_params`` — this rank's layers, leading axis = layers-per-stage.
    ``microbatches`` — [M, microbatch, ...], replicated across the axis
    (only stage 0 consumes them).
    ``remat=True`` rematerializes each tick's stage computation in the
    backward pass: activation memory stops scaling with the number of
    microbatches in flight — the memory property 1F1B scheduling
    (PipeDream, SURVEY.md §2.3) buys, achieved compiler-side instead of by
    hand-interleaving forward/backward.

    Returns [M, microbatch, ...] outputs, replicated to every rank.
    """
    n_stage = lax.axis_size(axis)
    rank = lax.axis_index(axis)
    n_micro = microbatches.shape[0]

    def stage_fn(x):
        def body(h, one_layer):
            return layer_fn(one_layer, h), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    if n_stage == 1:
        return jax.vmap(stage_fn)(microbatches)

    ticks = n_micro + n_stage - 1
    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]  # no wraparound; edge gets zeros

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests microbatch t; later stages consume what the
        # previous stage handed over on the prior tick
        feed = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x_in = jnp.where(rank == 0, feed, buf)
        active = (t - rank >= 0) & (t - rank < n_micro)
        y = jnp.where(active, stage_fn(x_in), jnp.zeros_like(x_in))
        # last stage completes microbatch (t - n_stage + 1)
        out_idx = t - (n_stage - 1)
        write = (rank == n_stage - 1) & (out_idx >= 0)
        slot = jnp.clip(out_idx, 0, n_micro - 1)
        outputs = outputs.at[slot].set(jnp.where(write, y, outputs[slot]))
        buf = lax.ppermute(y, axis, fwd_perm)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (_, outputs), _ = lax.scan(tick, (buf0, out0), jnp.arange(ticks))
    # outputs are resident on the last stage only; replicate so every rank
    # (e.g. a colocated loss/unembed) can proceed
    return lax.psum(jnp.where(rank == n_stage - 1, outputs, 0.0), axis)
