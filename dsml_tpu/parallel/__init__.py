"""Device-mesh parallelism: DP, TP, PP, SP (ring attention), Ulysses, EP."""

from dsml_tpu.parallel.mesh import MeshSpec, build_mesh  # noqa: F401
