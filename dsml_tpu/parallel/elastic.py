"""Elastic training: survive device loss by re-planning the mesh mid-run.

The reference detects failures and marks the communicator dead — permanently
(``gpu_coordinator_server.go:114-118``; recovery "none", SURVEY.md §5.3). Its
fault-tolerance literature (Varuna `3492321.3519584.pdf`, Bamboo
`nsdi23-thorpe.pdf`, Oobleck `2309.08125v2.pdf` — §2.4 folder 5) is about the
missing half: CONTINUING the job on the survivors. This module is that half,
TPU-style:

- The comm layer already does detection + communicator renumbering
  (``comm.coordinator``, ``CoordinatorConfig(elastic=True)``). Here the
  TRAINING STATE moves: :func:`reconfigure` takes a live (params, opt_state)
  sharded over a failed mesh, re-plans the parallelism for the survivor
  fleet (Oobleck's "pipeline template" re-instantiation, realized as
  ``parallel.auto.plan_mesh`` over the new device count), and re-shards the
  state onto the new mesh — no restart, no checkpoint round-trip.
- Recoverability of the state itself follows from the sharding layout, and
  :func:`check_recoverable` makes that auditable before a failure happens
  (Bamboo's redundant-computation guarantee, by construction instead of by
  extra compute): any leaf that is REPLICATED over some mesh axis survives
  the loss of all-but-one rank of that axis; a leaf sharded over a lost
  device is gone and needs the checkpoint fallback (``utils.checkpoint``,
  Varuna's approach — the caller chooses per
  :class:`ElasticPolicy`).

On a single TPU host device loss takes the process with it, so the unit of
failure this module models is the MESH SHRINKING between steps — exactly
what multi-host JAX gives you when a host drops and ``jax.devices()``
re-forms smaller. Tests simulate it by rebuilding meshes over device
subsets of the virtual CPU fleet.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsml_tpu.parallel.auto import plan_mesh
from dsml_tpu.parallel.mesh import MeshSpec, build_mesh

__all__ = [
    "ElasticPolicy",
    "check_recoverable",
    "reconfigure",
    "remap_error_feedback",
    "reshard_onto",
    "restore_from_checkpoint",
    "ElasticState",
]


@dataclasses.dataclass(frozen=True)
class ElasticPolicy:
    """What to do when devices are lost.

    ``allow_shrink`` — re-plan onto the survivors (False = fail fast, the
    reference's behavior). ``require_full_state`` — refuse to continue if
    any state leaf was exclusively sharded on lost devices (True means: fall
    back to your checkpoint instead of silently training on a torn state;
    False means: continue anyway, with torn leaves explicitly ZERO-FILLED —
    never fetched from dead devices — and the substitution recorded in the
    reconfiguration's audit trail).
    """

    allow_shrink: bool = True
    require_full_state: bool = True


@dataclasses.dataclass
class ElasticState:
    """Result of a reconfiguration."""

    params: object
    opt_state: object
    mesh: Mesh
    spec: MeshSpec
    reasons: tuple[str, ...]  # the auto-planner's audit trail for the new mesh
    # checkpoint-sourced states carry the step they restored (None for live
    # reconfigurations) — the supervision loop rewinds its step counter to
    # exactly this and replays, which is how "lost work" becomes a number
    step: int | None = None
    # error-feedback residual state remapped onto the new width (None when
    # the run doesn't use quantized sync with EF) — see
    # :func:`remap_error_feedback`
    error_feedback: object = None


def _leaf_shardings(tree):
    return [
        (leaf, getattr(leaf, "sharding", None))
        for leaf in jax.tree.leaves(tree)
        if isinstance(leaf, jax.Array)
    ]


def _piece_key(idx, shape) -> tuple:
    """Canonical (start, stop) tuple for a shard's index — normalized via
    ``slice.indices`` so ``slice(None)`` and ``slice(0, n)`` agree between
    ``devices_indices_map`` and ``Shard.index``."""
    return tuple(
        s.indices(dim)[:2] for s, dim in zip(idx, shape) if isinstance(s, slice)
    )


def _piece_holders(leaf, sharding) -> dict:
    """piece key → list of holder device ids (devices_indices_map groups:
    every device holding the same index tuple holds the same data)."""
    holders: dict = {}
    for dev, idx in sharding.devices_indices_map(leaf.shape).items():
        holders.setdefault(_piece_key(idx, leaf.shape), []).append(dev.id)
    return holders


def _torn_leaves(state, lost_devices) -> list[tuple[object, str]]:
    """(leaf, description) for every state leaf with at least one piece that
    lives ONLY on lost devices. Shared by the audit (:func:`check_recoverable`)
    and the torn-state continuation path in :func:`reconfigure`, so the two
    can never disagree about what "torn" means."""
    lost = {d.id for d in lost_devices}
    torn: list[tuple[object, str]] = []
    for leaf, sharding in _leaf_shardings(state):
        if sharding is None:  # host array: nothing to lose
            continue
        for piece, devs in _piece_holders(leaf, sharding).items():
            if all(d in lost for d in devs):
                torn.append(
                    (leaf, f"shape={leaf.shape} piece={piece} only on lost devices {devs}")
                )
                break
    return torn


def check_recoverable(state, lost_devices) -> list[str]:
    """Which state leaves would be LOST if ``lost_devices`` die right now?

    A leaf survives iff every shard of its value lives on at least one
    surviving device — i.e. for each addressable shard index, some replica
    sits outside ``lost_devices``. Returns a list of human-readable
    descriptions of unrecoverable leaves (empty = fully recoverable, the
    state every DP/replicated layout gives you)."""
    return [descr for _, descr in _torn_leaves(state, lost_devices)]


def remap_error_feedback(ef, new_mesh, axis: str = "dp", lost_devices=()):
    """Carry error-feedback residual mass across a width change.

    EF residuals (``parallel.bucketing.init_error_feedback``) are PER-RANK
    state — leaf shape ``[old_n, *grad_shape]``, sharded over ``axis``, and
    a rank's row exists only on that rank's device. A width change makes
    per-rank identity meaningless, but the residuals' TOTAL effect on the
    synced mean gradient is well defined: under AVG each rank's residual
    enters as ``r_i / n``, so the standing uncommitted mass is
    ``Σ r_i / old_n``. This remap gives every new rank
    ``Σ_surviving r_i / old_n`` — then ``new_sum / new_n = Σ r_i / old_n``
    and the next sync injects exactly the mass the compressor still owed,
    at any new width. Residual rows whose device died are GONE (their
    uncommitted gradient mass is lost, like the dead rank's local
    gradients themselves would be) and drop out of the sum — deterministic
    and honest, the same policy as the torn-state zero-fill.
    """
    new_n = new_mesh.shape[axis]
    lost = {getattr(d, "id", d) for d in lost_devices}
    sharding = NamedSharding(new_mesh, P(axis))

    def remap(leaf):
        old_n = leaf.shape[0]
        total = np.zeros(leaf.shape[1:], np.float32)
        seen = set()  # replicas (multi-axis meshes) must count once
        for shard in leaf.addressable_shards:
            key = _piece_key(shard.index, leaf.shape)
            if shard.device.id in lost or key in seen:
                continue
            seen.add(key)
            total += np.asarray(shard.data, np.float32).sum(axis=0)
        row = total / old_n
        host = np.broadcast_to(row, (new_n, *row.shape)).copy()
        return jax.device_put(jnp.asarray(host, jnp.float32), sharding)

    return jax.tree.map(remap, ef)


def _plan_for_survivors(
    model, n_params: int, survivors: list, batch_per_device: int,
    global_batch: int | None, planner_overrides: dict | None,
):
    """Re-instantiate the parallelism template on the survivor fleet (the
    Oobleck choice): the capacity-rule plan for the largest device subset
    whose dp×fsdp width divides ``global_batch`` (both axes shard batch
    rows in the hybrid step). Returns (plan, survivors_used)."""
    cfg = getattr(model, "config", None)
    plan = None
    overrides = dict(planner_overrides or {})
    # capacity must be read from a SURVIVOR's memory_stats: plan_mesh's
    # default device (jax.devices()[0]) can be exactly the chip that just
    # died — the shrink re-plan would then size the new mesh from a dead
    # device's (absent) stats and land on the fallback constant
    if survivors:
        overrides.setdefault("device", survivors[0])
    for n_use in range(len(survivors), 0, -1):
        candidate = plan_mesh(
            n_devices=n_use,
            n_params=n_params,
            n_head=getattr(cfg, "n_head", None),
            seq_len=getattr(cfg, "max_seq", 0),
            d_model=getattr(cfg, "d_model", 0),
            n_layer=getattr(cfg, "n_layer", 0),
            batch_per_device=batch_per_device,
            **overrides,
        )
        if global_batch is None or global_batch % (
            candidate.spec.dp * candidate.spec.fsdp
        ) == 0:
            plan = candidate
            if n_use < len(survivors):
                plan = dataclasses.replace(
                    plan,
                    reasons=plan.reasons
                    + (
                        f"global batch {global_batch} not divisible by the "
                        f"{len(survivors)}-chip plan's dp×fsdp → instantiated on "
                        f"{n_use} chips, {len(survivors) - n_use} idle",
                    ),
                )
            return plan, survivors[:n_use]
    raise AssertionError("unreachable: the n_use=1 plan always divides")


def reconfigure(
    model,
    optimizer,
    params,
    opt_state,
    surviving_devices,
    lost_devices=(),
    policy: ElasticPolicy = ElasticPolicy(),
    batch_per_device: int = 1,
    global_batch: int | None = None,
    planner_overrides: dict | None = None,
    migrator=None,
    non_addressable=(),
    error_feedback=None,
    ef_axis: str = "dp",
) -> ElasticState:
    """Continue training on the survivor fleet.

    1. Audit recoverability (:func:`check_recoverable`) — under
       ``require_full_state`` a torn state raises instead of continuing
       (checkpoint fallback is the caller's move, ``utils.checkpoint``).
    2. Re-plan parallelism for ``len(surviving_devices)`` chips with the
       capacity-rule planner (the Oobleck template re-instantiation). With
       ``global_batch`` set, the plan must also keep the batch divisible by
       its dp×fsdp width (both axes shard batch rows in the hybrid step) —
       survivor counts that can't (e.g. 5 chips for a batch of 4)
       instantiate the template on the largest workable device SUBSET
       and idle the rest, Oobleck's choice: n−1 busy chips beat a crash.
       ``planner_overrides`` forwards capacity inputs to ``plan_mesh``
       (measured ``hbm_bytes``/``act_bytes``, budget fractions) so the
       re-plan uses the same hardware facts the original plan did.
    3. Pull state to host once and re-shard onto the new mesh.

    ``error_feedback`` (a quantized-sync run's residual state) is remapped
    onto the new ``ef_axis`` width via :func:`remap_error_feedback` —
    surviving ranks' uncommitted compression error re-enters the first
    post-recovery sync at the same injected mass; dead ranks' residuals
    are lost like their local gradients.

    Returns :class:`ElasticState` with the new (params, opt_state, mesh);
    the caller rebuilds its step function with
    ``make_hybrid_train_step(model, optimizer, new_mesh)`` (jit caches keyed
    on the mesh make this a fresh compile, as it must be)."""
    if not policy.allow_shrink:
        raise RuntimeError(
            f"{len(lost_devices)} device(s) lost and ElasticPolicy.allow_shrink=False "
            "(reference semantics: communicator FAILED, job dead)"
        )
    torn_note: tuple[str, ...] = ()
    if lost_devices:
        torn = _torn_leaves((params, opt_state), lost_devices)
        if torn and policy.require_full_state:
            raise RuntimeError(
                "training state not recoverable from survivors — restore from "
                f"checkpoint instead; torn leaves: {[d for _, d in torn[:3]]}"
            )
        if torn:
            # require_full_state=False: the caller chose to continue on a
            # torn state; the pieces whose holders all died are explicitly
            # ZERO-FILLED in the host round-trip below (never fetched from
            # dead devices), and the substitution is recorded in the audit
            # trail. (Zeros are the deterministic, honest choice: lost
            # optimizer moments restart cold, lost param shards retrain;
            # anything cleverer belongs in the checkpoint fallback.)
            torn_note = (
                f"require_full_state=False: zero-filled the lost pieces of "
                f"{len(torn)} torn leaf/leaves: " + "; ".join(d for _, d in torn[:3]),
            )

    cfg = getattr(model, "config", None)
    old_pp = _detect_stacked_pp(params)
    # GPT2-family models expose n_params(params); the small dp models
    # (MLP/CNN) carry it as a plain attribute — accept both so a
    # data-parallel run can ride the same recovery path
    n_params = model.n_params
    if callable(n_params):
        n_params = n_params(params)
    plan, survivors = _plan_for_survivors(
        model, int(n_params), list(surviving_devices),
        batch_per_device, global_batch, planner_overrides,
    )
    new_mesh = build_mesh(plan.spec, survivors)

    # host round-trip: survivors hold every piece (audited above, unless the
    # caller accepted a torn state — those pieces substitute zeros); any leaf
    # touching a dead device is reassembled from surviving shards, never
    # fetched whole; device_put lays the state out fresh on the new mesh
    if hasattr(model, "param_specs"):
        pspecs = model.param_specs(pp=plan.spec.pp > 1, fsdp=plan.spec.fsdp)
    else:
        # dp-only models (MLP/CNN) carry no spec tree: params are
        # replicated, which is exactly what a data-parallel step expects
        pspecs = jax.tree.map(lambda _: P(), params)

    host_params, host_opt = _pull_host_state(
        params, opt_state, lost_devices,
        migrator=migrator, non_addressable=non_addressable,
    )
    if old_pp:
        # the failed mesh ran a pipeline (stacked layer axis, possibly in
        # interleave-permuted order for the OLD stage count) — always return
        # to the canonical per-layer list form first; if the new plan keeps
        # a pipeline it restacks for the NEW stage count below. Skipping
        # this when old and new pp happen to match would still be wrong
        # whenever v>1 and the stage count changed. Unstack params, and
        # apply the SAME transform to every params-shaped subtree of the
        # optimizer state (adam's mu/nu mirror the param tree)
        host_params, host_opt = _unstack_state(host_params, host_opt, cfg, old_pp)
    host_params, host_opt = _restack_state(host_params, host_opt, cfg, plan.spec.pp)
    new_params, new_opt = _place_state(
        host_params, host_opt, optimizer, pspecs, new_mesh
    )
    new_ef = None
    if error_feedback is not None:
        new_ef = remap_error_feedback(
            error_feedback, new_mesh, axis=ef_axis, lost_devices=lost_devices
        )
    return ElasticState(
        params=new_params, opt_state=new_opt, mesh=new_mesh, spec=plan.spec,
        reasons=plan.reasons + torn_note, error_feedback=new_ef,
    )


def _pull_host_state(params, opt_state, lost_devices, migrator=None,
                     non_addressable=()):
    """One host round-trip for the whole training state, never touching a
    dead device: leaves whose shards all live on survivors fetch plainly;
    leaves with dead holders reassemble piecewise from surviving addressable
    shards (pieces whose holders ALL died stay zero — the audited torn-state
    substitution). Shared by :func:`reconfigure` and :func:`reshard_onto`.

    A piece that survives only on a NON-addressable device (another host)
    cannot be fetched from here. With a ``migrator``
    (``comm.migration.ShardMigrator``), exactly those pieces are pulled
    over the P2P streams from the donor host and spliced into the piecewise
    buffer — the cross-host elastic state motion (docs/ELASTIC.md
    § Multi-host recovery); the leaf key handed to the migrator is the tree
    path (``params/layers/0/attn/wqkv``), matching what the donor's
    ``StateDonor.register_state`` derives from the same tree. Without one,
    the refusal stays loud — never zero silently-good data the audit said
    was safe. ``non_addressable`` (device ids or devices) forces local
    devices to be treated as another host's — the single-process simulation
    hook the multi-host tests and the chaos migration smoke drive."""
    lost_ids = {d.id for d in lost_devices}
    remote_ids = {getattr(d, "id", d) for d in non_addressable}
    unreachable = lost_ids | remote_ids

    def pull(prefix):
        def inner(path, leaf):
            sharding = getattr(leaf, "sharding", None)
            if (
                not isinstance(leaf, jax.Array)
                or sharding is None
                or not unreachable
                or not any(d.id in unreachable for d in sharding.device_set)
            ):
                # no shard of this leaf touches a dead/remote device: plain fetch
                return jax.device_get(leaf)
            # some holder died or sits on another host: NEVER device_get the
            # whole leaf — that would materialize dead shards and hang on a
            # real loss. Reassemble piecewise from surviving addressable
            # shards; pieces whose holders all died stay zero (audited by
            # the caller); remote-only survivors migrate or refuse.
            out = np.zeros(leaf.shape, jnp.dtype(leaf.dtype))
            filled: set = set()
            for shard in leaf.addressable_shards:
                if shard.device.id not in unreachable:
                    out[shard.index] = np.asarray(shard.data)
                    filled.add(_piece_key(shard.index, leaf.shape))
            for piece, devs in _piece_holders(leaf, sharding).items():
                if piece in filled or all(d in lost_ids for d in devs):
                    continue
                if migrator is None:
                    raise RuntimeError(
                        f"piece {piece} of a shape-{leaf.shape} leaf survives only "
                        f"on non-addressable devices {devs}; no ShardMigrator is "
                        "wired — restore from checkpoint on this host instead "
                        "(docs/ELASTIC.md § Multi-host recovery)"
                    )
                from dsml_tpu.comm.migration import tree_path_str

                idx = tuple(slice(s, e) for s, e in piece)
                out[idx] = migrator.fetch_piece(
                    tree_path_str(prefix, path), piece, out.dtype
                )
            return out

        return inner

    return (
        jax.tree_util.tree_map_with_path(pull("params"), params),
        jax.tree_util.tree_map_with_path(pull("opt_state"), opt_state),
    )


def _detect_stacked_pp(params) -> int:
    """pp width of a STACKED param tree (0 = list/canonical form): the
    layer axis is a dict node and some leaf sharding carries a 'pp' mesh
    axis (width 1 when stacked but pp-less — degenerate, treated as 1)."""
    if not (isinstance(params, dict) and isinstance(params.get("layers"), dict)):
        return 0
    for leaf, sharding in _leaf_shardings(params):
        if isinstance(sharding, NamedSharding) and "pp" in sharding.mesh.shape:
            return sharding.mesh.shape["pp"]
    return 1


def reshard_onto(
    model,
    optimizer,
    params,
    opt_state,
    mesh: Mesh,
    spec: MeshSpec,
    lost_devices=(),
    migrator=None,
    non_addressable=(),
) -> ElasticState:
    """Move LIVE state onto a KNOWN mesh — the grow-back primitive.

    :func:`reconfigure` re-plans; this does not: the supervision loop
    (``runtime.controller``) already knows the topology it is returning to
    (the pre-failure full mesh), and rebuilding exactly that mesh object
    keeps the original step function's jit cache valid and the post-grow
    trajectory bit-comparable to the pre-failure one. Same host round-trip
    / unstack / restack / place pipeline as :func:`reconfigure`."""
    cfg = getattr(model, "config", None)
    host_params, host_opt = _pull_host_state(
        params, opt_state, lost_devices,
        migrator=migrator, non_addressable=non_addressable,
    )
    old_pp = _detect_stacked_pp(params)
    if old_pp:
        host_params, host_opt = _unstack_state(host_params, host_opt, cfg, old_pp)
    host_params, host_opt = _restack_state(host_params, host_opt, cfg, spec.pp)
    pspecs = model.param_specs(pp=spec.pp > 1, fsdp=spec.fsdp)
    new_params, new_opt = _place_state(host_params, host_opt, optimizer, pspecs, mesh)
    return ElasticState(
        params=new_params, opt_state=new_opt, mesh=mesh, spec=spec,
        reasons=(f"resharded live state onto the given mesh {spec.sizes_dict()}",),
    )


def _map_layer_nodes(node, fn):
    """Apply ``fn`` to every dict node carrying a 'layers' entry, recursing
    through dicts/lists/(named)tuples — adam's mu/nu mirror the param tree,
    so one transform must hit every params-shaped subtree of the state."""
    if isinstance(node, dict):
        node = fn(node)
        return {k: _map_layer_nodes(v, fn) for k, v in node.items()}
    if isinstance(node, tuple):
        mapped = [_map_layer_nodes(v, fn) for v in node]
        return type(node)(*mapped) if hasattr(node, "_fields") else tuple(mapped)
    if isinstance(node, list):
        return [_map_layer_nodes(v, fn) for v in node]
    return node


def _unstack_state(host_params, host_opt, cfg, old_pp_size: int):
    """Stacked layer axis (possibly interleave-permuted for the OLD stage
    count) → canonical per-layer list form, applied to params and every
    params-shaped optimizer subtree."""
    n_layer = jax.tree.leaves(host_params["layers"])[0].shape[0]
    # interleaved pipelines stacked the layers in chunk-permuted order
    # (hybrid.init_hybrid); invert it so the list comes back in model order
    v = getattr(cfg, "pp_interleave", 1)
    if v > 1:
        from dsml_tpu.parallel.pp import interleave_layer_order

        order = interleave_layer_order(n_layer, old_pp_size, v)
        inverse = [0] * n_layer
        for pos, orig in enumerate(order):
            inverse[orig] = pos
    else:
        inverse = list(range(n_layer))

    def unstack(node):
        if "layers" in node and isinstance(node["layers"], dict):
            permuted = [
                jax.tree.map(lambda l: l[i], node["layers"]) for i in range(n_layer)
            ]
            return {**node, "layers": [permuted[inverse[i]] for i in range(n_layer)]}
        return node

    return _map_layer_nodes(host_params, unstack), _map_layer_nodes(host_opt, unstack)


def _restack_state(host_params, host_opt, cfg, new_pp: int):
    """Per-layer list form → stacked layer axis in the NEW stage count's
    interleave order, when the new plan keeps a pipeline (identity when
    ``new_pp <= 1`` — today's planner never emits pp>1, but the state
    transform must not silently depend on that)."""
    if new_pp <= 1:
        return host_params, host_opt
    from dsml_tpu.parallel.pp import interleave_layer_order, stack_layer_params

    v_new = getattr(cfg, "pp_interleave", 1)
    n_layer = len(host_params["layers"])
    order_new = (
        interleave_layer_order(n_layer, new_pp, v_new)
        if v_new > 1
        else list(range(n_layer))
    )

    def restack(node):
        if "layers" in node and isinstance(node["layers"], list):
            return {
                **node,
                "layers": stack_layer_params([node["layers"][i] for i in order_new]),
            }
        return node

    return _map_layer_nodes(host_params, restack), _map_layer_nodes(host_opt, restack)


def _place_state(host_params, host_opt, optimizer, pspecs, new_mesh):
    """Lay host state out on the new mesh: params per their PartitionSpecs,
    optimizer statistics adopting the param shardings directly (adam's
    mu/nu mirror the param tree; scalars like the step count replicate) —
    no fresh optimizer.init, whose transient zeros would double-allocate
    HBM at exactly the moment a shrunken fleet has the least headroom."""
    from dsml_tpu.parallel.hybrid import shard_params
    import optax.tree_utils as otu

    new_params = shard_params(host_params, new_mesh, pspecs)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(new_mesh, s), pspecs, is_leaf=lambda x: isinstance(x, P)
    )
    replicated = NamedSharding(new_mesh, P())
    new_opt = otu.tree_map_params(
        optimizer,
        lambda old, sh: jax.device_put(np.asarray(old), sh),
        host_opt,
        param_shardings,
        transform_non_params=lambda leaf: (
            jax.device_put(np.asarray(leaf), replicated) if leaf is not None else leaf
        ),
    )
    return new_params, new_opt


def restore_from_checkpoint(
    manager,
    model,
    optimizer,
    surviving_devices,
    step: int | None = None,
    seed: int = 0,
    batch_per_device: int = 1,
    global_batch: int | None = None,
    planner_overrides: dict | None = None,
) -> ElasticState:
    """The Varuna-style fallback :func:`reconfigure` points at, as one call:
    when the live state is torn (an entire pipeline stage / tp shard died
    with its devices), re-plan the parallelism for the survivor fleet and
    restore the checkpoint ONTO the new topology — the manifest's sharded
    pieces re-lay onto whatever mesh the plan emits (different device count,
    different layout; ``checkpoint.native``'s relayout path).

    ``manager`` is a ``checkpoint.CheckpointManager`` (or a directory path).
    A checkpoint saved from a pipeline mesh (stacked layer axis) restores
    onto a pipeline-less plan and vice versa: the same unstack/restack
    transforms :func:`reconfigure` applies to live state run on the restored
    host tree, driven by the manifest's recorded pp width.
    """
    if isinstance(manager, str):
        from dsml_tpu.checkpoint import CheckpointManager

        manager = CheckpointManager(manager)
    # resolve "latest" ONCE: an async save committing between the manifest
    # read (form detection) and the restore would otherwise mix two steps
    if step is None:
        step = manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {manager.directory}")
    cfg = getattr(model, "config", None)
    host_params = jax.tree.map(np.asarray, jax.device_get(model.init(seed)))
    plan, survivors = _plan_for_survivors(
        model, model.n_params(host_params), list(surviving_devices),
        batch_per_device, global_batch, planner_overrides,
    )
    new_mesh = build_mesh(plan.spec, survivors)

    # what form did the SAVE use? the manifest records each leaf's path and
    # sharding — a stacked run has 'params/layers/<field>' (dict) paths and
    # a 'pp' mesh axis; a list-form run has 'params/layers/<int>/...'
    from dsml_tpu.checkpoint import native as ckpt_native

    manifest = ckpt_native.read_manifest(manager._step_dir(step))
    saved_stacked = False
    saved_pp = 1
    for e in manifest["leaves"]:
        parts = e["path"].split("/")
        if len(parts) > 2 and parts[0] == "params" and parts[1] == "layers":
            saved_stacked = not parts[2].isdigit()
        sh = e.get("sharding")
        if sh and "pp" in sh.get("mesh_axes", []):
            saved_pp = sh["mesh_shape"][sh["mesh_axes"].index("pp")]
    # host-shaped template in the SAVED form (stacked in the saved pp
    # width's interleave order when the save ran a pipeline): the restore
    # hands back host-placeable arrays we unstack/restack below before
    # placing on the new mesh
    t_params = host_params
    if saved_stacked:
        t_params, _ = _restack_state(t_params, {}, cfg, max(saved_pp, 2))
    t_opt = jax.eval_shape(optimizer.init, t_params)
    state = manager.restore(
        step, template={"params": t_params, "opt_state": t_opt}, partial=True
    )
    host_p = jax.tree.map(np.asarray, jax.device_get(state["params"]))
    host_o = jax.tree.map(
        lambda l: np.asarray(l) if hasattr(l, "shape") else l,
        jax.device_get(state["opt_state"]),
    )
    if saved_stacked:
        host_p, host_o = _unstack_state(host_p, host_o, cfg, saved_pp)
    host_p, host_o = _restack_state(host_p, host_o, cfg, plan.spec.pp)
    pspecs = model.param_specs(pp=plan.spec.pp > 1, fsdp=plan.spec.fsdp)
    new_params, new_opt = _place_state(host_p, host_o, optimizer, pspecs, new_mesh)
    return ElasticState(
        params=new_params, opt_state=new_opt, mesh=new_mesh, spec=plan.spec,
        reasons=plan.reasons
        + (f"restored from checkpoint step {manifest['step']} "
           f"(saved pp={saved_pp}, {'stacked' if saved_stacked else 'list'} form)",),
        step=int(manifest["step"]),
    )
