"""Gradient bucketing: size-targeted flat buckets for overlap-friendly sync.

The reference's AllReduceRing moved ONE monolithic buffer per sync, and the
port kept that shape: ``parallel/dp.py`` raveled the whole gradient pytree
into a single flat vector before one 2(n−1)-hop ring pass — serializing the
entire backward against the entire exchange. Production data-parallel stacks
(PyTorch DDP, the MLPerf TPU-pod entries — PAPERS.md "Scale MLPerf-0.6
models on Google TPU-v3 Pods") instead partition gradients into
size-targeted buckets and reduce each bucket as an INDEPENDENT collective,
so the compiler's latency-hiding scheduler can overlap the exchange of
already-finished gradients with the backward compute still producing the
rest. For the quantized path the win is structural too: q8 quantizes per
bucket, removing the full-vector ravel→quantize serialization.

Mechanics:

- :func:`plan_buckets` — greedy, order-preserving partition of a pytree's
  leaves into buckets targeting ``bucket_size_mb`` MiB each. Buckets are
  PER-DTYPE (a bucket concatenates raveled leaves, which requires one
  dtype); a leaf larger than the target gets a bucket of its own — leaves
  are never split, matching DDP practice (the unit of readiness in a
  backward pass is the whole parameter's gradient).
- :func:`flatten_buckets` / :func:`unflatten_buckets` — pytree ⇄ list of
  flat per-bucket vectors, exact round trip (0-d leaves, mixed dtypes).
- :func:`bucketed_all_reduce` — the sync: one collective per bucket
  (``ring`` / ``ring2`` / ``naive`` / ``auto`` / ``xla`` via
  ``ops.collectives.all_reduce``, ``q8`` via
  ``ops.quantization.compressed_all_reduce``, or the block-quantized ring
  family ``q8_ring`` / ``q8_ring2`` / ``q4_ring`` / ``q4_ring2`` /
  ``quant`` via ``ops.quantization.quantized_ring_all_reduce`` — int8/int4
  quantization INSIDE the 2(n−1)-step schedule; ``quant`` resolves the
  scheme per bucket dtype from ``DSML_QUANT``), all emitted inside the
  same jitted program. ``bucket_size_mb=None`` reproduces the
  pre-bucketing single-buffer path bit-for-bit (same ``ravel_pytree`` +
  single collective jaxpr) for A/B comparison.
- **Error feedback** (EF-SGD): pass ``error_feedback=`` (a residual pytree
  from :func:`init_error_feedback`, per-rank) and the quantized sync runs
  on ``grads + residual`` with deterministic rounding, returning the new
  residual ``adjusted − roundtrip(adjusted)`` alongside the reduction —
  repeated quantized syncs stop drifting because every bit the compressor
  dropped is re-offered next step. Residuals are checkpointable state
  (``trainer.py`` rides them in the manifest) and f32 regardless of the
  gradient dtype, so a bf16 run's correction isn't itself truncated.

Default bucket size: 4 MiB, overridable via ``DSML_BUCKET_MB`` (the
``bench.py`` bucket-size sweep on the virtual-8 mesh is what the default is
chosen from — see docs/TUNING.md; the quantized grid rides
``bench.py --section quant_sweep``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from dsml_tpu.ops.collectives import ReduceOp, all_reduce

__all__ = [
    "BucketPlan",
    "QUANT_RING_ALGORITHMS",
    "default_bucket_mb",
    "plan_buckets",
    "flatten_buckets",
    "unflatten_buckets",
    "bucketed_all_reduce",
    "init_error_feedback",
    "is_quantized_algorithm",
    "supports_error_feedback",
    "plan_quant_wire_bytes",
]

# the v2 block-quantized ring family: algorithm name -> (scheme, bidirectional)
QUANT_RING_ALGORITHMS = {
    "q8_ring": ("int8", False),
    "q8_ring2": ("int8", True),
    "q4_ring": ("int4", False),
    "q4_ring2": ("int4", True),
}


def is_quantized_algorithm(algorithm: str) -> bool:
    """True for every compressed sync: the v1 gather (``q8``), the v2 ring
    family, and the env-resolved ``quant``."""
    return algorithm == "q8" or algorithm == "quant" or algorithm in QUANT_RING_ALGORITHMS


def supports_error_feedback(algorithm: str) -> bool:
    """EF pairs with the deterministic-rounding ring family (and ``quant``,
    which resolves into it). The v1 ``q8`` gather keeps its stochastic
    rounding and stays EF-less — its unbiasedness is its own drift story."""
    return algorithm == "quant" or algorithm in QUANT_RING_ALGORITHMS


def _resolve_quant(algorithm: str, dtype) -> str:
    """Resolve ``"quant"`` per bucket dtype via ``DSML_QUANT``
    (``ops.quantization.quant_algorithm_for``); every other name passes
    through. The result may be plain ``"ring"``/``"ring2"``
    (``DSML_QUANT=none``) — that bucket then syncs unquantized."""
    if algorithm != "quant":
        return algorithm
    from dsml_tpu.ops.quantization import quant_algorithm_for

    return quant_algorithm_for(dtype)


def default_bucket_mb() -> float:
    """The bucket-size default: 4 MiB (chosen from the bench sweep — see
    docs/TUNING.md), overridable via ``DSML_BUCKET_MB`` (malformed or
    non-positive values fall back, same policy as bench.py's env knobs —
    a size must be positive; "no bucketing" is ``bucket_size_mb=None`` at
    the call site, not an env value)."""
    try:
        mb = float(os.environ.get("DSML_BUCKET_MB", 4.0))
    except ValueError:
        return 4.0
    return mb if mb > 0 else 4.0


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static partition of a pytree into flat buckets (all fields are
    trace-time constants — shapes/dtypes/indices, never array data)."""

    treedef: Any
    shapes: tuple  # per-leaf shapes
    dtypes: tuple  # per-leaf dtypes
    buckets: tuple  # tuple of tuples of leaf indices, order-preserving

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def bucket_nbytes(self, b: int) -> int:
        return sum(
            _leaf_size(self.shapes[i]) * jnp.dtype(self.dtypes[i]).itemsize
            for i in self.buckets[b]
        )


def _leaf_size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def plan_buckets(tree, bucket_size_mb: float) -> BucketPlan:
    """Partition ``tree``'s leaves into per-dtype buckets of ~``bucket_size_mb``
    MiB. Greedy in leaf order: each dtype keeps one open bucket; a leaf
    joins it if the bucket hasn't reached the target yet and the leaf alone
    is under target, else a new bucket opens (so an over-target leaf always
    sits in a bucket of its own). Leaves are never split."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(jnp.result_type(l) for l in leaves)
    target = max(float(bucket_size_mb), 1e-6) * (1 << 20)
    open_bucket: dict = {}  # dtype -> [list of leaf idx, bytes so far]
    buckets: list = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        nbytes = _leaf_size(shape) * jnp.dtype(dtype).itemsize
        key = str(dtype)
        # an over-target leaf always opens its own bucket (it would blow an
        # open bucket far past target; once placed, the >= target bucket
        # closes itself via the same size check)
        if nbytes < target and key in open_bucket and open_bucket[key][1] < target:
            open_bucket[key][0].append(i)
            open_bucket[key][1] += nbytes
        else:
            open_bucket[key] = [[i], nbytes]
            buckets.append(open_bucket[key][0])
    return BucketPlan(treedef, shapes, dtypes, tuple(tuple(b) for b in buckets))


def flatten_buckets(tree, plan: BucketPlan) -> list:
    """Flat 1-D vector per bucket: the bucket's leaves raveled and
    concatenated in plan order (single-leaf buckets skip the concat)."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for idxs in plan.buckets:
        if len(idxs) == 1:
            out.append(leaves[idxs[0]].reshape(-1))
        else:
            out.append(jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
    return out

def unflatten_buckets(flat_buckets: Sequence, plan: BucketPlan):
    """Exact inverse of :func:`flatten_buckets` (shapes/dtypes restored from
    the plan, so a widened reduction dtype is cast back per leaf)."""
    leaves: list = [None] * len(plan.shapes)
    for idxs, flat in zip(plan.buckets, flat_buckets):
        off = 0
        for i in idxs:
            n = _leaf_size(plan.shapes[i])
            leaves[i] = (
                lax.slice_in_dim(flat, off, off + n)
                .reshape(plan.shapes[i])
                .astype(plan.dtypes[i])
            )
            off += n
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _q8_bucket_seed(flat: jax.Array, bucket_index: int) -> jax.Array:
    """Data-dependent dither seed, per bucket: the rounding pattern must
    vary per step (slowly-moving coordinates would otherwise see the same
    rounding direction every step — systematic bias) AND per bucket
    (identical buckets must not share noise). Hashing the bucket's own
    gradient bits decorrelates steps without threading a counter through
    the step signature — the same trick parallel/dp.py used on the
    monolithic vector, now applied per bucket with an index mix-in."""
    as_f32 = flat if flat.dtype == jnp.float32 else flat.astype(jnp.float32)
    return (
        jnp.sum(lax.bitcast_convert_type(as_f32, jnp.int32), dtype=jnp.int32)
        + jnp.int32(bucket_index * 7919)
    )


def _ef_plan(plan: BucketPlan) -> BucketPlan:
    """The residual tree's plan: same partition, every leaf f32 (residuals
    are kept full-precision so a bf16 run's correction isn't truncated)."""
    return dataclasses.replace(plan, dtypes=tuple(jnp.float32 for _ in plan.dtypes))


def init_error_feedback(tree, mesh, axis: str):
    """Zero error-feedback residuals for ``tree``'s gradients: one f32
    buffer per leaf PER RANK (EF residuals are rank-local state — each
    rank's compression error is its own), represented outside ``shard_map``
    as ``[n_ranks, *leaf.shape]`` sharded over ``axis`` so every device
    stores exactly its own residual (1× gradient memory per rank, the
    standard EF cost). Checkpointable like any state tree; across a width
    change use ``parallel.elastic.remap_error_feedback``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[axis]
    sh = NamedSharding(mesh, P(axis))

    def zeros(leaf):
        # jit with out_shardings materializes each device's row in place —
        # a host/device_put round trip would transiently hold the FULL
        # [n, *shape] buffer on one device (n× gradient memory at startup)
        shape = (n, *jnp.shape(leaf))
        return jax.jit(
            lambda: jnp.zeros(shape, jnp.float32), out_shardings=sh
        )()

    return jax.tree.map(zeros, tree)


def plan_quant_wire_bytes(plan: BucketPlan, n_ranks: int, algorithm: str) -> dict:
    """Analytic per-sync wire bytes by scheme for a bucket plan under a
    quantized algorithm — ``{scheme: bytes}`` (non-float buckets, which
    ride the fp32 ring, land under ``"fp32"``). Static shapes ⇒ exact;
    the dp/zero2 frontends bump ``collective_quant_bytes_total`` with
    this once per step."""
    from dsml_tpu.ops.quantization import (
        compressed_gather_wire_bytes,
        quantized_ring_wire_bytes,
    )
    from dsml_tpu.ops.collectives import ring_wire_bytes

    out: dict = {}
    for b in range(plan.n_buckets):
        dtype = plan.dtypes[plan.buckets[b][0]]
        n_elems = sum(_leaf_size(plan.shapes[i]) for i in plan.buckets[b])
        resolved = _resolve_quant(algorithm, dtype)
        is_float = jnp.issubdtype(dtype, jnp.floating)
        if resolved in QUANT_RING_ALGORITHMS and is_float:
            scheme, bidir = QUANT_RING_ALGORITHMS[resolved]
            nbytes = quantized_ring_wire_bytes(n_elems, n_ranks, scheme, bidir)
        elif resolved == "q8" and is_float:
            scheme = "int8"
            nbytes = compressed_gather_wire_bytes(n_elems, n_ranks)
        else:
            scheme = "fp32"
            nbytes = ring_wire_bytes(
                n_elems, n_ranks, jnp.dtype(dtype).itemsize
            )
        out[scheme] = out.get(scheme, 0) + nbytes
    return out


def _quant_ring_bucket(flat, axis_name, op, resolved, ef_bucket, bucket_index):
    """One float bucket through the quantized ring: with ``ef_bucket`` the
    sync runs on the residual-adjusted gradient under DETERMINISTIC
    rounding and returns the fresh residual; without, stochastic dithering
    (data-seeded, like the v1 q8 path) keeps repeated roundings unbiased."""
    from dsml_tpu.ops.quantization import (
        quantize_roundtrip,
        quantized_ring_all_reduce,
    )

    scheme, bidir = QUANT_RING_ALGORITHMS[resolved]
    mean = op == ReduceOp.AVG
    if ef_bucket is None:
        out = quantized_ring_all_reduce(
            flat, axis_name, scheme, bidirectional=bidir, mean=mean,
            stochastic=True, seed=_q8_bucket_seed(flat, bucket_index),
        )
        return out, None
    adjusted = flat.astype(jnp.float32) + ef_bucket
    out = quantized_ring_all_reduce(
        adjusted, axis_name, scheme, bidirectional=bidir, mean=mean,
        stochastic=False,
    )
    new_ef = adjusted - quantize_roundtrip(adjusted, scheme)
    return out.astype(flat.dtype), new_ef


def bucketed_all_reduce(
    tree,
    axis_name: str,
    op: ReduceOp = ReduceOp.AVG,
    algorithm: str = "ring",
    bucket_size_mb: float | None = None,
    error_feedback=None,
) -> Any:
    """All-reduce a pytree across ``axis_name`` as per-bucket collectives.

    Call under ``shard_map``. ``algorithm`` is any
    ``ops.collectives.all_reduce`` algorithm (``ring``/``ring2``/``naive``/
    ``auto``/``xla``), ``"q8"`` (v1 blockwise-int8 gather exchange —
    ``ops.quantization.compressed_all_reduce`` per bucket), one of the v2
    block-quantized ring schedules (``"q8_ring"``/``"q8_ring2"``/
    ``"q4_ring"``/``"q4_ring2"`` — int8/int4 inside the 2(n−1)-step ring,
    ``ops.quantization.quantized_ring_all_reduce``), or ``"quant"`` (per
    bucket dtype via ``DSML_QUANT``). Quantized syncs are SUM/AVG only;
    non-float buckets always ride the ring uncompressed, since quantizing
    integer gradients would corrupt them.

    ``error_feedback``: a per-rank residual pytree (leaf-shaped — the
    caller inside ``shard_map`` passes its own rank's slice of the
    :func:`init_error_feedback` state). Requires a ring-family quantized
    algorithm; the return becomes ``(reduced_tree, new_residual_tree)``.
    ``bucket_size_mb=None`` under EF means per-dtype buckets (the zero2
    convention), since the residual bookkeeping is plan-shaped.

    ``bucket_size_mb=None`` (without EF) is the pre-bucketing behavior:
    ONE flat buffer via ``ravel_pytree`` and a single collective —
    bit-identical to the old ``parallel/dp.py`` path (same jaxpr), kept
    for A/B measurement.
    """
    op = ReduceOp(op)
    if is_quantized_algorithm(algorithm) and op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"quantized sync ({algorithm}) supports SUM/AVG, got {op!r}")
    if error_feedback is not None and not supports_error_feedback(algorithm):
        raise ValueError(
            f"error_feedback requires a quantized ring algorithm "
            f"({sorted(QUANT_RING_ALGORITHMS)} or 'quant'), got {algorithm!r}"
        )
    if bucket_size_mb is None and error_feedback is None:
        flat, unravel = ravel_pytree(tree)
        if algorithm == "q8":
            from dsml_tpu.ops.quantization import compressed_all_reduce

            seed = jnp.sum(
                lax.bitcast_convert_type(flat, jnp.int32), dtype=jnp.int32
            )
            flat = compressed_all_reduce(
                flat, axis_name, seed=seed, mean=(op == ReduceOp.AVG)
            )
        elif algorithm == "quant" or algorithm in QUANT_RING_ALGORITHMS:
            resolved = _resolve_quant(algorithm, flat.dtype)
            if resolved in QUANT_RING_ALGORITHMS:
                flat, _ = _quant_ring_bucket(flat, axis_name, op, resolved, None, 0)
            else:
                flat = all_reduce(flat, axis_name, op, resolved)
        else:
            flat = all_reduce(flat, axis_name, op, algorithm)
        return unravel(flat)

    plan = plan_buckets(
        tree, bucket_size_mb if bucket_size_mb is not None else float("inf")
    )
    buckets = flatten_buckets(tree, plan)
    ef_buckets = (
        flatten_buckets(error_feedback, plan) if error_feedback is not None else None
    )
    reduced = []
    new_ef = []
    for b, flat in enumerate(buckets):
        is_float = jnp.issubdtype(flat.dtype, jnp.floating)
        resolved = _resolve_quant(algorithm, flat.dtype)
        if algorithm == "q8" and is_float:
            from dsml_tpu.ops.quantization import compressed_all_reduce

            out = compressed_all_reduce(
                flat, axis_name, seed=_q8_bucket_seed(flat, b),
                mean=(op == ReduceOp.AVG),
            )
        elif resolved in QUANT_RING_ALGORITHMS and is_float:
            ef_b = ef_buckets[b] if ef_buckets is not None else None
            out, ef_out = _quant_ring_bucket(flat, axis_name, op, resolved, ef_b, b)
            if ef_buckets is not None:
                new_ef.append(ef_out)
        else:
            # non-float buckets under a quantized algorithm ride the plain
            # ring; a float bucket whose resolution came back unquantized
            # (DSML_QUANT=none / a plain algorithm) uses that algorithm
            fallback = resolved if not is_quantized_algorithm(resolved) else "ring"
            if ef_buckets is not None and is_float:
                # exact exchange drains the standing residual (a mid-run
                # DSML_QUANT flip must deliver what the compressor owed)
                out = all_reduce(
                    flat.astype(jnp.float32) + ef_buckets[b], axis_name, op, fallback
                ).astype(flat.dtype)
                new_ef.append(jnp.zeros_like(ef_buckets[b]))
            else:
                out = all_reduce(flat, axis_name, op, fallback)
                if ef_buckets is not None:
                    new_ef.append(ef_buckets[b])  # integer bucket: stays zero
        reduced.append(out)
    result = unflatten_buckets(reduced, plan)
    if error_feedback is not None:
        return result, unflatten_buckets(new_ef, _ef_plan(plan))
    return result
