"""Gradient bucketing: size-targeted flat buckets for overlap-friendly sync.

The reference's AllReduceRing moved ONE monolithic buffer per sync, and the
port kept that shape: ``parallel/dp.py`` raveled the whole gradient pytree
into a single flat vector before one 2(n−1)-hop ring pass — serializing the
entire backward against the entire exchange. Production data-parallel stacks
(PyTorch DDP, the MLPerf TPU-pod entries — PAPERS.md "Scale MLPerf-0.6
models on Google TPU-v3 Pods") instead partition gradients into
size-targeted buckets and reduce each bucket as an INDEPENDENT collective,
so the compiler's latency-hiding scheduler can overlap the exchange of
already-finished gradients with the backward compute still producing the
rest. For the quantized path the win is structural too: q8 quantizes per
bucket, removing the full-vector ravel→quantize serialization.

Mechanics:

- :func:`plan_buckets` — greedy, order-preserving partition of a pytree's
  leaves into buckets targeting ``bucket_size_mb`` MiB each. Buckets are
  PER-DTYPE (a bucket concatenates raveled leaves, which requires one
  dtype); a leaf larger than the target gets a bucket of its own — leaves
  are never split, matching DDP practice (the unit of readiness in a
  backward pass is the whole parameter's gradient).
- :func:`flatten_buckets` / :func:`unflatten_buckets` — pytree ⇄ list of
  flat per-bucket vectors, exact round trip (0-d leaves, mixed dtypes).
- :func:`bucketed_all_reduce` — the sync: one collective per bucket
  (``ring`` / ``ring2`` / ``naive`` / ``auto`` / ``xla`` via
  ``ops.collectives.all_reduce``, or ``q8`` via
  ``ops.quantization.compressed_all_reduce``), all emitted inside the same
  jitted program. ``bucket_size_mb=None`` reproduces the pre-bucketing
  single-buffer path bit-for-bit (same ``ravel_pytree`` + single collective
  jaxpr) for A/B comparison.

Default bucket size: 4 MiB, overridable via ``DSML_BUCKET_MB`` (the
``bench.py`` bucket-size sweep on the virtual-8 mesh is what the default is
chosen from — see docs/TUNING.md).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree

from dsml_tpu.ops.collectives import ReduceOp, all_reduce

__all__ = [
    "BucketPlan",
    "default_bucket_mb",
    "plan_buckets",
    "flatten_buckets",
    "unflatten_buckets",
    "bucketed_all_reduce",
]


def default_bucket_mb() -> float:
    """The bucket-size default: 4 MiB (chosen from the bench sweep — see
    docs/TUNING.md), overridable via ``DSML_BUCKET_MB`` (malformed or
    non-positive values fall back, same policy as bench.py's env knobs —
    a size must be positive; "no bucketing" is ``bucket_size_mb=None`` at
    the call site, not an env value)."""
    try:
        mb = float(os.environ.get("DSML_BUCKET_MB", 4.0))
    except ValueError:
        return 4.0
    return mb if mb > 0 else 4.0


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Static partition of a pytree into flat buckets (all fields are
    trace-time constants — shapes/dtypes/indices, never array data)."""

    treedef: Any
    shapes: tuple  # per-leaf shapes
    dtypes: tuple  # per-leaf dtypes
    buckets: tuple  # tuple of tuples of leaf indices, order-preserving

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def bucket_nbytes(self, b: int) -> int:
        return sum(
            _leaf_size(self.shapes[i]) * jnp.dtype(self.dtypes[i]).itemsize
            for i in self.buckets[b]
        )


def _leaf_size(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def plan_buckets(tree, bucket_size_mb: float) -> BucketPlan:
    """Partition ``tree``'s leaves into per-dtype buckets of ~``bucket_size_mb``
    MiB. Greedy in leaf order: each dtype keeps one open bucket; a leaf
    joins it if the bucket hasn't reached the target yet and the leaf alone
    is under target, else a new bucket opens (so an over-target leaf always
    sits in a bucket of its own). Leaves are never split."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(l.shape) for l in leaves)
    dtypes = tuple(jnp.result_type(l) for l in leaves)
    target = max(float(bucket_size_mb), 1e-6) * (1 << 20)
    open_bucket: dict = {}  # dtype -> [list of leaf idx, bytes so far]
    buckets: list = []
    for i, (shape, dtype) in enumerate(zip(shapes, dtypes)):
        nbytes = _leaf_size(shape) * jnp.dtype(dtype).itemsize
        key = str(dtype)
        # an over-target leaf always opens its own bucket (it would blow an
        # open bucket far past target; once placed, the >= target bucket
        # closes itself via the same size check)
        if nbytes < target and key in open_bucket and open_bucket[key][1] < target:
            open_bucket[key][0].append(i)
            open_bucket[key][1] += nbytes
        else:
            open_bucket[key] = [[i], nbytes]
            buckets.append(open_bucket[key][0])
    return BucketPlan(treedef, shapes, dtypes, tuple(tuple(b) for b in buckets))


def flatten_buckets(tree, plan: BucketPlan) -> list:
    """Flat 1-D vector per bucket: the bucket's leaves raveled and
    concatenated in plan order (single-leaf buckets skip the concat)."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for idxs in plan.buckets:
        if len(idxs) == 1:
            out.append(leaves[idxs[0]].reshape(-1))
        else:
            out.append(jnp.concatenate([leaves[i].reshape(-1) for i in idxs]))
    return out

def unflatten_buckets(flat_buckets: Sequence, plan: BucketPlan):
    """Exact inverse of :func:`flatten_buckets` (shapes/dtypes restored from
    the plan, so a widened reduction dtype is cast back per leaf)."""
    leaves: list = [None] * len(plan.shapes)
    for idxs, flat in zip(plan.buckets, flat_buckets):
        off = 0
        for i in idxs:
            n = _leaf_size(plan.shapes[i])
            leaves[i] = (
                lax.slice_in_dim(flat, off, off + n)
                .reshape(plan.shapes[i])
                .astype(plan.dtypes[i])
            )
            off += n
    return jax.tree_util.tree_unflatten(plan.treedef, leaves)


def _q8_bucket_seed(flat: jax.Array, bucket_index: int) -> jax.Array:
    """Data-dependent dither seed, per bucket: the rounding pattern must
    vary per step (slowly-moving coordinates would otherwise see the same
    rounding direction every step — systematic bias) AND per bucket
    (identical buckets must not share noise). Hashing the bucket's own
    gradient bits decorrelates steps without threading a counter through
    the step signature — the same trick parallel/dp.py used on the
    monolithic vector, now applied per bucket with an index mix-in."""
    as_f32 = flat if flat.dtype == jnp.float32 else flat.astype(jnp.float32)
    return (
        jnp.sum(lax.bitcast_convert_type(as_f32, jnp.int32), dtype=jnp.int32)
        + jnp.int32(bucket_index * 7919)
    )


def bucketed_all_reduce(
    tree,
    axis_name: str,
    op: ReduceOp = ReduceOp.AVG,
    algorithm: str = "ring",
    bucket_size_mb: float | None = None,
) -> Any:
    """All-reduce a pytree across ``axis_name`` as per-bucket collectives.

    Call under ``shard_map``. ``algorithm`` is any
    ``ops.collectives.all_reduce`` algorithm (``ring``/``ring2``/``naive``/
    ``auto``/``xla``) or ``"q8"`` (blockwise-int8 compressed exchange,
    SUM/AVG only — ``ops.quantization.compressed_all_reduce`` per bucket;
    non-float buckets ride the ring uncompressed, since int8-quantizing
    integer gradients would corrupt them).

    ``bucket_size_mb=None`` is the pre-bucketing behavior: ONE flat buffer
    via ``ravel_pytree`` and a single collective — bit-identical to the old
    ``parallel/dp.py`` path (same jaxpr), kept for A/B measurement.
    """
    op = ReduceOp(op)
    if algorithm == "q8" and op not in (ReduceOp.SUM, ReduceOp.AVG):
        raise ValueError(f"q8 sync supports SUM/AVG, got {op!r}")
    if bucket_size_mb is None:
        flat, unravel = ravel_pytree(tree)
        if algorithm == "q8":
            from dsml_tpu.ops.quantization import compressed_all_reduce

            seed = jnp.sum(
                lax.bitcast_convert_type(flat, jnp.int32), dtype=jnp.int32
            )
            flat = compressed_all_reduce(
                flat, axis_name, seed=seed, mean=(op == ReduceOp.AVG)
            )
        else:
            flat = all_reduce(flat, axis_name, op, algorithm)
        return unravel(flat)

    plan = plan_buckets(tree, bucket_size_mb)
    buckets = flatten_buckets(tree, plan)
    reduced = []
    for b, flat in enumerate(buckets):
        if algorithm == "q8" and jnp.issubdtype(flat.dtype, jnp.floating):
            from dsml_tpu.ops.quantization import compressed_all_reduce

            out = compressed_all_reduce(
                flat, axis_name, seed=_q8_bucket_seed(flat, b),
                mean=(op == ReduceOp.AVG),
            )
        else:
            out = all_reduce(
                flat, axis_name, op, "ring" if algorithm == "q8" else algorithm
            )
        reduced.append(out)
    return unflatten_buckets(reduced, plan)
