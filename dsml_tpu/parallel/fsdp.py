"""FSDP / ZeRO-style parameter sharding over the ``fsdp`` mesh axis.

Memory-efficiency capability (reference: literature only — SURVEY.md §2.4
"7. Memory/"). TPU-idiomatic formulation: instead of hand-rolling gather/
scatter, each parameter leaf is *annotated* as sharded on its largest
divisible axis over ``fsdp``; XLA's SPMD partitioner then materializes
weights via all-gather just-in-time per layer and reduce-scatters gradients
— the ZeRO-3 communication pattern, derived by the compiler from sharding
annotations alone. Optimizer state inherits the same sharding (ZeRO-1/2 come
along for free: moments live sharded).
"""

from __future__ import annotations

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["fsdp_shardings", "shard_params_fsdp", "make_fsdp_train_step"]


def fsdp_shardings(params, mesh: Mesh, axis: str = "fsdp"):
    """NamedSharding pytree: each leaf sharded over ``axis`` on its first
    dimension divisible by the axis size (replicated when none is)."""
    size = mesh.shape[axis]

    def spec_for(leaf):
        for dim, n in enumerate(leaf.shape):
            if n % size == 0 and n >= size:
                return NamedSharding(mesh, P(*([None] * dim + [axis])))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec_for, params)


def shard_params_fsdp(params, mesh: Mesh, axis: str = "fsdp"):
    return jax.tree.map(jax.device_put, params, fsdp_shardings(params, mesh, axis))


def make_fsdp_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
):
    """jitted ``step(params, opt_state, x, y)`` with params FSDP-sharded and
    the batch sharded over ``batch_axes`` (fsdp doubles as a data axis, as in
    ZeRO: every rank computes on its batch shard with gathered weights).
    XLA inserts the all-gather/reduce-scatter schedule from the shardings."""
    batch_sh = NamedSharding(mesh, P(batch_axes))
    # value= lets loss-reactive transforms (utils.schedules.adaptive_plateau)
    # see the loss; the wrapper makes every optimizer accept it
    optimizer = optax.with_extra_args_support(optimizer)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
        return optax.apply_updates(params, updates), opt_state, loss

    jitted = jax.jit(step, donate_argnums=(0, 1))

    def run(params, opt_state, x, y):
        x = jax.device_put(x, batch_sh)
        y = jax.device_put(y, batch_sh)
        return jitted(params, opt_state, x, y)

    return run


def init_fsdp(model, optimizer, mesh: Mesh, seed: int = 0, axis: str = "fsdp"):
    params = shard_params_fsdp(model.init(seed), mesh, axis)
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state
