"""FSDP / ZeRO-style parameter sharding over the ``fsdp`` mesh axis.

Memory-efficiency capability (reference: literature only — SURVEY.md §2.4
"7. Memory/"). Two formulations:

- **Annotation-driven ZeRO-3** (:func:`make_fsdp_train_step`): each param
  leaf is *annotated* as sharded on its largest divisible axis over
  ``fsdp``; XLA's SPMD partitioner materializes weights via all-gather
  just-in-time per layer and reduce-scatters gradients — the ZeRO-3
  communication pattern derived by the compiler from sharding annotations
  alone. Optimizer state inherits the sharding (ZeRO-1/2 for free).
- **Explicit bucketed ZeRO-2** (:func:`make_zero2_train_step`): params stay
  replicated; gradients partition into ~``bucket_size_mb``-MiB buckets
  (``parallel.bucketing``) and each bucket REDUCE-SCATTERS as an
  independent collective inside the jitted step — so XLA can overlap early
  buckets' exchange with the rest of the backward — leaving each rank one
  contiguous flat shard (1/n) of the gradient space. The optimizer runs on
  that shard only (state is n×-sharded — ZeRO-2's memory shape), and the
  updated shards all-gather back per bucket. This is the explicit
  reduce-scatter data path the reference's ring schedule implied but never
  delivered, with the bucket granularity production DP stacks use.

  With ``quant="int8"`` / ``"int4"`` / ``"auto"`` the gradient
  reduce-scatter runs the BLOCK-QUANTIZED ring schedule
  (``ops.quantization.quantized_flat_reduce_scatter``): each of the n−1
  hops ships 8/4-bit chunks + f32 block scales instead of full-precision
  buckets — the compressed end-to-end ZeRO-2 sync. ``"auto"`` resolves the
  scheme per bucket dtype from ``DSML_QUANT``. ``error_feedback=True``
  adds per-rank residual state (EF-SGD: the compression error re-enters
  the next step's gradients) — the step then runs
  ``(params, opt_state, ef, x, y) -> (params, opt_state, ef, loss)``, with
  ``ef`` from ``parallel.bucketing.init_error_feedback(params, mesh,
  axis)``. The updated-param all-gather half stays full precision: params
  must land bit-identical on every rank (replication invariant), so only
  the gradient half — the hot, error-tolerant direction — compresses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsml_tpu.obs import record_collective_plan, record_quant_sync_bytes
from dsml_tpu.ops.collectives import ReduceOp, flat_all_gather, flat_reduce_scatter
from dsml_tpu.parallel.bucketing import (
    _leaf_size,
    default_bucket_mb,
    flatten_buckets,
    plan_buckets,
    unflatten_buckets,
)

__all__ = [
    "fsdp_shardings",
    "shard_params_fsdp",
    "make_fsdp_train_step",
    "make_zero2_train_step",
    "init_zero2",
    "zero2_abstract_state",
    "restore_zero2",
]


def fsdp_shardings(params, mesh: Mesh, axis: str = "fsdp"):
    """NamedSharding pytree: each leaf sharded over ``axis`` on its first
    dimension divisible by the axis size (replicated when none is)."""
    size = mesh.shape[axis]

    def spec_for(leaf):
        for dim, n in enumerate(leaf.shape):
            if n % size == 0 and n >= size:
                return NamedSharding(mesh, P(*([None] * dim + [axis])))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec_for, params)


def shard_params_fsdp(params, mesh: Mesh, axis: str = "fsdp"):
    return jax.tree.map(jax.device_put, params, fsdp_shardings(params, mesh, axis))


def make_fsdp_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    batch_axes: tuple[str, ...] = ("dp", "fsdp"),
):
    """jitted ``step(params, opt_state, x, y)`` with params FSDP-sharded and
    the batch sharded over ``batch_axes`` (fsdp doubles as a data axis, as in
    ZeRO: every rank computes on its batch shard with gathered weights).
    XLA inserts the all-gather/reduce-scatter schedule from the shardings."""
    batch_sh = NamedSharding(mesh, P(batch_axes))
    # value= lets loss-reactive transforms (utils.schedules.adaptive_plateau)
    # see the loss; the wrapper makes every optimizer accept it
    optimizer = optax.with_extra_args_support(optimizer)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
        return optax.apply_updates(params, updates), opt_state, loss

    jitted = jax.jit(step, donate_argnums=(0, 1))

    def run(params, opt_state, x, y):
        x = jax.device_put(x, batch_sh)
        y = jax.device_put(y, batch_sh)
        return jitted(params, opt_state, x, y)

    return run


def init_fsdp(model, optimizer, mesh: Mesh, seed: int = 0, axis: str = "fsdp"):
    params = shard_params_fsdp(model.init(seed), mesh, axis)
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state


# ---------------------------------------------------------------------------
# Explicit bucketed ZeRO-2 (reduce-scatter grads, sharded optimizer state)
# ---------------------------------------------------------------------------


def _local_shards(buckets, axis: str, n: int):
    """Each rank's contiguous segment of every (identity-padded) bucket."""
    rank = lax.axis_index(axis)
    out = []
    for flat in buckets:
        padded = -(-flat.shape[0] // n) * n
        if padded != flat.shape[0]:
            flat = jnp.pad(flat, (0, padded - flat.shape[0]))
        seg = padded // n
        out.append(lax.dynamic_slice_in_dim(flat, rank * seg, seg))
    return out


def _opt_specs(opt_state, axis: str):
    """shard_map specs for ZeRO-2 optimizer state: array leaves (per-rank
    moment shards) ride sharded over ``axis``; scalar leaves (step counts —
    identical on every rank) stay replicated. Sound for ELEMENTWISE
    optimizers (sgd/adam/adamw/...): their state mirrors the param shards
    leaf-for-leaf. Shape-aware optimizers (adafactor's factored moments)
    need the pytree-shaped :func:`make_fsdp_train_step` path instead."""
    return jax.tree.map(lambda l: P(axis) if jnp.ndim(l) >= 1 else P(), opt_state)


def init_zero2(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    seed: int = 0,
    axis: str = "fsdp",
    bucket_size_mb: float | None | str = "auto",
):
    """(params, opt_state) for :func:`make_zero2_train_step`: params
    replicated on the mesh, optimizer state initialized over each rank's
    flat bucket shards and left sharded over ``axis`` (the ZeRO-2 n× state
    saving). ``bucket_size_mb`` must match the step's."""
    if bucket_size_mb == "auto":
        bucket_size_mb = default_bucket_mb()
    n = mesh.shape[axis]
    optimizer = optax.with_extra_args_support(optimizer)
    params = jax.device_put(model.init(seed), NamedSharding(mesh, P()))
    # None → one bucket per dtype (must match make_zero2_train_step's plan)
    plan = plan_buckets(
        params, bucket_size_mb if bucket_size_mb is not None else float("inf")
    )

    def shard_structs():
        out = []
        for idxs in plan.buckets:
            size = sum(_leaf_size(plan.shapes[i]) for i in idxs)
            seg = -(-size // n) * n // n
            out.append(jax.ShapeDtypeStruct((seg,), plan.dtypes[idxs[0]]))
        return out

    opt_shapes = jax.eval_shape(optimizer.init, shard_structs())
    specs = _opt_specs(opt_shapes, axis)

    def init_fn(params):
        return optimizer.init(_local_shards(flatten_buckets(params, plan), axis, n))

    opt_state = jax.jit(
        jax.shard_map(
            init_fn, mesh=mesh, in_specs=(P(),), out_specs=specs, check_vma=False
        )
    )(params)
    # ledger attribution (docs/OBSERVABILITY.md § Memory ledger): the
    # replicated params vs the axis-sharded optimizer buckets — the n×
    # ZeRO-2 state saving shows up as the gap between the two claims
    from dsml_tpu.obs.memory import get_memory_ledger

    ledger = get_memory_ledger()
    ledger.claim_tree("params", params, detail="zero2")
    ledger.claim_tree("optimizer", opt_state, detail="zero2")
    return params, opt_state


def zero2_abstract_state(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    seed: int = 0,
    axis: str = "fsdp",
    bucket_size_mb: float | None | str = "auto",
):
    """(params_template, opt_template) as ShapeDtypeStructs carrying the
    ZeRO-2 layout's NamedShardings — the allocation-free restore template
    for :func:`restore_zero2` / ``CheckpointManager.restore``. Shapes are
    GLOBAL: each flat optimizer leaf is its bucket's identity-padded size
    for THIS mesh's ``axis`` width, so a checkpoint written at a different
    width re-pads on restore (``checkpoint.native``'s 1-D resize rule —
    the padding is provably zeros, adam/sgd moments of zero gradients)."""
    if bucket_size_mb == "auto":
        bucket_size_mb = default_bucket_mb()
    n = mesh.shape[axis]
    optimizer = optax.with_extra_args_support(optimizer)
    host_params = model.init(seed)
    plan = plan_buckets(
        host_params, bucket_size_mb if bucket_size_mb is not None else float("inf")
    )

    def shard_structs():
        out = []
        for idxs in plan.buckets:
            size = sum(_leaf_size(plan.shapes[i]) for i in idxs)
            seg = -(-size // n) * n // n
            out.append(jax.ShapeDtypeStruct((seg,), plan.dtypes[idxs[0]]))
        return out

    opt_shapes = jax.eval_shape(optimizer.init, shard_structs())
    specs = _opt_specs(opt_shapes, axis)
    repl = NamedSharding(mesh, P())
    params_t = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(jnp.shape(l), jnp.result_type(l), sharding=repl),
        host_params,
    )
    flat_sds, treedef = jax.tree.flatten(opt_shapes)
    flat_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    flat_t = []
    for sds, spec in zip(flat_sds, flat_specs):
        sharded = tuple(spec) and tuple(spec)[0] == axis
        # eval_shape saw the PER-RANK segment; the live (and saved) arrays
        # are the concatenation over ranks — scale dim 0 back to global
        shape = (sds.shape[0] * n, *sds.shape[1:]) if sharded else sds.shape
        flat_t.append(
            jax.ShapeDtypeStruct(shape, sds.dtype, sharding=NamedSharding(mesh, spec))
        )
    return params_t, jax.tree.unflatten(treedef, flat_t)


def restore_zero2(
    manager,
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    step: int | None = None,
    seed: int = 0,
    axis: str = "fsdp",
    bucket_size_mb: float | None | str = "auto",
):
    """Restore a ZeRO-2 run's (params, opt_state) from ``manager`` (a
    ``checkpoint.CheckpointManager``) onto ``mesh`` — including onto a
    DIFFERENT ``axis`` width than the save used: params are replicated
    (width-invariant) and each rank re-slices its 1/n of the flat moment
    buckets from the manifest's pieces. ``bucket_size_mb`` must match the
    saving run's (the bucket plan defines the flat layout)."""
    params_t, opt_t = zero2_abstract_state(
        model, optimizer, mesh, seed=seed, axis=axis, bucket_size_mb=bucket_size_mb
    )
    state = manager.restore(
        step, template={"params": params_t, "opt_state": opt_t}, partial=True
    )
    return state["params"], state["opt_state"]


def _zero2_scheme_for(quant: str | None, dtype) -> str | None:
    """Which quant scheme a ZeRO-2 bucket of ``dtype`` reduce-scatters
    with: ``None`` (full precision), a fixed scheme, or ``"auto"`` → the
    ``DSML_QUANT`` per-dtype choice (its algorithm half is irrelevant here
    — a reduce-scatter is single-direction by construction)."""
    if quant is None or not jnp.issubdtype(dtype, jnp.floating):
        return None
    if quant in ("int8", "int4"):
        return quant
    if quant == "auto":
        from dsml_tpu.ops.quantization import quant_algorithm_for

        algo = quant_algorithm_for(dtype)
        if algo.startswith("q8"):
            return "int8"
        if algo.startswith("q4"):
            return "int4"
        return None  # DSML_QUANT=none
    raise ValueError(f"unknown zero2 quant mode {quant!r}; use int8/int4/auto/None")


def make_zero2_train_step(
    loss_fn,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = "fsdp",
    bucket_size_mb: float | None | str = "auto",
    donate: bool = True,
    quant: str | None = None,
    error_feedback: bool = False,
):
    """Explicit ZeRO-2: ``step(params, opt_state, x, y)`` with replicated
    params, per-bucket gradient REDUCE-SCATTER, optimizer on each rank's
    flat shard (state sharded n×), and per-bucket all-gather of the updated
    shards. ``loss_fn(params, x, y)`` returns the mean loss over its batch
    shard; the batch shards over ``axis`` (fsdp doubles as a data axis).

    Restricted to elementwise optimizers (see ``_opt_specs``); initialize
    state with :func:`init_zero2` using the same ``bucket_size_mb``.
    ``bucket_size_mb``: ``"auto"`` = ``DSML_BUCKET_MB`` env default (4 MiB),
    a number = that many MiB, ``None`` = one bucket per dtype (the
    single-buffer A/B shape: the whole gradient space reduce-scatters as
    one collective per dtype — no backward/comm overlap possible).

    ``quant``: ``"int8"`` / ``"int4"`` runs each float bucket's
    reduce-scatter as the block-quantized ring (n−1 hops at 8/4 bits per
    element + f32 block scales); ``"auto"`` resolves per bucket dtype from
    ``DSML_QUANT``; ``None`` (default) is the full-precision psum-scatter.
    The gradient shard a rank is left with is bit-identical across the
    quantized and unquantized layouts' SHAPES, so the sharded optimizer
    state from :func:`init_zero2` fits unchanged. ``error_feedback=True``
    (requires ``quant``) threads per-rank residual state through the step
    — signature becomes ``(params, opt_state, ef, x, y)`` with ``ef`` from
    ``parallel.bucketing.init_error_feedback(params, mesh, axis)``.
    """
    if bucket_size_mb == "auto":
        bucket_size_mb = default_bucket_mb()
    if error_feedback and quant is None:
        raise ValueError("error_feedback=True requires quant= (int8/int4/auto)")
    if quant is not None and quant not in ("int8", "int4", "auto"):
        raise ValueError(f"unknown zero2 quant mode {quant!r}; use int8/int4/auto/None")
    n = mesh.shape[axis]
    batch_sh = NamedSharding(mesh, P(axis))
    ef_sh = NamedSharding(mesh, P(axis))
    optimizer = optax.with_extra_args_support(optimizer)
    # None → a single huge target so every dtype packs into ONE bucket
    plan_mb = bucket_size_mb if bucket_size_mb is not None else float("inf")
    quant_bytes_cell: dict = {}

    def _grad_shards(gbuckets, plan, ef_buckets):
        """Per-bucket reduce-scatter (quantized where configured) → each
        rank's flat gradient shards + the fresh EF residual buckets."""
        from dsml_tpu.parallel.bucketing import _q8_bucket_seed

        gshards, new_ef = [], []
        for b, g in enumerate(gbuckets):
            scheme = _zero2_scheme_for(quant, g.dtype)
            if scheme is None:
                if ef_buckets is not None and jnp.issubdtype(g.dtype, jnp.floating):
                    # exact exchange drains the standing residual
                    adj = g.astype(jnp.float32) + ef_buckets[b]
                    shard = flat_reduce_scatter(adj, axis, ReduceOp.AVG)[0]
                    new_ef.append(jnp.zeros_like(ef_buckets[b]))
                else:
                    shard = flat_reduce_scatter(g, axis, ReduceOp.AVG)[0]
                    if ef_buckets is not None:
                        new_ef.append(ef_buckets[b])
                gshards.append(shard.astype(g.dtype))
                continue
            from dsml_tpu.ops.quantization import (
                quantize_roundtrip,
                quantized_flat_reduce_scatter,
            )

            if ef_buckets is None:
                shard, _ = quantized_flat_reduce_scatter(
                    g, axis, scheme, mean=True, stochastic=True,
                    seed=_q8_bucket_seed(g, b),
                )
            else:
                adj = g.astype(jnp.float32) + ef_buckets[b]
                shard, _ = quantized_flat_reduce_scatter(
                    adj, axis, scheme, mean=True, stochastic=False,
                )
                new_ef.append(adj - quantize_roundtrip(adj, scheme))
            gshards.append(shard.astype(g.dtype))
        return gshards, new_ef

    def make_step(with_ef: bool):
        def step(params, opt_state, *rest):
            if with_ef:
                ef, x, y = rest
            else:
                x, y = rest
            plan = plan_buckets(params, plan_mb)
            specs = _opt_specs(opt_state, axis)
            # trace-time: the ZeRO-2 reduce-scatter plan, labeled "zero2"
            # next to the dp algorithms in the same registry metrics (None
            # means per-dtype buckets HERE, unlike dp's single ravel buffer
            # — pass the resolved plan_mb so the recorder models what
            # actually runs)
            record_collective_plan(
                "zero2" if quant is None else f"zero2_{quant}",
                params, plan_mb, axis,
            )
            if quant is not None and not quant_bytes_cell:
                from dsml_tpu.parallel.bucketing import plan_quant_wire_bytes

                # a reduce-scatter is the scatter-reduce half of the ring:
                # half the all-reduce's hop count, so half its wire bytes
                algo = {"int8": "q8_ring", "int4": "q4_ring", "auto": "quant"}[quant]
                quant_bytes_cell.update({
                    scheme: nbytes // 2
                    for scheme, nbytes in
                    plan_quant_wire_bytes(plan, n, algo).items()
                })

            def shard_fn(params, opt_state, *tail):
                if with_ef:
                    ef, x, y = tail
                else:
                    ef = None
                    x, y = tail
                loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
                loss = lax.pmean(loss, axis)
                gbuckets = flatten_buckets(grads, plan)
                sizes = [g.shape[0] for g in gbuckets]
                ef_buckets = None
                if with_ef:
                    ef_local = jax.tree.map(lambda l: l[0], ef)
                    ef_buckets = flatten_buckets(ef_local, plan)
                # one reduce-scatter per bucket: independent collectives the
                # scheduler can overlap with still-running backward compute
                gshards, new_ef = _grad_shards(gbuckets, plan, ef_buckets)
                pshards = _local_shards(flatten_buckets(params, plan), axis, n)
                updates, opt_state = optimizer.update(
                    gshards, opt_state, pshards, value=loss
                )
                new_shards = optax.apply_updates(pshards, updates)
                new_buckets = [
                    flat_all_gather(s, axis, size)
                    for s, size in zip(new_shards, sizes)
                ]
                out = (unflatten_buckets(new_buckets, plan), opt_state, loss)
                if with_ef:
                    from dsml_tpu.parallel.bucketing import _ef_plan

                    ef_tree = unflatten_buckets(new_ef, _ef_plan(plan))
                    out = out + (jax.tree.map(lambda l: l[None], ef_tree),)
                return out

            out_specs = (P(), specs, P()) + ((P(axis),) if with_ef else ())
            in_specs = (P(), specs) + ((P(axis),) if with_ef else ()) + (P(axis), P(axis))
            args = (params, opt_state) + ((ef,) if with_ef else ()) + (x, y)
            res = jax.shard_map(
                shard_fn,
                mesh=mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )(*args)
            if with_ef:
                new_params, opt_state, loss, new_ef = res
                return new_params, opt_state, new_ef, loss
            new_params, opt_state, loss = res
            return new_params, opt_state, loss

        return step

    if error_feedback:
        jitted = jax.jit(
            make_step(True), donate_argnums=(0, 1, 2) if donate else ()
        )

        def run(params, opt_state, ef, x, y):
            x = jax.device_put(x, batch_sh)
            y = jax.device_put(y, batch_sh)
            out = jitted(params, opt_state, ef, x, y)
            record_quant_sync_bytes(quant_bytes_cell, f"zero2_{quant}", axis)
            return out

        return run

    jitted = jax.jit(make_step(False), donate_argnums=(0, 1) if donate else ())

    def run(params, opt_state, x, y):
        x = jax.device_put(x, batch_sh)
        y = jax.device_put(y, batch_sh)
        out = jitted(params, opt_state, x, y)
        if quant is not None:
            record_quant_sync_bytes(quant_bytes_cell, f"zero2_{quant}", axis)
        return out

    return run
