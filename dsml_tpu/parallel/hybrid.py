"""Hybrid-parallel training: pp × dp × fsdp × sp/cp × tp in one jitted mesh
program.

The composable-mesh-axes design the reference's literature corpus points at
(Megatron PTD-P, OneFlow SBP, Colossal-AI — SURVEY.md §2.3 "hybrid
parallelism: literature only") realized for the transformer:

- params enter TP-sharded (``GPT2.param_specs``), replicated over dp/sp;
  with pp > 1 the layer stack is stage-sharded over 'pp' and runs as a
  GPipe pipeline (``parallel.pp``) inside the same step;
- with fsdp > 1 every param leaf is additionally ZeRO-sharded over the
  'fsdp' axis (``with_fsdp`` specs): inside the per-rank program weights
  are ``all_gather``-ed just-in-time, and the shard_map transpose of that
  gather IS the gradient reduce-scatter — the ZeRO-3 communication
  pattern, spelled as one collective whose autodiff does the rest.
  Optimizer state inherits the sharded layout (ZeRO-1/2 for free);
- the batch enters ``P(('dp','fsdp'), 'sp')`` (batch rows over dp and
  fsdp — fsdp doubles as a data axis, as in ZeRO — sequence over sp);
- inside ``shard_map``, the model runs Megatron TP psums + ring/Ulysses
  sequence-parallel attention; differentiation happens OUTSIDE shard_map so
  every collective's transpose assigns cotangents exactly once;
- the optimizer update runs OUTSIDE shard_map in the same jit — GSPMD
  propagates the param shardings through optax states automatically.

One step = one XLA program; every collective rides ICI.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dsml_tpu.parallel.mesh import MeshSpec

__all__ = ["shard_params", "make_hybrid_train_step", "hybrid_loss_fn",
           "default_attn_impl"]


def default_attn_impl(mesh: Mesh) -> str:
    """What ``attn_impl=None`` resolves to on this mesh: the context-parallel
    flash ring (``"ring2"``: bidirectional KV streaming, causal hop skip, KV
    re-streaming backward — ``ops.ring_attention``) when cp is sized, else
    the exact XLA ring. ONE definition, shared by the train-step builder and
    any caller (e.g. the example's eval loss) that must match it."""
    return "ring2" if mesh.shape.get("cp", 1) > 1 else "ring"


def shard_params(params, mesh: Mesh, specs) -> dict:
    """Place a param pytree onto the mesh per its PartitionSpec pytree."""
    return jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def gather_fsdp(params, pspecs, axis: str = "fsdp"):
    """Reconstruct full weights from their ZeRO shards inside the per-rank
    program: one tiled ``all_gather`` over ``axis`` per fsdp-sharded leaf.
    Under ``jax.grad`` of the surrounding shard_map, the transpose of each
    gather is a ``psum_scatter`` — gradients leave reduce-scattered into the
    same shard layout, which is exactly ZeRO's backward half."""

    def g(leaf, spec):
        for dim, ax in enumerate(spec):
            if ax == axis:
                return lax.all_gather(leaf, axis, axis=dim, tiled=True)
        return leaf

    return jax.tree.map(g, params, pspecs, is_leaf=lambda x: isinstance(x, P))


def hybrid_loss_fn(
    model, attn_impl: str = "ring", pp_axis: str | None = None, n_micro: int = 1,
    seq_axis: str = "sp",
) -> Callable:
    """Per-rank loss closure for shard_map over the framework mesh axes.

    ``seq_axis`` names the mesh axis the sequence dimension shards over —
    the legacy ``"sp"`` ring or the ``"cp"`` context-parallel axis; the
    model's per-rank positions offset by the shard origin on whichever is
    passed, and the per-rank loss (chunked xent — ``ops/xent.py``) runs on
    this rank's sequence rows alone, so the [B, S, vocab] logits tensor is
    never assembled on any chip."""

    def loss_fn(params, x, y):
        return model.loss_spmd(
            params, x, y, tp_axis="tp", sp_axis=seq_axis, attn_impl=attn_impl,
            pp_axis=pp_axis, n_micro=n_micro,
        )

    return loss_fn


def _with_step_watermark(jitted):
    """Wrap a jitted hybrid step so every call lands a memory-ledger peak
    watermark (docs/OBSERVABILITY.md § Memory ledger) — one enabled
    check when obs is off, one cached stats-availability check on
    backends without ``memory_stats``. ``.lower`` passes through so
    compile-introspection callers (memory analysis) keep working."""
    from dsml_tpu.obs.memory import get_memory_ledger

    ledger = get_memory_ledger()

    def step(params, opt_state, x, y):
        out = jitted(params, opt_state, x, y)
        ledger.note_step_peak()
        return out

    step.lower = jitted.lower
    step.jitted = jitted
    return step


def make_hybrid_train_step(
    model,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    attn_impl: str | None = None,
    grad_accum: int = 1,
    n_microbatches: int = 1,
    schedule: str = "gpipe",
    dp_sync: str = "xla",
    bucket_size_mb: float | None | str = "auto",
):
    """Build ``step(params, opt_state, x, y) -> (params, opt_state, loss)``.

    ``x``/``y``: int32 [global_batch, seq]; with ``grad_accum > 1`` the
    global batch is split into that many microbatches whose gradients
    accumulate on-device before one optimizer update (BASELINE.md's
    "data-parallel AllReduce + grad accumulation" config).

    With mesh cp > 1 (context parallelism) the SEQUENCE dimension shards
    over the ``cp`` ring: attention streams KV blocks around the axis
    (``attn_impl=None`` resolves to ``"ring2"`` — the bidirectional flash
    ring with causal hop skipping and the KV re-streaming backward,
    ``ops.ring_attention``), per-rank positions offset by the shard origin,
    and the loss stays sequence-parallel (each rank's chunked xent over its
    own rows + one pmean) so neither full-length activations nor the
    [B, S, vocab] logits ever exist on one chip. cp composes with dp/fsdp
    (and pp/tp) like sp does; sp and cp cannot both exceed 1 — a 2D
    sequence grid rides tp × sp via ``ops.attention.attention_2d`` instead.
    Selective remat (``config.remat="mlp"``) composes: the flash residuals
    each cp rank keeps are O(S/cp).

    When the mesh has pp > 1, the transformer block stack additionally runs
    as a pipeline of ``n_microbatches`` per step (params must be the
    STACKED form from :func:`init_hybrid`): the full pp×dp×sp×tp hybrid.
    ``schedule`` picks the pipeline schedule:

    - ``"gpipe"`` — synchronous GPipe: forward scan + ``jax.grad``'s
      mirrored backward; stores one residual set per tick (O(M) activation
      memory) unless ``config.remat`` rematerializes stages.
    - ``"1f1b"`` — hand-interleaved one-forward-one-backward
      (``parallel.pp.pipeline_train_1f1b``): each microbatch's backward
      starts as soon as its forward completes, in-flight activations are
      schedule-bounded at ≤ 2(pp−1)+1 microbatches with stage recompute.
      Same bubble fraction as GPipe (synchronous flush), much flatter
      memory in M.

    ``dp_sync`` picks the gradient-sync mechanism on dp-ONLY meshes (every
    other axis size 1): ``"xla"`` (default) keeps the shard_map-transpose
    psum — one sync per microbatch, XLA's collective choice. Any explicit
    algorithm (``"ring"``/``"ring2"``/``"naive"``/``"auto"``/``"q8"``, or
    the block-quantized ring family ``"q8_ring"``/``"q8_ring2"``/
    ``"q4_ring"``/``"q4_ring2"``/``"quant"`` — int8/int4 inside the
    2(n−1)-step schedule, ``DSML_QUANT`` resolves ``"quant"`` per dtype)
    instead accumulates LOCAL per-rank gradients across the grad-accum
    microbatches and syncs ONCE per step as per-bucket collectives
    (``parallel.bucketing``, ~``bucket_size_mb`` MiB each, ``"auto"`` =
    the 4 MiB env default, ``None`` = one buffer) — grad_accum× fewer
    bytes on the wire and per-bucket overlap with the backward. Grad-accum
    composes especially well with the quantized syncs: accumulation stays
    full-precision on-device, so quantization noise enters once per step,
    not once per microbatch. (Error-feedback residual state threads
    through the dp/zero2 step builders, which own their state signatures —
    here use ``parallel.dp.make_dp_train_step(error_feedback=True)`` for
    the EF variant.) Per-rank differentiation is exact here precisely
    because no collective crosses ranks inside the loss on a dp-only mesh;
    meshes with tp/sp/pp/fsdp > 1 reject explicit ``dp_sync`` rather than
    compute silently-wrong cotangents.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    pp_size = mesh.shape.get("pp", 1)
    pp_axis = "pp" if pp_size > 1 else None
    fsdp_size = mesh.shape.get("fsdp", 1)
    # ONE definition of the sequence-axis policy (MeshSpec.seq_axis: cp wins
    # when sized, sp>1 with cp>1 rejected); the batch spec names both axes
    # so either composes with dp/fsdp
    seq_axis = MeshSpec.from_mesh(mesh).seq_axis()
    seq_names = tuple(a for a in ("sp", "cp") if a in mesh.axis_names)
    if attn_impl is None:
        attn_impl = default_attn_impl(mesh)
    if schedule == "1f1b" and not pp_axis:
        # silent fallback would let a user "measure 1F1B" on a pipeline-less
        # mesh and actually measure the gpipe path
        raise ValueError("schedule='1f1b' requires a mesh with pp > 1")
    if schedule == "1f1b" and getattr(model.config, "pp_interleave", 1) > 1:
        raise ValueError("pp_interleave > 1 composes with the gpipe schedule only")
    pspecs = model.param_specs(pp=bool(pp_axis), fsdp=fsdp_size)
    # fsdp doubles as a data axis (ZeRO): batch rows shard over dp × fsdp;
    # the sequence dim shards over whichever sequence ring is sized
    batch_spec = P(("dp", "fsdp"), seq_names)
    loss_fn = hybrid_loss_fn(model, attn_impl, pp_axis, n_microbatches, seq_axis)
    # value= lets loss-reactive transforms (utils.schedules.adaptive_plateau)
    # see the loss; the wrapper makes every optimizer accept it
    optimizer = optax.with_extra_args_support(optimizer)

    def total_loss(params, x, y):
        # JIT weight reconstruction from ZeRO shards; the transpose of the
        # gathers reduce-scatters the gradients back into shard layout
        params = gather_fsdp(params, pspecs)
        # pmean over the batch axes so the per-rank value is the GLOBAL mean
        # loss, replicated on every rank (tp ranks agree by construction of
        # the vocab-sharded CE; pp ranks via the masked-head psum). cp/sp
        # ranks hold equal-length sequence shards, so the mean of per-rank
        # means IS the global mean — the sequence-parallel loss.
        return lax.pmean(loss_fn(params, x, y), ("dp", "fsdp") + seq_names)

    sharded_loss = jax.shard_map(
        total_loss,
        mesh=mesh,
        in_specs=(pspecs, batch_spec, batch_spec),
        out_specs=P(),
        check_vma=False,
    )

    def gpipe_grads(params, x, y):
        # Differentiate OUTSIDE shard_map: the outer grad seeds the
        # replicated loss once and shard_map's transpose machinery assigns
        # every collective's cotangent correctly (psum of per-rank
        # contributions for replicated params, per-stage cotangents for
        # pp-sharded layers). value_and_grad INSIDE shard_map would seed 1
        # per rank and inflate every psum-crossing gradient by the axis size
        # (tp, and pp's masked-head psum) — a silent n× lr scale.
        return jax.value_and_grad(sharded_loss)(params, x, y)

    def _1f1b_per_rank(params, x, y):
        # 1F1B differentiates INSIDE shard_map (per-tick jax.vjp — that is
        # what lets forward and backward interleave), which is sound only
        # under check_vma=True: vma tracking gives collective transposes
        # their exact cotangents, and the transpose of each auto-lifted
        # replicated input psums its cotangent across the lifted axes right
        # inside the per-tick vjp. With the schedule's seed carrying the
        # 1/(M·n_dp·n_fsdp·n_sp) normalization, grads therefore arrive
        # already reduced to each leaf's replication — no further psums.
        #
        # fsdp composes through an EXPLICIT vjp of the weight gather: the
        # schedule sees full weights (marked fsdp-varying, so its per-tick
        # transposes leave their cotangents per-rank), and pulling the
        # accumulated full-weight grads back through the gather's transpose
        # is one psum_scatter per sharded leaf — summing the fsdp data
        # ranks AND scattering into shard layout, exactly ZeRO's backward
        # half (the same collective the gpipe path gets from shard_map's
        # outer-grad transpose). Leaves without an fsdp dim pass through
        # untouched and their fsdp reduction happens via the schedule's
        # auto-lift psums like any replicated param.
        full, fsdp_vjp = jax.vjp(lambda p: gather_fsdp(p, pspecs), params)
        loss, grads_full = model.train_grads_1f1b_spmd(
            full, x, y, tp_axis="tp", sp_axis=seq_axis, attn_impl=attn_impl,
            pp_axis="pp", n_micro=n_microbatches,
            # the batch enters P(('dp','fsdp'), seq axes): data varies over
            # fsdp too (size 1 on fsdp-less meshes, but vma tracking still
            # sees it)
            batch_axes=("dp", "fsdp") + seq_names,
        )
        # loss is masked to the last pp rank; batch axes hold genuinely
        # different values (mean them); remaining marked axes (tp) hold
        # equal values (pmean is an identity that clears the marking)
        loss = lax.psum(loss, "pp")
        rest = tuple(jax.typeof(loss).vma)
        if rest:
            loss = lax.pmean(loss, rest)
        (grads,) = fsdp_vjp(grads_full)
        return loss, grads

    if dp_sync != "xla":
        # per-rank value_and_grad + one explicit bucketed sync is only
        # exact when NO collective crosses ranks inside the loss — i.e. a
        # dp-only mesh (psums over the size-1 tp/sp/pp axes are identities)
        busy = {a: s for a in ("pp", "fsdp", "sp", "cp", "tp")
                if (s := mesh.shape.get(a, 1)) > 1}
        if busy:
            raise ValueError(
                f"dp_sync={dp_sync!r} requires a dp-only mesh; got {busy} — "
                "use dp_sync='xla' on multi-axis meshes"
            )
        from dsml_tpu.ops.collectives import ReduceOp
        from dsml_tpu.parallel.bucketing import bucketed_all_reduce, default_bucket_mb

        mb = default_bucket_mb() if bucket_size_mb == "auto" else bucket_size_mb

        def _explicit_per_rank(params, x, y):
            def micro_grads(p, xm, ym):
                return jax.value_and_grad(loss_fn)(p, xm, ym)

            if grad_accum == 1:
                loss, grads = micro_grads(params, x, y)
            else:
                micro = x.shape[0] // grad_accum
                xs = x[: micro * grad_accum].reshape(grad_accum, micro, *x.shape[1:])
                ys = y[: micro * grad_accum].reshape(grad_accum, micro, *y.shape[1:])

                def body(carry, xy):
                    loss_acc, grads_acc = carry
                    loss, grads = micro_grads(params, *xy)
                    return (loss_acc + loss,
                            jax.tree.map(jax.numpy.add, grads_acc, grads)), None

                zero = jax.tree.map(jax.numpy.zeros_like, params)
                (loss, grads), _ = jax.lax.scan(body, (0.0, zero), (xs, ys))
                loss = loss / grad_accum
                grads = jax.tree.map(lambda g: g / grad_accum, grads)
            # the step's ONLY cross-rank exchange: per-bucket collectives,
            # once per step regardless of grad_accum
            from dsml_tpu.obs import record_collective_plan

            # trace-time: bucket plan labeled by algorithm, once per compile
            record_collective_plan(dp_sync, grads, mb, "dp")
            grads = bucketed_all_reduce(grads, "dp", ReduceOp.AVG, dp_sync, mb)
            return lax.pmean(loss, "dp"), grads

        explicit_step_grads = jax.shard_map(
            _explicit_per_rank,
            mesh=mesh,
            in_specs=(pspecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs),
            check_vma=False,
        )

        n_dp = mesh.shape.get("dp", 1)

        def step(params, opt_state, x, y):
            # the microbatch split runs on each rank's SHARD inside
            # shard_map, so per-rank rows must divide — global-only
            # divisibility would silently drop rows (or give 0-row
            # microbatches) whenever batch/dp % grad_accum != 0
            if grad_accum > 1 and x.shape[0] % (grad_accum * n_dp):
                raise ValueError(
                    f"global batch {x.shape[0]} not divisible by "
                    f"grad_accum*dp = {grad_accum}*{n_dp}"
                )
            loss, grads = explicit_step_grads(params, x, y)
            updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return _with_step_watermark(jax.jit(step, donate_argnums=(0, 1)))

    if pp_axis and schedule == "1f1b":
        sharded_grads = jax.shard_map(
            _1f1b_per_rank,
            mesh=mesh,
            in_specs=(pspecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs),
            check_vma=True,
        )
    else:
        sharded_grads = gpipe_grads

    def step(params, opt_state, x, y):
        if grad_accum == 1:
            loss, grads = sharded_grads(params, x, y)
        else:
            if x.shape[0] % grad_accum:
                raise ValueError(
                    f"global batch {x.shape[0]} not divisible by grad_accum={grad_accum}"
                )
            micro = x.shape[0] // grad_accum
            xs = x[: micro * grad_accum].reshape(grad_accum, micro, *x.shape[1:])
            ys = y[: micro * grad_accum].reshape(grad_accum, micro, *y.shape[1:])

            def body(carry, xy):
                loss_acc, grads_acc = carry
                loss, grads = sharded_grads(params, *xy)
                return (loss_acc + loss, jax.tree.map(jax.numpy.add, grads_acc, grads)), None

            zero = jax.tree.map(jax.numpy.zeros_like, params)
            (loss, grads), _ = jax.lax.scan(body, (0.0, zero), (xs, ys))
            loss = loss / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params, value=loss)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return _with_step_watermark(jax.jit(step, donate_argnums=(0, 1)))


def init_hybrid(model, optimizer, mesh: Mesh, seed: int = 0):
    """Initialize (params, opt_state) already placed on the mesh. With
    pp > 1 the layer list is stacked (leading layer axis) and stage-sharded
    over 'pp'; with fsdp > 1 leaves are ZeRO-sharded over 'fsdp'."""
    params = model.init(seed)
    pp = mesh.shape.get("pp", 1) > 1
    fsdp_size = mesh.shape.get("fsdp", 1)
    if pp:
        from dsml_tpu.parallel.pp import interleave_layer_order, stack_layer_params

        n_layer = len(params["layers"])
        pp_size = mesh.shape["pp"]
        if n_layer % pp_size:
            raise ValueError(f"n_layer={n_layer} not divisible by pp={pp_size}")
        v = getattr(model.config, "pp_interleave", 1)
        layers = params["layers"]
        if v > 1:
            # interleaved schedule: rank r owns chunks r, r+S, … — permute
            # the layer order so the plain P('pp') shard hands each rank
            # exactly its v chunks (pp.interleave_layer_order)
            order = interleave_layer_order(n_layer, pp_size, v)
            layers = [layers[i] for i in order]
        params = {**params, "layers": stack_layer_params(layers)}
    params = shard_params(params, mesh, model.param_specs(pp=pp, fsdp=fsdp_size))
    opt_state = jax.jit(optimizer.init)(params)

    # leaves jit creates from scratch (adam's step count) come back on a
    # single device with no mesh sharding; the live run tolerates the mix,
    # but a checkpoint RESTORE of such a leaf comes back committed and then
    # collides with the mesh-placed params inside the jitted step — pin
    # every leaf to the mesh now so saved templates carry real shardings
    def pin(leaf):
        if isinstance(leaf, jax.Array) and not isinstance(leaf.sharding, NamedSharding):
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        return leaf

    opt_state = jax.tree.map(pin, opt_state)
    # ledger attribution at the allocation site: per-device SHARD bytes
    # (an fsdp/pp-sharded state claims what one chip actually holds) —
    # no-op when obs is off
    from dsml_tpu.obs.memory import get_memory_ledger

    ledger = get_memory_ledger()
    ledger.claim_tree("params", params, detail="hybrid")
    ledger.claim_tree("optimizer", opt_state, detail="hybrid")
    return params, opt_state
