"""Automatic parallelism planning — pick the mesh for a model + fleet.

The reference carries auto-parallelism as literature only (Alpa,
``Literatures/4. Auto P/osdi22-zheng-lianmin.pdf`` — ILP over intra-op
shardings + DP over pipeline splits; SURVEY.md §2.3). A full ILP search is
out of scope here (and XLA's own auto-SPMD partitioner is the in-compiler
version of it); what a framework user actually needs first is the
*inter-op* decision Alpa's outer loop makes: which parallelism axes to use
at all, given the model and the chips. :func:`plan_mesh` makes that call
deterministically from first-order memory/communication arithmetic and
returns a :class:`MeshSpec` that drops straight into
``build_mesh`` + ``make_hybrid_train_step``.

The rules (each one is the standard capacity argument, documented inline):

1. Training state per replica ≈ params × (bytes(dtype) for weights +
   2×bytes for grads... conservatively ``dtype + grad + 2×f32 adam`` ≈
   12 bytes at bf16). If that fits in a fraction of one chip's HBM →
   pure DP (cheapest comm: one grad all-reduce).
2. If not, shard the state: prefer FSDP (params/grads/opt sharded over
   the whole fleet; communication = all-gather weights + reduce-scatter
   grads, overlappable) until per-chip state fits.
3. If even FSDP over every chip can't fit a shard, the MODEL itself must
   shard: PP first for deep models (stage boundaries move only
   activations — the cheapest model-sharding comm), then TP bounded by
   head divisibility, FSDP carrying the rest.
4. SP (ring attention) when the per-chip ACTIVATION footprint of the
   sequence — seq × d × layers × bytes — crosses the budget; ring hops
   are cheap next to attention FLOPs at that point.

Capacity inputs come from the hardware when available: per-chip HBM is
read from ``jax.Device.memory_stats()`` (VERDICT r2 weak #4 — the 16 GB
constant was fiction on anything but a v5e), and callers with profiled
runs can pass a measured activation footprint instead of the analytic
estimate.
"""

from __future__ import annotations

import dataclasses

from dsml_tpu.parallel.mesh import MeshSpec
from dsml_tpu.utils.logging import get_logger

__all__ = ["plan_mesh", "AutoPlan", "measured_activation_bytes"]

log = get_logger("auto")

# the pre-ledger fiction, now never silent: every plan that uses it warns
# once and stamps its provenance into the plan AND the obs registry
FALLBACK_HBM_BYTES = 16e9
_warned_fallback = False


def measured_activation_bytes(loss_fn, *example_args) -> float | None:
    """MEASURE the activation/workspace footprint of ``loss_fn``'s train
    step instead of estimating it: compile ``value_and_grad(loss_fn)`` for
    the example shapes (``jax.ShapeDtypeStruct``s are enough — no data, no
    execution) and read XLA's own ``temp_size_in_bytes`` from the compiled
    memory analysis. Feed the result to :func:`plan_mesh(act_bytes=...)`.

    Returns None only when the backend reports no memory analysis; a broken
    ``loss_fn``/shape mismatch raises from trace/compile as usual (a silent
    None there would make the planner fall back to the analytic guess this
    function exists to replace, with no signal). The number is
    backend-specific (a CPU-compiled figure approximates the TPU one —
    fusion decisions differ), but a compiler-measured footprint beats the
    20-tensors-per-layer guess (VERDICT r2 weak #4)."""
    import jax

    compiled = jax.jit(jax.value_and_grad(loss_fn)).lower(*example_args).compile()
    try:
        stats = compiled.memory_analysis()
    except (NotImplementedError, AttributeError):
        return None
    if stats is None:
        return None
    return float(stats.temp_size_in_bytes)


@dataclasses.dataclass(frozen=True)
class AutoPlan:
    spec: MeshSpec
    reasons: tuple[str, ...]  # one line per decision, in decision order
    # suggested interleaved-virtual-stage factor (Megatron PTD-P): when the
    # plan has pp > 1, setting the model's ``pp_interleave`` to this shrinks
    # the pipeline bubble by the same factor; 1 when no pipeline (or no
    # divisible chunking exists)
    pp_interleave: int = 1
    # where the per-chip HBM number came from: "caller" (explicit
    # hbm_bytes=), "memory_stats" (measured), or "fallback" (the 16 GB
    # constant — trust the plan accordingly). The plan REPORT carries the
    # provenance, not just the audit-trail prose.
    hbm_source: str = "caller"


def _divisors_desc(n: int, limit: int) -> list[int]:
    return [d for d in range(min(n, limit), 0, -1) if n % d == 0]


def _device_hbm_bytes(device=None) -> tuple[float, str]:
    """Per-chip HBM from the hardware (``memory_stats()['bytes_limit']``),
    with an explicit fallback constant when the backend doesn't report one
    (CPU meshes, older runtimes). Returns (bytes, provenance) so the plan's
    audit trail records where the number came from. The fallback is never
    silent (VERDICT weak point): first use logs a warning, and every plan
    exports ``plan_hbm_bytes{source}`` so a dashboard (or the plan_mesh
    report) shows whether capacity math ran on a measurement or a guess."""
    global _warned_fallback
    nbytes, source = FALLBACK_HBM_BYTES, "fallback"
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
        limit = (stats or {}).get("bytes_limit")
        if limit:
            kind = getattr(device, "device_kind", "?")
            nbytes, source = float(limit), "memory_stats"
            detail = f"memory_stats of {kind}"
    except Exception:
        pass
    if source == "fallback":
        detail = (f"fallback constant {FALLBACK_HBM_BYTES/1e9:.0f} GB "
                  "(device reports no memory_stats)")
        if not _warned_fallback:
            _warned_fallback = True
            log.warning(
                "plan_mesh: device reports no memory_stats — capacity "
                "planning assumes %.0f GB/chip; pass hbm_bytes= (or run on "
                "a stats-reporting backend) for a measured plan",
                FALLBACK_HBM_BYTES / 1e9,
            )
    from dsml_tpu.obs import get_registry

    get_registry().gauge(
        "plan_hbm_bytes",
        "per-chip HBM the mesh planner used, by provenance "
        "(memory_stats = measured, fallback = the 16 GB constant)",
        labels=("source",),
    ).set(nbytes, source=source)
    return nbytes, detail


def plan_mesh(
    n_devices: int,
    n_params: int,
    n_head: int | None = None,
    seq_len: int = 0,
    d_model: int = 0,
    n_layer: int = 0,
    batch_per_device: int = 1,
    param_bytes: int = 2,
    hbm_bytes: float | None = None,
    hbm_budget: float = 0.6,
    act_bytes: float | None = None,
    device=None,
) -> AutoPlan:
    """Choose (pp, dp, fsdp, sp, tp) for ``n_devices`` chips.

    ``param_bytes`` — weight dtype width (2 = bf16). ``hbm_bytes`` — per-chip
    HBM; None (default) reads it from ``device`` (or the first local device)
    via ``memory_stats()``, falling back to 16 GB when the backend doesn't
    report one. ``hbm_budget`` — fraction of HBM the plan may assume for
    state + activations (the rest is XLA workspace/fragmentation).
    ``act_bytes`` — measured per-device activation footprint in bytes (e.g.
    from a profiled step); None uses the analytic ~20-tensors-per-layer
    estimate.

    Returns the spec plus human-readable reasons, so the decision is
    auditable rather than oracular.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    reasons: list[str] = []
    hbm_source = "caller"
    if hbm_bytes is None:
        hbm_bytes, hbm_src = _device_hbm_bytes(device)
        hbm_source = "fallback" if "fallback" in hbm_src else "memory_stats"
        reasons.append(f"per-chip HBM {hbm_bytes/1e9:.1f} GB ({hbm_src})")
    act_src = "caller-measured"
    if act_bytes is None:
        # a ledger-measured activation footprint (the trainer's
        # DSML_MEASURE_ACT wiring) beats the 20-tensors-per-layer guess
        # below — RESCALED to this plan's batch_per_device (a shrink
        # re-plan's per-device batch grows; the absolute number measured
        # at the old geometry would undersize the split), with provenance
        from dsml_tpu.obs.memory import get_memory_ledger

        ledger_act = get_memory_ledger().activation_bytes_for(batch_per_device)
        if ledger_act:
            act_bytes = ledger_act
            act_src = (f"ledger-measured, rescaled to "
                       f"batch_per_device={batch_per_device}")
    # disjoint pools so state + activations can never be double-promised
    # against the same bytes: 2/3 of the budget for training state, 1/3 for
    # activations
    budget = hbm_bytes * hbm_budget * 2 / 3
    act_budget = hbm_bytes * hbm_budget / 3
    # weights + grads at param dtype, adam m/v at f32
    state_bytes = n_params * (2 * param_bytes + 8)

    remaining = n_devices
    pp = 1
    tp = 1
    fsdp = 1
    sp = 1

    if state_bytes <= budget:
        reasons.append(
            f"training state {state_bytes/1e9:.2f} GB fits one chip's "
            f"{budget/1e9:.1f} GB budget → replicate (pure DP)"
        )
    else:
        need = -(-int(state_bytes) // int(budget))  # ceil shards needed
        if need <= remaining:
            # fsdp alone can fit the state: smallest divisor covering the
            # need, leaving the rest for dp (rule 2)
            fsdp = min(c for c in _divisors_desc(remaining, remaining) if c >= need)
            reasons.append(
                f"training state {state_bytes/1e9:.2f} GB > budget → fsdp={fsdp} "
                f"(per-chip shard {state_bytes/fsdp/1e9:.2f} GB)"
            )
            remaining //= fsdp
        else:
            # even fsdp over every chip can't fit a shard: the MODEL must
            # shard (rule 3). Whatever the split, pp×tp×fsdp covers the same
            # chips, so the choice is about communication structure, not
            # capacity: pp first (stage boundaries move only activations —
            # one ppermute per microbatch tick, the cheapest model-sharding
            # comm) with the SMALLEST stage count > 1 that divides the layer
            # stack; then tp (per-layer psums, bounded by head divisibility)
            # likewise smallest; fsdp (overlappable gather/scatter) carries
            # the rest
            if n_layer > 1:
                pp = min(
                    (c for c in _divisors_desc(remaining, remaining)
                     if c > 1 and n_layer % c == 0),
                    default=1,
                )
            if pp > 1:
                remaining //= pp
                reasons.append(
                    f"state needs {need} shards > {n_devices} chips → model "
                    f"sharding: pp={pp} ({n_layer // pp} layers/stage; stage "
                    "boundaries move activations only)"
                )
            if n_head:
                tp = min(
                    (c for c in _divisors_desc(remaining, n_head) if c > 1 and n_head % c == 0),
                    default=1,
                )
            if tp > 1:
                remaining //= tp
                reasons.append(
                    f"add tp={tp} (smallest head-divisible split; n_head={n_head})"
                    if pp > 1
                    else f"state needs {need} shards > {n_devices} chips → add tp={tp} "
                    f"(smallest head-divisible split; n_head={n_head})"
                )
            fsdp = remaining
            remaining = 1
            per_chip = state_bytes / fsdp / max(tp, 1) / max(pp, 1)
            reasons.append(
                f"fsdp={fsdp} over all remaining chips (best effort: per-chip "
                f"shard {per_chip/1e9:.2f} GB still exceeds "
                f"the budget — more chips or a smaller model needed)"
                if per_chip > budget
                else f"fsdp={fsdp} over all remaining chips"
            )

    # activations: per-device batch × seq × d × ~20 tensors/layer × layers,
    # unless the caller measured the real footprint
    if act_bytes is None and seq_len and d_model and n_layer:
        act_bytes = batch_per_device * seq_len * d_model * n_layer * 20 * param_bytes
    elif act_bytes is not None:
        reasons.append(f"activation footprint {act_bytes/1e9:.2f} GB ({act_src})")
    if act_bytes:
        if act_bytes > act_budget and remaining > 1:
            # smallest sufficient split — the rest stays with dp
            sp = min(
                (c for c in _divisors_desc(remaining, remaining) if act_bytes / c <= act_budget),
                default=remaining,
            )
            if sp > 1:
                remaining //= sp
                shard = act_bytes / sp
                reasons.append(
                    f"sequence activations {act_bytes/1e9:.2f} GB > "
                    f"{act_budget/1e9:.1f} GB activation budget → sp={sp} "
                    f"(ring attention shards the sequence)"
                    + (
                        f" — best effort: {shard/1e9:.2f} GB/chip still exceeds the "
                        "budget; more chips or remat needed"
                        if shard > act_budget
                        else ""
                    )
                )

    dp = remaining
    if dp > 1:
        reasons.append(f"remaining {dp} devices → dp={dp}")

    # interleaved virtual stages: with a pipeline, rank r holding v
    # non-contiguous chunks shrinks the bubble by v (Megatron PTD-P) at the
    # cost of v× boundary traffic — suggest the largest v ≤ 4 the layer
    # stack divides into
    interleave = 1
    if pp > 1 and n_layer:
        interleave = max(
            (v for v in (4, 3, 2) if n_layer % (pp * v) == 0), default=1
        )
        if interleave > 1:
            reasons.append(
                f"pp_interleave={interleave} ({interleave} virtual stage "
                f"chunks/rank shrink the pipeline bubble {interleave}×)"
            )

    spec = MeshSpec(pp=pp, dp=dp, fsdp=fsdp, sp=sp, tp=tp)
    total = pp * dp * fsdp * sp * tp
    if total != n_devices:
        raise AssertionError(f"planned {total} devices for {n_devices}")  # pragma: no cover
    return AutoPlan(spec=spec, reasons=tuple(reasons),
                    pp_interleave=interleave, hbm_source=hbm_source)
