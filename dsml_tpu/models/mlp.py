"""MNIST MLP — the reference's (only) model, as jitted XLA programs.

The reference implements a 784→128(ReLU)→10(softmax) MLP with hand-rolled
pure-Go loops on the client CPU (``DSML/client/client.go:36-202``: init,
forward, softmax/ReLU, cross-entropy backward, SGD at ``:254-267``). Its
README documents — but never shipped — a second 64-unit hidden layer and an
adaptive LR schedule (SURVEY.md §8.8). Here the architecture is configurable
(default is the documented 784-128-64-10) and everything — forward, backward,
SGD — is a jitted XLA program that runs on whatever device the params live on
(TPU MXU for the matmuls).

Also provides the flat-float32 parameter codec the wire protocol needs: the
reference client ships gradients/weights as one concatenated f32 buffer
(``client.go:60-74,619``), and the device runtime's ``RunForward`` /
``RunBackward`` use the same layout.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["MLP"]


class MLP:
    """Configurable fully-connected classifier with flat-param codecs."""

    def __init__(self, sizes: Sequence[int] = (784, 128, 64, 10), dtype=jnp.float32):
        if len(sizes) < 2:
            raise ValueError("MLP needs at least input and output sizes")
        self.sizes = tuple(int(s) for s in sizes)
        self.dtype = dtype
        # Flat layout: [W0, b0, W1, b1, ...] — same concatenation order as the
        # reference's gradient buffer (client.go:619: dW1,dB1,dW2,dB2).
        self._shapes: list[tuple[int, ...]] = []
        for fan_in, fan_out in zip(self.sizes[:-1], self.sizes[1:]):
            self._shapes.append((fan_in, fan_out))
            self._shapes.append((fan_out,))
        self.n_params = int(sum(np.prod(s) for s in self._shapes))

    # ---- params ---------------------------------------------------------------

    def init(self, rng: jax.Array | int = 0) -> dict:
        """He-initialized params (the reference scales by sqrt(2/fan_in) too,
        client.go:43-58)."""
        if isinstance(rng, int):
            rng = jax.random.PRNGKey(rng)
        params = {}
        keys = jax.random.split(rng, len(self.sizes) - 1)
        for i, (fan_in, fan_out) in enumerate(zip(self.sizes[:-1], self.sizes[1:])):
            params[f"w{i}"] = jax.random.normal(keys[i], (fan_in, fan_out), self.dtype) * jnp.sqrt(
                2.0 / fan_in
            )
            params[f"b{i}"] = jnp.zeros((fan_out,), self.dtype)
        return params

    def flatten(self, params: dict) -> jax.Array:
        leaves = []
        for i in range(len(self.sizes) - 1):
            leaves.append(params[f"w{i}"].reshape(-1))
            leaves.append(params[f"b{i}"].reshape(-1))
        return jnp.concatenate(leaves)

    def unflatten(self, flat: jax.Array) -> dict:
        params = {}
        offset = 0
        for i, _ in enumerate(range(len(self.sizes) - 1)):
            w_shape, b_shape = self._shapes[2 * i], self._shapes[2 * i + 1]
            w_n, b_n = int(np.prod(w_shape)), int(np.prod(b_shape))
            params[f"w{i}"] = flat[offset : offset + w_n].reshape(w_shape)
            offset += w_n
            params[f"b{i}"] = flat[offset : offset + b_n].reshape(b_shape)
            offset += b_n
        return params

    # ---- compute --------------------------------------------------------------

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        """Forward pass to logits. ReLU hidden layers (client.go:112-141)."""
        h = x
        n_layers = len(self.sizes) - 1
        for i in range(n_layers):
            h = h @ params[f"w{i}"] + params[f"b{i}"]
            if i < n_layers - 1:
                h = jax.nn.relu(h)
        return h

    def loss(self, params: dict, x: jax.Array, y: jax.Array) -> jax.Array:
        """Mean softmax cross-entropy (client.go:143-202's objective)."""
        logits = self.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    @functools.partial(jax.jit, static_argnums=0)
    def loss_and_grads(self, params: dict, x: jax.Array, y: jax.Array):
        return jax.value_and_grad(self.loss)(params, x, y)

    @functools.partial(jax.jit, static_argnums=0)
    def accuracy_count(self, params: dict, x: jax.Array, y: jax.Array) -> jax.Array:
        return jnp.sum(jnp.argmax(self.apply(params, x), axis=1) == y)

    # ---- flat-buffer compute (wire-protocol surface) --------------------------
    # Inputs/outputs as flat f32 device buffers; used by the device runtime's
    # RunForward/RunBackward RPCs.

    @functools.partial(jax.jit, static_argnums=0)
    def forward_flat(self, flat_params: jax.Array, x: jax.Array) -> jax.Array:
        return self.apply(self.unflatten(flat_params), x)

    @functools.partial(jax.jit, static_argnums=0)
    def backward_flat(self, flat_params: jax.Array, x: jax.Array, dlogits: jax.Array) -> jax.Array:
        """Param-gradient of <logits, dlogits> — i.e. backprop from an
        upstream logits-gradient, returned in the flat layout."""

        def scalar_fwd(fp):
            return jnp.vdot(self.apply(self.unflatten(fp), x), dlogits)

        return jax.grad(scalar_fwd)(flat_params)

    @functools.partial(jax.jit, static_argnums=0)
    def sgd_step(self, params: dict, grads: dict, lr: float) -> dict:
        return jax.tree.map(lambda p, g: p - lr * g, params, grads)
