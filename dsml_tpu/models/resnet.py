"""ResNet-18 for CIFAR-10 — BASELINE.json config #4.

CIFAR-style ResNet-18 (3x3 stem, no max-pool, 4 stages × 2 basic blocks,
[64, 128, 256, 512] channels). Normalization is batch-stat BatchNorm
evaluated in "train mode" at all times: statistics come from the current
batch, so the model stays a pure function of (params, batch) — no mutable
running-stat state to thread through jit/shard_map, and under data
parallelism each shard normalizes over its local batch (what sync-free BN
does on real multi-chip runs). Pair with the cosine LR schedule in
TrainConfig for the "ring AllReduce + adaptive LR scheduler" baseline row.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["ResNet18"]


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _batch_norm(x, scale, bias, eps=1e-5):
    mean = x.mean(axis=(0, 1, 2))
    var = x.var(axis=(0, 1, 2))
    return (x - mean) * lax.rsqrt(var + eps) * scale + bias


class ResNet18:
    STAGES = (64, 128, 256, 512)
    BLOCKS_PER_STAGE = 2

    def __init__(self, classes: int = 10, channels: int = 3):
        self.classes = classes
        self.channels = channels

    # ---- params ---------------------------------------------------------------

    def init(self, seed: int = 0) -> dict:
        from dsml_tpu.models.common import he_init

        rng = np.random.default_rng(seed)

        def he(*shape, fan_in):
            return he_init(rng, *shape, fan_in=fan_in)

        def bn(c):
            return {"scale": jnp.ones(c), "bias": jnp.zeros(c)}

        params = {
            "stem": {"w": he(3, 3, self.channels, 64, fan_in=9 * self.channels), "bn": bn(64)},
            "stages": [],
            "fc": {"w": he(512, self.classes, fan_in=512), "b": jnp.zeros(self.classes)},
        }
        in_c = 64
        for out_c in self.STAGES:
            blocks = []
            for b in range(self.BLOCKS_PER_STAGE):
                stride = 2 if (b == 0 and out_c != 64) else 1
                block = {
                    "conv1": {"w": he(3, 3, in_c, out_c, fan_in=9 * in_c), "bn": bn(out_c)},
                    "conv2": {"w": he(3, 3, out_c, out_c, fan_in=9 * out_c), "bn": bn(out_c)},
                }
                if stride != 1 or in_c != out_c:
                    block["down"] = {"w": he(1, 1, in_c, out_c, fan_in=in_c), "bn": bn(out_c)}
                blocks.append(block)
                in_c = out_c
            params["stages"].append(blocks)
        return params

    # ---- forward --------------------------------------------------------------

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        if x.ndim == 2:  # flat → NHWC (32x32x3 CIFAR)
            side = int(np.sqrt(x.shape[1] // self.channels))
            x = x.reshape(-1, side, side, self.channels)
        h = jax.nn.relu(_batch_norm(_conv(x, params["stem"]["w"]), **params["stem"]["bn"]))
        for s, blocks in enumerate(params["stages"]):
            for b, block in enumerate(blocks):
                stride = 2 if (b == 0 and s != 0) else 1
                r = jax.nn.relu(_batch_norm(_conv(h, block["conv1"]["w"], stride), **block["conv1"]["bn"]))
                r = _batch_norm(_conv(r, block["conv2"]["w"]), **block["conv2"]["bn"])
                shortcut = h
                if "down" in block:
                    shortcut = _batch_norm(_conv(h, block["down"]["w"], stride), **block["down"]["bn"])
                h = jax.nn.relu(r + shortcut)
        h = h.mean(axis=(1, 2))  # global average pool
        return h @ params["fc"]["w"] + params["fc"]["b"]

    def loss(self, params: dict, x: jax.Array, y: jax.Array) -> jax.Array:
        from dsml_tpu.models.common import softmax_xent

        return softmax_xent(self.apply(params, x), y)

    @functools.partial(jax.jit, static_argnums=0)
    def accuracy_count(self, params, x, y):
        from dsml_tpu.models.common import count_correct

        return count_correct(self.apply(params, x), y)
