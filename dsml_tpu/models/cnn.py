"""MNIST CNN (2×conv + 2×fc) — BASELINE.json config #3.

Same protocol as the other models (``init``/``apply``/``loss``) so the
data-parallel Trainer and the 8-device psum gradient sync drive it
unchanged. Convs lower to ``lax.conv_general_dilated`` in NHWC, which XLA
maps onto the MXU as implicit GEMMs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["CNN"]


class CNN:
    """conv3x3(32) → pool → conv3x3(64) → pool → fc(128) → fc(classes)."""

    def __init__(self, image_size: int = 28, channels: int = 1, classes: int = 10,
                 conv_features: tuple[int, int] = (32, 64), fc_width: int = 128):
        self.image_size = image_size
        self.channels = channels
        self.classes = classes
        self.conv_features = conv_features
        self.fc_width = fc_width
        self._flat = (image_size // 4) * (image_size // 4) * conv_features[1]

    def init(self, seed: int = 0) -> dict:
        from dsml_tpu.models.common import he_init

        rng = np.random.default_rng(seed)

        def he(*shape, fan_in):
            return he_init(rng, *shape, fan_in=fan_in)

        c1, c2 = self.conv_features
        return {
            "conv1": {"w": he(3, 3, self.channels, c1, fan_in=9 * self.channels), "b": jnp.zeros(c1)},
            "conv2": {"w": he(3, 3, c1, c2, fan_in=9 * c1), "b": jnp.zeros(c2)},
            "fc1": {"w": he(self._flat, self.fc_width, fan_in=self._flat), "b": jnp.zeros(self.fc_width)},
            "fc2": {"w": he(self.fc_width, self.classes, fan_in=self.fc_width), "b": jnp.zeros(self.classes)},
        }

    def apply(self, params: dict, x: jax.Array) -> jax.Array:
        if x.ndim == 2:  # flat pixels → NHWC
            x = x.reshape(-1, self.image_size, self.image_size, self.channels)

        def conv(p, t):
            return lax.conv_general_dilated(
                t, p["w"], window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + p["b"]

        def pool(t):
            return lax.reduce_window(t, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")

        h = pool(jax.nn.relu(conv(params["conv1"], x)))
        h = pool(jax.nn.relu(conv(params["conv2"], h)))
        h = h.reshape(h.shape[0], -1)
        h = jax.nn.relu(h @ params["fc1"]["w"] + params["fc1"]["b"])
        return h @ params["fc2"]["w"] + params["fc2"]["b"]

    def loss(self, params: dict, x: jax.Array, y: jax.Array) -> jax.Array:
        from dsml_tpu.models.common import softmax_xent

        return softmax_xent(self.apply(params, x), y)

    @functools.partial(jax.jit, static_argnums=0)
    def accuracy_count(self, params, x, y):
        from dsml_tpu.models.common import count_correct

        return count_correct(self.apply(params, x), y)
