"""Model families: MLP (MNIST), CNN, ResNet-18 (CIFAR-10), GPT-2, Llama."""

from dsml_tpu.models.mlp import MLP  # noqa: F401
