"""Model families: MLP (MNIST), CNN, ResNet-18 (CIFAR-10), GPT-2, Llama."""

from dsml_tpu.models.mlp import MLP  # noqa: F401


def model_by_family(family: str, name: str, **tiny_kwargs):
    """(model, config) for a family + preset — the ONE dispatch point the
    CLI examples share (``--family gpt2|llama``). ``tiny_kwargs`` reach only
    the ``tiny`` preset (each family's ``by_name`` enforces that)."""
    if family == "llama":
        from dsml_tpu.models.llama import Llama, LlamaConfig

        cfg = LlamaConfig.by_name(name, **tiny_kwargs)
        return Llama(cfg), cfg
    if family == "gpt2":
        from dsml_tpu.models.gpt2 import GPT2, GPT2Config

        cfg = GPT2Config.by_name(name, **tiny_kwargs)
        return GPT2(cfg), cfg
    raise ValueError(f"unknown family {family!r}; choose gpt2 | llama")
