"""Shared model utilities: initializers, classification losses, and the
FSDP spec transform every family's ``param_specs`` routes through."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["he_init", "softmax_xent", "count_correct", "with_fsdp", "fsdp_spec_fn"]


def with_fsdp(spec, shape: tuple, fsdp: int, axis: str = "fsdp"):
    """Add ``axis`` to ``spec`` on the first UNSHARDED dim of ``shape`` that
    divides by ``fsdp`` (the ZeRO-3 rule ``parallel.fsdp.fsdp_shardings``
    applies to NamedShardings, here at the PartitionSpec level so it composes
    with TP/PP inside one spec). Leaves with no divisible free dim stay as
    given (replicated over fsdp) — small norms/biases, where sharding buys
    nothing. ``shape`` is the GLOBAL (unstacked) leaf shape; callers state it
    analytically next to the spec, and the placement itself verifies it:
    ``device_put``/``shard_map`` reject indivisible dims, so a drifted shape
    can't silently mis-shard."""
    from jax.sharding import PartitionSpec as P

    if fsdp <= 1:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, n) in enumerate(zip(parts, shape)):
        if p is None and n % fsdp == 0 and n >= fsdp:
            parts[i] = axis
            return P(*parts)
    return spec


def fsdp_spec_fn(fsdp: int, axis: str = "fsdp"):
    """``F(spec, *shape)`` adapter over :func:`with_fsdp` — the one-liner
    every ``param_specs`` implementation binds, kept here so the call shape
    can't drift between model families."""
    return lambda spec, *shape: with_fsdp(spec, shape, fsdp, axis)


def he_init(rng: np.random.Generator, *shape: int, fan_in: int) -> jax.Array:
    """He-normal initialization (scale sqrt(2/fan_in)), float32."""
    return jnp.asarray(rng.standard_normal(shape) * np.sqrt(2.0 / fan_in), jnp.float32)


def softmax_xent(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def count_correct(logits: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.sum(jnp.argmax(logits, axis=-1) == y)
