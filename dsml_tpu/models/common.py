"""Shared model utilities: initializers and classification losses."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["he_init", "softmax_xent", "count_correct"]


def he_init(rng: np.random.Generator, *shape: int, fan_in: int) -> jax.Array:
    """He-normal initialization (scale sqrt(2/fan_in)), float32."""
    return jnp.asarray(rng.standard_normal(shape) * np.sqrt(2.0 / fan_in), jnp.float32)


def softmax_xent(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def count_correct(logits: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.sum(jnp.argmax(logits, axis=-1) == y)
