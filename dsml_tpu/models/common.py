"""Shared model utilities: initializers, classification losses, the
FSDP spec transform every family's ``param_specs`` routes through, and
weight-only int8 quantization for the serving path."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "he_init", "softmax_xent", "count_correct", "with_fsdp", "fsdp_spec_fn",
    "quantize_weights_int8", "quantize_weights_blocked", "maybe_dequant",
    "qmatmul", "transformer_train_flops", "mlp_train_flops",
]


def transformer_train_flops(cfg, n_tokens: int, seq: int,
                            gated_mlp: bool = False) -> int:
    """Analytic matmul FLOPs for ONE training step over ``n_tokens`` tokens
    at sequence length ``seq`` — the PaLM-appendix accounting (fwd matmuls
    + causal attention term; bwd = 2×fwd; remat recompute NOT counted).
    This is the single FLOP numerator behind every MFU the bench and
    ``obs.step_stats`` report, kept here so model families cannot drift
    apart in their accounting.

    ``cfg`` needs ``n_layer / n_head / d_model / d_ff / vocab_size``;
    GQA shrinks the k/v projections via ``n_kv_head`` when present.
    ``gated_mlp=True`` counts the 3-matmul SwiGLU form (Llama), else the
    2-matmul in/out form (GPT-2)."""
    T = int(n_tokens)
    d, ff, L, V = cfg.d_model, cfg.d_ff, cfg.n_layer, cfg.vocab_size
    kv_frac = getattr(cfg, "n_kv_head", cfg.n_head) / cfg.n_head
    mlp_mats = 3 if gated_mlp else 2
    fwd = L * (
        2 * T * d * d                       # q projection
        + int(2 * 2 * T * d * d * kv_frac)  # k and v projections (GQA-shrunk)
        + 2 * T * d * d                     # attention output projection
        + 2 * 2 * T * seq * d // 2          # q·kᵀ and p·v, causal halves the area
        + mlp_mats * 2 * T * d * ff         # MLP matmuls
    ) + 2 * T * d * V                       # unembedding
    return 3 * fwd


def mlp_train_flops(n_params: int, n_samples: int) -> int:
    """The dense-MLP rule the reference baseline is scored by: 6 FLOPs per
    parameter per sample (fwd 2 + bwd 4)."""
    return 6 * int(n_params) * int(n_samples)

# transformer-block matmul weights both families contract on AXIS 0 —
# the per-output-channel absmax scale is therefore max|w| over axis 0
# (GPT-2: fused wqkv [d, 3, d] keeps a scale per (qkv-slot, channel))
_WQ_KEYS = frozenset({
    "wqkv", "wo", "wq", "wk", "wv",           # attention projections
    "w_in", "w_out", "w_gate", "w_up", "w_down",  # dense MLP
})


def quantize_weights_int8(params: dict) -> dict:
    """Weight-only int8 (w8a16) for SERVING: every transformer-block
    attention/MLP matmul weight becomes ``{"qw": int8, "qs": f32 scale}``
    with per-output-channel absmax scales; embeddings, the unembedding,
    norms, biases, and MoE experts stay full precision (MoE contracts on
    a middle axis and the gate is routing-sensitive — out of scope).

    Decode is weight-HBM-bandwidth-bound, so halving weight bytes vs bf16
    (4x vs f32) raises decode tokens/s; the int8→float convert + scale
    feed the dot operand, which XLA fuses into the matmul read — no
    dequantized weight copy is ever materialized in HBM. Quantized params
    serve the single-device decode surfaces (``generate``, the continuous
    batcher, speculative decode); the TP/shard_map paths expect plain
    leaves matching ``param_specs`` and are not supported."""

    def quant_layer(layer: dict) -> dict:
        out = {}
        for group, leaves in layer.items():
            if group in ("attn", "mlp") and isinstance(leaves, dict):
                out[group] = {
                    k: _quant_leaf(v) if k in _WQ_KEYS else v
                    for k, v in leaves.items()
                }
            else:
                out[group] = leaves
        return out

    def _quant_leaf(w: jax.Array) -> dict:
        a = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0, keepdims=True)
        qs = jnp.where(a > 0, a / 127.0, 1.0)
        qw = jnp.round(w.astype(jnp.float32) / qs).astype(jnp.int8)
        return {"qw": qw, "qs": qs.astype(jnp.float32)}

    return {
        k: ([quant_layer(l) for l in v] if k == "layers" else v)
        for k, v in params.items()
    }


def quantize_weights_blocked(params: dict, scheme: str = "int8",
                             block: int | None = None) -> dict:
    """Serving weight quantization for the DEQUANT-FUSED kernel path: the
    same leaf selection as :func:`quantize_weights_int8`, but each matmul
    weight becomes an ``ops.quantization.QuantizedWeight`` — nibble-packed
    int4 or int8 codes with one f32 scale per (k-block, output channel) —
    consumed by :func:`qmatmul`, which runs the Pallas dequant-fused
    matmul (``quantized_matmul``) instead of letting XLA expand the
    weight. HBM holds the weights at ~4× (int8) / ~8× (int4) under f32
    and the full-width form only ever exists one VMEM tile at a time.
    Same scope limits: single-device serving surfaces only (TP shard_map
    paths expect plain leaves matching ``param_specs``)."""
    from dsml_tpu.ops.quantization import quantize_weight_blocks

    def quant_layer(layer: dict) -> dict:
        out = {}
        for group, leaves in layer.items():
            if group in ("attn", "mlp") and isinstance(leaves, dict):
                out[group] = {
                    k: (quantize_weight_blocks(v, scheme, block)
                        if k in _WQ_KEYS else v)
                    for k, v in leaves.items()
                }
            else:
                out[group] = leaves
        return out

    return {
        k: ([quant_layer(l) for l in v] if k == "layers" else v)
        for k, v in params.items()
    }


def maybe_dequant(w, dtype=None):
    """Matmul-site hook for weight-only int8: plain arrays pass through;
    ``{"qw", "qs"}`` leaves dequantize into the requested dtype (default
    f32) right at the dot operand, where XLA fuses the convert+scale into
    the read instead of materializing a full-width copy."""
    if isinstance(w, dict) and "qw" in w:
        dt = dtype or jnp.float32
        return w["qw"].astype(dt) * w["qs"].astype(dt)
    return w


def qmatmul(x, w, dtype=None):
    """THE matmul-site dispatcher for every weight codec the serving path
    carries: plain arrays and per-channel ``{"qw","qs"}`` dicts keep their
    exact pre-existing lowering (``@`` / einsum on ``maybe_dequant`` — the
    w8a16 fast path), while block-quantized ``QuantizedWeight`` leaves
    route to the Pallas dequant-fused matmul, contracting ``x``'s last
    axis against the weight's first and restoring the weight's trailing
    axes (GPT-2's fused ``wqkv [d, 3, d]`` comes back ``[..., 3, d]``, so
    the einsum call site needs no special casing)."""
    from dsml_tpu.ops.quantization import QuantizedWeight, quantized_matmul

    if isinstance(w, QuantizedWeight):
        lead = x.shape[:-1]
        out = quantized_matmul(x.reshape(-1, x.shape[-1]), w)
        return out.reshape(*lead, *w.shape[1:]).astype(dtype or x.dtype)
    w = maybe_dequant(w, dtype)
    if w.ndim == 3:
        # the fused-QKV form: [b, s, d] · [d, slots, d] — kept as the
        # einsum the site always compiled to
        return jnp.einsum("bsd,dke->bske", x, w)
    return x @ w


def with_fsdp(spec, shape: tuple, fsdp: int, axis: str = "fsdp"):
    """Add ``axis`` to ``spec`` on the first UNSHARDED dim of ``shape`` that
    divides by ``fsdp`` (the ZeRO-3 rule ``parallel.fsdp.fsdp_shardings``
    applies to NamedShardings, here at the PartitionSpec level so it composes
    with TP/PP inside one spec). Leaves with no divisible free dim stay as
    given (replicated over fsdp) — small norms/biases, where sharding buys
    nothing. ``shape`` is the GLOBAL (unstacked) leaf shape; callers state it
    analytically next to the spec, and the placement itself verifies it:
    ``device_put``/``shard_map`` reject indivisible dims, so a drifted shape
    can't silently mis-shard."""
    from jax.sharding import PartitionSpec as P

    if fsdp <= 1:
        return spec
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, n) in enumerate(zip(parts, shape)):
        if p is None and n % fsdp == 0 and n >= fsdp:
            parts[i] = axis
            return P(*parts)
    return spec


def fsdp_spec_fn(fsdp: int, axis: str = "fsdp"):
    """``F(spec, *shape)`` adapter over :func:`with_fsdp` — the one-liner
    every ``param_specs`` implementation binds, kept here so the call shape
    can't drift between model families."""
    return lambda spec, *shape: with_fsdp(spec, shape, fsdp, axis)


def he_init(rng: np.random.Generator, *shape: int, fan_in: int) -> jax.Array:
    """He-normal initialization (scale sqrt(2/fan_in)), float32."""
    return jnp.asarray(rng.standard_normal(shape) * np.sqrt(2.0 / fan_in), jnp.float32)


def softmax_xent(logits: jax.Array, y: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer labels."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))


def count_correct(logits: jax.Array, y: jax.Array) -> jax.Array:
    return jnp.sum(jnp.argmax(logits, axis=-1) == y)
