"""Speculative decoding with prompt-lookup (n-gram) drafting — TPU-first.

Greedy KV-cache decode emits one token per model call; each call is
memory-bound (the whole model streams from HBM per token). Speculative
decoding scores a WINDOW of C candidate tokens in one call
(``model.verify_step`` — multi-query decode, the same machinery as
chunked prefill) and accepts the longest prefix that matches the model's
own greedy choices, so one HBM sweep can yield up to C tokens. The draft
comes from prompt lookup (n-gram matching against the already-seen
tokens — Saxena's "prompt lookup decoding", the vLLM ngram speculator):
no draft model, free proposals, large wins exactly where decode is
longest (summarization/code/chat with reuse of earlier spans).

The ENTIRE decode loop — n-gram lookup, draft gather, verify, accept,
cache/history update — runs inside ONE jitted ``lax.while_loop``: static
shapes throughout, zero host round trips per token (on a tunneled chip a
host-looped speculator would pay ~100 ms per step and lose everything it
won). Guaranteed progress ≥ 1 token per iteration, so the loop is bounded
by ``max_new_tokens`` iterations.

Token-level guarantee: greedy speculative output is IDENTICAL to plain
greedy ``generate`` (tests pin it). Acceptance only changes how many
model calls it takes, never what tokens come out:

- verify feeds [last_accepted, d_1..d_{C-1}] at positions p..p+C-1;
- g_i = argmax(logits[i]) is the greedy continuation after consuming
  token i of that window; d_{i+1} is accepted iff it equals g_i and all
  earlier drafts were accepted; the first non-matching position emits
  g_acc (the model's own token), exactly what step-by-step greedy decode
  would have produced.

Rejected drafts leave garbage K/V rows beyond the accepted prefix; the
next verify window starts at the first garbage row and is at least as
long, so garbage is always overwritten before any query can attend to it
(``verify_step`` docstring carries the full argument).

Reference: the upstream has no inference path at all (SURVEY.md §5);
this module is beyond-reference serving capability on top of the
framework's decode stack, model-generic (GPT-2 and Llama share
``verify_step`` through ``_decode_core``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["generate_speculative", "lookup_draft_host", "lookup_draft_batch"]


def lookup_draft_host(history: np.ndarray, n: int, k: int) -> np.ndarray:
    """Prompt-lookup draft, HOST side (numpy): the ``k`` tokens that
    followed the MOST RECENT prior occurrence of ``history``'s trailing
    n-gram; repeats the last token when no match exists (acceptance then
    falls to the guaranteed +1-token/tick floor — wrong drafts only cost
    speed, never tokens). THE one host drafting rule: the continuous
    batcher's speculative tick drafts through here, and
    :func:`lookup_draft_batch` is the same rule device-side (the
    in-``lax.while_loop`` speculator) — equivalence pinned in tests."""
    history = np.asarray(history)
    length = len(history)
    n = min(n, length)
    gram = history[length - n:]
    win = np.lib.stride_tricks.sliding_window_view(history, n)  # [L-n+1, n]
    # exclude only the trailing gram itself (windows ending before the last
    # position; overlap with the gram region is allowed) — the same rule as
    # the device-side lookup (j + n - 1 < pos)
    matches = np.flatnonzero(np.all(win[: length - n] == gram, axis=1))
    if len(matches) == 0:
        return np.full(k, history[-1], np.int32)
    best = int(matches[-1])
    src = history[best + n : best + n + k].astype(np.int32)
    if len(src) < k:  # match near the end: pad with last-token repeats
        src = np.concatenate([src, np.full(k - len(src), history[-1], np.int32)])
    return src


def lookup_draft_batch(hbuf: jax.Array, pos: jax.Array, window: int,
                      ngram: int) -> jax.Array:
    """Prompt-lookup draft, DEVICE side (traceable): for each row of
    ``hbuf`` [b, max_seq] whose last accepted token sits at ``pos[b]``,
    the ``window - 1`` tokens that followed the most recent match of the
    trailing ``ngram``-gram strictly inside accepted history
    (``j + ngram - 1 < pos``); no match → repeat the last token. Static
    ``ngram`` unrolls into shifted equalities — no gather, no sort.
    Shared by the jitted speculative ``while_loop`` and (via vmap in
    tests) pinned equivalent to :func:`lookup_draft_host`."""
    b, max_seq = hbuf.shape
    n, c = ngram, window
    jidx = jnp.arange(max_seq - n + 1, dtype=jnp.int32)
    # gram[b] = hbuf[b, pos-n+1 .. pos]
    gram = jax.vmap(
        lambda h, p: lax.dynamic_slice_in_dim(h, p - (n - 1), n)
    )(hbuf, pos)  # [b, n]
    match = jnp.ones((b, max_seq - n + 1), bool)
    for i in range(n):  # static n (2-3): unrolled shifted equality
        match &= hbuf[:, i : max_seq - n + 1 + i] == gram[:, i : i + 1]
    # window must end strictly inside accepted history (j+n-1 < pos)
    legal = jidx[None, :] <= pos[:, None] - n
    best = jnp.max(jnp.where(match & legal, jidx[None, :], -1), axis=1)  # [b]
    found = best >= 0
    src = best[:, None] + n + jnp.arange(c - 1, dtype=jnp.int32)[None, :]
    # a match near the end runs out of followers: read the LAST ACCEPTED
    # token instead of whatever sits past pos in the buffer (unfilled or
    # stale rows) — the host rule's pad-with-last, and a strictly better
    # draft than garbage (wrong drafts only cost speed, never tokens)
    src = jnp.where(src <= pos[:, None], src, pos[:, None])
    draft = jnp.take_along_axis(hbuf, src, axis=1)
    return jnp.where(found[:, None], draft, gram[:, -1:])  # [b, C-1]


def _build_speculative_fn(model, prompt_len: int, max_new: int, window: int, ngram: int):
    """The jitted speculative decode program for static shapes
    (prompt_len, max_new, window=C, ngram=n). Returns
    ``run(params, prompt) -> (tokens [b, max_new], n_calls [])``."""
    cfg = model.config
    max_seq = cfg.max_seq
    c = window  # tokens scored per verify call (1 real + C-1 drafts)
    n = ngram

    def run(params, prompt):
        b, t = prompt.shape
        # history buffer: prompt now, emitted tokens appended as they are
        # ACCEPTED — positions <= pos[b] always hold real tokens, and the
        # final output is simply hbuf[:, t : t + max_new]
        hbuf = jnp.zeros((b, max_seq), jnp.int32).at[:, :t].set(prompt)

        # prefill the prompt (logits at t-1 give the first greedy token)
        logits0, cache = model.prefill(params, prompt, last_index=t - 1)
        first = jnp.argmax(logits0, axis=-1).astype(jnp.int32)  # [b]
        hbuf = hbuf.at[:, t].set(first)
        pos = jnp.full((b,), t, jnp.int32)  # position of last accepted token
        n_gen = jnp.ones((b,), jnp.int32)

        def body(state):
            hbuf, cache, pos, n_gen, calls = state
            draft = lookup_draft_batch(hbuf, pos, c, n)
            last = jnp.take_along_axis(hbuf, pos[:, None], axis=1)  # [b, 1]
            window_toks = jnp.concatenate([last, draft], axis=1)  # [b, C]
            logits, cache = model.verify_step(params, cache, window_toks, pos)
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [b, C]
            # accepted = longest prefix of drafts matching the greedy chain
            matches = draft == g[:, : c - 1]
            acc = jnp.sum(jnp.cumprod(matches.astype(jnp.int32), axis=1), axis=1)  # [b]
            # emit vector: accepted drafts then the model's own next token
            i_idx = jnp.arange(c, dtype=jnp.int32)[None, :]
            vshift = jnp.concatenate(
                [draft, jnp.zeros((b, 1), jnp.int32)], axis=1
            )  # v[:, i+1] for i in 0..C-1 (junk at i = C-1 when acc = C-1)
            g_at_acc = jnp.take_along_axis(g, acc[:, None], axis=1)  # [b, 1]
            emit = jnp.where(
                i_idx < acc[:, None], vshift,
                jnp.where(i_idx == acc[:, None], g_at_acc, 0),
            )  # [b, C]
            # rows that already hit max_new freeze (their writes land beyond
            # the output region and their pos stops advancing)
            adv = jnp.minimum(acc + 1, jnp.maximum(max_new - n_gen, 0))
            hbuf = jax.vmap(
                lambda h, e, p: lax.dynamic_update_slice_in_dim(h, e, p + 1, axis=0)
            )(hbuf, emit, pos)
            return hbuf, cache, pos + adv, n_gen + adv, calls + 1

        def cond(state):
            return jnp.min(state[3]) < max_new

        hbuf, cache, pos, n_gen, calls = lax.while_loop(
            cond, body, (hbuf, cache, pos, n_gen, jnp.zeros((), jnp.int32))
        )
        return lax.dynamic_slice_in_dim(hbuf, t, max_new, axis=1), calls

    return run


def generate_speculative(
    model,
    params: dict,
    prompt: jax.Array,  # [b, t] int32
    max_new_tokens: int,
    window: int = 8,
    ngram: int = 2,
    return_calls: bool = False,
):
    """Greedy decode via prompt-lookup speculative decoding — tokens
    identical to ``model.generate(..., temperature=0)``, in fewer model
    calls whenever generated text revisits earlier spans.

    ``window`` — tokens scored per verify call (1 committed + window−1
    drafted); ``ngram`` — match length for the prompt lookup (2-3).
    ``return_calls=True`` also returns the number of verify iterations
    (the speedup diagnostic: plain greedy decode would be
    ``max_new_tokens`` calls).

    Requires ``t >= ngram`` and ``t + max_new_tokens + window <= max_seq``
    (the verify window of a just-finishing row must stay inside the
    cache)."""
    t = prompt.shape[1]
    model._check_generate_args(t, max_new_tokens, 0.0, 0, 0.0)
    if window < 2:
        raise ValueError(f"window must be >= 2 (1 real + >=1 draft), got {window}")
    if ngram < 1 or t < ngram:
        raise ValueError(f"need prompt_len ({t}) >= ngram ({ngram}) >= 1")
    if t + max_new_tokens + window > model.config.max_seq:
        raise ValueError(
            f"prompt ({t}) + max_new ({max_new_tokens}) + window ({window}) "
            f"must fit max_seq={model.config.max_seq} (the final verify "
            "window writes cache rows past the last emitted token)"
        )
    key = ("spec", t, max_new_tokens, window, ngram)
    cache = model._gen_cache_dict()
    run = cache.get(key)
    if run is None:
        run = jax.jit(_build_speculative_fn(model, t, max_new_tokens, window, ngram))
        cache[key] = run
    tokens, calls = run(params, prompt.astype(jnp.int32))
    if return_calls:
        return tokens, int(calls)
    return tokens
