"""GPT-2 — the flagship transformer, designed as an SPMD mesh program.

BASELINE.md's top config is "TinyStories GPT-2-small (125M), data-parallel +
grad accumulation"; the reference itself never got past an MLP (SURVEY.md
§2.3), with TP/SP/hybrid existing only in its literature corpus (Megatron
PTD-P, Ring Self-Attention, LoongTrain 2D attention). This module implements
that roadmap TPU-first:

- **TP** (Megatron-style): QKV/MLP-in weights column-sharded, out-projections
  row-sharded over the ``tp`` axis, ONE ``psum`` per attention block and one
  per MLP block; the unembedding is vocab-sharded with a
  distributed-logsumexp cross-entropy so full logits never materialize.
- **SP/CP**: the sequence axis is sharded over ``sp`` (legacy XLA ring /
  Ulysses) or the ``cp`` context-parallel axis (``attn_impl="ring2"``: the
  bidirectional flash ring with causal hop skipping and a KV re-streaming
  backward, ``ops.ring_attention``) — the model is axis-name-generic, the
  hybrid step passes whichever axis the mesh sizes; LoongTrain's 2D
  head×context grid is exactly ``tp × sp`` here.
- **DP**: batch axis sharded over ``dp``; gradients ``psum`` over (dp, sp).
- **EP (MoE)**: optionally the MLP is a top-k-gated expert layer with experts
  sharded over ``tp`` and token dispatch via ``all_to_all``.

Everything below is shape-static, scan-free Python-loop-over-layers (unrolled
by trace), bf16-friendly, and runs under ``jax.shard_map`` on the framework
mesh (``dsml_tpu.parallel.mesh``). ``apply``/``loss`` (no axis names) give
the plain single-device semantics used for parity tests.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dsml_tpu.models.common import fsdp_spec_fn, maybe_dequant, qmatmul
from dsml_tpu.ops.attention import _NEG_INF, attention, ring_attention, ulysses_attention

__all__ = ["GPT2Config", "GPT2"]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dtype: str = "float32"  # params/activations dtype ("bfloat16" for TPU runs)
    # MoE: 0 experts = dense MLP; otherwise top-k gated expert layer
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    # rematerialization: recompute each block's activations in the backward
    # pass instead of storing them — trades FLOPs for HBM (the memory-
    # efficiency capability of the reference's §7 literature, ActNN/GACT).
    # True = plain jax.checkpoint (full-precision input stash); "int8" =
    # compressed remat (ops.quantization.compressed_checkpoint): the stash is
    # blockwise-int8, 4x smaller again, gradients exact in expectation
    remat: bool | str = False
    # unsharded-vocab losses stream the unembedding in chunks of this many
    # rows (ops/xent.py) instead of materializing [tokens, vocab] logits;
    # only kicks in when vocab_size > xent_chunk (0 disables)
    xent_chunk: int = 8192
    # interleaved virtual pipeline stages (Megatron PTD-P): each pp rank
    # holds this many non-contiguous layer chunks; >1 shrinks the pipeline
    # bubble by the same factor (parallel.pp.pipeline_apply_interleaved).
    # Requires n_layer divisible by pp×pp_interleave; gpipe schedule only
    pp_interleave: int = 1
    # serving: store the KV cache int8 with a per-(b, h, position) scale —
    # ~4x below the f32 cache / 2x below bf16 in both HBM footprint and
    # decode read bandwidth (the cache read IS the decode bottleneck at
    # long context). Dequantized at the attention boundary; prefill/decode/
    # decode_step_slots and both model families share the one code path
    kv_quant: bool | str = False  # False | True/"int8" | "int4"

    @staticmethod
    def small() -> "GPT2Config":
        """GPT-2-small, 125M params (the BASELINE config)."""
        return GPT2Config()

    @staticmethod
    def medium() -> "GPT2Config":
        """GPT-2-medium, 350M params."""
        return GPT2Config(n_layer=24, n_head=16, d_model=1024, d_ff=4096)

    @staticmethod
    def large() -> "GPT2Config":
        """GPT-2-large, 774M params."""
        return GPT2Config(n_layer=36, n_head=20, d_model=1280, d_ff=5120)

    @staticmethod
    def xl() -> "GPT2Config":
        """GPT-2-XL, 1.5B params."""
        return GPT2Config(n_layer=48, n_head=25, d_model=1600, d_ff=6400)

    @classmethod
    def by_name(cls, name: str, **tiny_kwargs) -> "GPT2Config":
        """Preset lookup over the EXPLICIT family ({tiny, small, medium,
        large, xl}) — a raw getattr would accept any class attribute and
        fail obscurely."""
        presets = {"tiny": cls.tiny, "small": cls.small, "medium": cls.medium,
                   "large": cls.large, "xl": cls.xl}
        if name not in presets:
            raise ValueError(f"unknown GPT-2 preset {name!r}; choose from {sorted(presets)}")
        return presets[name](**tiny_kwargs) if name == "tiny" else presets[name]()

    @staticmethod
    def tiny(vocab_size: int = 512, n_experts: int = 0) -> "GPT2Config":
        """Test-sized config that still exercises every code path."""
        return GPT2Config(
            vocab_size=vocab_size, max_seq=128, n_layer=2, n_head=8, d_model=64, d_ff=128,
            n_experts=n_experts,
        )


def sample_token_logits(logits, key, temperature: float, top_k: int = 0,
                        top_p: float = 0.0):
    """Sample next-token ids from ``logits`` [..., vocab] — greedy at
    ``temperature <= 0``, else softmax sampling optionally truncated to the
    ``top_k`` most likely tokens and/or the nucleus holding ``top_p``
    probability mass. THE one sampler shared by ``generate``/
    ``generate_spmd`` and the continuous batcher (host and in-scan paths),
    so the truncation semantics cannot drift between serving surfaces.
    Pure in (logits, key): callers own the key discipline."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p > 0.0:
        # nucleus: keep the smallest prefix (by descending prob) whose mass
        # reaches top_p; always keep the argmax
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # cutoff logit: last sorted position with cum - p < top_p
        keep = (cum - probs) < top_p  # mass BEFORE this token < p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


class GPT2:
    """Decoder-only transformer with mesh-aware sharding rules."""

    def __init__(self, config: GPT2Config | None = None):
        self.config = config or GPT2Config.small()
        self._kv_mode()  # a bad kv_quant string fails at construction

    # ---- params ---------------------------------------------------------------

    def init(self, seed: int = 0) -> dict:
        cfg = self.config
        rng = np.random.default_rng(seed)
        dt = jnp.dtype(cfg.dtype)

        def normal(*shape, std=0.02):
            return jnp.asarray(rng.standard_normal(shape) * std, dt)

        def zeros(*shape):
            return jnp.zeros(shape, dt)

        # GPT-2 scales residual-path projections by 1/sqrt(2*n_layer)
        res_std = 0.02 / math.sqrt(2 * cfg.n_layer)
        params = {
            "wte": normal(cfg.vocab_size, cfg.d_model),
            "wpe": normal(cfg.max_seq, cfg.d_model, std=0.01),
            "ln_f": {"scale": jnp.ones(cfg.d_model, dt), "bias": zeros(cfg.d_model)},
            "layers": [],
        }
        for _ in range(cfg.n_layer):
            layer = {
                "ln_1": {"scale": jnp.ones(cfg.d_model, dt), "bias": zeros(cfg.d_model)},
                "ln_2": {"scale": jnp.ones(cfg.d_model, dt), "bias": zeros(cfg.d_model)},
                # wqkv is [d, 3, d] with the LAST dim TP-sharded: a contiguous
                # column shard of a fused [d, 3d] matrix would hand each rank
                # a mix of q/k/v columns and scramble the head assignment
                "attn": {
                    "wqkv": normal(cfg.d_model, 3, cfg.d_model),
                    "bqkv": zeros(3, cfg.d_model),
                    "wo": normal(cfg.d_model, cfg.d_model, std=res_std),
                    "bo": zeros(cfg.d_model),
                },
            }
            if cfg.n_experts:
                layer["moe"] = self._moe_param_init(normal, res_std)
            else:
                layer["mlp"] = {
                    "w_in": normal(cfg.d_model, cfg.d_ff),
                    "b_in": zeros(cfg.d_ff),
                    "w_out": normal(cfg.d_ff, cfg.d_model, std=res_std),
                    "b_out": zeros(cfg.d_model),
                }
            params["layers"].append(layer)
        return params

    def n_params(self, params) -> int:
        return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))

    # ---- sharding rules (GSPMD specs over the framework mesh axes) -------------

    def param_specs(self, pp: bool = False, fsdp: int = 1) -> dict:
        """PartitionSpec pytree: Megatron TP sharding over 'tp', everything
        else replicated (dp/sp replicate params). With ``pp=True`` the layer
        list is expected STACKED (leading layer axis,
        ``parallel.pp.stack_layer_params``) and sharded over the 'pp' axis so
        each rank holds its pipeline stage. With ``fsdp > 1`` every leaf is
        additionally ZeRO-sharded over the 'fsdp' axis on its first free
        divisible dim (``models.common.with_fsdp``); the hybrid step gathers
        weights just-in-time and reduce-scatters gradients
        (``parallel.hybrid``), so fsdp composes with tp/pp/sp in one mesh."""
        from jax.sharding import PartitionSpec as P

        cfg = self.config
        d, ff, V, S = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.max_seq
        F = fsdp_spec_fn(fsdp)
        layer_spec = {
            "ln_1": {"scale": F(P(), d), "bias": F(P(), d)},
            "ln_2": {"scale": F(P(), d), "bias": F(P(), d)},
            "attn": {
                # column-parallel (heads split); fsdp takes the input dim
                "wqkv": F(P(None, None, "tp"), d, 3, d),
                "bqkv": F(P(None, "tp"), 3, d),
                "wo": F(P("tp", None), d, d),  # row-parallel
                "bo": F(P(), d),
            },
        }
        if cfg.n_experts:
            layer_spec["moe"] = self._moe_specs(fsdp)
        else:
            layer_spec["mlp"] = {
                "w_in": F(P(None, "tp"), d, ff),
                "b_in": F(P("tp"), ff),
                "w_out": F(P("tp", None), ff, d),
                "b_out": F(P(), d),
            }
        if pp:
            from dsml_tpu.parallel.pp import pipeline_specs

            layers_spec = pipeline_specs(layer_spec, "pp")
        else:
            layers_spec = [layer_spec for _ in range(cfg.n_layer)]
        return {
            "wte": F(P("tp", None), V, d),  # vocab-sharded embedding/unembedding
            "wpe": F(P(), S, d),
            "ln_f": {"scale": F(P(), d), "bias": F(P(), d)},
            "layers": layers_spec,
        }

    # ---- forward (per-rank SPMD function; axis names optional) -----------------

    def apply_spmd(
        self,
        params: dict,
        tokens: jax.Array,  # [batch_shard, seq_shard] int32
        tp_axis: str | None = None,
        sp_axis: str | None = None,
        attn_impl: str = "ring",
        seq_offset: int | None = None,
        pp_axis: str | None = None,
        n_micro: int = 1,
    ) -> jax.Array:
        """Per-rank forward to vocab-shard logits.

        Under shard_map: ``tokens`` is this rank's (batch, sequence) shard;
        weights arrive TP-sharded per :meth:`param_specs`. Returns logits
        sharded over tp on the vocab dim: [batch_shard, seq_shard, vocab/tp].

        With ``pp_axis`` set, ``params['layers']`` must be the STACKED stage
        shard (``param_specs(pp=True)``) and the block stack runs as a GPipe
        pipeline of ``n_micro`` microbatches (``parallel.pp``): every rank
        computes the embedding but only stage 0's result enters the pipeline
        (so embedding gradients land on rank 0 alone), activations hop
        stage→stage over ``ppermute``, and the returned logits are replicated
        across pp ranks.
        """
        h = self._hidden_spmd(params, tokens, tp_axis, sp_axis, attn_impl, seq_offset, pp_axis, n_micro)
        return h @ self._unembed_matrix(params).T  # unembedding → [b, s, vocab/tp]

    def _head_loss_spmd(self, params, h_raw, targets, tp_axis=None):
        """Final norm + tied unembedding + next-token CE for PRE-final-norm
        hidden states ``h_raw`` [b, s, d] → scalar mean loss. The head the
        pipeline's last stage owns; shared by :meth:`loss_spmd` and the 1F1B
        schedule (which must run it per microbatch, inside the schedule)."""
        cfg = self.config
        h = self._final_norm(params, h_raw)
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        if tp_size == 1:
            if cfg.xent_chunk and cfg.vocab_size > cfg.xent_chunk:
                # big unsharded vocab: stream the unembedding — [tokens,
                # vocab] logits never exist (ops/xent.py)
                from dsml_tpu.ops.xent import chunked_softmax_xent

                return chunked_softmax_xent(h, self._unembed_matrix(params), targets, cfg.xent_chunk)
            logits = (h @ self._unembed_matrix(params).T).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
            return nll.mean()
        logits = (h @ self._unembed_matrix(params).T).astype(jnp.float32)
        vocab_shard = logits.shape[-1]
        tp_rank = lax.axis_index(tp_axis)
        # distributed logsumexp (max-shift carries no gradient, and pmax has
        # no VJP rule — stop_gradient on both)
        local_max = lax.stop_gradient(logits.max(-1, keepdims=True))
        global_max = lax.stop_gradient(lax.pmax(local_max, tp_axis))
        sumexp = jnp.sum(jnp.exp(logits - global_max), axis=-1, keepdims=True)
        lse = jnp.log(lax.psum(sumexp, tp_axis)) + global_max  # [b, s, 1]
        # target logit lives on exactly one shard
        local_ids = targets - tp_rank * vocab_shard
        in_shard = (local_ids >= 0) & (local_ids < vocab_shard)
        safe_ids = jnp.clip(local_ids, 0, vocab_shard - 1)
        tgt = jnp.take_along_axis(logits, safe_ids[..., None], axis=-1)
        tgt = lax.psum(jnp.where(in_shard[..., None], tgt, 0.0), tp_axis)
        return jnp.mean(lse - tgt)

    def _embed_spmd(self, params, tokens, tp_axis=None, sp_axis=None, seq_offset=None):
        """Token + position embedding → [b, s_local, d]. ``wte`` is
        vocab-sharded over tp → masked gather + psum (each token's row lives
        on exactly one shard); positions offset by this rank's sp shard."""
        seq_local = tokens.shape[1]
        if sp_axis:
            sp_rank = lax.axis_index(sp_axis)
            pos = sp_rank * seq_local + jnp.arange(seq_local)
        else:
            # seq_offset may be a traced position (decode steps) — no `or`
            pos = jnp.arange(seq_local) + (0 if seq_offset is None else seq_offset)
        if tp_axis:
            vocab_shard = params["wte"].shape[0]
            tp_rank = lax.axis_index(tp_axis)
            local_ids = tokens - tp_rank * vocab_shard
            in_shard = (local_ids >= 0) & (local_ids < vocab_shard)
            safe_ids = jnp.clip(local_ids, 0, vocab_shard - 1)
            h = lax.psum(params["wte"][safe_ids] * in_shard[..., None], tp_axis)
        else:
            h = params["wte"][tokens]
        return h + params["wpe"][pos]

    def _block_closure(self, tp_axis, sp_axis, attn_impl):
        """``block(one_layer_params, x) -> x`` for the current sharding —
        the unit both pipeline schedules stream microbatches through."""
        cfg = self.config
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        if cfg.n_head % tp_size:
            raise ValueError(f"n_head={cfg.n_head} not divisible by tp={tp_size}")
        n_head_local = cfg.n_head // tp_size

        def block(layer, x):
            return self._block(layer, x, n_head_local, tp_axis, sp_axis, attn_impl)

        return block

    def _blocks_spmd(
        self, params, tokens, tp_axis=None, sp_axis=None, attn_impl="ring",
        seq_offset=None, pp_axis=None, n_micro=1,
    ):
        """Embedding + transformer block stack → PRE-final-norm hidden
        states [b, s, d]."""
        cfg = self.config
        if cfg.remat not in (False, True, "int8", "mlp"):
            # a typo ("INT8", "int4") would otherwise silently degrade to
            # plain remat here and to NO remat in the pipeline path
            raise ValueError(
                f"unknown remat mode {cfg.remat!r}; choose False, True, 'int8', or 'mlp'"
            )
        block = self._block_closure(tp_axis, sp_axis, attn_impl)
        h = self._embed_spmd(params, tokens, tp_axis, sp_axis, seq_offset)

        if pp_axis:
            from dsml_tpu.parallel.pp import pipeline_apply, pipeline_apply_interleaved

            b = h.shape[0]
            if b % n_micro:
                raise ValueError(f"per-rank batch {b} not divisible by n_micro={n_micro}")
            micro = h.reshape(n_micro, b // n_micro, *h.shape[1:])
            if cfg.pp_interleave > 1:
                # local stacked layers = this rank's v chunks concatenated
                # (init_hybrid permuted the layer order before sharding);
                # reshape the leading axis to [v, layers_per_chunk]
                v = cfg.pp_interleave
                chunks = jax.tree.map(
                    lambda p: p.reshape(v, p.shape[0] // v, *p.shape[1:]),
                    params["layers"],
                )
                outs = pipeline_apply_interleaved(
                    block, chunks, micro, v, pp_axis,
                    # "mlp" checkpoints inside the block closure itself
                    remat=False if cfg.remat == "mlp" else cfg.remat,
                )
            else:
                # remat at STAGE granularity (one checkpoint per tick) rather
                # than per block — the coarser cut bounds in-flight activations
                # the way 1F1B does
                outs = pipeline_apply(
                    block, params["layers"], micro, pp_axis,
                    remat=False if cfg.remat == "mlp" else cfg.remat,
                )
            h = outs.reshape(b, *h.shape[1:])
        else:
            if cfg.remat == "int8":
                from dsml_tpu.ops.quantization import compressed_checkpoint

                block = compressed_checkpoint(block)
            elif cfg.remat is True:
                # "mlp" (selective) already checkpoints inside _block;
                # wrapping the whole block again would discard the saved
                # attention activations it exists to keep
                block = jax.checkpoint(block)
            for layer in params["layers"]:
                h = block(layer, h)
        return h

    def _hidden_spmd(
        self, params, tokens, tp_axis=None, sp_axis=None, attn_impl="ring",
        seq_offset=None, pp_axis=None, n_micro=1,
    ):
        """Forward to the final-layer-norm hidden states [b, s, d] (shared by
        the logits head and the chunked-xent loss that never builds logits)."""
        h = self._blocks_spmd(
            params, tokens, tp_axis, sp_axis, attn_impl, seq_offset, pp_axis, n_micro
        )
        return self._final_norm(params, h)

    def _block(self, layer, h, n_head_local, tp_axis, sp_axis, attn_impl):
        """One transformer block (pre-LN attention + MLP/MoE residuals) —
        the unit the pipeline schedule streams microbatches through.

        ``remat="mlp"`` is SELECTIVE rematerialization: only the FFN
        sub-block is checkpointed, so the backward pass keeps the
        attention activations (incl. the flash kernel's saved residuals —
        re-running the O(s²·d) attention forward is the expensive part of
        whole-block remat at long context) and recomputes just the two
        cheap O(s·d·ff) FFN matmuls. ~half the activation memory of no
        remat for ~a tenth of whole-block remat's recompute FLOPs."""
        h = h + self._attn_block(layer, h, n_head_local, tp_axis, sp_axis, attn_impl)
        sub, key = ((self._moe_block, "moe") if self.config.n_experts
                    else (self._mlp_block, "mlp"))

        def ffn(sub_p, ln_p, hh):
            return sub(sub_p, _layer_norm(hh, **ln_p), tp_axis)

        if self.config.remat == "mlp":
            ffn = jax.checkpoint(ffn)
        return h + ffn(layer[key], layer["ln_2"], h)

    _ATTN_IMPLS = ("ring", "ring2", "ulysses", "ulysses_flash", "ring_flash", "flash", "xla")

    def _route_attention(self, q, k, v, sp_axis, attn_impl):
        """[b, h_local, s, hd] q/k/v → causal attention output, routed to the
        impl that is CORRECT for the sharding (shared by GPT-2 and Llama).

        ``sp_axis`` is whichever mesh axis the SEQUENCE is sharded over —
        the legacy ``sp`` ring or the ``cp`` context-parallel axis
        (``parallel.hybrid`` passes the resolved name; the impls are
        axis-name-generic). ``"ring2"`` is the cp tentpole: bidirectional
        flash ring with causal hop skipping and the KV re-streaming backward
        (``ops.ring_attention``) — the training default on cp meshes."""
        if attn_impl not in self._ATTN_IMPLS:
            # a typo would otherwise silently train on the ring/XLA fallback
            raise ValueError(f"unknown attn_impl {attn_impl!r}; choose from {self._ATTN_IMPLS}")
        if sp_axis and lax.axis_size(sp_axis) == 1:
            # a size-1 sequence ring means the sequence is NOT sharded: route
            # as single-chip so "flash" actually runs the Pallas kernel (the
            # truthy-name check used to send it through the n=1 XLA ring →
            # dense attention — silently benching the wrong implementation)
            sp_axis = None
        if sp_axis:
            # sequence is sharded: only ring/Ulysses see the full context.
            # Anything else (incl. "flash", a single-chip kernel) would be
            # silently-wrong block-diagonal attention — route it to ring.
            if attn_impl == "ring2":
                from dsml_tpu.ops.ring_attention import ring_attention as ring2_attention

                return ring2_attention(q, k, v, sp_axis, causal=True)
            if attn_impl == "ulysses":
                return ulysses_attention(q, k, v, sp_axis, causal=True)
            if attn_impl == "ulysses_flash":
                return ulysses_attention(q, k, v, sp_axis, causal=True, flash=True)
            if attn_impl == "ring_flash":
                from dsml_tpu.ops.flash import ring_flash_attention

                return ring_flash_attention(q, k, v, sp_axis, causal=True)
            return ring_attention(q, k, v, sp_axis, causal=True)
        if attn_impl in ("flash", "ring_flash", "ulysses_flash", "ring2"):
            # no sp axis → every flash variant degenerates to the
            # single-chip kernel (falling through to plain attention would
            # materialize the [seq, seq] scores the caller chose flash to
            # avoid)
            from dsml_tpu.ops.flash import flash_attention

            return flash_attention(q, k, v, causal=True)
        return attention(q, k, v, causal=True)

    def _attn_block(self, layer, h, n_head_local, tp_axis, sp_axis, attn_impl):
        x = _layer_norm(h, **layer["ln_1"])
        q, k, v = self._qkv_heads(layer, x, n_head_local)
        out = self._route_attention(q, k, v, sp_axis, attn_impl)
        out = qmatmul(self._merge_heads(out), layer["attn"]["wo"], out.dtype)  # row-parallel → partial sums
        if tp_axis:
            out = lax.psum(out, tp_axis)  # Megatron psum #1
        return out + layer["attn"]["bo"]

    def _mlp_block(self, mlp, x, tp_axis):
        hmid = jax.nn.gelu(qmatmul(x, mlp["w_in"], x.dtype) + mlp["b_in"])  # [b, s, d_ff/tp]
        out = qmatmul(hmid, mlp["w_out"], x.dtype)
        if tp_axis:
            out = lax.psum(out, tp_axis)  # Megatron psum #2
        return out + mlp["b_out"]

    def _moe_param_init(self, normal, res_std):
        """One expert layer's params — shared by every family that mounts
        the MoE block (GPT-2, Llama/Mixtral), so the layout and
        ``_moe_block``'s expectations can never drift apart."""
        cfg = self.config
        return {
            "gate": normal(cfg.d_model, cfg.n_experts),
            "w_in": normal(cfg.n_experts, cfg.d_model, cfg.d_ff),
            "b_in": jnp.zeros((cfg.n_experts, cfg.d_ff), jnp.dtype(cfg.dtype)),
            "w_out": normal(cfg.n_experts, cfg.d_ff, cfg.d_model, std=res_std),
            "b_out": jnp.zeros((cfg.n_experts, cfg.d_model), jnp.dtype(cfg.dtype)),
        }

    def _moe_specs(self, fsdp: int = 1):
        from jax.sharding import PartitionSpec as P

        cfg = self.config
        d, ff, E = cfg.d_model, cfg.d_ff, cfg.n_experts
        F = fsdp_spec_fn(fsdp)
        return {
            "gate": F(P(), d, E),
            "w_in": F(P("tp", None, None), E, d, ff),  # experts sharded over tp (EP)
            "b_in": F(P("tp", None), E, ff),
            "w_out": F(P("tp", None, None), E, ff, d),
            "b_out": F(P("tp", None), E, d),
        }

    def _moe_block(self, moe, x, tp_axis):
        """Top-k gated mixture of experts with experts sharded over
        ``tp_axis`` — real expert parallelism: token payloads ride
        ``all_to_all`` over the expert axis.

        Activations are replicated across tp (Megatron invariant), and the
        routing is capacity-bounded over this dp×sp shard's tokens with
        overflow dropped, static shapes throughout. Under EP each rank
        routes only its 1/ep token slice — gate matmul, top_k, and argsort
        all scale with T/ep (VERDICT r3 item 6) — and the GLOBAL capacity
        position of each assignment is reconstructed exactly from an
        all_gather of the per-rank [E] count vectors (rank slices are
        contiguous token-major ranges, so global position = earlier ranks'
        counts for that expert + local position). Every capacity slot
        (e, c) is therefore still owned by exactly ONE assignment, which is
        what makes the exchange exact. Routing is the sort/segment
        formulation — O(T·k) index vectors plus the [E, C, d] capacity
        buffers — NOT the dense [T, E, C] one-hot dispatch/combine tensors,
        which at Mixtral shapes (T=32k, E=8, C≈8k) would cost multi-GB per
        layer (VERDICT r2 weak #3):

        1. stable-argsort the T·k expert assignments by expert id;
        2. each assignment's position inside its expert's capacity buffer =
           its sorted index minus the expert's segment start (exclusive
           prefix over ``bincount``) — identical priority order (flattened
           token-major) to the cumsum-of-one-hots it replaces;
        3. dispatch = scatter-add of token vectors into the flat [E·C, d]
           buffer (dropped/overflow assignments scatter to a dummy row);
        4. combine = gather each assignment's expert output back from the
           buffer and weighted-sum the k assignments per token.

        Under EP, each rank scatters only its 1/ep token slice,
        ``all_to_all`` ships the slot payloads to the rank owning each
        expert shard (disjoint slots → summing the received blocks
        reconstructs the buffers exactly), the resident experts run, and a
        second ``all_to_all`` + token ``all_gather`` route the combined
        outputs back to replication (the standard MoE dispatch/return
        pair). The dispatch hop carries the capacity buffers
        (≈ top_k·capacity_factor·T·d/ep per rank).

        Values equal the single-device forward up to f32 reduction order
        (tests pin loss AND gradient parity) — with the caveat that
        routing/capacity are computed per dp×sp token shard, so drop
        patterns under capacity overflow differ from a global-batch
        dispatch (standard local-group MoE semantics).

        Falls back to replicated dispatch + psum when the token count
        doesn't split over ep (warned at trace time — the fallback loses
        the a2a bandwidth saving but not correctness)."""
        cfg = self.config
        b, s, d = x.shape
        n_exp = cfg.n_experts
        k = cfg.expert_top_k
        ep = lax.axis_size(tp_axis) if tp_axis else 1
        exp_local = n_exp // ep
        if exp_local * ep != n_exp:
            raise ValueError(f"n_experts={n_exp} not divisible by tp={ep}")
        tokens = x.reshape(-1, d)  # [T, d]
        t = tokens.shape[0]
        capacity = int(cfg.capacity_factor * t * k / n_exp) + 1
        n_assign = t * k
        n_slots = n_exp * capacity

        def route(toks):
            """Sort/segment routing over ``toks`` [t', d] → (top_p [t', k],
            flat_e [t'·k], pos [t'·k], counts [E]). ``pos`` is each
            assignment's position within its expert's segment counting only
            THESE assignments; stable sort keeps the flattened (token-major)
            order within each expert, so priority under overflow matches
            the dense cumsum formulation exactly."""
            gate_logits = toks @ moe["gate"].astype(toks.dtype)
            gate_probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
            top_p, top_e = lax.top_k(gate_probs, k)
            top_p = (top_p / top_p.sum(-1, keepdims=True)).astype(x.dtype)
            flat_e = top_e.reshape(-1)
            n = flat_e.shape[0]
            order = jnp.argsort(flat_e, stable=True)
            counts = jnp.zeros(n_exp, jnp.int32).at[flat_e].add(1)
            starts = jnp.cumsum(counts) - counts  # exclusive prefix
            pos_sorted = jnp.arange(n, dtype=jnp.int32) - starts[flat_e[order]]
            inv = jnp.zeros_like(order).at[order].set(jnp.arange(n))
            return top_p, flat_e, pos_sorted[inv], counts

        def scatter_tokens(slot, tok_idx, toks, n_rows):
            """Flat [n_rows, d] capacity buffer: scatter-add ``toks[tok_idx]``
            into ``slot``; slot ``n_rows`` is the dummy row dropped
            assignments land in."""
            buf = jnp.zeros((n_rows + 1, d), tokens.dtype)
            return buf.at[slot].add(toks[tok_idx])[:-1]

        use_a2a = ep > 1 and t % ep == 0
        if ep > 1 and not use_a2a:
            import warnings

            warnings.warn(
                f"MoE a2a dispatch disabled: {t} tokens per rank do not split "
                f"over ep={ep}; falling back to replicated dispatch + psum "
                "(correct, but pays replicated expert FLOPs and a psum instead "
                "of the all_to_all payload exchange)",
                stacklevel=2,
            )
        r = lax.axis_index(tp_axis) if ep > 1 else 0
        if use_a2a:
            from dsml_tpu.ops.collectives import all_gather, all_to_all

            # routing runs on this rank's 1/ep token slice ONLY (VERDICT r3
            # item 6: the gate matmul, top_k, and argsort all scale with
            # T/ep, not T). Global capacity positions are reconstructed from
            # the per-rank, per-expert counts: rank slices are contiguous
            # token-major ranges, so an assignment's global position within
            # its expert = (assignments to that expert on earlier ranks)
            # + its local position — an all_gather of the tiny [E] count
            # vector replaces the replicated full-T sort.
            t_local = t // ep
            n_loc = t_local * k
            tok_r = lax.dynamic_slice_in_dim(tokens, r * t_local, t_local, axis=0)
            top_p_r, flat_e_r, pos_loc, counts_r = route(tok_r)
            counts_all = all_gather(counts_r, tp_axis, axis=0, tiled=False)  # [ep, E]
            rank_base = jnp.cumsum(counts_all, axis=0) - counts_all  # exclusive
            base_r = lax.dynamic_index_in_dim(rank_base, r, 0, keepdims=False)
            pos_r = pos_loc + base_r[flat_e_r]  # global capacity position
            kept_r = pos_r < capacity
            partial = scatter_tokens(
                jnp.where(kept_r, flat_e_r * capacity + pos_r, n_slots),
                jnp.arange(n_loc, dtype=jnp.int32) // k,
                tok_r,
                n_slots,
            ).reshape(n_exp, capacity, d)
            # all_to_all over experts: send [E_local, C, d] blocks, receive
            # the ep partials for OUR experts concatenated on the capacity
            # axis; slots are disjoint so the sum is the exact buffer
            recv = all_to_all(partial, tp_axis, split_axis=0, concat_axis=1)
            expert_in = recv.reshape(exp_local, ep, capacity, d).sum(axis=1)
            # the return path combines every token's assignments on the
            # expert-owner rank, so the global index/weight vectors are
            # reconstructed by all_gathering the per-rank slices — ~12
            # bytes per assignment, vs the d-wide payloads the a2a carries
            flat_e = all_gather(flat_e_r, tp_axis, axis=0, tiled=True)  # [N]
            pos_flat = all_gather(pos_r, tp_axis, axis=0, tiled=True)
            top_p = all_gather(top_p_r, tp_axis, axis=0, tiled=True)  # [T, k]
            kept = pos_flat < capacity
            is_local_e = (flat_e // exp_local) == r
            local_slot = jnp.where(
                kept & is_local_e,
                (flat_e - r * exp_local) * capacity + pos_flat,
                exp_local * capacity,
            )
        else:
            # single-device or non-a2a fallback: full-T routing on every rank
            top_p, flat_e, pos_flat, _ = route(tokens)
            flat_tok = jnp.arange(n_assign, dtype=jnp.int32) // k  # owning token
            kept = pos_flat < capacity
            slot_flat = jnp.where(kept, flat_e * capacity + pos_flat, n_slots)
            if ep > 1:
                # slot within this rank's expert shard for each assignment
                # whose expert the shard owns (experts are contiguous blocks
                # of exp_local); everyone else lands in the dummy row
                is_local_e = (flat_e // exp_local) == r
                local_slot = jnp.where(
                    kept & is_local_e,
                    (flat_e - r * exp_local) * capacity + pos_flat,
                    exp_local * capacity,
                )
                expert_in = scatter_tokens(
                    local_slot, flat_tok, tokens, exp_local * capacity
                ).reshape(exp_local, capacity, d)
            else:
                expert_in = scatter_tokens(slot_flat, flat_tok, tokens, n_slots).reshape(
                    n_exp, capacity, d
                )

        hmid = jax.nn.gelu(
            jnp.einsum("ecd,edf->ecf", expert_in, moe["w_in"]) + moe["b_in"][:, None, :]
        )
        expert_out = jnp.einsum("ecf,efd->ecd", hmid, moe["w_out"]) + moe["b_out"][:, None, :]

        def combine_from(buf_flat, slot):
            """[T, d] weighted sum of each token's k assignment outputs,
            gathered from the flat buffer (+1 dummy zero row)."""
            buf = jnp.concatenate([buf_flat, jnp.zeros((1, d), buf_flat.dtype)])
            gathered = buf[slot].reshape(t, k, d)
            return jnp.einsum("tkd,tk->td", gathered, top_p)

        if use_a2a:
            # return path: each expert-owner combines ITS resident experts'
            # outputs for every token (non-local assignments hit the dummy
            # zero row), then a SECOND all_to_all routes each token slice's
            # partials to its owner rank — the standard MoE return — and a
            # token all_gather restores replication. ~2·T·d bytes moved,
            # matching the psum it replaces.
            partial_out = combine_from(
                expert_out.reshape(exp_local * capacity, d), local_slot
            )  # [T, d], zero outside local experts
            recv = all_to_all(
                partial_out.reshape(ep, t_local, d), tp_axis, split_axis=0, concat_axis=0
            )  # [ep, T_local, d]: block i = rank i's partial for OUR tokens
            out_r = recv.sum(axis=0)  # [T_local, d]
            out = all_gather(out_r, tp_axis, axis=0, tiled=True)  # [T, d] replicated
        elif ep > 1:
            out = lax.psum(
                combine_from(expert_out.reshape(exp_local * capacity, d), local_slot),
                tp_axis,
            )
        else:
            out = combine_from(expert_out.reshape(n_slots, d), slot_flat)
        return out.reshape(b, s, d)

    # ---- loss ------------------------------------------------------------------

    def loss_spmd(
        self,
        params: dict,
        tokens: jax.Array,
        targets: jax.Array,
        tp_axis: str | None = None,
        sp_axis: str | None = None,
        attn_impl: str = "ring",
        pp_axis: str | None = None,
        n_micro: int = 1,
    ) -> jax.Array:
        """Mean next-token cross-entropy with vocab-sharded logits: the full
        [.., vocab] row never exists on one chip — logsumexp and the target
        logit are combined across the tp axis.

        Under pipeline parallelism the head runs on replicated pipeline
        outputs, but the loss is masked to the LAST stage and ``psum``-ed over
        pp — so head/final-norm gradients land on exactly one rank (and the
        embedding's on rank 0 via the pipeline feed mask), letting the caller
        reconstruct full non-layer grads with one psum over pp
        (``parallel.hybrid``)."""
        h_raw = self._blocks_spmd(
            params, tokens, tp_axis, sp_axis, attn_impl, pp_axis=pp_axis, n_micro=n_micro
        )
        # tp of size 1 (the hybrid step always has a tp axis, often unit —
        # e.g. GPT-2-small pure-DP) is an UNsharded vocab: _head_loss_spmd
        # routes it to the chunked/dense single-shard path, not TP logits
        loss = self._head_loss_spmd(params, h_raw, targets, tp_axis)
        if pp_axis:
            is_last = lax.axis_index(pp_axis) == lax.axis_size(pp_axis) - 1
            loss = lax.psum(jnp.where(is_last, loss, 0.0), pp_axis)
        return loss

    def train_grads_1f1b_spmd(
        self,
        params: dict,
        tokens: jax.Array,
        targets: jax.Array,
        tp_axis: str | None = None,
        sp_axis: str | None = None,
        attn_impl: str = "ring",
        pp_axis: str = "pp",
        n_micro: int = 1,
        batch_axes: tuple = ("dp", "sp"),
    ):
        """Per-rank (loss, grads) via the hand-interleaved 1F1B pipeline
        schedule (``parallel.pp.pipeline_train_1f1b``) — must run under
        ``shard_map(check_vma=True)``.

        Grads come back already reduced to each leaf's replication (the
        schedule's internal-psum semantics; the head seed carries the
        1/(M·n_dp·n_sp) normalization of the global-mean loss), so the
        caller uses them as-is. The returned loss is nonzero on the LAST
        pp rank only: reduce with psum over pp + pmean over the batch axes.

        The embedding runs (replicated) outside the schedule under its own
        VJP; its cotangent is stage 0's input cotangent (``d_micros``),
        psummed over pp (rank-0 masked) and tp (per-rank partials of the
        tp-replicated residual stream) before the pullback."""
        from dsml_tpu.parallel.pp import pipeline_train_1f1b

        b = tokens.shape[0]
        if b % n_micro:
            raise ValueError(f"per-rank batch {b} not divisible by n_micro={n_micro}")
        block = self._block_closure(tp_axis, sp_axis, attn_impl)
        head_params = {k: v for k, v in params.items() if k != "layers"}

        h, embed_vjp = jax.vjp(
            lambda hp: self._embed_spmd(hp, tokens, tp_axis, sp_axis), head_params
        )
        micros = h.reshape(n_micro, b // n_micro, *h.shape[1:])
        tgt_micros = targets.reshape(n_micro, b // n_micro, *targets.shape[1:])
        vary_axes = tuple(
            dict.fromkeys(a for a in (pp_axis, *batch_axes, tp_axis, sp_axis) if a is not None)
        )
        batch_ranks = 1
        for a in batch_axes:
            batch_ranks *= lax.axis_size(a)
        # On a jax without vma tracking (compat shim), the in-shard_map vjp
        # transposes psum to psum, so the REPLICATED head seed crossing the
        # logits' tp psum comes out multiplied by tp_size (exactly once:
        # every later crossing sees an already-varying cotangent, which
        # psum-transpose reduces correctly). Pre-divide the seed to cancel.
        seed_div = batch_ranks
        if getattr(jax, "_dsml_shimmed_vma", False) and tp_axis:
            seed_div *= lax.axis_size(tp_axis)

        def stage_fn(stage_layers, x):
            def body(hh, one_layer):
                return block(one_layer, hh), None

            out, _ = lax.scan(body, x, stage_layers)
            return out

        def head_fn(hp, y, tgt):
            return self._head_loss_spmd(hp, y, tgt, tp_axis)

        loss, d_stage, d_head, d_micros = pipeline_train_1f1b(
            stage_fn, head_fn, params["layers"], head_params, micros, tgt_micros,
            pp_axis, vary_axes=vary_axes, loss_seed_scale=1.0 / (n_micro * seed_div),
        )
        # On a jax WITHOUT vma tracking (the 0.4.x compat shim), the
        # per-tick vjps do not auto-psum cotangents of replicated inputs:
        # each rank holds a partial over every axis its compute varied on
        # (and non-last pp ranks hold the head's masked zeros). Reduce each
        # grad leaf over the varying axes its PartitionSpec leaves it
        # REPLICATED on. On new jax a reduced leaf's vma already excludes
        # those axes, so the psum list is empty and this is a no-op.
        specs = self.param_specs(pp=True)

        def _respec(g, spec):
            named = set()
            for part in spec:
                if part is None:
                    continue
                named.update(part if isinstance(part, (tuple, list)) else (part,))
            axes = tuple(
                a for a in vary_axes if a not in named and a in jax.typeof(g).vma
            )
            return lax.psum(g, axes) if axes else g

        head_specs = {k: v for k, v in specs.items() if k != "layers"}
        d_head = jax.tree.map(_respec, d_head, head_specs)
        d_stage = jax.tree.map(_respec, d_stage, specs["layers"])
        if getattr(jax, "_dsml_shimmed_vma", False):
            # no-vma jax: keep the feed cotangent VARYING over tp so the
            # embed vjp's internal psum transpose performs the tp reduction
            # itself (a replicated d_h would come out of that transpose
            # multiplied by tp_size — the vocab-sharded wte leg). Leaves
            # with no collective in their leg (wpe) stay per-rank partials;
            # sum them over every non-pp axis their spec replicates.
            d_h = lax.psum(d_micros.reshape(b, *h.shape[1:]), pp_axis)
            (d_embed,) = embed_vjp(d_h)

            def _reduce_partials(g, spec):
                named = set()
                for part in spec:
                    if part is None:
                        continue
                    named.update(part if isinstance(part, (tuple, list)) else (part,))
                axes = tuple(
                    a for a in vary_axes if a != pp_axis and a not in named
                )
                return lax.psum(g, axes) if axes else g

            d_embed = jax.tree.map(_reduce_partials, d_embed, head_specs)
        else:
            # cotangent of the (pp/tp-replicated) embedded stream: rank 0
            # holds the pipeline's feed cotangent, tp ranks hold partials
            sum_axes = (pp_axis,) + ((tp_axis,) if tp_axis else ())
            d_h = lax.psum(d_micros.reshape(b, *h.shape[1:]), sum_axes)
            (d_embed,) = embed_vjp(d_h)
        grads_head = jax.tree.map(jnp.add, d_head, d_embed)
        return loss, {**grads_head, "layers": d_stage}

    # ---- single-device conveniences (parity + Trainer protocol) ----------------

    def apply(self, params: dict, tokens: jax.Array) -> jax.Array:
        return self.apply_spmd(params, tokens)

    def loss(self, params: dict, tokens: jax.Array, targets: jax.Array) -> jax.Array:
        return self.loss_spmd(params, tokens, targets)

    # ---- autoregressive decoding (KV cache) ------------------------------------
    # The reference has no inference path at all; a serving-shaped decode loop
    # is table stakes for a framework. Static shapes throughout: the cache is
    # pre-allocated at max_seq and positions are masked, so prefill + every
    # decode step are fixed-shape XLA programs (one compile each).

    def init_cache(self, batch: int, tp_size: int = 1) -> list:
        """KV cache, pre-allocated at max_seq. Under TP the cache holds only
        this rank's head shard — attention is head-parallel, so decode's
        per-chip cache memory drops by tp (the point of sharded serving).
        With ``config.kv_quant`` the entries are int8 + per-position scales
        (see :meth:`_cache_write`)."""
        cfg = self.config
        if cfg.n_head % tp_size:
            raise ValueError(f"n_head={cfg.n_head} not divisible by tp={tp_size}")
        return [
            self._cache_entry(batch, cfg.n_head // tp_size)
            for _ in range(cfg.n_layer)
        ]

    def _kv_mode(self) -> str | None:
        """None | "int8" | "int4" — the normalized ``config.kv_quant``
        (True is "int8" for back-compat). Unknown strings fail loudly
        rather than silently serving an unquantized cache."""
        kq = self.config.kv_quant
        if not kq:
            return None
        if kq is True or kq == "int8":
            return "int8"
        if kq == "int4":
            return "int4"
        raise ValueError(
            f"unknown kv_quant mode {kq!r}; choose False, True/'int8', or 'int4'"
        )

    def _cache_entry(self, batch: int, n_heads: int) -> dict:
        cfg = self.config
        hd = cfg.d_model // cfg.n_head
        mode = self._kv_mode()
        if mode:
            if mode == "int4":
                if hd % 2:
                    raise ValueError(f"kv_quant='int4' needs an even head_dim, got {hd}")
                shape = (batch, n_heads, cfg.max_seq, hd // 2)  # 2 nibbles/byte
                dt = jnp.uint8
            else:
                shape = (batch, n_heads, cfg.max_seq, hd)
                dt = jnp.int8
            return {
                "k": jnp.zeros(shape, dt),
                "k_s": jnp.zeros((*shape[:3], 1), jnp.float32),
                "v": jnp.zeros(shape, dt),
                "v_s": jnp.zeros((*shape[:3], 1), jnp.float32),
            }
        dt = jnp.dtype(cfg.dtype)
        shape = (batch, n_heads, cfg.max_seq, hd)
        return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}

    def _kv_quantize(self, x, mode: str | None = None):
        """[b, h, s, hd] → (quantized values, f32 scale [b, h, s, 1]):
        symmetric absmax per position — each token's K/V row quantizes
        independently, so cache writes never touch other rows' scales.
        Delegates to ``ops.quantization.quantize_kv_rows`` — THE one KV
        codec (int4 packs channel halves contiguously via the shared
        ``pack_int4`` nibble format the collective wire path uses too),
        so the dense cache and the serving page pool produce identical
        bytes per row (the page-table gather parity rests on it)."""
        from dsml_tpu.ops.quantization import quantize_kv_rows

        return quantize_kv_rows(x, mode or self._kv_mode())

    def _cache_write(self, c: dict, kc, vc, write) -> dict:
        """Write new K/V rows through ``write(cache_array, new_rows)`` —
        the ONE place the quantized and plain layouts branch. ``write`` is
        the caller's placement (full-prefix ``dynamic_update_slice``, shared
        decode position, or the per-slot batched scatter); scale tensors ride
        the same placement with their trailing dim of 1 (int4's packed
        values ride it with trailing dim hd/2)."""
        if self._kv_mode():
            kq, ks = self._kv_quantize(kc)
            vq, vs = self._kv_quantize(vc)
            return {"k": write(c["k"], kq), "k_s": write(c["k_s"], ks),
                    "v": write(c["v"], vq), "v_s": write(c["v_s"], vs)}
        return {"k": write(c["k"], kc), "v": write(c["v"], vc)}

    @staticmethod
    def _unpack_int4(p):
        """[..., hd/2] packed nibbles → [..., hd] int8 in [-7, 7] (channel
        halves are contiguous — see :meth:`_kv_quantize`; the shared
        ``ops.quantization.unpack_int4``, a concat of two elementwise ops,
        not an interleaving gather)."""
        from dsml_tpu.ops.quantization import unpack_int4

        return unpack_int4(p)

    def _cache_attn_inputs(self, c: dict):
        """(ck, cv, k_s, v_s) for :meth:`_decode_attention` — scales are
        None for the plain cache. The int8 values go INTO the attention
        dots as-is (the int8→float convert feeds the dot operand, which XLA
        fuses, instead of materializing a dequantized full-width cache
        copy); the per-position scales, constant along ``hd``, fold in
        AFTER each dot — mathematically identical to dequantize-then-dot.
        int4 unpacks its nibbles to the same int8 form first (fused the
        same way — the packed cache is what HBM traffic pays for)."""
        mode = self._kv_mode()
        if mode == "int4":
            return (self._unpack_int4(c["k"]), self._unpack_int4(c["v"]),
                    c["k_s"], c["v_s"])
        if mode:
            return c["k"], c["v"], c["k_s"], c["v_s"]
        return c["k"], c["v"], None, None

    def _qkv_heads(self, layer, x, n_head_local: int | None = None):
        """Fused QKV projection + head split. ``layer['attn']['wqkv']`` is
        [d, 3, d(/tp)] — the slot axis separates q/k/v so a TP shard of the
        last dim is purely a head split; ``n_head_local`` is the head count
        actually present in this shard (full ``n_head`` when unsharded)."""
        n_head_local = n_head_local or self.config.n_head
        qkv = qmatmul(x, layer["attn"]["wqkv"], x.dtype) + layer["attn"]["bqkv"]

        def heads(t):  # [b, s, d_local] -> [b, h_local, s, hd]
            b, s, _ = t.shape
            return t.reshape(b, s, n_head_local, -1).transpose(0, 2, 1, 3)

        return heads(qkv[:, :, 0]), heads(qkv[:, :, 1]), heads(qkv[:, :, 2])

    def _merge_heads(self, t):  # [b, H, s, hd] -> [b, s, d]
        b, _, s, _ = t.shape
        return t.transpose(0, 2, 1, 3).reshape(b, s, -1)

    def _final_norm(self, params, h):
        """Pre-head normalization hook (Llama: RMSNorm over rms_f)."""
        return _layer_norm(h, **params["ln_f"])

    def _unembed_matrix(self, params):
        """[vocab(/tp), d] unembedding hook — GPT-2 ties it to wte; Llama
        overrides with the untied lm_head."""
        return params["wte"]

    def _ffn(self, layer, h, tp_axis=None):
        if self.config.n_experts:
            return h + self._moe_block(layer["moe"], _layer_norm(h, **layer["ln_2"]), tp_axis)
        return h + self._mlp_block(layer["mlp"], _layer_norm(h, **layer["ln_2"]), tp_axis)

    def _unembed_full(self, params, h, tp_axis):
        """h [..., d] → FULL-vocab logits. Under TP the unembedding is
        vocab-sharded; decode needs the whole row for sampling, so the local
        [..., vocab/tp] shards all_gather over tp (tiny at decode batch
        sizes — [batch, vocab], not [tokens, vocab])."""
        local = h @ self._unembed_matrix(params).T
        if tp_axis:
            return lax.all_gather(local, tp_axis, axis=-1, tiled=True)
        return local

    # Serving hooks — ONE prefill/decode loop serves every model family;
    # subclasses override only the architecture-specific pieces (Llama:
    # RMSNorm, RoPE'd GQA projections, grouped cache attention, no biases).

    def _norm1(self, layer, h):
        return _layer_norm(h, **layer["ln_1"])

    def _attn_out_bias(self, layer):
        return layer["attn"]["bo"]

    def _prefill_use_flash(self, t: int) -> bool:
        """Gate for the flash-kernel prefill path — separable so tests can
        force it on under the Pallas interpreter (CI has no TPU)."""
        return jax.default_backend() == "tpu" and t >= 512

    def _serving_qkv(self, layer, x, positions, tp_size):
        """(q, k_cache, v_cache, k_attn, v_attn) for the serving path.
        ``positions`` [s] are the global token positions of ``x`` (ignored
        here — GPT-2 positions live in wpe; Llama applies RoPE)."""
        q, k, v = self._qkv_heads(layer, x, self.config.n_head // tp_size)
        return q, k, v, k, v

    @staticmethod
    def _valid_to_mask(valid):
        """``valid`` → broadcastable [b?, 1(head), q?, S] mask. Accepted
        shapes: [S] (shared depth), [b, S] (per-slot depth, continuous
        batching), [b, q, S] (multi-query — chunked prefill's causal+prefix
        mask)."""
        if valid.ndim == 1:
            return valid[None, None, None, :]
        if valid.ndim == 2:
            return valid[:, None, None, :]
        return valid[:, None, :, :]

    def _decode_attention(self, q, ck, cv, valid, k_s=None, v_s=None):
        """q [b, H, q, hd] against the full cache [b, Hc, S, hd] (H == Hc
        here; Llama overrides with the grouped-query form; q=1 for decode
        steps, q=C for chunked prefill). ``valid`` is [S] (shared depth),
        [b, S] (per-slot depth, continuous batching), or [b, q, S]
        (chunked prefill). ``k_s``/``v_s`` [b, Hc, S, 1] are the int8
        cache's per-position scales, folded in after each dot (see
        ``_cache_attn_inputs``)."""
        vmask = self._valid_to_mask(valid)
        if k_s is None:
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, ck) * (q.shape[-1] ** -0.5)
            scores = jnp.where(vmask, scores, _NEG_INF)
            return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), cv)
        scores = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), ck.astype(jnp.float32)
        ) * (q.shape[-1] ** -0.5)
        scores = scores * jnp.swapaxes(k_s, -1, -2)  # fold key scales: [b, h, 1, S]
        scores = jnp.where(vmask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1) * jnp.swapaxes(v_s, -1, -2)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, cv.astype(jnp.float32)).astype(q.dtype)

    def prefill(
        self,
        params: dict,
        tokens: jax.Array,
        tp_axis: str | None = None,
        last_index=None,
    ):
        """Run the prompt [batch, T] in ONE pass, filling the cache.
        Returns (last-position logits [batch, vocab], cache).

        ``last_index`` (static or traced int) reads the logits at that
        position instead of T-1 — the bucketed-prefill hook: a prompt of
        true length L right-padded to a compiled bucket length passes
        ``last_index=L-1`` (causality keeps positions < L pad-free; pad
        rows land in the cache beyond L but the decode mask never admits
        them before they're overwritten).

        With ``tp_axis`` (call under shard_map with Megatron-sharded
        params), the pass is head-parallel: local-head attention + one psum
        per block pair, vocab-sharded embed/unembed, per-rank cache shard."""
        b, t = tokens.shape
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        positions = jnp.arange(t, dtype=jnp.int32)
        h = self._embed_spmd(params, tokens, tp_axis)
        cache = self.init_cache(b, tp_size)
        # long prompts: the plain path materializes [T, T] scores per head —
        # route through the flash kernel so prefill memory stays O(block²)
        # (untileable lengths ride the kernel's padded kv_stop path)
        use_flash = self._prefill_use_flash(t)
        if use_flash:
            from dsml_tpu.ops.flash import flash_attention

        for i, layer in enumerate(params["layers"]):
            x = self._norm1(layer, h)
            q, kc, vc, ka, va = self._serving_qkv(layer, x, positions, tp_size)
            out = (
                flash_attention(q, ka, va, causal=True)
                if use_flash
                else attention(q, ka, va, causal=True)
            )
            attn_out = qmatmul(self._merge_heads(out), layer["attn"]["wo"], h.dtype)
            if tp_axis:
                attn_out = lax.psum(attn_out, tp_axis)
            h = h + attn_out + self._attn_out_bias(layer)
            h = self._ffn(layer, h, tp_axis)
            cache[i] = self._cache_write(
                cache[i], kc, vc,
                lambda arr, new: lax.dynamic_update_slice(
                    arr, new, (0,) * arr.ndim
                ),
            )
        h = self._final_norm(params, h)
        if last_index is None:
            h_last = h[:, -1]
        else:
            h_last = lax.dynamic_index_in_dim(
                h, jnp.asarray(last_index, jnp.int32), axis=1, keepdims=False
            )
        return self._unembed_full(params, h_last, tp_axis), cache

    def _decode_core(self, params, cache, h, positions, valid, write, tp_axis,
                     read_index=None):
        """The shared decode layer loop: norm → qkv → cache write (via the
        caller's ``write`` placement) → cached attention → wo/psum → ffn,
        then final-norm + full-vocab unembed. ``decode_step`` (shared
        scalar position) and ``decode_step_slots`` (per-slot position
        vector) differ ONLY in positions/valid/write; ``prefill_chunk``
        additionally passes ``read_index`` (the chunk-local position whose
        logits to return — decode's single query reads index 0), and
        ``verify_step`` passes ``read_index="all"`` for per-position
        logits [b, C, vocab]."""
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        new_cache = []
        for layer, c in zip(params["layers"], cache):
            x = self._norm1(layer, h)
            q, kc, vc, _, _ = self._serving_qkv(layer, x, positions, tp_size)
            c = self._cache_write(c, kc, vc, write)
            ck, cv, k_s, v_s = self._cache_attn_inputs(c)
            out = self._decode_attention(q, ck, cv, valid, k_s, v_s)
            attn_out = qmatmul(self._merge_heads(out), layer["attn"]["wo"], h.dtype)
            if tp_axis:
                attn_out = lax.psum(attn_out, tp_axis)
            h = h + attn_out + self._attn_out_bias(layer)
            h = self._ffn(layer, h, tp_axis)
            new_cache.append(c)
        h = self._final_norm(params, h)
        if isinstance(read_index, str) and read_index == "all":
            h_last = h  # [b, C, d] → logits at every query position
        elif read_index is None:
            h_last = h[:, 0]
        else:
            h_last = lax.dynamic_index_in_dim(
                h, jnp.asarray(read_index, jnp.int32), axis=1, keepdims=False
            )
        return self._unembed_full(params, h_last, tp_axis), new_cache

    def decode_step(
        self, params: dict, cache: list, tokens: jax.Array, pos: jax.Array,
        tp_axis: str | None = None,
    ):
        """One decode step: ``tokens`` [batch] at position ``pos`` (scalar,
        int or traced). Returns (logits [batch, vocab], updated cache)."""
        cfg = self.config
        positions = jnp.reshape(jnp.asarray(pos, jnp.int32), (1,))
        h = self._embed_spmd(params, tokens[:, None], tp_axis, seq_offset=pos)
        valid = jnp.arange(cfg.max_seq) <= pos  # attend to cache[0..pos]
        return self._decode_core(
            params, cache, h, positions, valid,
            lambda arr, new: lax.dynamic_update_slice(arr, new, (0, 0, pos, 0)),
            tp_axis,
        )

    def decode_step_slots(
        self, params: dict, cache: list, tokens: jax.Array, pos: jax.Array,
        tp_axis: str | None = None,
    ):
        """One decode step with PER-SLOT positions — the continuous-batching
        kernel (``dsml_tpu.serving``): ``tokens`` [batch] are each slot's
        last token, ``pos`` [batch] each slot's own depth. Shapes are fully
        static; per-slot cache writes are a batched scatter at
        ``(b, :, pos[b], :)`` and the attention mask admits ``s <= pos[b]``
        per row, so slots at different depths decode in ONE program.
        Returns (logits [batch, vocab], updated cache)."""
        cfg = self.config
        b = tokens.shape[0]
        pos = jnp.asarray(pos, jnp.int32)
        positions = pos[:, None]  # [b, 1]: per-row position of the 1 new token
        h = self._embed_spmd(params, tokens[:, None], tp_axis, seq_offset=positions)
        valid = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]  # [b, S]
        bidx = jnp.arange(b)
        return self._decode_core(
            params, cache, h, positions, valid,
            lambda arr, new: arr.at[bidx, :, pos, :].set(new[:, :, 0, :]),
            tp_axis,
        )

    def verify_step(
        self, params: dict, cache: list, tokens: jax.Array, start,
        tp_axis: str | None = None,
    ):
        """Multi-query decode for SPECULATIVE verification: ``tokens``
        [b, C] (each row: its last accepted token followed by C−1 draft
        tokens) run at per-row positions ``start[b]..start[b]+C-1``
        against the cache, writing their K/V rows and returning logits at
        EVERY position — (logits [b, C, vocab], cache).

        One call scores all C candidate continuations of every row (the
        verify half of speculative decoding — ``models.speculative``);
        rows sit at independent depths, so the write is a per-row
        ``dynamic_update_slice`` (vmapped → batched scatter) and the mask
        admits ``s <= start[b]+i`` per query. Rejected drafts leave
        garbage K/V rows beyond the accepted prefix; the NEXT verify
        window starts at the first garbage row and is at least as long,
        so every garbage row is overwritten before any query can attend
        to it (same argument as bucketed prefill's pad rows)."""
        cfg = self.config
        _, c = tokens.shape
        start = jnp.asarray(start, jnp.int32)  # [b]
        positions = start[:, None] + jnp.arange(c, dtype=jnp.int32)  # [b, C]
        h = self._embed_spmd(params, tokens, tp_axis, seq_offset=start[:, None])
        valid = (
            jnp.arange(cfg.max_seq)[None, None, :] <= positions[:, :, None]
        )  # [b, C, S]

        def write(arr, new):  # arr [b, H, S, x], new [b, H, C, x]
            return jax.vmap(
                lambda a, nw, p: lax.dynamic_update_slice(a, nw, (0, p, 0))
            )(arr, new, start)

        return self._decode_core(
            params, cache, h, positions, valid, write, tp_axis, read_index="all"
        )

    def prefill_chunk(
        self, params: dict, cache: list, tokens: jax.Array, start,
        tp_axis: str | None = None, last_index=None,
    ):
        """One CHUNK of a chunked prefill: run ``tokens`` [b, C] at global
        positions ``start..start+C-1`` against a cache whose rows < start
        are already filled, writing this chunk's K/V rows at
        [start, start+C). Returns (logits [b, vocab] read at chunk-LOCAL
        ``last_index`` — default C-1 — and the updated cache).

        Chaining ceil(L/C) chunks over a prompt reproduces :meth:`prefill`
        (pinned in tests): each chunk's queries attend to the cached prefix
        plus causally to the chunk itself. This is the Orca/vLLM
        chunked-prefill schedule shape — the continuous batcher runs decode
        quanta BETWEEN a long admission's chunks instead of stalling every
        active slot for the whole prompt (``dsml_tpu.serving``).

        ``start`` and ``last_index`` may be traced: one compile serves every
        chunk. ``start + C`` must not exceed ``max_seq`` (the caller pads
        the final partial chunk; pad rows land in the cache beyond the true
        length, where the decode mask never admits them before they are
        overwritten — the same argument as bucketed prefill). With
        ``config.kv_quant`` the within-prompt attention reads int8 cache
        rows, whereas whole-prompt prefill attends exactly — the standard
        chunked-prefill approximation, documented at the serving layer."""
        cfg = self.config
        _, c = tokens.shape
        start = jnp.asarray(start, jnp.int32)
        positions = start + jnp.arange(c, dtype=jnp.int32)  # [C] global
        h = self._embed_spmd(params, tokens, tp_axis, seq_offset=start)
        # query i (global position start+i) sees cache rows s <= start+i:
        # the already-filled prefix plus the chunk's own causal triangle
        valid = (
            jnp.arange(cfg.max_seq)[None, None, :] <= positions[None, :, None]
        )  # [1, C, S] — broadcasts over batch
        return self._decode_core(
            params, cache, h, positions, valid,
            lambda arr, new: lax.dynamic_update_slice(arr, new, (0, 0, start, 0)),
            tp_axis,
            read_index=c - 1 if last_index is None else last_index,
        )

    # ---- paged KV cache (the serving page pool) --------------------------------
    # The dense cache above pre-allocates max_seq rows PER SLOT; the paged
    # variants below read/write a shared POOL of fixed-size token pages
    # through a per-slot page table, so a worker's HBM pays for the rows
    # requests actually hold (int4-quantized by default) instead of
    # n_slots × max_seq dense rows — the concurrent-sequence capacity
    # lever (``dsml_tpu.serving.batcher`` owns the allocator/CoW logic;
    # docs/SERVING.md § Paged KV). Same layer loop, same attention, same
    # sampling surfaces: only the cache placement (scatter at
    # (physical page, row)) and the attention read (page-table gather)
    # differ, which is what keeps paged tokens bit-identical to the
    # dense quantized cache's (pinned in tests).

    @staticmethod
    def _page_mode(quant) -> str | None:
        """None | "int8" | "int4" — normalized page-pool quantization
        (the paged analog of :meth:`_kv_mode`, but per-call: a serving
        pool's codec is a deployment choice, not a model-config one)."""
        if not quant:
            return None
        if quant is True or quant == "int4":
            return "int4"
        if quant == "int8":
            return "int8"
        raise ValueError(
            f"unknown page quant mode {quant!r}; choose False, 'int8', or "
            "True/'int4'"
        )

    def init_page_pool(self, n_pages: int, page_size: int, tp_size: int = 1,
                       quant="int4") -> list:
        """Per-layer page pool: ``n_pages`` physical pages of ``page_size``
        token rows each, shared by every slot through a page table.
        ``page_size`` must divide ``max_seq`` (a slot's table then has
        exactly ``max_seq // page_size`` entries and the gathered view is
        shape-identical to the dense cache). Page 0 is the caller's
        SCRATCH page by convention: free/retired slots point every table
        entry at it, so their (masked, never-read) writes can't land in
        another slot's pages."""
        cfg = self.config
        if cfg.n_head % tp_size:
            raise ValueError(f"n_head={cfg.n_head} not divisible by tp={tp_size}")
        if page_size < 1 or cfg.max_seq % page_size:
            raise ValueError(
                f"page_size must divide max_seq={cfg.max_seq}, got {page_size}"
            )
        if n_pages < 2:
            raise ValueError(
                f"need n_pages >= 2 (page 0 is the scratch page), got {n_pages}"
            )
        mode = self._page_mode(quant)
        hd = cfg.d_model // cfg.n_head
        n_heads = getattr(cfg, "n_kv_head", cfg.n_head) // tp_size
        if mode == "int4":
            if hd % 2:
                raise ValueError(f"int4 pages need an even head_dim, got {hd}")
            shape, dt = (n_pages, n_heads, page_size, hd // 2), jnp.uint8
        elif mode == "int8":
            shape, dt = (n_pages, n_heads, page_size, hd), jnp.int8
        else:
            shape, dt = (n_pages, n_heads, page_size, hd), jnp.dtype(cfg.dtype)
        def entry():
            # fresh buffers PER LAYER: sharing one zeros array across
            # layers would hand the same buffer to the jitted programs
            # twice, which donation rejects
            e = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            if mode:
                sshape = (n_pages, n_heads, page_size, 1)
                e.update(k_s=jnp.zeros(sshape, jnp.float32),
                         v_s=jnp.zeros(sshape, jnp.float32))
            return e

        return [entry() for _ in range(cfg.n_layer)]

    def _paged_write(self, c: dict, kc, vc, write, mode):
        """The paged analog of :meth:`_cache_write`: quantize the new K/V
        rows per the pool codec and place values + scales through the
        caller's ``write`` (a scatter at (physical page, row in page))."""
        if mode:
            kq, ks = self._kv_quantize(kc, mode)
            vq, vs = self._kv_quantize(vc, mode)
            return {"k": write(c["k"], kq), "k_s": write(c["k_s"], ks),
                    "v": write(c["v"], vq), "v_s": write(c["v_s"], vs)}
        return {"k": write(c["k"], kc), "v": write(c["v"], vc)}

    def _paged_attn_inputs(self, c: dict, page_table, mode):
        """Gather one layer's pool through ``page_table`` [b, n_pt] into
        the dense attention view ``[b, H, n_pt·page_size, ·]`` —
        :meth:`_decode_attention` then runs unchanged (the gather IS the
        paged-attention read; positions past a slot's depth land on
        whatever page the table names, page 0 for unallocated entries,
        and the validity mask never admits them)."""

        def g(arr):
            t = arr[page_table]  # [b, n_pt, H, page, x]
            b, npt, h, pg, x = t.shape
            return t.transpose(0, 2, 1, 3, 4).reshape(b, h, npt * pg, x)

        if mode == "int4":
            return (self._unpack_int4(g(c["k"])), self._unpack_int4(g(c["v"])),
                    g(c["k_s"]), g(c["v_s"]))
        if mode:
            return g(c["k"]), g(c["v"]), g(c["k_s"]), g(c["v_s"])
        return g(c["k"]), g(c["v"]), None, None

    def _decode_core_paged(self, params, pool, page_table, h, positions,
                           valid, write, tp_axis, mode, read_index=None):
        """:meth:`_decode_core` against a page pool: per layer — norm →
        qkv → quantized page write (the caller's scatter placement) →
        paged-attention read → wo/psum → ffn. The three paged serving
        surfaces (decode / chunked prefill / verify) differ only in
        positions/valid/write, exactly like their dense twins.

        The attention read routes per ``DSML_PAGED_ATTN`` (trace-time):
        the Pallas kernel walks the page table directly — one page DMA'd
        per grid step, dequantized in-kernel, folded into a running
        (out, lse) merge, dead/scratch entries skip-predicated — so the
        dense ``[b, H, S, hd]`` view is never materialized and HBM
        traffic scales with LIVE pages; the XLA gather path stays the
        fallback and the parity oracle (``ops.paged_attention``). All
        three surfaces' masks are ``key_pos <= query_pos``, which is why
        one kernel serves them: ``positions`` broadcast to [b, C] IS the
        mask."""
        from dsml_tpu.ops.paged_attention import paged_attention, paged_attn_impl

        # pass the page geometry so the router can veto a working set that
        # would blow the VMEM budget (falls back to the XLA gather with a
        # warn-once instead of dying inside Mosaic at compile time)
        use_pallas = paged_attn_impl(
            page_size=pool[0]["k"].shape[2],
            head_dim=self.config.d_model // self.config.n_head,
            mode=mode,
        ) == "pallas"
        b_q, c_q = h.shape[0], h.shape[1]
        posq = jnp.broadcast_to(
            jnp.atleast_2d(jnp.asarray(positions, jnp.int32)), (b_q, c_q)
        )
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        new_pool = []
        for layer, c in zip(params["layers"], pool):
            x = self._norm1(layer, h)
            q, kc, vc, _, _ = self._serving_qkv(layer, x, positions, tp_size)
            c = self._paged_write(c, kc, vc, write, mode)
            if use_pallas:
                out = paged_attention(q, c, page_table, posq, mode)
            else:
                ck, cv, k_s, v_s = self._paged_attn_inputs(c, page_table, mode)
                out = self._decode_attention(q, ck, cv, valid, k_s, v_s)
            attn_out = qmatmul(self._merge_heads(out), layer["attn"]["wo"], h.dtype)
            if tp_axis:
                attn_out = lax.psum(attn_out, tp_axis)
            h = h + attn_out + self._attn_out_bias(layer)
            h = self._ffn(layer, h, tp_axis)
            new_pool.append(c)
        h = self._final_norm(params, h)
        if isinstance(read_index, str) and read_index == "all":
            h_last = h
        elif read_index is None:
            h_last = h[:, 0]
        else:
            h_last = lax.dynamic_index_in_dim(
                h, jnp.asarray(read_index, jnp.int32), axis=1, keepdims=False
            )
        return self._unembed_full(params, h_last, tp_axis), new_pool

    def decode_step_slots_paged(
        self, params: dict, pool: list, page_table: jax.Array,
        tokens: jax.Array, pos: jax.Array, tp_axis: str | None = None,
        quant="int4",
    ):
        """:meth:`decode_step_slots` against a page pool: ``page_table``
        [b, max_seq/page_size] names each slot's physical pages; the new
        K/V row scatters at (table[b, pos[b]//page], pos[b] % page).
        Returns (logits [b, vocab], updated pool)."""
        cfg = self.config
        b = tokens.shape[0]
        mode = self._page_mode(quant)
        pos = jnp.asarray(pos, jnp.int32)
        page_size = cfg.max_seq // page_table.shape[1]
        positions = pos[:, None]
        h = self._embed_spmd(params, tokens[:, None], tp_axis, seq_offset=positions)
        valid = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        bidx = jnp.arange(b)
        phys = page_table[bidx, pos // page_size]  # [b]
        row = pos % page_size

        def write(arr, new):  # arr [P, H, page, x], new [b, H, 1, x]
            return arr.at[phys, :, row, :].set(new[:, :, 0, :])

        return self._decode_core_paged(
            params, pool, page_table, h, positions, valid, write, tp_axis, mode
        )

    def prefill_chunk_paged(
        self, params: dict, pool: list, page_table: jax.Array,
        tokens: jax.Array, start, tp_axis: str | None = None,
        last_index=None, quant="int4",
    ):
        """:meth:`prefill_chunk` against a page pool: ``tokens`` [1, C] at
        global positions ``start..start+C-1`` scatter into the pages the
        1-row ``page_table`` [1, n_pt] names. Chunk chaining under a
        quantized pool is CHUNK-SIZE-INVARIANT (every query reads every
        key quantized, regardless of where chunk boundaries fall), which
        is why prefix pages registered with one chunk size match a
        prefill worker's bytes at another — pinned in tests."""
        cfg = self.config
        _, c = tokens.shape
        mode = self._page_mode(quant)
        start = jnp.asarray(start, jnp.int32)
        page_size = cfg.max_seq // page_table.shape[1]
        positions = start + jnp.arange(c, dtype=jnp.int32)  # [C] global
        h = self._embed_spmd(params, tokens, tp_axis, seq_offset=start)
        valid = (
            jnp.arange(cfg.max_seq)[None, None, :] <= positions[None, :, None]
        )  # [1, C, S]
        phys = page_table[0, positions // page_size]  # [C]
        row = positions % page_size

        def write(arr, new):  # arr [P, H, page, x], new [1, H, C, x]
            return arr.at[phys, :, row, :].set(new[0].transpose(1, 0, 2))

        return self._decode_core_paged(
            params, pool, page_table, h, positions, valid, write, tp_axis,
            mode, read_index=c - 1 if last_index is None else last_index,
        )

    def verify_step_paged(
        self, params: dict, pool: list, page_table: jax.Array,
        tokens: jax.Array, start, tp_axis: str | None = None, quant="int4",
    ):
        """:meth:`verify_step` against a page pool — the speculative
        verify window [b, C] written/read through each slot's page table.
        Rejected drafts leave garbage rows in the slot's own reserved
        pages (never shared ones — the allocator reserves decode+window
        rows privately), and the next window overwrites them before any
        query attends — the dense path's invariant, unchanged."""
        cfg = self.config
        b, c = tokens.shape
        mode = self._page_mode(quant)
        start = jnp.asarray(start, jnp.int32)  # [b]
        page_size = cfg.max_seq // page_table.shape[1]
        positions = start[:, None] + jnp.arange(c, dtype=jnp.int32)  # [b, C]
        h = self._embed_spmd(params, tokens, tp_axis, seq_offset=start[:, None])
        valid = (
            jnp.arange(cfg.max_seq)[None, None, :] <= positions[:, :, None]
        )  # [b, C, S]
        phys = page_table[jnp.arange(b)[:, None], positions // page_size]  # [b, C]
        row = positions % page_size

        def write(arr, new):  # arr [P, H, page, x], new [b, H, C, x]
            return arr.at[phys, :, row, :].set(new.transpose(0, 2, 1, 3))

        return self._decode_core_paged(
            params, pool, page_table, h, positions, valid, write, tp_axis,
            mode, read_index="all",
        )

    def generate(
        self,
        params: dict,
        prompt: jax.Array,  # [batch, T] int32
        max_new_tokens: int,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
        eos_id: int | None = None,
    ) -> jax.Array:
        """Sample ``max_new_tokens`` continuations. ``temperature == 0`` is
        greedy; otherwise softmax sampling, optionally truncated to the
        ``top_k`` most likely tokens and/or the nucleus holding ``top_p``
        probability mass. Returns [batch, max_new_tokens]; with ``eos_id``
        a row that emits it keeps emitting ``eos_id`` for its remaining
        positions (shapes stay static — the pad region marks early stop,
        matching the serving batcher's per-request truncation point)."""
        t = prompt.shape[1]
        self._check_generate_args(t, max_new_tokens, temperature, top_k, top_p)
        run = self._generate_fn(t, max_new_tokens, float(temperature), int(top_k),
                                float(top_p),
                                eos_id=None if eos_id is None else int(eos_id))
        return run(params, prompt.astype(jnp.int32), jax.random.PRNGKey(seed))

    def _check_generate_args(self, t, max_new_tokens, temperature, top_k, top_p):
        cfg = self.config
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if t + max_new_tokens > cfg.max_seq:
            raise ValueError(
                f"prompt ({t}) + max_new_tokens ({max_new_tokens}) exceeds max_seq={cfg.max_seq}"
            )
        if top_k < 0 or top_k > cfg.vocab_size:
            raise ValueError(f"top_k must be in [0, vocab_size={cfg.vocab_size}], got {top_k}")
        if not 0.0 <= top_p <= 1.0:
            raise ValueError(f"top_p must be in [0, 1], got {top_p}")
        if temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")

    def generate_spmd(
        self,
        params: dict,
        prompt: jax.Array,
        max_new_tokens: int,
        mesh,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 0.0,
        seed: int = 0,
        dp_shard: bool = False,
        eos_id: int | None = None,
    ) -> jax.Array:
        """TP-sharded serving: :meth:`generate` with Megatron-sharded params
        over the mesh's ``tp`` axis (``shard_params(model.param_specs())``
        placement). Head-parallel prefill/decode with a per-rank KV-cache
        shard; every rank reconstructs the full logits row (vocab-shard
        all_gather) and runs the identical sampler with the identical key,
        so the tokens match the single-device path exactly (tests pin it).
        The reference has no inference at all — this is the serving shape a
        125M+ flagship needs.

        ``dp_shard=True`` additionally shards the BATCH over the mesh's
        ``dp`` axis — throughput serving: each dp group decodes its own
        prompt rows, tp still shards heads within the group. Sampler keys
        fold in the GLOBAL row index, so results are independent of how the
        batch is split (dp=N equals dp=1, both with ``dp_shard=True``);
        greedy decoding additionally equals :meth:`generate`. Sampled runs
        use a different key-per-row derivation than the shared-key unsharded
        paths, so they are row-decomposable rather than bit-identical to
        ``dp_shard=False``."""
        b, t = prompt.shape
        self._check_generate_args(t, max_new_tokens, temperature, top_k, top_p)
        tp_size = mesh.shape.get("tp", 1)
        if self.config.n_head % tp_size:
            raise ValueError(f"n_head={self.config.n_head} not divisible by tp={tp_size}")
        from jax.sharding import PartitionSpec as P

        dp_size = mesh.shape.get("dp", 1) if dp_shard else 1
        if dp_shard and b % dp_size:
            raise ValueError(f"batch {b} not divisible by dp={dp_size} for dp_shard")
        batch_spec = P("dp") if dp_shard else P()
        eos_id = None if eos_id is None else int(eos_id)  # stable cache key
        key_ = ("spmd", mesh, t, max_new_tokens, float(temperature), int(top_k),
                float(top_p), dp_shard, eos_id)
        cache = self._gen_cache_dict()
        run = cache.get(key_)
        if run is None:
            raw = self._generate_fn(
                t, max_new_tokens, float(temperature), int(top_k), float(top_p),
                tp_axis="tp", jit=False, dp_axis="dp" if dp_shard else None,
                eos_id=eos_id,
            )
            run = jax.jit(
                jax.shard_map(
                    raw, mesh=mesh,
                    in_specs=(self.param_specs(), batch_spec, P()),
                    out_specs=batch_spec, check_vma=False,
                )
            )
            cache[key_] = run
        return run(params, prompt.astype(jnp.int32), jax.random.PRNGKey(seed))

    def _gen_cache_dict(self) -> dict:
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        return cache

    def _generate_fn(
        self, prompt_len: int, max_new_tokens: int, temperature: float, top_k: int,
        top_p: float = 0.0, tp_axis: str | None = None, jit: bool = True,
        dp_axis: str | None = None, eos_id: int | None = None,
    ):
        """Compiled generate program, cached per (prompt_len, max_new,
        temperature, top_k, top_p) so repeated serving calls don't re-trace.
        ``dp_axis`` (dp-sharded serving) folds each GLOBAL batch row's index
        (this rank's shard offset from that axis) into its sampler key, so a
        dp-sharded run samples per row independently of how the batch is
        split across ranks."""
        key_ = (prompt_len, max_new_tokens, temperature, top_k, top_p, tp_axis, jit,
                dp_axis, eos_id)
        cache = self._gen_cache_dict()
        if key_ in cache:
            return cache[key_]

        def sample(logits, key):
            return sample_token_logits(logits, key, temperature, top_k, top_p)

        def sample_rows(logits, key):
            if dp_axis is None:
                return sample(logits, key)
            b = logits.shape[0]
            row_ids = lax.axis_index(dp_axis) * b + jnp.arange(b)
            keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(row_ids)
            return jax.vmap(lambda lg, kk: sample(lg[None], kk)[0])(logits, keys)

        def run(params, prompt, key):
            logits, kv = self.prefill(params, prompt, tp_axis)
            key, sub = jax.random.split(key)
            first = sample_rows(logits, sub)
            done0 = (
                first == eos_id if eos_id is not None
                else jnp.zeros(first.shape, bool)
            )

            def body(carry, _):
                kv, tok, pos, key, done = carry
                logits, kv = self.decode_step(params, kv, tok, pos, tp_axis)
                key, sub = jax.random.split(key)
                nxt = sample_rows(logits, sub)
                if eos_id is not None:
                    # rows past their EOS keep emitting eos_id (static
                    # shapes — the pad region marks the truncation point)
                    nxt = jnp.where(done, eos_id, nxt)
                    done = done | (nxt == eos_id)
                return (kv, nxt, pos + 1, key, done), nxt

            carry = (kv, first, jnp.asarray(prompt_len, jnp.int32), key, done0)
            _, rest = lax.scan(body, carry, None, length=max_new_tokens - 1)
            return jnp.concatenate([first[None], rest], axis=0).T  # [b, max_new]

        if jit:
            run = jax.jit(run)
        cache[key_] = run
        return run
