"""Llama-family decoder — the second transformer family on the same mesh
program infrastructure.

The reference never got past an MLP (SURVEY.md §2.3); GPT-2 realizes its
literature roadmap, and this module demonstrates the framework claim that
matters beyond any one model: the parallelism stack (Megatron TP psums,
ring/Ulysses/2D/flash sequence parallelism, GPipe/interleaved/1F1B
pipelines, FSDP, elastic reconfigure) is MODEL-GENERIC. Llama subclasses
:class:`~dsml_tpu.models.gpt2.GPT2` and overrides only the architecture:

- **RMSNorm** instead of LayerNorm (no mean-centering, no bias).
- **RoPE** rotary position embeddings applied to q/k inside attention — no
  learned position table; under sequence parallelism each sp/cp rank rotates
  by its GLOBAL positions (rank · s_local offset), so ring/Ulysses attention
  — including the context-parallel flash ring, ``attn_impl="ring2"``
  (``ops.ring_attention``; parity pinned in tests/test_ring_attention.py) —
  stays exact.
- **SwiGLU** MLP: ``silu(x·w_gate) ⊙ (x·w_up) · w_down`` — gate/up
  column-sharded, down row-sharded (same Megatron psum points as GPT-2).
- **GQA** (grouped-query attention): ``n_kv_head ≤ n_head`` K/V heads,
  repeated to query heads for the shared attention impls; the KV cache holds
  only the kv heads (the GQA serving memory win). TP requires
  ``n_kv_head % tp == 0``.
- **Untied unembedding** (``lm_head``), vocab-sharded like ``wte``.

Everything else — ``loss_spmd`` (vocab-sharded CE / chunked xent), pipeline
integration (``pp_interleave`` included), remat modes (incl. ``"int8"``
compressed), ``generate``/``generate_spmd`` serving, 1F1B — is inherited
unchanged: the subclass overrides the layer math, the mesh machinery never
notices.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dsml_tpu.models.common import maybe_dequant, qmatmul
from dsml_tpu.models.gpt2 import GPT2
from dsml_tpu.ops.attention import _NEG_INF

__all__ = ["LlamaConfig", "Llama"]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq: int = 2048
    n_layer: int = 22
    n_head: int = 32
    n_kv_head: int = 4  # GQA: kv heads grouped under query heads
    d_model: int = 2048
    d_ff: int = 5632  # SwiGLU hidden width
    dtype: str = "float32"
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    # Mixtral-style expert parallelism: >0 replaces the SwiGLU MLP with the
    # inherited capacity-bounded top-k expert layer (token payloads ride
    # all_to_all over tp — models/gpt2.py::_moe_block; expert MLPs use that
    # layer's GELU form, the routing/dispatch machinery being the point)
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    remat: bool | str = False
    xent_chunk: int = 8192
    pp_interleave: int = 1
    # int8 KV cache with per-position scales (see GPT2Config.kv_quant) —
    # stacks with the GQA cache's kv-heads-only memory win
    kv_quant: bool | str = False  # False | True/"int8" | "int4"

    @staticmethod
    def tinyllama_1b() -> "LlamaConfig":
        """TinyLlama-1.1B shape (22×2048, GQA 32q/4kv)."""
        return LlamaConfig()

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig(
            n_layer=32, n_head=32, n_kv_head=32, d_model=4096, d_ff=11008, max_seq=4096
        )

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, n_layer=32, n_head=32, n_kv_head=8, d_model=4096,
            d_ff=14336, max_seq=8192, rope_theta=500000.0,
        )

    @classmethod
    def by_name(cls, name: str, **tiny_kwargs) -> "LlamaConfig":
        presets = {
            "tiny": cls.tiny,
            "tinyllama_1b": cls.tinyllama_1b,
            "llama2_7b": cls.llama2_7b,
            "llama3_8b": cls.llama3_8b,
            "mixtral_8x7b": cls.mixtral_8x7b,
        }
        if name not in presets:
            raise ValueError(f"unknown Llama preset {name!r}; choose from {sorted(presets)}")
        return presets[name](**tiny_kwargs) if name == "tiny" else presets[name]()

    @staticmethod
    def tiny(vocab_size: int = 512, n_experts: int = 0) -> "LlamaConfig":
        """Test-sized config exercising GQA (8q/2kv), RoPE, SwiGLU."""
        return LlamaConfig(
            vocab_size=vocab_size, max_seq=128, n_layer=2, n_head=8, n_kv_head=2,
            d_model=64, d_ff=128, n_experts=n_experts,
        )

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        """Mixtral-8x7B shape: Llama-2-7B trunk, 8 experts, top-2 routing."""
        return LlamaConfig(
            n_layer=32, n_head=32, n_kv_head=8, d_model=4096, d_ff=14336,
            max_seq=4096, n_experts=8, expert_top_k=2,
        )


def _rms_norm(x, scale, eps=1e-5):
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * scale


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding, rotate-half convention. ``x`` [b, h, s, hd],
    ``positions`` [s] GLOBAL token positions (int32) shared across the
    batch, or [b, s] per-row positions (continuous-batching decode, where
    every slot sits at its own depth)."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) * 2.0 / hd)  # [half]
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [(b,) s, half]
    if angles.ndim == 2:  # shared positions → broadcast over batch and heads
        cos = jnp.cos(angles)[None, None, :, :]
        sin = jnp.sin(angles)[None, None, :, :]
    else:  # per-row positions → broadcast over heads only
        cos = jnp.cos(angles)[:, None, :, :]
        sin = jnp.sin(angles)[:, None, :, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class Llama(GPT2):
    """Llama on the GPT-2 mesh scaffolding (see module docstring)."""

    def __init__(self, config: LlamaConfig | None = None):
        self.config = config or LlamaConfig.tinyllama_1b()
        self._kv_mode()  # a bad kv_quant string fails at construction

    # ---- params ---------------------------------------------------------------

    def init(self, seed: int = 0) -> dict:
        cfg = self.config
        rng = np.random.default_rng(seed)
        dt = jnp.dtype(cfg.dtype)
        hd = cfg.d_model // cfg.n_head
        kv_d = cfg.n_kv_head * hd

        def normal(*shape, std=0.02):
            return jnp.asarray(rng.standard_normal(shape) * std, dt)

        res_std = 0.02 / math.sqrt(2 * cfg.n_layer)
        params = {
            "wte": normal(cfg.vocab_size, cfg.d_model),
            "lm_head": normal(cfg.vocab_size, cfg.d_model),
            "rms_f": {"scale": jnp.ones(cfg.d_model, dt)},
            "layers": [
                {
                    "rms_1": {"scale": jnp.ones(cfg.d_model, dt)},
                    "rms_2": {"scale": jnp.ones(cfg.d_model, dt)},
                    "attn": {
                        "wq": normal(cfg.d_model, cfg.d_model),
                        "wk": normal(cfg.d_model, kv_d),
                        "wv": normal(cfg.d_model, kv_d),
                        "wo": normal(cfg.d_model, cfg.d_model, std=res_std),
                    },
                    **(
                        {"moe": self._moe_param_init(normal, res_std)}
                        if cfg.n_experts
                        else {
                            "mlp": {
                                "w_gate": normal(cfg.d_model, cfg.d_ff),
                                "w_up": normal(cfg.d_model, cfg.d_ff),
                                "w_down": normal(cfg.d_ff, cfg.d_model, std=res_std),
                            }
                        }
                    ),
                }
                for _ in range(cfg.n_layer)
            ],
        }
        return params

    def param_specs(self, pp: bool = False, fsdp: int = 1) -> dict:
        """Megatron sharding: q/k/v/gate/up column-parallel (head split for
        q/k/v), wo/w_down row-parallel, vocab matrices vocab-sharded; with
        ``fsdp > 1`` each leaf is additionally ZeRO-sharded on its first
        free divisible dim (``models.common.with_fsdp``)."""
        from jax.sharding import PartitionSpec as P

        from dsml_tpu.models.common import fsdp_spec_fn

        cfg = self.config
        d, ff, V = cfg.d_model, cfg.d_ff, cfg.vocab_size
        kv_d = cfg.n_kv_head * (cfg.d_model // cfg.n_head)
        F = fsdp_spec_fn(fsdp)
        layer_spec = {
            "rms_1": {"scale": F(P(), d)},
            "rms_2": {"scale": F(P(), d)},
            "attn": {
                "wq": F(P(None, "tp"), d, d),
                "wk": F(P(None, "tp"), d, kv_d),
                "wv": F(P(None, "tp"), d, kv_d),
                "wo": F(P("tp", None), d, d),
            },
        }
        if cfg.n_experts:
            layer_spec["moe"] = self._moe_specs(fsdp)
        else:
            layer_spec["mlp"] = {
                "w_gate": F(P(None, "tp"), d, ff),
                "w_up": F(P(None, "tp"), d, ff),
                "w_down": F(P("tp", None), ff, d),
            }
        if pp:
            from dsml_tpu.parallel.pp import pipeline_specs

            layers = pipeline_specs(layer_spec, "pp")
        else:
            layers = [layer_spec for _ in range(cfg.n_layer)]
        return {
            "wte": F(P("tp", None), V, d),
            "lm_head": F(P("tp", None), V, d),
            "rms_f": {"scale": F(P(), d)},
            "layers": layers,
        }

    # ---- architecture hooks ---------------------------------------------------

    def _final_norm(self, params, h):
        return _rms_norm(h, params["rms_f"]["scale"], self.config.rms_eps)

    def _unembed_matrix(self, params):
        return params["lm_head"]

    def _block_closure(self, tp_axis, sp_axis, attn_impl):
        cfg = self.config
        tp_size = lax.axis_size(tp_axis) if tp_axis else 1
        if cfg.n_head % tp_size or cfg.n_kv_head % tp_size:
            raise ValueError(
                f"n_head={cfg.n_head}/n_kv_head={cfg.n_kv_head} not divisible by tp={tp_size}"
            )
        return super()._block_closure(tp_axis, sp_axis, attn_impl)

    def _embed_spmd(self, params, tokens, tp_axis=None, sp_axis=None, seq_offset=None):
        """Token embedding only — positions enter through RoPE, not a table."""
        if tp_axis:
            vocab_shard = params["wte"].shape[0]
            tp_rank = lax.axis_index(tp_axis)
            local_ids = tokens - tp_rank * vocab_shard
            in_shard = (local_ids >= 0) & (local_ids < vocab_shard)
            safe_ids = jnp.clip(local_ids, 0, vocab_shard - 1)
            return lax.psum(params["wte"][safe_ids] * in_shard[..., None], tp_axis)
        return params["wte"][tokens]

    def _qkv_gqa(self, layer, x, n_head_local, n_kv_local, positions):
        """Separate q/k/v projections, head split, RoPE on q/k. Returns
        ``(q, k_kv, v_kv, k_attn, v_attn)``: the kv-head forms (what the
        serving cache stores) and the query-head-repeated forms (what the
        shared MHA attention impls consume) — ONE copy of the GQA math for
        both the training and serving paths."""
        hd = self.config.d_model // self.config.n_head

        def heads(t, n):
            b, s, _ = t.shape
            return t.reshape(b, s, n, hd).transpose(0, 2, 1, 3)

        q = heads(qmatmul(x, layer["attn"]["wq"], x.dtype), n_head_local)
        k = heads(qmatmul(x, layer["attn"]["wk"], x.dtype), n_kv_local)
        v = heads(qmatmul(x, layer["attn"]["wv"], x.dtype), n_kv_local)
        q = _rope(q, positions, self.config.rope_theta)
        k = _rope(k, positions, self.config.rope_theta)
        repeat = n_head_local // n_kv_local
        ka = jnp.repeat(k, repeat, axis=1) if repeat > 1 else k
        va = jnp.repeat(v, repeat, axis=1) if repeat > 1 else v
        return q, k, v, ka, va

    def _block(self, layer, h, n_head_local, tp_axis, sp_axis, attn_impl):
        cfg = self.config
        n_kv_local = n_head_local * cfg.n_kv_head // cfg.n_head
        s_local = h.shape[1]
        # global positions: this sp rank's sequence shard starts at rank·s_local
        offset = lax.axis_index(sp_axis) * s_local if sp_axis else 0
        positions = offset + jnp.arange(s_local, dtype=jnp.int32)

        x = _rms_norm(h, layer["rms_1"]["scale"], cfg.rms_eps)
        q, _, _, ka, va = self._qkv_gqa(layer, x, n_head_local, n_kv_local, positions)
        out = self._route_attention(q, ka, va, sp_axis, attn_impl)
        out = qmatmul(self._merge_heads(out), layer["attn"]["wo"], out.dtype)
        if tp_axis:
            out = lax.psum(out, tp_axis)
        h = h + out
        h = self._ffn(layer, h, tp_axis)
        return h

    def _mlp_block(self, mlp, x, tp_axis):
        mid = jax.nn.silu(qmatmul(x, mlp["w_gate"], x.dtype)) * qmatmul(x, mlp["w_up"], x.dtype)  # [b, s, ff/tp]
        out = qmatmul(mid, mlp["w_down"], x.dtype)
        if tp_axis:
            out = lax.psum(out, tp_axis)  # Megatron psum #2
        return out

    def _ffn(self, layer, h, tp_axis=None):
        # Mixtral-style MoE: the inherited capacity-bounded top-k expert
        # layer — token payloads ride all_to_all over tp (real EP)
        sub, key = ((self._moe_block, "moe") if self.config.n_experts
                    else (self._mlp_block, "mlp"))

        def ffn(sub_p, scale, hh):
            return sub(sub_p, _rms_norm(hh, scale, self.config.rms_eps), tp_axis)

        if self.config.remat == "mlp":
            # selective remat, same contract as GPT2._block: attention
            # activations stay saved, only the FFN recomputes in backward
            ffn = jax.checkpoint(ffn)
        return h + ffn(layer[key], layer["rms_2"]["scale"], h)

    def _hidden_spmd(
        self, params, tokens, tp_axis=None, sp_axis=None, attn_impl="ring",
        seq_offset=None, pp_axis=None, n_micro=1,
    ):
        if seq_offset is not None:
            # GPT-2 realizes seq_offset through its wpe table; Llama positions
            # enter via RoPE inside _block, which derives them from the sp
            # rank — an externally supplied offset would be silently ignored
            raise ValueError(
                "Llama forward does not take seq_offset (RoPE positions derive "
                "from the sp shard); use prefill/decode_step for offset decoding"
            )
        return super()._hidden_spmd(
            params, tokens, tp_axis, sp_axis, attn_impl, None, pp_axis, n_micro
        )

    # ---- serving hooks (KV cache holds kv heads only — the GQA memory win) ----
    # prefill/decode_step themselves are inherited: the base loops call these.

    def init_cache(self, batch: int, tp_size: int = 1) -> list:
        cfg = self.config
        if cfg.n_kv_head % tp_size:
            raise ValueError(f"n_kv_head={cfg.n_kv_head} not divisible by tp={tp_size}")
        return [
            self._cache_entry(batch, cfg.n_kv_head // tp_size)
            for _ in range(cfg.n_layer)
        ]

    def _norm1(self, layer, h):
        return _rms_norm(h, layer["rms_1"]["scale"], self.config.rms_eps)

    def _attn_out_bias(self, layer):
        return 0.0

    def _serving_qkv(self, layer, x, positions, tp_size):
        """Thin wrapper over :meth:`_qkv_gqa` (one copy of the GQA math):
        cache forms keep the kv heads, attention forms repeat them."""
        cfg = self.config
        return self._qkv_gqa(
            layer, x, cfg.n_head // tp_size, cfg.n_kv_head // tp_size, positions
        )

    def _decode_attention(self, q, ck, cv, valid, k_s=None, v_s=None):
        """Grouped-query attention against the kv-head cache — query heads
        grouped over their kv head, no materialized repeat; scores
        accumulate f32 via preferred_element_type (no full-cache upcast
        copies on the decode hot path). ``valid`` is [S] (shared depth) or
        [b, S] (per-slot depth, continuous batching); ``k_s``/``v_s``
        [b, kv, S, 1] are the int8 cache's per-position scales, folded in
        after each dot so the dequantize never materializes a full-width
        cache copy (see ``GPT2._cache_attn_inputs``)."""
        b, hq, s, hd = q.shape
        repeat = hq // ck.shape[1]
        qg = q.reshape(b, hq // repeat, repeat, s, hd)
        if k_s is not None:
            # quantized branch upcasts BOTH q·k operands to f32, matching
            # GPT2._decode_attention exactly — the two families' kv_quant
            # feature must apply identical precision (int8 magnitudes are
            # exact in bf16, but the q operand's rounding would differ)
            qg = qg.astype(jnp.float32)
            ck = ck.astype(jnp.float32)
        scores = jnp.einsum(
            "bgrqd,bgkd->bgrqk", qg, ck,
            preferred_element_type=jnp.float32,
        ) * (hd ** -0.5)
        if k_s is not None:
            # [b, kv, S, 1] → [b, kv, 1, 1, S]: per-key-position scale
            scores = scores * jnp.swapaxes(k_s, -1, -2)[:, :, None]
        if valid.ndim == 1:  # [S] shared depth
            vmask = valid[None, None, None, None, :]
        elif valid.ndim == 2:  # [b, S] per-slot depth
            vmask = valid[:, None, None, None, :]
        else:  # [b, q, S] multi-query (chunked prefill)
            vmask = valid[:, None, None, :, :]
        scores = jnp.where(vmask, scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        if v_s is not None:
            probs = probs * jnp.swapaxes(v_s, -1, -2)[:, :, None]
            cv = cv.astype(jnp.float32)
        else:
            probs = probs.astype(cv.dtype)
        # bf16 inputs feed the MXU at full rate; f32 accumulation keeps the
        # long-context value sum from drifting (same precision as the scores)
        out = jnp.einsum("bgrqk,bgkd->bgrqd", probs, cv,
                         preferred_element_type=jnp.float32)
        return out.reshape(b, hq, s, hd).astype(q.dtype)
