"""L1 — per-chip device runtime: HBM buffer registry, streams, compute.

TPU-native rebuild of the reference's simulated GPU
(``DSML/gpu_device_service/gpu_device_server.go``): there, a "device" was a
``map[uint64][]byte`` plus a stream state machine and zero compute
(``:26-49``). Here every buffer written through ``Memcpy`` lands in the HBM
of a real ``jax.Device``, ``RunForward``/``RunBackward`` execute jitted XLA
programs on that device (the reference shipped these RPCs in its generated
stubs but never implemented them, SURVEY.md §8.9), and P2P streams actually
move bytes device-server→device-server (the reference's streams were a
same-device loopback, SURVEY.md §8.1).

Semantics preserved from the reference:
- flat address space ``[0x1000, 0x1000+memSize)`` with bounds-checked access
  (``gpu_device_server.go:45-47,195-230``);
- stream lifecycle IN_PROGRESS→SUCCESS/FAILED with received-length validation
  (``:112-181``);
- ``GetDeviceMetadata`` advertising the address range (``:51-62``).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from dsml_tpu.comm import rpc
from dsml_tpu.comm.proto import gpu_sim_pb2 as pb
from dsml_tpu.models.mlp import MLP
from dsml_tpu.obs import get_registry, span
from dsml_tpu.utils.config import env_float as _env_float
from dsml_tpu.utils.logging import get_logger

log = get_logger("device")

DEFAULT_MIN_ADDR = 0x1000  # the reference's base address (gpu_device_server.go:45)
_STREAM_CHUNK = 1 << 18  # 256 KiB per DataChunk

# Process-local registry: deviceId -> DeviceRuntime. Lets a colocated
# coordinator reach device buffers zero-copy instead of through its own
# socket (the reference ran all "devices" as goroutines of one process too,
# cmd/gpu_device_server/main.go:13-23).
_LOCAL_DEVICES: dict[int, "DeviceRuntime"] = {}
_LOCAL_LOCK = threading.Lock()


def local_device(device_id: int) -> "DeviceRuntime | None":
    with _LOCAL_LOCK:
        return _LOCAL_DEVICES.get(device_id)


class DeviceError(Exception):
    def __init__(self, code: grpc.StatusCode, message: str):
        super().__init__(message)
        self.code = code


@dataclass
class StreamState:
    """One P2P stream (reference StreamState, gpu_device_server.go:14-24)."""

    stream_id: int
    status: int = pb.IN_PROGRESS
    send_addr: int | None = None
    num_bytes: int = 0
    dst_rank: int | None = None
    src_rank: int | None = None
    recv_addr: int | None = None
    chunks: list[bytes] = field(default_factory=list)
    received: int = 0
    armed: bool = False  # BeginReceive seen
    sender_done: bool = False  # StreamSend finished delivering
    # lifecycle stamps (monotonic clock): terminal streams are TTL-evicted
    # from the table, and an armed stream making no progress past the stall
    # deadline is FAILED instead of staying IN_PROGRESS forever (the
    # dropped-StreamSend hole the migration path must not fall into)
    created_at: float = field(default_factory=time.monotonic)
    done_at: float | None = None
    last_progress: float = field(default_factory=time.monotonic)
    fail_reason: str = ""


class BufferRegistry:
    """Address-keyed device buffers. Each entry is a uint8 ``jax.Array``
    resident on ``device`` (HBM on TPU)."""

    def __init__(self, device: jax.Device, min_addr: int, mem_size: int):
        self.device = device
        self.min_addr = min_addr
        self.max_addr = min_addr + mem_size
        self._buffers: dict[int, jax.Array] = {}
        # logical payload size = bytes of the most recent write at an addr
        # (a short write splices into a larger resident buffer, so the
        # physical array can be bigger than the current payload)
        self._last_write: dict[int, int] = {}
        self._lock = threading.Lock()

    def check_bounds(self, addr: int, num_bytes: int = 0) -> None:
        if addr < self.min_addr or addr + num_bytes > self.max_addr:
            raise DeviceError(
                grpc.StatusCode.OUT_OF_RANGE,
                f"address range [{addr:#x}, {addr + num_bytes:#x}) outside "
                f"device memory [{self.min_addr:#x}, {self.max_addr:#x})",
            )

    def write(self, addr: int, data: bytes | np.ndarray) -> None:
        data = np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray, memoryview)) else data
        self.check_bounds(addr, data.nbytes)
        nbytes_in = data.nbytes
        with self._lock:
            existing = self._buffers.get(addr)
            if existing is not None and existing.nbytes > data.nbytes:
                # Partial write into a larger resident buffer: splice into the
                # prefix, keep the tail (a plain replace would truncate it).
                host = np.asarray(jax.device_get(existing)).view(np.uint8).reshape(-1).copy()
                host[: data.nbytes] = data
                data = host
            self._buffers[addr] = jax.device_put(data, self.device)
            self._last_write[addr] = nbytes_in

    def put_array(self, addr: int, arr: jax.Array, logical_nbytes: int | None = None) -> None:
        """Store an already-on-device array (zero-copy path for collectives).

        ``logical_nbytes`` records a payload size smaller than the physical
        array — the collective fast path splices a reduced prefix into a
        larger resident buffer on device, mirroring :meth:`write`'s splice
        semantics (which set the logical size to the bytes written)."""
        self.check_bounds(addr, arr.nbytes)
        with self._lock:
            self._buffers[addr] = arr
            self._last_write[addr] = logical_nbytes if logical_nbytes is not None else arr.nbytes

    def logical_nbytes(self, addr: int) -> int:
        """Size of the most recent payload written at ``addr`` (≤ physical)."""
        with self._lock:
            if addr not in self._buffers:
                raise DeviceError(grpc.StatusCode.NOT_FOUND, f"no buffer at address {addr:#x}")
            return self._last_write.get(addr, self._buffers[addr].nbytes)

    def get_logical_array(self, addr: int) -> jax.Array:
        """The current payload at ``addr``: the resident array sliced to the
        most recent write's length, read under ONE lock acquisition (a
        concurrent rewrite between a size query and an array fetch must not
        mix the two)."""
        with self._lock:
            arr = self._buffers.get(addr)
            if arr is None:
                raise DeviceError(grpc.StatusCode.NOT_FOUND, f"no buffer at address {addr:#x}")
            nbytes = self._last_write.get(addr, arr.nbytes)
        return arr[:nbytes] if nbytes < arr.nbytes else arr

    def read(self, addr: int, num_bytes: int | None = None) -> np.ndarray:
        with self._lock:
            arr = self._buffers.get(addr)
        if arr is None:
            raise DeviceError(grpc.StatusCode.NOT_FOUND, f"no buffer at address {addr:#x}")
        host = np.asarray(jax.device_get(arr)).view(np.uint8).reshape(-1)
        if num_bytes is None:
            return host
        if num_bytes > host.nbytes:
            raise DeviceError(
                grpc.StatusCode.OUT_OF_RANGE,
                f"requested {num_bytes} bytes from {host.nbytes}-byte buffer at {addr:#x}",
            )
        return host[:num_bytes]

    def get_array(self, addr: int) -> jax.Array:
        with self._lock:
            arr = self._buffers.get(addr)
        if arr is None:
            raise DeviceError(grpc.StatusCode.NOT_FOUND, f"no buffer at address {addr:#x}")
        return arr

    def nbytes(self, addr: int) -> int:
        return self.get_array(addr).nbytes


class DeviceRuntime:
    """The device logic, directly callable (the reference's white-box unit
    tests call server methods the same way, gpu_device_server_test.go)."""

    def __init__(
        self,
        device_id: int,
        mem_size: int = 0x3000,
        jax_device: jax.Device | None = None,
        min_addr: int = DEFAULT_MIN_ADDR,
        model: MLP | None = None,
        weights_addr: int = 0x2000,
    ):
        self.device_id = device_id
        self.jax_device = jax_device if jax_device is not None else jax.devices()[0]
        self.memory = BufferRegistry(self.jax_device, min_addr, mem_size)
        self.streams: dict[int, StreamState] = {}
        self._stream_lock = threading.Lock()
        # Stream ids are sender-namespaced (device_id << 32 | counter), but
        # a RESTARTED sender process would reset its counter to 1 and reuse
        # ids a long-lived receiver still holds as terminal entries — the
        # receiver would then "complete" the new stream with stale state.
        # A random counter origin makes cross-restart collisions
        # vanishingly unlikely.
        self._next_stream = int.from_bytes(os.urandom(4), "little") % (1 << 31) or 1
        self.peers: dict[int, str] = {}
        self.self_rank: int | None = None
        self._peer_stubs: dict[int, rpc._Stub] = {}
        self._peer_lock = threading.Lock()
        # On-device compute: flat-f32 MLP programs (RunForward/RunBackward).
        self.model = model or MLP()
        self.weights_addr = weights_addr
        self._last_input: jax.Array | None = None
        self.bound_address: str | None = None  # set by serve_device once bound
        self.donor = None  # StateDonor, attached by serve_device
        with _LOCAL_LOCK:
            _LOCAL_DEVICES[device_id] = self

    # ---- metadata -------------------------------------------------------------

    def metadata(self) -> pb.DeviceMetadata:
        return pb.DeviceMetadata(
            deviceId=pb.DeviceId(value=self.device_id),
            minMemAddr=pb.MemAddr(value=self.memory.min_addr),
            maxMemAddr=pb.MemAddr(value=self.memory.max_addr),
        )

    # ---- memcpy ---------------------------------------------------------------

    def memcpy_h2d(self, addr: int, data: bytes) -> None:
        self.memory.write(addr, data)

    def memcpy_d2h(self, addr: int, num_bytes: int) -> bytes:
        return self.read_bytes(addr, num_bytes)

    def read_bytes(self, addr: int, num_bytes: int | None = None) -> bytes:
        return self.memory.read(addr, num_bytes).tobytes()

    # ---- streams --------------------------------------------------------------

    def begin_send(self, send_addr: int, num_bytes: int, dst_rank: int) -> int:
        self.memory.check_bounds(send_addr, num_bytes)
        with self._stream_lock:
            # Globally unique id (sender-namespaced): two devices' concurrent
            # sends to the same receiver must not collide in its stream table.
            stream_id = (self.device_id << 32) | self._next_stream
            self._next_stream += 1
            self.streams[stream_id] = StreamState(
                stream_id, send_addr=send_addr, num_bytes=num_bytes, dst_rank=dst_rank
            )
            self._update_stream_gauge_locked()
        # Push the payload to the destination in the background, as the proto
        # intends ("the actual data transfer should happen in the background
        # initiated by the devices", gpu_sim.proto) — the reference never
        # implemented the cross-device leg (SURVEY.md §8.1).
        threading.Thread(target=self._push_stream, args=(stream_id,), daemon=True).start()
        return stream_id

    def begin_receive(self, stream_id: int, recv_addr: int, num_bytes: int, src_rank: int) -> None:
        self.memory.check_bounds(recv_addr, num_bytes)
        with self._stream_lock:
            st = self.streams.get(stream_id)
            if st is None or st.status != pb.IN_PROGRESS:
                # arming a TERMINAL id means the sender recycled it (e.g. a
                # restarted peer): this is a NEW stream, not a re-arm of the
                # finished one — a fresh state, never stale bytes
                st = self.streams[stream_id] = StreamState(stream_id)
            st.recv_addr = recv_addr
            st.num_bytes = num_bytes
            st.src_rank = src_rank
            st.armed = True
            st.last_progress = time.monotonic()
            self._maybe_complete_locked(st)
            self._update_stream_gauge_locked()

    def receive_chunks(self, chunk_iter) -> bool:
        """StreamSend handler: accumulate chunks; complete when the armed
        length arrives (length validation as gpu_device_server.go:165-179)."""
        stream_id = None
        seen: set = set()
        for chunk in chunk_iter:
            with self._stream_lock:
                st = self.streams.get(chunk.streamId)
                if st is None or (st.status != pb.IN_PROGRESS
                                  and chunk.streamId not in seen):
                    # FIRST chunk of a stream whose id maps to a TERMINAL
                    # entry: a restarted sender recycled the id (same case
                    # begin_receive handles) — this is a NEW stream; a
                    # stale SUCCESS entry must not swallow its payload and
                    # report delivery that never landed. A stream that
                    # goes terminal MID-call (harvest/stall) keeps its
                    # entry: those chunks are the old stream's stragglers.
                    st = self.streams[chunk.streamId] = StreamState(chunk.streamId)
                seen.add(chunk.streamId)
                stream_id = chunk.streamId
                st.chunks.append(chunk.data)
                st.received += len(chunk.data)
                st.last_progress = time.monotonic()
        if stream_id is None:
            return False
        with self._stream_lock:
            st = self.streams[stream_id]
            st.sender_done = True
            ok = self._maybe_complete_locked(st, final=True)
        # GC on the RECEIVE path too: a receive-only server (exactly what a
        # migration receiver is) never pushes, so _push_stream's GC call
        # would never run for it and its terminal entries would accumulate
        self._gc_streams()
        return ok

    def _maybe_complete_locked(self, st: StreamState, final: bool = False) -> bool:
        if st.status != pb.IN_PROGRESS:
            # terminal is terminal: a LATE full delivery on a stream already
            # failed (stall verdict, take_partial harvest) must not write to
            # recv_addr — the migrator may have re-armed the same landing
            # address for its next piece, and a stale write there would
            # clobber it between completion and read-back
            return st.status == pb.SUCCESS
        if not st.armed or st.recv_addr is None:
            return True  # waiting for BeginReceive; chunks stay buffered
        # a late BeginReceive must still see that the sender already finished
        # (otherwise an under-delivered stream would stay IN_PROGRESS forever)
        final = final or st.sender_done
        if st.received == st.num_bytes and st.num_bytes > 0:
            data = b"".join(st.chunks)
            st.chunks = []  # payload now lives in the registry; don't retain it
            try:
                self.memory.write(st.recv_addr, data)
            except DeviceError as e:
                self._finish_locked(st, pb.FAILED, f"recv write failed: {e}")
                return False
            self._finish_locked(st, pb.SUCCESS)
            return True
        if final or st.received > st.num_bytes:
            self._finish_locked(
                st, pb.FAILED,
                f"length mismatch: received {st.received} of {st.num_bytes}",
            )
            return False
        return True

    def _finish_locked(self, st: StreamState, status: int, reason: str = "") -> None:
        """Terminal transition (idempotent): stamp ``done_at`` so the TTL GC
        can reap the entry, count failures, refresh the active gauge."""
        if st.status != pb.IN_PROGRESS:
            return  # already terminal — a late writer must not double-count
        st.status = status
        st.done_at = time.monotonic()
        if status == pb.FAILED:
            st.fail_reason = reason
            reg = get_registry()
            if reg.enabled:
                reg.counter(
                    "comm_stream_failures_total",
                    "P2P streams that ended FAILED", labels=("device",),
                ).inc(device=self.device_id)
            if reason:
                log.warning("device %d: stream %d FAILED: %s",
                            self.device_id, st.stream_id, reason)
        self._update_stream_gauge_locked()

    def _update_stream_gauge_locked(self) -> None:
        reg = get_registry()
        if reg.enabled:
            reg.gauge(
                "comm_streams_active",
                "P2P streams not yet terminal", labels=("device",),
            ).set(
                sum(1 for s in self.streams.values() if s.status == pb.IN_PROGRESS),
                device=self.device_id,
            )

    def stream_status(self, stream_id: int) -> int:
        stall_s = _env_float("DSML_STREAM_STALL_S", 120.0)
        with self._stream_lock:
            st = self.streams.get(stream_id)
            if st is None:
                raise DeviceError(grpc.StatusCode.NOT_FOUND, f"unknown stream {stream_id}")
            # stall detection at the query point: a dropped StreamSend used
            # to leave an armed receiver IN_PROGRESS forever — a stream with
            # no progress past the deadline is now a FAILED verdict the
            # poller can act on (retry / resume from the partial prefix)
            if (
                st.status == pb.IN_PROGRESS
                and stall_s > 0
                and time.monotonic() - st.last_progress > stall_s
            ):
                self._finish_locked(
                    st, pb.FAILED,
                    f"stalled: no progress in {stall_s:.0f}s "
                    f"({st.received}/{st.num_bytes} bytes)",
                )
            return st.status

    def take_partial(self, stream_id: int) -> bytes:
        """Harvest the contiguous prefix a dead/stalled stream delivered and
        mark the stream FAILED — the resumable-offset hook: the migration
        layer re-requests the remainder from ``len(prefix)`` instead of
        re-shipping bytes that already arrived."""
        with self._stream_lock:
            st = self.streams.get(stream_id)
            if st is None:
                raise DeviceError(grpc.StatusCode.NOT_FOUND, f"unknown stream {stream_id}")
            prefix = b"".join(st.chunks)
            st.chunks = []
            st.received = 0
            self._finish_locked(st, pb.FAILED, "partial prefix harvested for resume")
            return prefix

    # ---- peer table + background push ------------------------------------------

    def configure_peers(self, peers: dict[int, str], self_rank: int) -> None:
        with self._peer_lock:
            self.peers = dict(peers)
            self.self_rank = self_rank
            self._peer_stubs.clear()

    def _peer_stub(self, rank: int) -> rpc._Stub:
        with self._peer_lock:
            stub = self._peer_stubs.get(rank)
            if stub is None:
                addr = self.peers.get(rank)
                if addr is None:
                    raise DeviceError(grpc.StatusCode.FAILED_PRECONDITION, f"no peer address for rank {rank}")
                stub = rpc.device_stub(grpc.insecure_channel(addr))
                self._peer_stubs[rank] = stub
            return stub

    def _push_stream(self, stream_id: int) -> None:
        with self._stream_lock:
            st = self.streams[stream_id]
            send_addr, num_bytes, dst_rank = st.send_addr, st.num_bytes, st.dst_rank
        try:
            payload = self.read_bytes(send_addr, num_bytes)
            if dst_rank is not None and dst_rank == self.self_rank:
                # Local delivery (a rank sending to itself): the sender's
                # StreamState IS the receiver's, so only _maybe_complete may
                # set its status — if BeginReceive hasn't armed it yet the
                # chunks stay buffered and status stays IN_PROGRESS.
                with self._stream_lock:
                    st = self.streams[stream_id]
                    st.chunks.append(payload)
                    st.received += len(payload)
                    st.sender_done = True  # a late mismatched arm must FAIL, not hang
                    self._maybe_complete_locked(st, final=True)
            else:
                # wire-fault injection (chaos harness): the plan may corrupt
                # the payload, delay the push, truncate the stream mid-send
                # (drop), or sever the link entirely (partition) — how the
                # migration path's CRC / timeout / resume story is proven
                # under fault instead of asserted (runtime.chaos.WireFaultPlan)
                fault = None
                from dsml_tpu.runtime import chaos as _chaos

                plan = _chaos.wire_fault_plan()
                if plan is not None:
                    fault = plan.on_send(self.self_rank, dst_rank)
                if fault is not None:
                    payload = fault.apply_payload(payload)
                    if fault.action == "partition":
                        raise RuntimeError(
                            f"wire fault: link to rank {dst_rank} partitioned"
                        )
                stub = self._peer_stub(dst_rank)

                def chunks():
                    if fault is not None and fault.action == "drop":
                        # truncate MID-STREAM: deliver half the payload, then
                        # error the call — the receiver keeps the prefix (the
                        # resume path's raw material), the sender records FAILED.
                        # The prefix ships in normal-size chunks (one oversized
                        # message would hit grpc's 4 MiB cap on big pieces and
                        # deliver NOTHING, silently skipping the resume path);
                        # the sleep lets grpc's sender thread flush before the
                        # cancel, so the prefix actually lands.
                        cut = max(1, len(payload) // 2)
                        for off in range(0, cut, _STREAM_CHUNK):
                            yield pb.DataChunk(data=payload[off : off + _STREAM_CHUNK],
                                               streamId=stream_id)
                        time.sleep(0.05)
                        raise RuntimeError("wire fault: stream dropped")
                    for off in range(0, len(payload), _STREAM_CHUNK):
                        # progress heartbeat: the stall verdict reads
                        # last_progress, and a sender mid-push is NOT stalled
                        # — without this a long multi-GB push would be
                        # falsely FAILED at DSML_STREAM_STALL_S off its
                        # creation timestamp
                        with self._stream_lock:
                            sending = self.streams.get(stream_id)
                            if sending is not None:
                                sending.last_progress = time.monotonic()
                        yield pb.DataChunk(data=payload[off : off + _STREAM_CHUNK], streamId=stream_id)

                ok = stub.StreamSend(chunks()).success
                with self._stream_lock:
                    self._finish_locked(
                        self.streams[stream_id],
                        pb.SUCCESS if ok else pb.FAILED,
                        "" if ok else "receiver reported failure",
                    )
        except Exception as e:  # noqa: BLE001 — background thread must record failure
            log.warning("device %d: stream %d push failed: %s", self.device_id, stream_id, e)
            with self._stream_lock:
                self._finish_locked(self.streams[stream_id], pb.FAILED, f"push failed: {e}")
        self._gc_streams()

    _MAX_STREAMS = 4096

    def _gc_streams(self) -> None:
        """Stream-table hygiene: terminal streams are evicted after a TTL
        (``DSML_STREAM_TTL_S``, default 300 s) — completed/FAILED entries
        used to accumulate for the life of the process — with the size cap
        kept as a backstop for pathological churn inside one TTL window."""
        ttl_s = _env_float("DSML_STREAM_TTL_S", 300.0)
        now = time.monotonic()
        with self._stream_lock:
            expired = [
                sid for sid, s in self.streams.items()
                if s.done_at is not None and now - s.done_at > ttl_s
            ]
            for sid in expired:
                del self.streams[sid]
            if len(self.streams) > self._MAX_STREAMS:
                for sid in [s.stream_id for s in self.streams.values()
                            if s.status != pb.IN_PROGRESS]:
                    del self.streams[sid]
                    if len(self.streams) <= self._MAX_STREAMS // 2:
                        break
            self._update_stream_gauge_locked()

    # ---- on-device compute ------------------------------------------------------

    def _flat_params(self) -> jax.Array:
        raw = self.memory.get_logical_array(self.weights_addr)
        if raw.nbytes != self.model.n_params * 4:
            raise DeviceError(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"weights buffer at {self.weights_addr:#x} has {raw.nbytes} bytes; "
                f"model needs {self.model.n_params * 4}",
            )
        return jax.lax.bitcast_convert_type(raw.reshape(-1, 4), jnp.float32).reshape(-1)

    def run_forward(self, input_addr: int, output_addr: int) -> int:
        """Jitted forward on this chip: f32 batch at ``input_addr`` →
        logits written to ``output_addr``. Returns output byte count."""
        raw = self.memory.get_logical_array(input_addr)
        in_features = self.model.sizes[0]
        if raw.nbytes % (4 * in_features) != 0:
            raise DeviceError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"input buffer ({raw.nbytes} B) is not a multiple of a {in_features}-feature f32 row",
            )
        x = jax.lax.bitcast_convert_type(raw.reshape(-1, 4), jnp.float32).reshape(-1, in_features)
        logits = self.model.forward_flat(self._flat_params(), x)
        out_u8 = jax.lax.bitcast_convert_type(logits, jnp.uint8).reshape(-1)
        self.memory.put_array(output_addr, out_u8)
        self._last_input = x
        return int(out_u8.nbytes)

    def run_backward(self, gradient_addr: int) -> None:
        """Jitted backward: reads upstream dL/dlogits (f32 [batch, n_out])
        at ``gradient_addr``, backprops through the last ``run_forward``
        batch, and overwrites ``gradient_addr`` with flat param grads."""
        if self._last_input is None:
            raise DeviceError(grpc.StatusCode.FAILED_PRECONDITION, "run_forward must precede run_backward")
        raw = self.memory.get_logical_array(gradient_addr)
        n_out = self.model.sizes[-1]
        expected = self._last_input.shape[0] * n_out * 4
        if raw.nbytes != expected:
            raise DeviceError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"gradient buffer has {raw.nbytes} bytes; expected {expected} "
                f"(batch {self._last_input.shape[0]} × {n_out} f32)",
            )
        dlogits = jax.lax.bitcast_convert_type(raw.reshape(-1, 4), jnp.float32).reshape(-1, n_out)
        grads = self.model.backward_flat(self._flat_params(), self._last_input, dlogits)
        self.memory.put_array(gradient_addr, jax.lax.bitcast_convert_type(grads, jnp.uint8).reshape(-1))


# ---------------------------------------------------------------------------
# gRPC servicer + process bootstrap
# ---------------------------------------------------------------------------


class DeviceServicer:
    """Wire adapter: DeviceRuntime ⇄ gpu_sim.GPUDevice."""

    def __init__(self, runtime: DeviceRuntime):
        self.rt = runtime

    def _abort(self, context, err: DeviceError):
        context.abort(err.code, str(err))

    def GetDeviceMetadata(self, request, context):  # noqa: N802 (RPC names)
        return pb.GetDeviceMetadataResponse(metadata=self.rt.metadata())

    def BeginSend(self, request, context):  # noqa: N802
        try:
            sid = self.rt.begin_send(request.sendBuffAddr.value, request.numBytes, request.dstRank.value)
        except DeviceError as e:
            self._abort(context, e)
        return pb.BeginSendResponse(initiated=True, streamId=pb.StreamId(value=sid))

    def BeginReceive(self, request, context):  # noqa: N802
        try:
            self.rt.begin_receive(
                request.streamId.value, request.recvBuffAddr.value, request.numBytes, request.srcRank.value
            )
        except DeviceError as e:
            self._abort(context, e)
        return pb.BeginReceiveResponse(initiated=True)

    def StreamSend(self, request_iterator, context):  # noqa: N802
        return pb.StreamSendResponse(success=self.rt.receive_chunks(request_iterator))

    def GetStreamStatus(self, request, context):  # noqa: N802
        try:
            status = self.rt.stream_status(request.streamId.value)
        except DeviceError as e:
            self._abort(context, e)
        return pb.GetStreamStatusResponse(status=status)

    def Memcpy(self, request, context):  # noqa: N802
        # device-side execution span: in the STITCHED cluster timeline this
        # lane shows what the device actually did inside the coordinator's
        # wire_op span (clock-offset-aligned, docs/OBSERVABILITY.md § Cluster)
        try:
            if request.HasField("hostToDevice"):
                h2d = request.hostToDevice
                with span("device_memcpy", direction="h2d",
                          device=self.rt.device_id):
                    self.rt.memcpy_h2d(h2d.dstMemAddr.value, h2d.hostSrcData)
                return pb.MemcpyResponse(hostToDevice=pb.MemcpyHostToDeviceResponse(success=True))
            d2h = request.deviceToHost
            with span("device_memcpy", direction="d2h",
                      device=self.rt.device_id):
                data = self.rt.memcpy_d2h(d2h.srcMemAddr.value, d2h.numBytes or None)
            return pb.MemcpyResponse(deviceToHost=pb.MemcpyDeviceToHostResponse(dstData=data))
        except DeviceError as e:
            self._abort(context, e)

    def ConfigurePeers(self, request, context):  # noqa: N802
        self.rt.configure_peers(dict(request.peerAddresses), request.selfRank)
        return pb.ConfigurePeersResponse(success=True)

    def RunForward(self, request, context):  # noqa: N802
        try:
            with span("device_forward", device=self.rt.device_id):
                n = self.rt.run_forward(request.inputAddr.value, request.outputAddr.value)
        except DeviceError as e:
            self._abort(context, e)
        return pb.RunForwardResponse(success=True, outputBytes=n)

    def RunBackward(self, request, context):  # noqa: N802
        try:
            with span("device_backward", device=self.rt.device_id):
                self.rt.run_backward(request.gradientAddr.value)
        except DeviceError as e:
            self._abort(context, e)
        return pb.RunBackwardResponse(success=True)


@dataclass
class DeviceServerHandle:
    runtime: DeviceRuntime
    server: grpc.Server
    address: str

    def stop(self, grace: float = 0.2) -> None:
        self.server.stop(grace)


def serve_device(
    device_id: int,
    port: int = 0,
    mem_size: int = 0x3000,
    jax_device: jax.Device | None = None,
    host: str = "127.0.0.1",
    model: MLP | None = None,
) -> DeviceServerHandle:
    """Boot one GPUDevice server (ephemeral port when ``port=0``)."""
    runtime = DeviceRuntime(device_id, mem_size=mem_size, jax_device=jax_device, model=model)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
    rpc.add_device_servicer(DeviceServicer(runtime), server)
    # cluster obs plane on the SAME port: the aggregator pulls this
    # process's registry/trace snapshot over the channel it already has
    from dsml_tpu.obs.cluster import ObsServicer, current_role

    rpc.add_obs_servicer(ObsServicer(current_role("device_server")), server)
    # shard-migration plane (same port): this host serves pieces of
    # whatever state its StateDonor registers (runtime.donor) — the elastic
    # cross-host recovery path (comm/migration.py, docs/ELASTIC.md)
    from dsml_tpu.comm.migration import MigrationServicer, StateDonor

    runtime.donor = StateDonor(runtime)
    rpc.add_migration_servicer(MigrationServicer(runtime.donor), server)
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    runtime.bound_address = f"{host}:{bound}"
    return DeviceServerHandle(runtime, server, runtime.bound_address)


def serve_local_devices(
    n: int,
    base_device_id: int = 1,
    mem_size: int = 0x3000,
    ports: list[int] | None = None,
    model: MLP | None = None,
) -> list[DeviceServerHandle]:
    """Boot n device servers in this process, one per local ``jax.Device``
    (round-robin if n exceeds the device count) — the shape of the
    reference's launcher, which ran 3 simulated devices as goroutines
    (cmd/gpu_device_server/main.go:13-23), except each server here fronts
    real accelerator memory."""
    devs = jax.devices()
    handles = []
    for i in range(n):
        handles.append(
            serve_device(
                base_device_id + i,
                port=(ports[i] if ports else 0),
                mem_size=mem_size,
                jax_device=devs[i % len(devs)],
                model=model,
            )
        )
    return handles
