"""L4 — training-client library for the gpu_sim wire API.

Python counterpart of the reference's Go client helpers
(``DSML/client/client.go``): connect to coordinator + devices (``:504-514``),
CommInit (``:532-539``), float32↔bytes codecs (``:60-74``), weight/gradient
shipping (``:204-252``), and the AllReduceRing call (``:622-628``) — plus the
on-device compute path (RunForward/RunBackward) the reference only stubbed.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass

import grpc
import numpy as np

from dsml_tpu.comm import rpc
from dsml_tpu.comm.proto import gpu_sim_pb2 as pb

GRAD_ADDR = 0x1000  # conventional addresses, as in client.go:29-30
WEIGHTS_ADDR = 0x2000

# transient control-plane failures worth retrying: the server is restarting
# / the channel flaked (UNAVAILABLE) or one probe window was missed
# (DEADLINE_EXCEEDED). Everything else — NOT_FOUND, INVALID_ARGUMENT,
# FAILED_PRECONDITION — is a REAL answer and retrying it only hides bugs.
TRANSIENT_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


def _rpc_code(e: grpc.RpcError):
    code = getattr(e, "code", None)
    return code() if callable(code) else None


def call_with_retries(op: str, fn, retries: int | None = None,
                      base_s: float = 0.05, cap_s: float = 2.0,
                      rng=random.random, sleep=time.sleep):
    """Run ``fn()`` with bounded exponential backoff + jitter on transient
    gRPC codes (:data:`TRANSIENT_CODES`); anything else raises immediately.

    The RPCs this wraps — control plane (CommInit / GetCommStatus /
    membership refresh) AND the data-plane arm ops (BeginSend /
    BeginReceive / GetStreamStatus, plus the migration plane's
    PlanPieces / BeginMigration) — are exactly the calls a preemption
    storm flakes:
    failing a whole training job on one UNAVAILABLE while the coordinator
    restarts is the reference's brittleness, not a contract. Retries are
    BOUNDED (default 4, ``DSML_COMM_RETRIES``) and jittered (0.5–1.5× the
    exponential delay) so a thundering herd of recovering clients doesn't
    re-flatten the coordinator it is waiting for. Every retry counts into
    ``comm_retry_total{op}``."""
    if retries is None:
        try:
            retries = int(os.environ.get("DSML_COMM_RETRIES", 4))
        except ValueError:
            retries = 4
    attempt = 0
    while True:
        try:
            return fn()
        except grpc.RpcError as e:
            if _rpc_code(e) not in TRANSIENT_CODES or attempt >= retries:
                raise
            from dsml_tpu.obs import get_registry

            get_registry().counter(
                "comm_retry_total",
                "transient control-plane RPC retries", labels=("op",),
            ).inc(op=op)
            delay = min(cap_s, base_s * (2 ** attempt)) * (0.5 + rng())
            sleep(delay)
            attempt += 1


def f32_to_bytes(x: np.ndarray) -> bytes:
    return np.ascontiguousarray(x, dtype=np.float32).tobytes()


def bytes_to_f32(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=np.float32).copy()


@dataclass
class PipelineClient:
    """Handle on one coordinator + its communicator's devices."""

    coordinator: rpc._Stub
    devices: list[rpc._Stub]
    comm_id: int
    device_ids: list[int]
    addresses: list[str] | None = None

    @classmethod
    def connect(
        cls, coordinator_addr: str, device_addrs: list[str], timeout: float = 5.0
    ) -> "PipelineClient":
        coord = rpc.coordinator_stub(grpc.insecure_channel(coordinator_addr))
        resp = call_with_retries(
            "CommInit",
            lambda: coord.CommInit(
                pb.CommInitRequest(
                    numDevices=len(device_addrs), device_addresses=device_addrs
                ),
                timeout=timeout,
            ),
        )
        devices = [rpc.device_stub(grpc.insecure_channel(a)) for a in device_addrs]
        return cls(
            coord, devices, resp.commId,
            [m.deviceId.value for m in resp.devices], list(device_addrs),
        )

    def refresh_membership(self, timeout: float = 5.0, expect_change: bool = False) -> int:
        """Re-resolve rank→device from the coordinator's CURRENT view.

        After elastic recovery renumbers survivors, the client's per-rank
        stubs/ids from CommInit are stale (SURVEY.md §5.3 had no recovery at
        all; VERDICT r1 flagged the stale-client half). GetCommStatus's
        additive ``members`` extension carries (rank, deviceId, address);
        rebuild the stub table in rank order, reusing live channels by
        address (closing replaced ones). Returns the new communicator size.

        Polls past two windows: while the comm reports FAILED the old table
        may still be installed (recovery drains in-flight collectives before
        renumbering) — a comm still FAILED at the deadline raises rather
        than silently keeping stale ranks. And with ``expect_change=True``
        (use after a per-rank RPC error), also poll until the membership
        actually DIFFERS from the client's current table — the coordinator's
        health probe may simply not have noticed the failure yet."""
        # addresses may be unknown (directly-constructed client): fall back
        # to device-id comparison so expect_change still means something
        if self.addresses:
            current = list(zip(self.device_ids, self.addresses))
        else:
            current = list(self.device_ids)
        deadline = time.monotonic() + timeout
        while True:
            # retries=1 here: the surrounding poll loop IS the retry
            # mechanism, bounded by `deadline` — the full default budget
            # would let one wedged-coordinator iteration block ~5× the
            # caller's timeout before the outer deadline is even checked
            resp = call_with_retries(
                "GetCommStatus",
                lambda: self.coordinator.GetCommStatus(
                    pb.GetCommStatusRequest(commId=self.comm_id), timeout=timeout
                ),
                retries=1,
            )
            ordered = sorted(resp.members, key=lambda m: m.rank)
            if self.addresses:
                fresh = [(m.deviceId.value, m.address) for m in ordered]
            else:
                fresh = [m.deviceId.value for m in ordered]
            if resp.status != pb.FAILED and not (expect_change and fresh == current):
                break
            if time.monotonic() >= deadline:
                if resp.status == pb.FAILED:
                    raise RuntimeError(
                        f"communicator {self.comm_id} still FAILED after {timeout}s; "
                        "membership not refreshed (re-CommInit required)"
                    )
                raise RuntimeError(
                    f"communicator {self.comm_id} membership unchanged after {timeout}s; "
                    "the coordinator has not (yet) observed the expected failure"
                )
            time.sleep(0.05)
        by_addr = dict(zip(self.addresses or [], self.devices))
        new_devices = [
            by_addr.get(m.address) or rpc.device_stub(grpc.insecure_channel(m.address))
            for m in ordered
        ]
        # channel hygiene (mirrors the coordinator's): close every old stub
        # that was NOT carried over — including the addresses-unknown case,
        # where nothing can be matched and ALL old channels are replaced
        reused = {id(s) for s in new_devices}
        for stub in self.devices:
            if id(stub) not in reused:
                channel = getattr(stub, "_channel", None)
                if channel is not None:
                    channel.close()
        self.devices = new_devices
        self.device_ids = [m.deviceId.value for m in ordered]
        self.addresses = [m.address for m in ordered]
        return len(ordered)

    # ---- per-device data movement ---------------------------------------------

    def write(self, rank: int, addr: int, data: bytes | np.ndarray) -> None:
        if isinstance(data, np.ndarray):
            data = f32_to_bytes(data)
        self.devices[rank].Memcpy(
            pb.MemcpyRequest(
                hostToDevice=pb.MemcpyHostToDeviceRequest(
                    hostSrcData=data,
                    dstDeviceId=pb.DeviceId(value=self.device_ids[rank]),
                    dstMemAddr=pb.MemAddr(value=addr),
                )
            )
        )

    def read(self, rank: int, addr: int, num_bytes: int) -> bytes:
        resp = self.devices[rank].Memcpy(
            pb.MemcpyRequest(
                deviceToHost=pb.MemcpyDeviceToHostRequest(
                    srcDeviceId=pb.DeviceId(value=self.device_ids[rank]),
                    srcMemAddr=pb.MemAddr(value=addr),
                    numBytes=num_bytes,
                )
            )
        )
        return resp.deviceToHost.dstData

    def broadcast_weights(self, weights: np.ndarray, addr: int = WEIGHTS_ADDR) -> None:
        """Ship one weight vector to every device (client.go:642-644)."""
        data = f32_to_bytes(weights)
        for rank in range(len(self.devices)):
            self.write(rank, addr, data)

    # ---- collectives -----------------------------------------------------------

    def all_reduce_ring(
        self,
        num_bytes: int,
        op: int = pb.SUM,
        mem_addrs: dict[int, int] | None = None,
        dtype: str = "",
        timeout: float = 120.0,
    ) -> None:
        req = pb.AllReduceRingRequest(commId=self.comm_id, count=num_bytes, op=op, dtype=dtype)
        for rank, addr in (mem_addrs or {}).items():
            req.memAddrs[rank].value = addr
        self.coordinator.AllReduceRing(req, timeout=timeout)

    def naive_all_reduce(self, data_size: int, latency_ms: int = 0, timeout: float = 120.0):
        return self.coordinator.NaiveAllReduce(
            pb.NaiveAllReduceRequest(commId=self.comm_id, dataSize=data_size, latencyMs=latency_ms),
            timeout=timeout,
        )

    def all_reduce_gradients(
        self, per_rank_grads: list[np.ndarray], op: int = pb.SUM, addr: int = GRAD_ADDR
    ) -> np.ndarray:
        """The training-loop step the reference faked (SURVEY.md §8.4): write
        each rank's gradient shard-sum, ring-reduce for real, read back the
        reduction."""
        n = len(self.devices)
        if n != len(per_rank_grads):
            raise ValueError(f"{len(per_rank_grads)} gradient arrays for {n} devices")
        nbytes = None
        for rank, g in enumerate(per_rank_grads):
            data = f32_to_bytes(g)
            nbytes = len(data) if nbytes is None else nbytes
            if len(data) != nbytes:
                raise ValueError("all ranks must contribute equal-size gradients")
            self.write(rank, addr, data)
        self.all_reduce_ring(nbytes, op=op, mem_addrs={r: addr for r in range(n)})
        return bytes_to_f32(self.read(0, addr, nbytes))

    # ---- P2P streams (data-plane arm RPCs, retried like control-plane) ----------

    def begin_send(self, rank: int, send_addr: int, num_bytes: int,
                   dst_rank: int, timeout: float = 5.0) -> int:
        """Arm a P2P send on ``rank``; returns the stream id. The arm RPCs
        are the data plane's CONTROL half — a transient flake here used to
        fail the whole transfer while CommInit-class ops retried; now all
        three (BeginSend / BeginReceive / GetStreamStatus) ride
        :func:`call_with_retries` with the same bounded jittered backoff."""
        resp = call_with_retries(
            "BeginSend",
            lambda: self.devices[rank].BeginSend(
                pb.BeginSendRequest(
                    sendBuffAddr=pb.MemAddr(value=send_addr),
                    numBytes=num_bytes,
                    dstRank=pb.Rank(value=dst_rank),
                ),
                timeout=timeout,
            ),
        )
        return resp.streamId.value

    def begin_receive(self, rank: int, stream_id: int, recv_addr: int,
                      num_bytes: int, src_rank: int, timeout: float = 5.0) -> None:
        call_with_retries(
            "BeginReceive",
            lambda: self.devices[rank].BeginReceive(
                pb.BeginReceiveRequest(
                    streamId=pb.StreamId(value=stream_id),
                    recvBuffAddr=pb.MemAddr(value=recv_addr),
                    numBytes=num_bytes,
                    srcRank=pb.Rank(value=src_rank),
                ),
                timeout=timeout,
            ),
        )

    def stream_status(self, rank: int, stream_id: int, timeout: float = 5.0) -> int:
        return call_with_retries(
            "GetStreamStatus",
            lambda: self.devices[rank].GetStreamStatus(
                pb.GetStreamStatusRequest(streamId=pb.StreamId(value=stream_id)),
                timeout=timeout,
            ),
        ).status

    # ---- on-device compute -----------------------------------------------------

    def run_forward(self, rank: int, input_addr: int, output_addr: int) -> int:
        resp = self.devices[rank].RunForward(
            pb.RunForwardRequest(
                deviceId=pb.DeviceId(value=self.device_ids[rank]),
                inputAddr=pb.MemAddr(value=input_addr),
                outputAddr=pb.MemAddr(value=output_addr),
            )
        )
        return resp.outputBytes

    def run_backward(self, rank: int, gradient_addr: int) -> None:
        self.devices[rank].RunBackward(
            pb.RunBackwardRequest(
                deviceId=pb.DeviceId(value=self.device_ids[rank]),
                gradientAddr=pb.MemAddr(value=gradient_addr),
            )
        )

    # ---- lifecycle --------------------------------------------------------------

    def status(self) -> int:
        return call_with_retries(
            "GetCommStatus",
            lambda: self.coordinator.GetCommStatus(
                pb.GetCommStatusRequest(commId=self.comm_id)
            ),
        ).status

    def destroy(self) -> None:
        self.coordinator.CommDestroy(pb.CommDestroyRequest(commId=self.comm_id))

    def finalize(self) -> None:
        self.coordinator.CommFinalize(pb.CommFinalizeRequest(commId=self.comm_id))
