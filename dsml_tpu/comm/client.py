"""L4 — training-client library for the gpu_sim wire API.

Python counterpart of the reference's Go client helpers
(``DSML/client/client.go``): connect to coordinator + devices (``:504-514``),
CommInit (``:532-539``), float32↔bytes codecs (``:60-74``), weight/gradient
shipping (``:204-252``), and the AllReduceRing call (``:622-628``) — plus the
on-device compute path (RunForward/RunBackward) the reference only stubbed.
"""

from __future__ import annotations

from dataclasses import dataclass

import grpc
import numpy as np

from dsml_tpu.comm import rpc
from dsml_tpu.comm.proto import gpu_sim_pb2 as pb

GRAD_ADDR = 0x1000  # conventional addresses, as in client.go:29-30
WEIGHTS_ADDR = 0x2000


def f32_to_bytes(x: np.ndarray) -> bytes:
    return np.ascontiguousarray(x, dtype=np.float32).tobytes()


def bytes_to_f32(b: bytes) -> np.ndarray:
    return np.frombuffer(b, dtype=np.float32).copy()


@dataclass
class PipelineClient:
    """Handle on one coordinator + its communicator's devices."""

    coordinator: rpc._Stub
    devices: list[rpc._Stub]
    comm_id: int
    device_ids: list[int]
    addresses: list[str] | None = None

    @classmethod
    def connect(
        cls, coordinator_addr: str, device_addrs: list[str], timeout: float = 5.0
    ) -> "PipelineClient":
        coord = rpc.coordinator_stub(grpc.insecure_channel(coordinator_addr))
        resp = coord.CommInit(
            pb.CommInitRequest(numDevices=len(device_addrs), device_addresses=device_addrs),
            timeout=timeout,
        )
        devices = [rpc.device_stub(grpc.insecure_channel(a)) for a in device_addrs]
        return cls(
            coord, devices, resp.commId,
            [m.deviceId.value for m in resp.devices], list(device_addrs),
        )

    def refresh_membership(self, timeout: float = 5.0, expect_change: bool = False) -> int:
        """Re-resolve rank→device from the coordinator's CURRENT view.

        After elastic recovery renumbers survivors, the client's per-rank
        stubs/ids from CommInit are stale (SURVEY.md §5.3 had no recovery at
        all; VERDICT r1 flagged the stale-client half). GetCommStatus's
        additive ``members`` extension carries (rank, deviceId, address);
        rebuild the stub table in rank order, reusing live channels by
        address (closing replaced ones). Returns the new communicator size.

        Polls past two windows: while the comm reports FAILED the old table
        may still be installed (recovery drains in-flight collectives before
        renumbering) — a comm still FAILED at the deadline raises rather
        than silently keeping stale ranks. And with ``expect_change=True``
        (use after a per-rank RPC error), also poll until the membership
        actually DIFFERS from the client's current table — the coordinator's
        health probe may simply not have noticed the failure yet."""
        import time

        # addresses may be unknown (directly-constructed client): fall back
        # to device-id comparison so expect_change still means something
        if self.addresses:
            current = list(zip(self.device_ids, self.addresses))
        else:
            current = list(self.device_ids)
        deadline = time.monotonic() + timeout
        while True:
            resp = self.coordinator.GetCommStatus(
                pb.GetCommStatusRequest(commId=self.comm_id), timeout=timeout
            )
            ordered = sorted(resp.members, key=lambda m: m.rank)
            if self.addresses:
                fresh = [(m.deviceId.value, m.address) for m in ordered]
            else:
                fresh = [m.deviceId.value for m in ordered]
            if resp.status != pb.FAILED and not (expect_change and fresh == current):
                break
            if time.monotonic() >= deadline:
                if resp.status == pb.FAILED:
                    raise RuntimeError(
                        f"communicator {self.comm_id} still FAILED after {timeout}s; "
                        "membership not refreshed (re-CommInit required)"
                    )
                raise RuntimeError(
                    f"communicator {self.comm_id} membership unchanged after {timeout}s; "
                    "the coordinator has not (yet) observed the expected failure"
                )
            time.sleep(0.05)
        by_addr = dict(zip(self.addresses or [], self.devices))
        new_devices = [
            by_addr.get(m.address) or rpc.device_stub(grpc.insecure_channel(m.address))
            for m in ordered
        ]
        # channel hygiene (mirrors the coordinator's): close every old stub
        # that was NOT carried over — including the addresses-unknown case,
        # where nothing can be matched and ALL old channels are replaced
        reused = {id(s) for s in new_devices}
        for stub in self.devices:
            if id(stub) not in reused:
                channel = getattr(stub, "_channel", None)
                if channel is not None:
                    channel.close()
        self.devices = new_devices
        self.device_ids = [m.deviceId.value for m in ordered]
        self.addresses = [m.address for m in ordered]
        return len(ordered)

    # ---- per-device data movement ---------------------------------------------

    def write(self, rank: int, addr: int, data: bytes | np.ndarray) -> None:
        if isinstance(data, np.ndarray):
            data = f32_to_bytes(data)
        self.devices[rank].Memcpy(
            pb.MemcpyRequest(
                hostToDevice=pb.MemcpyHostToDeviceRequest(
                    hostSrcData=data,
                    dstDeviceId=pb.DeviceId(value=self.device_ids[rank]),
                    dstMemAddr=pb.MemAddr(value=addr),
                )
            )
        )

    def read(self, rank: int, addr: int, num_bytes: int) -> bytes:
        resp = self.devices[rank].Memcpy(
            pb.MemcpyRequest(
                deviceToHost=pb.MemcpyDeviceToHostRequest(
                    srcDeviceId=pb.DeviceId(value=self.device_ids[rank]),
                    srcMemAddr=pb.MemAddr(value=addr),
                    numBytes=num_bytes,
                )
            )
        )
        return resp.deviceToHost.dstData

    def broadcast_weights(self, weights: np.ndarray, addr: int = WEIGHTS_ADDR) -> None:
        """Ship one weight vector to every device (client.go:642-644)."""
        data = f32_to_bytes(weights)
        for rank in range(len(self.devices)):
            self.write(rank, addr, data)

    # ---- collectives -----------------------------------------------------------

    def all_reduce_ring(
        self,
        num_bytes: int,
        op: int = pb.SUM,
        mem_addrs: dict[int, int] | None = None,
        dtype: str = "",
        timeout: float = 120.0,
    ) -> None:
        req = pb.AllReduceRingRequest(commId=self.comm_id, count=num_bytes, op=op, dtype=dtype)
        for rank, addr in (mem_addrs or {}).items():
            req.memAddrs[rank].value = addr
        self.coordinator.AllReduceRing(req, timeout=timeout)

    def naive_all_reduce(self, data_size: int, latency_ms: int = 0, timeout: float = 120.0):
        return self.coordinator.NaiveAllReduce(
            pb.NaiveAllReduceRequest(commId=self.comm_id, dataSize=data_size, latencyMs=latency_ms),
            timeout=timeout,
        )

    def all_reduce_gradients(
        self, per_rank_grads: list[np.ndarray], op: int = pb.SUM, addr: int = GRAD_ADDR
    ) -> np.ndarray:
        """The training-loop step the reference faked (SURVEY.md §8.4): write
        each rank's gradient shard-sum, ring-reduce for real, read back the
        reduction."""
        n = len(self.devices)
        if n != len(per_rank_grads):
            raise ValueError(f"{len(per_rank_grads)} gradient arrays for {n} devices")
        nbytes = None
        for rank, g in enumerate(per_rank_grads):
            data = f32_to_bytes(g)
            nbytes = len(data) if nbytes is None else nbytes
            if len(data) != nbytes:
                raise ValueError("all ranks must contribute equal-size gradients")
            self.write(rank, addr, data)
        self.all_reduce_ring(nbytes, op=op, mem_addrs={r: addr for r in range(n)})
        return bytes_to_f32(self.read(0, addr, nbytes))

    # ---- on-device compute -----------------------------------------------------

    def run_forward(self, rank: int, input_addr: int, output_addr: int) -> int:
        resp = self.devices[rank].RunForward(
            pb.RunForwardRequest(
                deviceId=pb.DeviceId(value=self.device_ids[rank]),
                inputAddr=pb.MemAddr(value=input_addr),
                outputAddr=pb.MemAddr(value=output_addr),
            )
        )
        return resp.outputBytes

    def run_backward(self, rank: int, gradient_addr: int) -> None:
        self.devices[rank].RunBackward(
            pb.RunBackwardRequest(
                deviceId=pb.DeviceId(value=self.device_ids[rank]),
                gradientAddr=pb.MemAddr(value=gradient_addr),
            )
        )

    # ---- lifecycle --------------------------------------------------------------

    def status(self) -> int:
        return self.coordinator.GetCommStatus(pb.GetCommStatusRequest(commId=self.comm_id)).status

    def destroy(self) -> None:
        self.coordinator.CommDestroy(pb.CommDestroyRequest(commId=self.comm_id))

    def finalize(self) -> None:
        self.coordinator.CommFinalize(pb.CommFinalizeRequest(commId=self.comm_id))
