"""L2 — coordinator: communicator lifecycle + collectives control plane.

TPU-native rebuild of the reference coordinator
(``DSML/gpu_coordinator_service/gpu_coordinator_server.go``). API surface and
status-code contract preserved (INTERNAL on failed CommInit ``:167-169``,
NOT_FOUND on unknown commId ``:596-608``, FAILED_PRECONDITION on a FAILED
communicator ``:282-285``, 5s health loop with 2s probes ``:57,69-119``),
but the data plane is real:

- ``AllReduceRing`` reduces the devices' ACTUAL buffers (the reference
  reduced a coordinator-local shadow map and returned the client its own
  unreduced gradients, SURVEY.md §8.4-8.5) with dtype-aware arithmetic
  (§8.2), honoring ``op`` and per-rank ``memAddrs`` (§8.3). When the
  communicator's devices are distinct local accelerators, the whole
  2(n-1)-step ring executes as ONE jitted XLA program over the device mesh
  (``dsml_tpu.ops.collectives``) fed DIRECTLY from the device servers'
  HBM-resident registry buffers and written back on device
  (``_all_reduce_zero_copy``) — data moves over ICI with zero host copies;
  gRPC carries only the control messages.
- ``Memcpy`` forwards to the owning device instead of writing a shadow map.
- ``GroupStart``/``GroupEnd`` actually batch: collectives issued inside a
  group are queued and dispatched at ``GroupEnd`` (§8.12).
- ``CommFinalize`` is implemented (drain, then destroy) — declared but
  handler-less in the reference (§8.10).
"""

from __future__ import annotations

import threading
import time
from concurrent import futures
from dataclasses import dataclass, field

import grpc
import numpy as np

from dsml_tpu.comm import rpc
from dsml_tpu.comm.device_server import DeviceError, local_device
from dsml_tpu.comm.proto import gpu_sim_pb2 as pb
from dsml_tpu.obs import get_registry, observe_collective_latency_ms, span
from dsml_tpu.obs import flight_recorder, hangwatch
from dsml_tpu.ops.collectives import ReduceOp, make_stacked_all_reduce
from dsml_tpu.utils.config import Config, field as cfg_field
from dsml_tpu.utils.logging import get_logger

import dataclasses

log = get_logger("coordinator")

DEFAULT_BUFFER_ADDR = 0x1000  # the reference's conventional gradient address


@dataclasses.dataclass
class CoordinatorConfig(Config):
    health_interval_s: float = cfg_field(5.0, help="health-probe period (reference: 5s)")
    probe_timeout_s: float = cfg_field(2.0, help="per-device health probe timeout (reference: 2s)")
    dial_retries: int = cfg_field(3, help="CommInit dial attempts per device (reference: 3)")
    dial_backoff_s: float = cfg_field(0.5, help="sleep between dial attempts (reference: 500ms)")
    ring_algorithm: str = cfg_field("ring", help="AllReduceRing algorithm: ring|ring2|xla|naive|auto (ring2 = bidirectional full-duplex ring; auto = payload/axis-aware latency-vs-bandwidth selection)")
    elastic: bool = cfg_field(
        False,
        help="on device failure, re-rank the surviving devices and keep the "
        "communicator alive instead of failing it permanently (the reference "
        "marks it FAILED forever, SURVEY.md §5.3)",
    )
    straggler_multiplier: float = cfg_field(
        3.0,
        help="a device whose health-probe latency exceeds this multiple of "
        "the pass's median counts into the coordinator_stragglers gauge",
    )


def _remote_error(info: "DeviceInfo", e: grpc.RpcError) -> DeviceError:
    """Surface a remote device's status code as this RPC's own (a raw
    RpcError would reach the client as UNKNOWN)."""
    code = e.code() if callable(getattr(e, "code", None)) else grpc.StatusCode.UNAVAILABLE
    return DeviceError(code, f"device {info.device_id} ({info.address}): {e.details() if callable(getattr(e, 'details', None)) else e}")


@dataclass
class DeviceInfo:
    rank: int
    device_id: int
    address: str
    stub: rpc._Stub
    channel: grpc.Channel
    metadata: pb.DeviceMetadata


@dataclass
class Communicator:
    comm_id: int
    devices: list[DeviceInfo]
    status: int = pb.IN_PROGRESS
    group_active: bool = False
    queued: list = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)
    in_flight: int = 0


class CoordinatorRuntime:
    """Coordinator logic, directly callable by tests and the gRPC adapter."""

    def __init__(self, config: CoordinatorConfig | None = None):
        self.config = config or CoordinatorConfig()
        # Warm the native runtime now: its first use otherwise triggers a
        # synchronous C++ build inside an RPC handler.
        from dsml_tpu.runtime import native as _native

        _native.available()
        self.comms: dict[int, Communicator] = {}
        self._next_comm = 1
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # failure listeners: the health loop's verdicts, pushed instead of
        # polled — the elastic controller subscribes here so a coordinator
        # death sentence becomes a DeviceLost signal, not a hung step
        self._failure_listeners: list = []
        # failure forensics: wire ops ride in the flight-recorder ring, and
        # with DSML_HANGWATCH set each collective arms a deadline at k× the
        # trailing-median op wall — a wedged (alive-but-stuck) device then
        # leaves a stack dump + bundle instead of a silently hung client
        self._recorder = flight_recorder.get_flight_recorder()
        hw_cfg = hangwatch.config_from_env()
        self._hangwatch = hangwatch.get_hangwatch() if hw_cfg is not None else None
        self._wire_deadline = (
            hangwatch.TrailingDeadline.from_config(
                hw_cfg, floor_s=max(2 * self.config.probe_timeout_s, 1.0)
            )
            if hw_cfg is not None else None
        )
        self._health_thread = threading.Thread(target=self._health_loop, daemon=True)
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()

    def add_failure_listener(self, fn) -> None:
        """Subscribe to health-probe death verdicts:
        ``fn(comm_id, failed_device_ids, alive_device_ids)`` fires from the
        health loop whenever a probe pass finds dead devices (before any
        elastic renumbering, so the ids are the pre-failure ones). Listener
        exceptions are logged, never allowed to wedge the health loop."""
        with self._lock:
            self._failure_listeners.append(fn)

    def failure_feed(self):
        """A LIVE feed for ``runtime.controller.ElasticController``'s
        ``failure_feed=`` hook: registers an internal listener and returns
        a zero-arg callable that drains the device ids the health loop has
        declared dead since the last call. The push verdict becomes the
        controller's poll — the glue that turns a coordinator death
        sentence into a ``DeviceLost`` signal instead of a hung step
        (closes the ROADMAP item: tests previously used injected feeds
        only)."""
        import collections

        pending: collections.deque = collections.deque()

        def on_failure(comm_id, failed_ids, alive_ids):
            pending.extend(failed_ids)  # deque.extend is thread-safe

        self.add_failure_listener(on_failure)

        def feed() -> list:
            out = []
            while True:
                try:
                    out.append(pending.popleft())
                except IndexError:
                    return out

        return feed

    # ---- communicator lifecycle -----------------------------------------------

    def comm_init(self, num_devices: int, addresses: list[str]) -> Communicator:
        """Dial + probe every device; all-or-nothing (reference
        gpu_coordinator_server.go:121-192). Also installs each device's peer
        table so P2P streams can route cross-device."""
        if num_devices < 1 or len(addresses) != num_devices:
            raise DeviceError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"numDevices={num_devices} but {len(addresses)} addresses given",
            )
        infos: list[DeviceInfo] = []
        try:
            for rank, addr in enumerate(addresses):
                channel = grpc.insecure_channel(addr)
                stub = rpc.device_stub(channel)
                meta = None
                last_err: Exception | None = None
                for attempt in range(self.config.dial_retries):
                    try:
                        meta = stub.GetDeviceMetadata(
                            pb.GetDeviceMetadataRequest(), timeout=self.config.probe_timeout_s
                        ).metadata
                        break
                    except grpc.RpcError as e:
                        last_err = e
                        if attempt + 1 < self.config.dial_retries:
                            time.sleep(self.config.dial_backoff_s)
                if meta is None:
                    raise DeviceError(
                        grpc.StatusCode.INTERNAL, f"device {addr} unreachable: {last_err}"
                    )
                infos.append(DeviceInfo(rank, meta.deviceId.value, addr, stub, channel, meta))
        except DeviceError:
            for info in infos:
                info.channel.close()
            raise

        peer_map = {info.rank: info.address for info in infos}
        for info in infos:
            try:
                info.stub.ConfigurePeers(
                    pb.ConfigurePeersRequest(peerAddresses=peer_map, selfRank=info.rank),
                    timeout=self.config.probe_timeout_s,
                )
            except grpc.RpcError:
                # Extension RPC: a reference-proto peer won't know it; P2P
                # streams then only support loopback, collectives still work.
                log.info("device %s lacks ConfigurePeers (reference-proto peer?)", info.address)

        with self._lock:
            comm = Communicator(self._next_comm, infos)
            self._next_comm += 1
            self.comms[comm.comm_id] = comm
        log.info("CommInit: comm %d over %d devices", comm.comm_id, len(infos))
        return comm

    def _get_comm(self, comm_id: int) -> Communicator:
        with self._lock:
            comm = self.comms.get(comm_id)
        if comm is None:
            raise DeviceError(grpc.StatusCode.NOT_FOUND, f"unknown communicator {comm_id}")
        return comm

    def comm_members(self, comm_id: int) -> tuple[int, list[tuple[int, int, str]]]:
        """(status, [(rank, device_id, address)…]) — the CURRENT membership,
        which elastic recovery may have renumbered; clients re-resolve their
        rank→device maps from this instead of holding stale CommInit ranks."""
        comm = self._get_comm(comm_id)
        with comm.lock:
            return comm.status, [(i.rank, i.device_id, i.address) for i in comm.devices]

    def broker_migration(self, comm_id: int, local_device_id: int):
        """Membership-table routing for cross-host shard migration
        (``comm.migration.ShardMigrator``): resolve which member is the
        caller (``self_rank`` — where donors push their streams) and which
        are potential donors. Returns ``(self_rank, [(rank, address), …])``
        over the CURRENT membership, which elastic recovery may have
        renumbered — the same freshness contract as :meth:`comm_members`."""
        _, members = self.comm_members(comm_id)
        self_rank, donors = None, []
        for rank, device_id, address in members:
            if device_id == local_device_id:
                self_rank = rank
            else:
                donors.append((rank, address))
        if self_rank is None:
            raise DeviceError(
                grpc.StatusCode.NOT_FOUND,
                f"device {local_device_id} is not a member of comm {comm_id}",
            )
        return self_rank, donors

    def comm_destroy(self, comm_id: int) -> None:
        comm = self._get_comm(comm_id)
        with self._lock:
            self.comms.pop(comm_id, None)
        for info in comm.devices:
            info.channel.close()
        log.info("CommDestroy: comm %d", comm_id)

    def comm_finalize(self, comm_id: int, drain_timeout_s: float = 30.0) -> None:
        """Drain queued/in-flight collectives, then destroy."""
        comm = self._get_comm(comm_id)
        with comm.lock:
            if comm.queued:
                self._flush_group_locked(comm)
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            with comm.lock:
                if comm.in_flight == 0:
                    break
            time.sleep(0.01)
        self.comm_destroy(comm_id)

    # ---- group semantics --------------------------------------------------------

    def group_start(self, comm_id: int) -> None:
        comm = self._get_comm(comm_id)
        with comm.lock:
            comm.group_active = True

    def group_end(self, comm_id: int) -> bool:
        comm = self._get_comm(comm_id)
        with comm.lock:
            comm.group_active = False
            return self._flush_group_locked(comm)

    def _flush_group_locked(self, comm: Communicator) -> bool:
        ok = True
        queued, comm.queued = comm.queued, []
        for fn in queued:
            try:
                fn()
            except DeviceError as e:
                log.warning("queued collective failed: %s", e)
                ok = False
        return ok

    # ---- memcpy (forwards to the owning device) ---------------------------------

    def memcpy_h2d(self, device_id: int, addr: int, data: bytes) -> None:
        self._store_bytes(self._find_device(device_id), addr, data)

    def memcpy_d2h(self, device_id: int, addr: int, num_bytes: int) -> bytes:
        return self._fetch_bytes(self._find_device(device_id), addr, num_bytes)

    def _find_device(self, device_id: int) -> DeviceInfo:
        with self._lock:
            for comm in self.comms.values():
                for info in comm.devices:
                    if info.device_id == device_id:
                        return info
        raise DeviceError(grpc.StatusCode.NOT_FOUND, f"no known device with id {device_id}")

    # ---- collectives -------------------------------------------------------------

    def all_reduce_ring(
        self,
        comm_id: int,
        count: int,
        op: int = pb.SUM,
        mem_addrs: dict[int, int] | None = None,
        dtype: str = "",
    ) -> None:
        comm = self._get_comm(comm_id)
        if comm.status == pb.FAILED:
            raise DeviceError(
                grpc.StatusCode.FAILED_PRECONDITION, f"communicator {comm_id} is FAILED"
            )

        def run():
            self._execute_all_reduce(comm, count, op, mem_addrs or {}, dtype or "float32")

        with comm.lock:
            if comm.group_active:
                comm.queued.append(run)
                return
            comm.in_flight += 1
        hw_token = None
        if self._hangwatch is not None:
            deadline_s = self._wire_deadline.timeout_s()
            if deadline_s is not None:
                hw_token = self._hangwatch.arm(
                    "wire_op", deadline_s, comm=comm_id, count=count,
                    algorithm=self.config.ring_algorithm,
                )
        t0 = time.perf_counter()
        try:
            # wire_op span: the coordinator lane of the STITCHED cluster
            # timeline — device-side device_memcpy/device_forward spans from
            # the device servers' own processes land inside this interval
            # once clock offsets are aligned (obs.cluster.stitch_traces)
            with span("wire_op", comm=comm_id, count=count,
                      algorithm=self.config.ring_algorithm):
                run()
            wall_s = time.perf_counter() - t0
            # per-op latency, labeled by the algorithm that actually ran —
            # the accounting surface the reference reported as totalTimeMs
            observe_collective_latency_ms(
                self.config.ring_algorithm, wall_s * 1e3,
                payload_bytes=count, axis="wire",
            )
            self._recorder.record(
                "wire_op", comm=comm_id, count=count,
                algorithm=self.config.ring_algorithm,
                ms=round(wall_s * 1e3, 3),
            )
        finally:
            if self._hangwatch is not None:
                if hw_token is not None:
                    self._hangwatch.disarm(hw_token)
                self._wire_deadline.observe(time.perf_counter() - t0)
            with comm.lock:
                comm.in_flight -= 1

    def _execute_all_reduce(
        self, comm: Communicator, count: int, op: int, mem_addrs: dict[int, int], dtype: str
    ) -> None:
        n = len(comm.devices)
        if n < 2:
            comm.status = pb.SUCCESS  # nothing to reduce (reference :289-295)
            return
        np_dtype = np.dtype(dtype)
        if count % np_dtype.itemsize:
            raise DeviceError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"count={count} bytes is not a multiple of {dtype} itemsize",
            )
        addrs = {info.rank: mem_addrs.get(info.rank, DEFAULT_BUFFER_ADDR) for info in comm.devices}
        try:
            if self._all_reduce_zero_copy(comm, addrs, count, ReduceOp(op), np_dtype):
                comm.status = pb.SUCCESS
                return
            rows = []
            for info in comm.devices:
                raw = self._fetch_bytes(info, addrs[info.rank], count)
                rows.append(np.frombuffer(raw, dtype=np_dtype))
            stacked = np.stack(rows)
            reduced = self._reduce_stack(comm, stacked, ReduceOp(op))
            for info in comm.devices:
                self._store_bytes(info, addrs[info.rank], np.asarray(reduced[info.rank]).tobytes())
            comm.status = pb.SUCCESS
        except DeviceError:
            comm.status = pb.FAILED  # reference fails the comm on any step error (:340-345)
            raise
        except Exception as e:  # noqa: BLE001
            comm.status = pb.FAILED
            raise DeviceError(grpc.StatusCode.INTERNAL, f"all-reduce failed: {e}") from e

    def _all_reduce_zero_copy(
        self, comm: Communicator, addrs: dict[int, int], count: int, op: ReduceOp, np_dtype
    ) -> bool:
        """HBM-resident collective: when every communicator device is a local
        runtime on its own chip, feed the jitted ring straight from the
        registries' device buffers and write the results back on device —
        zero host copies end to end (the design `device_server.py` promises
        at ``put_array``; VERDICT r1 weak #3 measured the old host-roundtrip
        ends at ~114 ms for 1 MB). Returns False when preconditions don't
        hold and the host path must run instead. Missing buffers / short
        buffers raise exactly what the host path would (NOT_FOUND /
        OUT_OF_RANGE), keeping the wire contract identical."""
        if count == 0:
            return False  # host path's "0 = whole buffer" convention applies
        mesh = self._comm_mesh(comm)
        if mesh is None:
            return False
        rts = []
        for info in comm.devices:
            rt = self._local_rt(info)
            if rt is None:
                return False
            rts.append(rt)
        buffers = []
        for info, rt in zip(comm.devices, rts):
            addr = addrs[info.rank]
            arr = rt.memory.get_array(addr)  # NOT_FOUND — same as host path
            if count > arr.nbytes:
                raise DeviceError(
                    grpc.StatusCode.OUT_OF_RANGE,
                    f"requested {count} bytes from {arr.nbytes}-byte buffer at {addr:#x}",
                )
            buffers.append(arr[:count] if arr.nbytes > count else arr)

        from dsml_tpu.ops.collectives import device_buffers_all_reduce

        reduced = device_buffers_all_reduce(
            buffers, mesh, op, self.config.ring_algorithm, str(np_dtype)
        )
        import jax.numpy as jnp

        for info, rt, red in zip(comm.devices, rts, reduced):
            addr = addrs[info.rank]
            old = rt.memory.get_array(addr)
            if old.nbytes > count:
                # splice the reduced prefix, keep the tail — write()'s
                # partial-write semantics, still on device
                red = jnp.concatenate([red, old[count:]])
            rt.memory.put_array(addr, red, logical_nbytes=count)
        return True

    def _reduce_stack(self, comm: Communicator, stacked: np.ndarray, op: ReduceOp) -> np.ndarray:
        """Run the reduction over the communicator's accelerator mesh when its
        devices are distinct local chips (one jitted ring over ICI); otherwise
        reduce on the coordinator's default device (cross-host fallback)."""
        mesh = self._comm_mesh(comm)
        if mesh is not None:
            return np.asarray(make_stacked_all_reduce(mesh, op, self.config.ring_algorithm)(stacked))
        # cross-host fallback: reduce on the coordinator host — float32 goes
        # through the native C++ kernel when built
        if stacked.dtype == np.float32:
            from dsml_tpu.runtime import native

            reduced = native.reduce_f32(stacked.reshape(stacked.shape[0], -1), int(op))
            return np.broadcast_to(reduced.reshape(stacked.shape[1:]), stacked.shape)
        combine = {
            ReduceOp.SUM: np.add.reduce,
            ReduceOp.AVG: lambda a: np.add.reduce(a) / a.shape[0],
            ReduceOp.PROD: np.multiply.reduce,
            ReduceOp.MIN: np.minimum.reduce,
            ReduceOp.MAX: np.maximum.reduce,
        }[op]
        reduced = combine(stacked.astype(np.float64) if stacked.dtype.kind in "iu" else stacked)
        reduced = reduced.astype(stacked.dtype)
        return np.broadcast_to(reduced, stacked.shape)

    def _comm_mesh(self, comm: Communicator):
        from jax.sharding import Mesh

        jax_devs = []
        for info in comm.devices:
            rt = local_device(info.device_id)
            if rt is None:
                return None
            jax_devs.append(rt.jax_device)
        if len({d.id for d in jax_devs}) != len(jax_devs):
            return None  # servers sharing a chip: no physical ring to run
        return Mesh(np.array(jax_devs), ("dev",))

    def _local_rt(self, info: DeviceInfo):
        """In-process shortcut, only when the registered runtime really is the
        one serving info.address (a remote device with a colliding id must
        not be shadowed by a local chip)."""
        rt = local_device(info.device_id)
        if rt is not None and rt.bound_address == info.address:
            return rt
        return None

    def _fetch_bytes(self, info: DeviceInfo, addr: int, count: int) -> bytes:
        rt = self._local_rt(info)
        if rt is not None:
            return rt.read_bytes(addr, count or None)
        try:
            resp = info.stub.Memcpy(
                pb.MemcpyRequest(
                    deviceToHost=pb.MemcpyDeviceToHostRequest(
                        srcDeviceId=pb.DeviceId(value=info.device_id),
                        srcMemAddr=pb.MemAddr(value=addr),
                        numBytes=count,
                    )
                )
            )
        except grpc.RpcError as e:
            raise _remote_error(info, e) from e
        return resp.deviceToHost.dstData

    def _store_bytes(self, info: DeviceInfo, addr: int, data: bytes) -> None:
        rt = self._local_rt(info)
        if rt is not None:
            rt.memcpy_h2d(addr, data)
            return
        try:
            info.stub.Memcpy(
                pb.MemcpyRequest(
                    hostToDevice=pb.MemcpyHostToDeviceRequest(
                        hostSrcData=data,
                        dstDeviceId=pb.DeviceId(value=info.device_id),
                        dstMemAddr=pb.MemAddr(value=addr),
                    )
                )
            )
        except grpc.RpcError as e:
            raise _remote_error(info, e) from e

    def naive_all_reduce(self, comm_id: int, data_size: int, latency_ms: int) -> tuple[int, int]:
        """Gather→reduce→broadcast through the coordinator host, with the
        reference's simulated per-op latency and metrics
        (gpu_coordinator_server.go:611-717). Devices with no buffer at
        0x1000 are seeded with all-ones (the reference always re-seeded;
        here real data is respected). Returns (totalTimeMs, totalBytes)."""
        comm = self._get_comm(comm_id)
        if comm.status == pb.FAILED:
            raise DeviceError(grpc.StatusCode.FAILED_PRECONDITION, f"communicator {comm_id} is FAILED")
        latency = latency_ms / 1000.0
        # init phase (excluded from timing, reference :634-656): any device
        # without a full dataSize buffer at 0x1000 is seeded with all-ones,
        # the reference's demo pattern (:634-656)
        for info in comm.devices:
            time.sleep(latency)
            try:
                self._fetch_bytes(info, DEFAULT_BUFFER_ADDR, data_size)
            except DeviceError as e:
                if e.code in (grpc.StatusCode.NOT_FOUND, grpc.StatusCode.OUT_OF_RANGE):
                    self._store_bytes(info, DEFAULT_BUFFER_ADDR, b"\x01" * data_size)
                else:
                    raise
        start = time.monotonic()
        rows = []
        for info in comm.devices:
            time.sleep(latency)
            rows.append(np.frombuffer(self._fetch_bytes(info, DEFAULT_BUFFER_ADDR, data_size), np.uint8))
        # dtype-aware reduce (f32 when the size allows, else wide-int bytes —
        # never the reference's wrapping uint8 add, SURVEY.md §8.2)
        stacked = np.stack(rows)
        if data_size % 4 == 0:
            reduced = stacked.view(np.float32).sum(axis=0).tobytes()
        else:
            reduced = stacked.astype(np.uint16).sum(axis=0).clip(0, 255).astype(np.uint8).tobytes()
        for info in comm.devices:
            time.sleep(latency)
            self._store_bytes(info, 0x2000, reduced)
        total_ms = int((time.monotonic() - start) * 1000)
        total_bytes = 2 * len(comm.devices) * data_size
        comm.status = pb.SUCCESS
        observe_collective_latency_ms(
            "naive", float(total_ms), payload_bytes=total_bytes, axis="wire"
        )
        log.info("NaiveAllReduce: %d ms, %d bytes", total_ms, total_bytes)
        return total_ms, total_bytes

    # ---- health loop (reference :69-119) -----------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.config.health_interval_s):
            with self._lock:
                comms = list(self.comms.values())
            for comm in comms:
                self._check_comm_health(comm)

    def _check_comm_health(self, comm: Communicator) -> None:
        alive, failed = [], []
        probe_ms: dict[int, float] = {}  # device_id -> probe latency
        for info in comm.devices:
            t0 = time.perf_counter()
            try:
                info.stub.GetDeviceMetadata(
                    pb.GetDeviceMetadataRequest(), timeout=self.config.probe_timeout_s
                )
                probe_ms[info.device_id] = (time.perf_counter() - t0) * 1e3
                alive.append(info)
            except grpc.RpcError:
                failed.append(info)
        # per-probe outcome counts (matching the reference's health loop,
        # now queryable instead of log-only)
        reg = get_registry()
        probes = reg.counter(
            "coordinator_health_probes_total", "device health-probe outcomes",
            labels=("outcome",),
        )
        probes.inc(len(alive), outcome="alive")
        if failed:
            probes.inc(len(failed), outcome="failed")
        # per-device probe latency + straggler derivation: the loop used to
        # discard timing and only count alive/failed — but at pod scale the
        # run-killers are devices that answer SLOWLY, not just dead ones
        stragglers = 0
        if probe_ms:
            lat_hist = reg.histogram(
                "coordinator_probe_ms", "per-device health-probe latency",
                labels=("device",),
            )
            for device_id, ms in probe_ms.items():
                lat_hist.observe(ms, device=device_id)
            lats = sorted(probe_ms.values())
            median = lats[len(lats) // 2]
            bar = self.config.straggler_multiplier * max(median, 1e-6)
            slow = {d: ms for d, ms in probe_ms.items() if ms > bar}
            stragglers = len(slow)
            if slow:
                log.warning(
                    "health: comm %d stragglers (> %.1f ms = %.1f× median): %s",
                    comm.comm_id, bar, self.config.straggler_multiplier,
                    {d: round(ms, 1) for d, ms in slow.items()},
                )
        # set UNCONDITIONALLY: an all-probes-failed pass must zero the gauge,
        # not leave the previous pass's count standing during the outage
        reg.gauge(
            "coordinator_stragglers",
            "devices whose probe latency exceeds k× the pass median",
        ).set(stragglers)
        self._recorder.record(
            "health_probe", comm=comm.comm_id, alive=len(alive),
            failed=len(failed), stragglers=stragglers,
            probe_ms={str(d): round(ms, 3) for d, ms in probe_ms.items()},
        )
        if failed:
            with self._lock:
                listeners = list(self._failure_listeners)
            for fn in listeners:
                try:
                    fn(comm.comm_id,
                       [i.device_id for i in failed],
                       [i.device_id for i in alive])
                except Exception as e:  # noqa: BLE001 — never wedge health
                    log.warning("failure listener raised: %r", e)
            if self.config.elastic and alive:
                # Elastic recovery: shrink the ring and keep going — the
                # Varuna/Bamboo/Oobleck capability the reference shelved as
                # literature (SURVEY.md §5.3). Survivors keep their relative
                # order and get dense new ranks as FRESH DeviceInfo objects.
                # Order matters: (1) fail the comm so no NEW collective
                # starts, (2) drain in-flight collectives (they run against
                # the OLD rank tables and must fail on the dead device, not
                # get misrouted to a renumbered survivor), (3) only then push
                # the new peer tables device-side and swap coordinator state.
                # Clients re-resolve their rank→device maps afterwards via
                # GetCommStatus's members extension
                # (PipelineClient.refresh_membership).
                with comm.lock:
                    comm.status = pb.FAILED
                deadline = time.monotonic() + self.config.probe_timeout_s
                while time.monotonic() < deadline:
                    with comm.lock:
                        if comm.in_flight == 0:
                            break
                    time.sleep(0.01)
                survivors = [
                    dataclasses.replace(info, rank=new_rank)
                    for new_rank, info in enumerate(alive)
                ]
                peer_map = {info.rank: info.address for info in survivors}
                for info in survivors:
                    try:
                        info.stub.ConfigurePeers(
                            pb.ConfigurePeersRequest(peerAddresses=peer_map, selfRank=info.rank),
                            timeout=self.config.probe_timeout_s,
                        )
                    except grpc.RpcError as e:
                        log.warning(
                            "health: comm %d survivor %s did not take the new peer "
                            "table (%s); its P2P routes may be stale until the next "
                            "recovery pass", comm.comm_id, info.address, e,
                        )
                with comm.lock:
                    comm.devices = survivors
                    comm.status = pb.IN_PROGRESS  # recovered; accept collectives again
                log.warning(
                    "health: comm %d lost %d device(s); recovered with %d survivors",
                    comm.comm_id, len(failed), len(alive),
                )
            else:
                with comm.lock:
                    comm.devices = alive  # prune (reference :114)
                    comm.status = pb.FAILED
                for info in failed:
                    log.warning("health: device %d (%s) unreachable; comm %d FAILED",
                                info.device_id, info.address, comm.comm_id)
            for info in failed:
                info.channel.close()  # pruned entries would otherwise leak channels


# ---------------------------------------------------------------------------
# gRPC adapter + bootstrap
# ---------------------------------------------------------------------------


class CoordinatorServicer:
    def __init__(self, runtime: CoordinatorRuntime):
        self.rt = runtime

    def _abort(self, context, err: DeviceError):
        context.abort(err.code, str(err))

    def CommInit(self, request, context):  # noqa: N802
        try:
            comm = self.rt.comm_init(request.numDevices, list(request.device_addresses))
        except DeviceError as e:
            self._abort(context, e)
        return pb.CommInitResponse(
            success=True, commId=comm.comm_id, devices=[i.metadata for i in comm.devices]
        )

    def GetCommStatus(self, request, context):  # noqa: N802
        try:
            status, members = self.rt.comm_members(request.commId)
        except DeviceError as e:
            self._abort(context, e)
        return pb.GetCommStatusResponse(
            status=status,
            members=[
                pb.CommMember(rank=r, deviceId=pb.DeviceId(value=d), address=a)
                for r, d, a in members
            ],
        )

    def CommDestroy(self, request, context):  # noqa: N802
        try:
            self.rt.comm_destroy(request.commId)
        except DeviceError as e:
            self._abort(context, e)
        return pb.CommDestroyResponse(success=True)

    def CommFinalize(self, request, context):  # noqa: N802
        try:
            self.rt.comm_finalize(request.commId)
        except DeviceError as e:
            self._abort(context, e)
        return pb.CommFinalizeResponse(success=True)

    def GroupStart(self, request, context):  # noqa: N802
        try:
            self.rt.group_start(request.commId)
        except DeviceError as e:
            self._abort(context, e)
        return pb.GroupStartResponse(success=True)

    def GroupEnd(self, request, context):  # noqa: N802
        try:
            ok = self.rt.group_end(request.commId)
        except DeviceError as e:
            self._abort(context, e)
        return pb.GroupEndResponse(success=ok)

    def AllReduceRing(self, request, context):  # noqa: N802
        try:
            self.rt.all_reduce_ring(
                request.commId,
                request.count,
                request.op,
                {rank: addr.value for rank, addr in request.memAddrs.items()},
                request.dtype,
            )
        except DeviceError as e:
            self._abort(context, e)
        return pb.AllReduceRingResponse(success=True)

    def NaiveAllReduce(self, request, context):  # noqa: N802
        try:
            total_ms, total_bytes = self.rt.naive_all_reduce(
                request.commId, request.dataSize, request.latencyMs
            )
        except DeviceError as e:
            self._abort(context, e)
        return pb.NaiveAllReduceResponse(
            success=True, totalTimeMs=total_ms, totalDataTransferred=total_bytes
        )

    def Memcpy(self, request, context):  # noqa: N802
        try:
            if request.HasField("hostToDevice"):
                h2d = request.hostToDevice
                self.rt.memcpy_h2d(h2d.dstDeviceId.value, h2d.dstMemAddr.value, h2d.hostSrcData)
                return pb.MemcpyResponse(hostToDevice=pb.MemcpyHostToDeviceResponse(success=True))
            d2h = request.deviceToHost
            data = self.rt.memcpy_d2h(d2h.srcDeviceId.value, d2h.srcMemAddr.value, d2h.numBytes)
            return pb.MemcpyResponse(deviceToHost=pb.MemcpyDeviceToHostResponse(dstData=data))
        except DeviceError as e:
            self._abort(context, e)


@dataclass
class CoordinatorHandle:
    runtime: CoordinatorRuntime
    server: grpc.Server
    address: str

    def stop(self, grace: float = 0.2) -> None:
        self.runtime.stop()
        self.server.stop(grace)


def serve_coordinator(
    port: int = 0, config: CoordinatorConfig | None = None, host: str = "127.0.0.1"
) -> CoordinatorHandle:
    runtime = CoordinatorRuntime(config)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
    rpc.add_coordinator_servicer(CoordinatorServicer(runtime), server)
    # cluster obs plane (same port): the aggregator pulls the coordinator's
    # registry/trace snapshot — wire-op latency, health probes, stragglers
    from dsml_tpu.obs.cluster import ObsServicer, current_role

    rpc.add_obs_servicer(ObsServicer(current_role("coordinator")), server)
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return CoordinatorHandle(runtime, server, f"{host}:{bound}")
