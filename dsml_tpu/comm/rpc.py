"""gRPC service bindings for the gpu_sim protocol, built by hand.

The image ships ``protoc`` (message codegen) but not ``grpc_tools`` (the
``*_pb2_grpc.py`` plugin), so the service layer is declared here from method
tables and wired through grpc's generic-handler API. This replaces the
reference's generated ``gpu_sim_grpc.pb.go`` stubs
(``/root/reference/DSML/proto/gpu_sim_grpc.pb.go:22-31,147-185``) — same
RPC paths on the wire (``/gpu_sim.GPUDevice/...``), so peers generated from
the reference proto interoperate.
"""

from __future__ import annotations

import grpc

from dsml_tpu.comm.proto import gpu_sim_pb2 as pb

# method name -> (arity, request type, response type)
# arity: "uu" = unary-unary, "su" = stream-unary
_DEVICE_METHODS = {
    "GetDeviceMetadata": ("uu", pb.GetDeviceMetadataRequest, pb.GetDeviceMetadataResponse),
    "BeginSend": ("uu", pb.BeginSendRequest, pb.BeginSendResponse),
    "BeginReceive": ("uu", pb.BeginReceiveRequest, pb.BeginReceiveResponse),
    "StreamSend": ("su", pb.DataChunk, pb.StreamSendResponse),
    "GetStreamStatus": ("uu", pb.GetStreamStatusRequest, pb.GetStreamStatusResponse),
    "Memcpy": ("uu", pb.MemcpyRequest, pb.MemcpyResponse),
    "ConfigurePeers": ("uu", pb.ConfigurePeersRequest, pb.ConfigurePeersResponse),
    "RunForward": ("uu", pb.RunForwardRequest, pb.RunForwardResponse),
    "RunBackward": ("uu", pb.RunBackwardRequest, pb.RunBackwardResponse),
}

_COORDINATOR_METHODS = {
    "CommInit": ("uu", pb.CommInitRequest, pb.CommInitResponse),
    "GetCommStatus": ("uu", pb.GetCommStatusRequest, pb.GetCommStatusResponse),
    "CommDestroy": ("uu", pb.CommDestroyRequest, pb.CommDestroyResponse),
    "CommFinalize": ("uu", pb.CommFinalizeRequest, pb.CommFinalizeResponse),
    "GroupStart": ("uu", pb.GroupStartRequest, pb.GroupStartResponse),
    "GroupEnd": ("uu", pb.GroupEndRequest, pb.GroupEndResponse),
    "AllReduceRing": ("uu", pb.AllReduceRingRequest, pb.AllReduceRingResponse),
    "NaiveAllReduce": ("uu", pb.NaiveAllReduceRequest, pb.NaiveAllReduceResponse),
    "Memcpy": ("uu", pb.MemcpyRequest, pb.MemcpyResponse),
}

# Observability plane — an EXTENSION service carrying raw JSON bytes
# (req/resp class None = no protobuf codec: grpc passes bytes through).
# The reference proto stays byte-for-byte untouched; reference peers never
# call it, and our peers that lack it just fail the obs scrape, never the
# data plane. Workers (device servers, the coordinator) attach it to the
# grpc.Server they already run, so the cluster aggregator pulls snapshots
# over the SAME port/channel as the gpu_sim traffic.
_OBS_METHODS = {
    "PullSnapshot": ("uu", None, None),
    "PushSnapshot": ("uu", None, None),
}

# Shard-migration control plane — a second raw-JSON extension service
# (same pattern as ObsPlane; reference proto untouched). Only the CONTROL
# messages ride here: which pieces a donor holds (PlanPieces) and the
# request to serialize + BeginSend them (BeginMigration, whose response
# carries stream ids and CRC32C frame checksums). The piece BYTES move
# over the gpu_sim P2P stream RPCs the donor initiates.
_MIGRATION_METHODS = {
    "PlanPieces": ("uu", None, None),
    "BeginMigration": ("uu", None, None),
}

_SERVICES = {
    "gpu_sim.GPUDevice": _DEVICE_METHODS,
    "gpu_sim.GPUCoordinator": _COORDINATOR_METHODS,
    "dsml_obs.ObsPlane": _OBS_METHODS,
    "dsml_migrate.ShardMigration": _MIGRATION_METHODS,
}


def add_servicer_to_server(service_name: str, servicer, server: grpc.Server) -> None:
    """Register ``servicer`` (an object with one method per RPC) on ``server``."""
    methods = _SERVICES[service_name]
    handlers = {}
    for name, (arity, req_cls, resp_cls) in methods.items():
        fn = getattr(servicer, name)
        deser = req_cls.FromString if req_cls is not None else None
        ser = resp_cls.SerializeToString if resp_cls is not None else None
        if arity == "uu":
            handlers[name] = grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=deser, response_serializer=ser
            )
        else:
            handlers[name] = grpc.stream_unary_rpc_method_handler(
                fn, request_deserializer=deser, response_serializer=ser
            )
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(service_name, handlers),))


class _Stub:
    """Client stub: one callable per RPC, matching generated-stub ergonomics."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        self._channel = channel  # retained so owners can close() on replace
        for name, (arity, req_cls, resp_cls) in _SERVICES[service_name].items():
            path = f"/{service_name}/{name}"
            ser = req_cls.SerializeToString if req_cls is not None else None
            deser = resp_cls.FromString if resp_cls is not None else None
            if arity == "uu":
                callable_ = channel.unary_unary(
                    path, request_serializer=ser, response_deserializer=deser
                )
            else:
                callable_ = channel.stream_unary(
                    path, request_serializer=ser, response_deserializer=deser
                )
            setattr(self, name, callable_)


def device_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, "gpu_sim.GPUDevice")


def coordinator_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, "gpu_sim.GPUCoordinator")


def obs_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, "dsml_obs.ObsPlane")


def add_device_servicer(servicer, server: grpc.Server) -> None:
    add_servicer_to_server("gpu_sim.GPUDevice", servicer, server)


def add_coordinator_servicer(servicer, server: grpc.Server) -> None:
    add_servicer_to_server("gpu_sim.GPUCoordinator", servicer, server)


def add_obs_servicer(servicer, server: grpc.Server) -> None:
    add_servicer_to_server("dsml_obs.ObsPlane", servicer, server)


def migration_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, "dsml_migrate.ShardMigration")


def add_migration_servicer(servicer, server: grpc.Server) -> None:
    add_servicer_to_server("dsml_migrate.ShardMigration", servicer, server)
