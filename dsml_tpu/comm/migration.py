"""Cross-host elastic state motion over hardened P2P streams.

``parallel.elastic._pull_host_state`` can reassemble a torn training state
from every shard a LOCAL device still holds — but a piece whose only
survivors sit on another host used to be a refusal ("cross-host state
motion is not implemented") that degraded a real multi-host shrink into a
full checkpoint restore. This module is the missing motion, built on the
paper's own P2P stream API (``BeginSend``/``BeginReceive``/``StreamSend``,
reimplemented for real in ``comm.device_server``):

- **Donor side** — :class:`StateDonor` registers the host's live training
  state (tree leaves keyed by path); on request it serializes the exact
  surviving piece, stages the bytes in its device registry, and
  ``BeginSend``s them to the requesting host. The response carries the
  stream id plus **per-chunk CRC32C frame checksums** computed sender-side
  (``runtime.native.crc32c`` — the C kernel when built).
- **Receiver side** — :class:`ShardMigrator` resolves donors from the
  coordinator's membership table (``from_comm``), arms ``BeginReceive``
  with bounded-backoff re-arm, polls ``GetStreamStatus`` under a deadline
  (``DSML_MIGRATE_TIMEOUT_S``), validates every CRC frame on arrival, and
  on a dropped stream harvests the delivered prefix
  (``DeviceRuntime.take_partial``) and re-requests the remainder from a
  **resumable offset** instead of re-shipping delivered bytes.
- **Fallback contract** — when streams cannot deliver (donor dead,
  integrity failure after retries, deadline blown), :class:`MigrationError`
  is raised; the elastic controller converts exactly that into the
  coordinated checkpoint restore (``docs/ELASTIC.md`` § Multi-host
  recovery). Corrupted bytes NEVER land silently: a CRC mismatch aborts
  the piece before anything is written into the training state.

Only control messages (JSON over the ``dsml_migrate.ShardMigration``
extension service, same raw-bytes pattern as the obs plane) ride the new
RPCs; the payload bytes move over the existing gpu_sim stream RPCs, so the
recovery path exercises — and is protected by the same chaos harness as —
the data plane itself (``runtime.chaos.WireFaultPlan``).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

import grpc
import numpy as np

from dsml_tpu.comm import rpc
from dsml_tpu.comm.client import call_with_retries
from dsml_tpu.comm.device_server import _STREAM_CHUNK, DeviceError
from dsml_tpu.comm.proto import gpu_sim_pb2 as pb
from dsml_tpu.obs import flight_recorder, get_registry
from dsml_tpu.runtime.native import crc32c
from dsml_tpu.utils.config import env_float as _env_float
from dsml_tpu.utils.config import env_int as _env_int
from dsml_tpu.utils.logging import get_logger

__all__ = [
    "MIGRATE_CHUNK",
    "MigrationConfig",
    "MigrationError",
    "MigrationServicer",
    "ShardMigrator",
    "StateDonor",
    "tree_path_str",
]

log = get_logger("migration")

# CRC frame size — THE stream DataChunk size, so "one corrupt chunk" maps
# to exactly one failed frame in the receiver's validation (structural,
# not a comment-enforced copy).
MIGRATE_CHUNK = _STREAM_CHUNK


class MigrationError(RuntimeError):
    """P2P streams could not deliver a piece (donor dead, integrity
    failure, deadline blown). The caller's contract is the coordinated
    checkpoint fallback — never a silent zero-fill or partial landing."""


@dataclasses.dataclass
class MigrationConfig:
    """Receiver-side knobs (env defaults: ``DSML_MIGRATE_*``)."""

    timeout_s: float = 30.0      # per-piece stream deadline
    retries: int = 2             # whole-piece retries after the first attempt
    poll_interval_s: float = 0.01
    recv_addr: int = 0x1000      # landing address in the local registry

    @classmethod
    def from_env(cls) -> "MigrationConfig":
        return cls(
            timeout_s=_env_float("DSML_MIGRATE_TIMEOUT_S", cls.timeout_s),
            retries=_env_int("DSML_MIGRATE_RETRIES", cls.retries),
            poll_interval_s=_env_float(
                "DSML_MIGRATE_POLL_S", cls.poll_interval_s
            ),
            recv_addr=_env_int("DSML_MIGRATE_RECV_ADDR", cls.recv_addr),
        )


def tree_path_str(prefix: str, path) -> str:
    """Canonical string key for a tree leaf: ``prefix/part/part/...`` —
    DictKey/SequenceKey/GetAttrKey entries stringify to their key/index,
    so donor and receiver derive identical keys from identical trees."""
    parts = [prefix]
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        else:  # pragma: no cover — future jax key types
            parts.append(str(entry))
    return "/".join(parts)


def payload_chunk_crcs(payload: bytes) -> list[int]:
    """CRC32C per MIGRATE_CHUNK frame, at ABSOLUTE payload offsets — a
    resumed suffix re-validates against the original frame table."""
    return [
        crc32c(payload[off : off + MIGRATE_CHUNK])
        for off in range(0, len(payload), MIGRATE_CHUNK)
    ] or [crc32c(b"")]


# ---------------------------------------------------------------------------
# donor side
# ---------------------------------------------------------------------------


class StateDonor:
    """Serves pieces of this host's live training state to migrating peers.

    ``register_state`` snapshots array leaves of a tree (host numpy — the
    donor's addressable view); each leaf is keyed by :func:`tree_path_str`
    so both hosts agree on names without any schema exchange. Piece
    requests slice the registered array, stage the bytes in the device
    registry, and ``BeginSend`` them toward the requester's rank (routing
    via the peer table the coordinator installed at CommInit)."""

    def __init__(self, runtime, stage_addr: int | None = None):
        self.runtime = runtime
        self._arrays: dict[str, np.ndarray] = {}
        # per-key request trace identity (serving KV handoffs register
        # per-request transients): carried in plan/stream descriptors so
        # the cross-host pull stays attributable to ONE request's trace
        self._trace_ids: dict[str, str] = {}
        if stage_addr is None:
            # default to the UPPER half of the registry: the lower half is
            # where a ShardMigrator on this same host lands INCOMING pieces
            # (recv_addr default = min_addr) — a bidirectional shrink (both
            # hosts donate to each other) must not have arrivals overwrite
            # staged outgoing payloads
            mem = runtime.memory
            stage_addr = mem.min_addr + (mem.max_addr - mem.min_addr) // 2
        self._stage_base = stage_addr
        self._stage_next = self._stage_base
        # staged ranges whose background push may not have read them yet:
        # stream_id -> (addr, nbytes); pruned once the stream is terminal
        self._live_stages: dict[int, tuple[int, int]] = {}
        self._lock = threading.Lock()
        # snapshot version (e.g. the training step the registered state
        # belongs to): carried in every plan/stream descriptor so a
        # receiver expecting a specific step REFUSES a stale donor instead
        # of silently landing old bytes that pass their own CRCs
        self.version = None
        # memory-ledger source (docs/OBSERVABILITY.md § Memory ledger):
        # the staging spans whose background pushes are still reading
        # them — a wedged stream shows up as bytes that never release
        from dsml_tpu.obs.memory import get_memory_ledger

        get_memory_ledger().register_source(
            "migration_staging", self.staged_bytes, name=f"donor/{id(self):x}"
        )

    def staged_bytes(self) -> float:
        """Bytes of staging spans still owned by in-flight (or reserved)
        sends — terminal streams are pruned before counting."""
        with self._lock:
            self._prune_stages_locked()
            return float(sum(span for _, span in self._live_stages.values()))

    # -- registration ------------------------------------------------------

    def register_array(self, key: str, arr,
                       trace_id: str | None = None) -> None:
        self._arrays[key] = np.asarray(arr)
        if trace_id is not None:
            self._trace_ids[key] = str(trace_id)
        else:
            self._trace_ids.pop(key, None)

    def register_state(self, tree, prefix: str = "state",
                       version=None) -> int:
        """Register every array leaf of ``tree`` under ``prefix``; returns
        the number of leaves registered. Device arrays are pulled to host
        once here (the donor's addressable shards are, by definition, the
        ones it can serve). ``version`` stamps the snapshot (conventionally
        the training step) — re-register per step in a live trainer so
        receivers can pin the step they expect."""
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        n = 0
        for path, leaf in flat:
            if leaf is None or not hasattr(leaf, "shape"):
                continue
            self.register_array(
                tree_path_str(prefix, path),
                jax.device_get(leaf) if isinstance(leaf, jax.Array) else leaf,
            )
            n += 1
        if version is not None:
            self.version = version
        return n

    def keys(self) -> list[str]:
        return sorted(self._arrays)

    def unregister(self, prefix: str) -> int:
        """Drop every registered array at ``prefix`` or under
        ``prefix/...``; returns how many were dropped. The serving KV
        handoff registers per-request transients
        (``serving.handoff.register_with_donor``) — without release, a
        long-lived prefill host would grow its donor table one request at
        a time. Elastic-migration state is re-registered per step and
        never needs this."""
        doomed = [k for k in self._arrays
                  if k == prefix or k.startswith(prefix + "/")]
        for k in doomed:
            del self._arrays[k]
            self._trace_ids.pop(k, None)
        return len(doomed)

    # -- piece serving -----------------------------------------------------

    def plan(self, keys: list[str]) -> dict:
        """Which of ``keys`` this donor holds → {key: {shape, dtype,
        version}}; missing keys map to None (the receiver's
        donor-selection input)."""
        out = {}
        for key in keys:
            arr = self._arrays.get(key)
            if arr is None:
                out[key] = None
                continue
            info = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                    "version": self.version}
            trace_id = self._trace_ids.get(key)
            if trace_id is not None:  # request-scoped keys only (handoffs)
                info["trace_id"] = trace_id
            out[key] = info
        return out

    def _note_staged_locked(self) -> None:
        get_registry().gauge(
            "migration_staging_bytes",
            "donor staging-area bytes held by in-flight P2P sends",
        ).set(float(sum(s for _, s in self._live_stages.values())))

    def _prune_stages_locked(self) -> None:
        for sid in list(self._live_stages):
            if not isinstance(sid, int):
                continue  # uncommitted reservation token: always live
            st = self.runtime.streams.get(sid)
            if st is None or st.status != pb.IN_PROGRESS:
                del self._live_stages[sid]
        self._note_staged_locked()

    def _stage(self, nbytes: int) -> tuple[int, object]:
        """Sequential staging allocator over the registry's upper half,
        wrapping when the next payload would overrun. A wrap must never
        clobber a staged payload whose background push has not finished
        reading it — live ranges are tracked per stream and an allocation
        that would overlap one raises RESOURCE_EXHAUSTED (the receiver
        retries or falls back) instead of corrupting an in-flight send.
        The range is RESERVED under the allocation lock (returned token),
        then re-keyed to the stream id via :meth:`_commit_stage` — two
        concurrent BeginMigrations can otherwise both wrap onto the same
        base before either records its range."""
        span = max((nbytes + 15) & ~15, 16)
        token = object()
        with self._lock:
            self._prune_stages_locked()
            if self._stage_base + span > self.runtime.memory.max_addr:
                raise DeviceError(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"piece of {nbytes} bytes exceeds the staging area "
                    f"({self.runtime.memory.max_addr - self._stage_base} bytes)",
                )
            if self._stage_next + span > self.runtime.memory.max_addr:
                self._stage_next = self._stage_base
            addr = self._stage_next
            for a, m in self._live_stages.values():
                if addr < a + m and a < addr + span:
                    raise DeviceError(
                        grpc.StatusCode.RESOURCE_EXHAUSTED,
                        f"staging area exhausted by in-flight sends "
                        f"({len(self._live_stages)} live)",
                    )
            self._stage_next = addr + span
            self._live_stages[token] = (addr, span)
            self._note_staged_locked()
            return addr, token

    def _commit_stage(self, token: object, stream_id: int) -> None:
        with self._lock:
            self._live_stages[stream_id] = self._live_stages.pop(token)

    def _abort_stage(self, token: object) -> None:
        with self._lock:
            self._live_stages.pop(token, None)
            self._note_staged_locked()

    def begin_pieces(self, pieces: list[dict], dst_rank: int) -> list[dict]:
        """Serialize + BeginSend each requested piece; returns one stream
        descriptor per piece: stream id, sizes, and the CRC32C frame table
        the receiver validates against. ``offset`` resumes a dropped
        stream: only ``payload[offset:]`` is re-shipped, but the checksum
        table always describes the FULL payload."""
        out = []
        for req in pieces:
            key = req["key"]
            arr = self._arrays.get(key)
            if arr is None:
                raise KeyError(f"donor holds no array for {key!r}")
            idx = tuple(slice(int(s), int(e)) for s, e in req["piece"])
            sub = np.ascontiguousarray(arr[idx])
            payload = sub.tobytes()
            offset = int(req.get("offset", 0))
            if not 0 <= offset < max(len(payload), 1):
                raise ValueError(
                    f"resume offset {offset} outside payload of {len(payload)} bytes"
                )
            send = payload[offset:]
            addr, token = self._stage(len(send))
            try:
                self.runtime.memory.write(addr, send)
                stream_id = self.runtime.begin_send(addr, len(send), dst_rank)
            except BaseException:
                self._abort_stage(token)
                raise
            self._commit_stage(token, stream_id)
            out.append({
                "key": key,
                "stream_id": stream_id,
                "offset": offset,
                "nbytes": len(send),
                "total_nbytes": len(payload),
                # the frame table describes the FULL payload and the
                # receiver keeps the copy from the offset-0 response —
                # re-CRCing every byte per resume would tax exactly the
                # path that is already struggling
                "chunk_crcs": payload_chunk_crcs(payload) if offset == 0 else [],
                "dtype": str(sub.dtype),
                "shape": list(sub.shape),
                "version": self.version,
                **({"trace_id": self._trace_ids[key]}
                   if key in self._trace_ids else {}),
            })
            log.info(
                "donor: piece %s %s -> rank %d (stream %d, %d B from offset %d)",
                key, req["piece"], dst_rank, stream_id, len(send), offset,
            )
        return out


class MigrationServicer:
    """Wire adapter: StateDonor ⇄ dsml_migrate.ShardMigration (raw JSON)."""

    def __init__(self, donor: StateDonor):
        self.donor = donor

    def PlanPieces(self, request, context):  # noqa: N802 (RPC names)
        req = json.loads(bytes(request).decode("utf-8"))
        return json.dumps({"pieces": self.donor.plan(req.get("keys", []))}).encode()

    def BeginMigration(self, request, context):  # noqa: N802
        req = json.loads(bytes(request).decode("utf-8"))
        try:
            streams = self.donor.begin_pieces(
                req.get("pieces", []), int(req["dst_rank"])
            )
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except DeviceError as e:
            context.abort(e.code, str(e))
        except (ValueError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return json.dumps({"streams": streams}).encode()


# ---------------------------------------------------------------------------
# receiver side
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Donor:
    rank: int
    address: str
    channel: object
    stub: object        # dsml_migrate.ShardMigration
    dev_stub: object    # gpu_sim.GPUDevice on the same channel
    alive: bool = True


class ShardMigrator:
    """Pulls remote-only pieces into the local piecewise reassembly.

    ``donors`` is the membership-table view of the other hosts'
    device-server endpoints ([(rank, address)]); ``self_rank`` is the rank
    donors push streams to (this host's device server). Integrity and
    liveness hardening per piece:

    1. donor selection — first live donor whose ``PlanPieces`` lists the
       leaf (plans are cached per donor);
    2. ``BeginMigration`` / ``BeginReceive`` / ``GetStreamStatus`` all ride
       :func:`comm.client.call_with_retries` (transient UNAVAILABLE /
       DEADLINE_EXCEEDED flakes retried with jittered bounded backoff);
    3. every arrived payload is validated frame-by-frame against the
       donor's CRC32C table before a byte reaches the caller — a mismatch
       counts into ``comm_stream_integrity_failures_total`` and aborts the
       attempt;
    4. a dropped/stalled stream is harvested (``take_partial``) and the
       remainder re-requested from the delivered offset, under one
       per-piece deadline; exhausting retries raises
       :class:`MigrationError` (the checkpoint-fallback signal)."""

    def __init__(
        self,
        local_runtime,
        self_rank: int,
        donors: list[tuple[int, str]],
        config: MigrationConfig | None = None,
        local_address: str | None = None,
        expect_version=None,
    ):
        self.local = local_runtime
        self.self_rank = self_rank
        self.config = config or MigrationConfig.from_env()
        # pin the snapshot version (conventionally the training step) the
        # donors must serve: a donor whose registered state carries any
        # OTHER version is treated as not holding the piece — stale bytes
        # pass their own CRCs, so freshness must be checked explicitly
        self.expect_version = expect_version
        self._donors: list[_Donor] = []
        for rank, addr in donors:
            channel = grpc.insecure_channel(addr)
            self._donors.append(_Donor(
                rank, addr, channel,
                rpc.migration_stub(channel), rpc.device_stub(channel),
            ))
        # loopback stub for the local arm/poll RPCs: with an address the
        # calls ride real gRPC (and its retry semantics); without one they
        # go straight at the runtime object (in-process tests)
        self._local_stub = None
        if local_address is not None:
            self._local_channel = grpc.insecure_channel(local_address)
            self._local_stub = rpc.device_stub(self._local_channel)
        self._plans: dict[str, dict] = {}
        self._lock = threading.Lock()
        self.stats = {
            "pieces": 0, "bytes": 0, "ms": 0.0,
            "retries": 0, "resumed": 0, "integrity_failures": 0,
        }
        self._registry = get_registry()

    @classmethod
    def from_comm(
        cls,
        members: list[tuple[int, int, str]],
        local_runtime,
        config: MigrationConfig | None = None,
        expect_version=None,
    ) -> "ShardMigrator":
        """Coordinator-brokered routing: ``members`` is the membership
        table ``CoordinatorRuntime.comm_members`` /
        ``GetCommStatus.members`` returns ([(rank, device_id, address)]);
        this host's own entry (matched by device id or bound address)
        becomes ``self_rank``, every other entry a donor."""
        self_rank = None
        donors = []
        for rank, device_id, address in members:
            if (device_id == local_runtime.device_id
                    or address == local_runtime.bound_address):
                self_rank = rank
            else:
                donors.append((rank, address))
        if self_rank is None:
            raise ValueError(
                f"local device {local_runtime.device_id} "
                f"({local_runtime.bound_address}) is not in the membership table"
            )
        return cls(local_runtime, self_rank, donors, config=config,
                   local_address=local_runtime.bound_address,
                   expect_version=expect_version)

    def close(self) -> None:
        for donor in self._donors:
            try:
                donor.channel.close()
            except Exception:  # noqa: BLE001 — close is best-effort
                pass
        if self._local_stub is not None:
            self._local_channel.close()

    # -- donor selection ---------------------------------------------------

    def reset_donors(self) -> None:
        """Forget donor death verdicts and cached plans — called at the
        START of each recovery (``ElasticController._recover``): a donor
        that flaked during the LAST outage may be healthy now, and its
        registered snapshot may have moved to a new version. Without this,
        one transient outage would permanently degrade every later
        recovery to the checkpoint fallback."""
        for donor in self._donors:
            donor.alive = True
        with self._lock:
            self._plans.clear()

    def _donors_holding(self, key: str) -> list[_Donor]:
        """Live donors that hold ``key`` at the expected snapshot version
        (PlanPieces answers cached per donor+key for one recovery —
        ``reset_donors`` clears the cache)."""
        held = []
        for donor in self._donors:
            if not donor.alive:
                continue
            cache_key = (donor.address, key)
            with self._lock:
                cached = self._plans.get(cache_key)
            if cached is None:
                try:
                    resp = call_with_retries(
                        "PlanPieces",
                        lambda d=donor: d.stub.PlanPieces(
                            json.dumps({"keys": [key]}).encode(),
                            timeout=self.config.timeout_s,
                        ),
                    )
                except grpc.RpcError as e:
                    log.warning("migration: donor %s unreachable (%s)",
                                donor.address, e)
                    donor.alive = False
                    continue
                info = json.loads(bytes(resp).decode("utf-8"))["pieces"].get(key)
                cached = info if info is not None else False
                with self._lock:
                    self._plans[cache_key] = cached
            if not cached:
                continue
            if (self.expect_version is not None
                    and cached.get("version") != self.expect_version):
                log.warning(
                    "migration: donor %s holds %s at version %r, expected "
                    "%r — skipping (stale snapshot)", donor.address, key,
                    cached.get("version"), self.expect_version,
                )
                continue
            held.append(donor)
        return held

    # -- the per-piece pull ------------------------------------------------

    def fetch_piece(self, key: str, piece, dtype,
                    trace_id: str | None = None) -> np.ndarray:
        """Pull one piece (``piece`` = ((start, stop), ...) per dim) of leaf
        ``key`` over P2P streams; returns the typed array in piece shape.
        Raises :class:`MigrationError` when no donor can deliver.
        ``trace_id`` tags the flight-recorder event with the request trace
        this piece belongs to (the serving KV-handoff pull path)."""
        piece = [[int(s), int(e)] for s, e in piece]
        t0 = time.perf_counter()
        donors = self._donors_holding(key)
        if not donors:
            raise MigrationError(
                f"no live donor holds {key!r} (of {len(self._donors)} known)"
            )
        last_err: Exception | None = None
        for attempt in range(1 + max(self.config.retries, 0)):
            for donor in donors:
                if not donor.alive:
                    continue
                try:
                    data = self._fetch_from(donor, key, piece, dtype)
                except MigrationError as e:
                    last_err = e
                    self.stats["retries"] += 1
                    self._count("migration_retries_total")
                    log.warning("migration: %s from %s failed (attempt %d): %s",
                                key, donor.address, attempt + 1, e)
                    continue
                except grpc.RpcError as e:
                    last_err = e
                    donor.alive = False
                    log.warning("migration: donor %s died mid-piece (%s)",
                                donor.address, e)
                    continue
                ms = (time.perf_counter() - t0) * 1e3
                self.stats["pieces"] += 1
                self.stats["bytes"] += len(data)
                self.stats["ms"] += ms
                if self._registry.enabled:
                    self._registry.counter(
                        "migration_bytes_total",
                        "bytes moved by P2P shard migration",
                    ).inc(len(data))
                    self._registry.histogram(
                        "migration_ms", "per-piece shard-migration latency",
                        labels=("outcome",),
                    ).observe(ms, outcome="migrated")
                    self._registry.counter(
                        "migration_pieces_total",
                        "shard-migration piece outcomes", labels=("outcome",),
                    ).inc(outcome="migrated")
                extra = {"trace_id": trace_id} if trace_id else {}
                flight_recorder.record(
                    "migration_piece", key=key, bytes=len(data),
                    ms=round(ms, 3), donor=donor.address, **extra,
                )
                expect_shape = tuple(e - s for s, e in piece)
                try:
                    return np.frombuffer(data, dtype=np.dtype(dtype)).reshape(
                        expect_shape
                    )
                except ValueError as e:
                    # must stay a MigrationError: the controller's fallback
                    # catches RuntimeError — a raw ValueError would crash
                    # the recovery instead of degrading to the checkpoint
                    raise MigrationError(
                        f"delivered bytes for {key!r} do not reinterpret as "
                        f"{dtype}{expect_shape}: {e}"
                    ) from e
        if self._registry.enabled:
            self._registry.counter(
                "migration_pieces_total",
                "shard-migration piece outcomes", labels=("outcome",),
            ).inc(outcome="failed")
            self._registry.histogram(
                "migration_ms", "per-piece shard-migration latency",
                labels=("outcome",),
            ).observe((time.perf_counter() - t0) * 1e3, outcome="failed")
        raise MigrationError(
            f"piece {piece} of {key!r} undeliverable after "
            f"{1 + max(self.config.retries, 0)} attempt(s): {last_err}"
        )

    def _fetch_from(self, donor: _Donor, key: str, piece, dtype) -> bytes:
        """One delivery attempt with resumable offsets under one deadline."""
        cfg = self.config
        deadline = time.monotonic() + cfg.timeout_s
        parts: list[bytes] = []
        offset = 0
        total = None
        chunk_crcs = None
        backoff = 0.02
        expect_shape = [int(e - s) for s, e in piece]
        expect_nbytes = int(np.prod(expect_shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        while True:
            req = json.dumps({
                "dst_rank": self.self_rank,
                "pieces": [{"key": key, "piece": piece, "offset": offset}],
            }).encode()
            resp = call_with_retries(
                "BeginMigration",
                lambda: donor.stub.BeginMigration(req, timeout=cfg.timeout_s),
            )
            desc = json.loads(bytes(resp).decode("utf-8"))["streams"][0]
            if (self.expect_version is not None
                    and desc.get("version") != self.expect_version):
                raise MigrationError(
                    f"donor {donor.address} began serving {key!r} at "
                    f"version {desc.get('version')!r}, expected "
                    f"{self.expect_version!r} (snapshot moved mid-piece)"
                )
            # SEMANTIC validation, not just transport: the CRCs only prove
            # the bytes match the donor's snapshot — a donor holding the
            # leaf at a different dtype/shape would otherwise land bytes
            # that reinterpret silently (same itemsize) or crash the
            # recovery (different itemsize)
            if (desc.get("dtype") != str(np.dtype(dtype))
                    or list(desc.get("shape", [])) != expect_shape
                    or int(desc["total_nbytes"]) != expect_nbytes):
                raise MigrationError(
                    f"donor {donor.address} serves {key!r} as "
                    f"{desc.get('dtype')}{desc.get('shape')} "
                    f"({desc.get('total_nbytes')} B); expected "
                    f"{np.dtype(dtype)}{expect_shape} ({expect_nbytes} B)"
                )
            if total is None:
                total = int(desc["total_nbytes"])
                chunk_crcs = list(desc["chunk_crcs"])
            sid = int(desc["stream_id"])
            nbytes = int(desc["nbytes"])
            # bounded-backoff re-arm: the receive arm itself may flake
            self._arm(sid, nbytes, donor.rank)
            status = self._poll(sid, deadline, donor)
            if status == pb.SUCCESS:
                parts.append(self._read_local(cfg.recv_addr, nbytes))
                payload = b"".join(parts)
                if len(payload) != total:
                    raise MigrationError(
                        f"reassembled {len(payload)} of {total} bytes for {key!r}"
                    )
                self._validate(key, payload, chunk_crcs)
                return payload
            # FAILED or deadline: harvest whatever landed, then resume
            prefix = b""
            try:
                prefix = self.local.take_partial(sid)
            except Exception:  # noqa: BLE001 — stream may be unknown locally
                pass
            if prefix:
                parts.append(prefix)
                offset += len(prefix)
                self.stats["resumed"] += 1
                log.warning(
                    "migration: stream %d died at %d/%d bytes of %s; "
                    "resuming from offset %d", sid, offset, total, key, offset,
                )
            else:
                # the stream died before ANY byte flushed: nothing to
                # resume from, so this is a whole-suffix re-request — count
                # it as a retry so the stats (and the chaos verdict) see
                # that the fault exercised the recovery machinery
                self.stats["retries"] += 1
                self._count("migration_retries_total")
                log.warning(
                    "migration: stream %d died at %d/%d bytes of %s with no "
                    "new bytes; re-requesting", sid, offset, total, key,
                )
            if offset >= total:
                # the stream died AFTER delivering everything: the harvest
                # completed the payload — validate it like any other arrival
                payload = b"".join(parts)
                if len(payload) != total:
                    raise MigrationError(
                        f"reassembled {len(payload)} of {total} bytes for {key!r}"
                    )
                self._validate(key, payload, chunk_crcs)
                return payload
            if time.monotonic() >= deadline:
                raise MigrationError(
                    f"deadline ({cfg.timeout_s:.1f}s) blown at "
                    f"{offset}/{total} bytes of {key!r}"
                )
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.5)

    def _validate(self, key: str, payload: bytes, chunk_crcs) -> None:
        # the frames tile the payload exactly, so a whole-payload CRC on
        # top would re-scan every byte for zero extra information — one
        # pass over the frame table is the entire integrity check
        got = payload_chunk_crcs(payload)
        bad = [i for i, (a, b) in enumerate(zip(got, chunk_crcs)) if a != b]
        if len(got) != len(chunk_crcs) or bad:
            self.stats["integrity_failures"] += 1
            self._count("comm_stream_integrity_failures_total")
            flight_recorder.record(
                "migration_integrity_failure", key=key,
                bad_frames=bad[:8], frames=len(got),
            )
            raise MigrationError(
                f"CRC32C mismatch on {key!r}: frame(s) {bad[:8]} of "
                f"{len(got)} failed sender-side checksum validation"
            )

    def _count(self, name: str) -> None:
        if self._registry.enabled:
            self._registry.counter(name, name.replace("_", " ")).inc()

    # -- local stream plumbing (stub when an address is known, else direct) --

    def _arm(self, stream_id: int, nbytes: int, src_rank: int) -> None:
        # a LOCAL arm failure (e.g. the piece exceeds the landing buffer's
        # bounds) must surface as a MigrationError, not a grpc.RpcError —
        # fetch_piece attributes raw RpcErrors to donor death, and marking
        # healthy donors dead over a receiver-side problem both misleads
        # the logs and (per recovery) disables migration entirely
        try:
            if self._local_stub is not None:
                call_with_retries(
                    "BeginReceive",
                    lambda: self._local_stub.BeginReceive(
                        pb.BeginReceiveRequest(
                            streamId=pb.StreamId(value=stream_id),
                            recvBuffAddr=pb.MemAddr(value=self.config.recv_addr),
                            numBytes=nbytes,
                            srcRank=pb.Rank(value=src_rank),
                        ),
                        timeout=self.config.timeout_s,
                    ),
                )
            else:
                self.local.begin_receive(
                    stream_id, self.config.recv_addr, nbytes, src_rank
                )
        except (grpc.RpcError, DeviceError) as e:
            raise MigrationError(
                f"local BeginReceive for stream {stream_id} failed "
                f"(receiver-side): {e}"
            ) from e

    def _status(self, stream_id: int) -> int:
        try:
            if self._local_stub is not None:
                return call_with_retries(
                    "GetStreamStatus",
                    lambda: self._local_stub.GetStreamStatus(
                        pb.GetStreamStatusRequest(
                            streamId=pb.StreamId(value=stream_id)
                        ),
                        timeout=self.config.timeout_s,
                    ),
                ).status
            return self.local.stream_status(stream_id)
        except (grpc.RpcError, DeviceError) as e:
            raise MigrationError(
                f"local GetStreamStatus for stream {stream_id} failed "
                f"(receiver-side): {e}"
            ) from e

    def _poll(self, stream_id: int, deadline: float,
              donor: _Donor | None = None) -> int | None:
        """Poll the LOCAL stream to completion. Every few iterations also
        ask the DONOR's sender-side status: a dead push is terminal there
        immediately, while the receiver would sit IN_PROGRESS on a partial
        prefix until its stall deadline — the donor verdict is what lets a
        dropped stream resume within the piece deadline instead of after it."""
        ticks = 0
        while True:
            status = self._status(stream_id)
            if status != pb.IN_PROGRESS:
                return status
            if donor is not None and ticks % 5 == 4:
                try:
                    sender = call_with_retries(
                        "GetStreamStatus",
                        lambda: donor.dev_stub.GetStreamStatus(
                            pb.GetStreamStatusRequest(
                                streamId=pb.StreamId(value=stream_id)
                            ),
                            timeout=self.config.timeout_s,
                        ),
                        retries=1,
                    ).status
                except grpc.RpcError:
                    return pb.FAILED  # donor gone mid-stream: harvest + retry
                if sender == pb.FAILED:
                    return pb.FAILED
            if time.monotonic() >= deadline:
                return None
            ticks += 1
            time.sleep(self.config.poll_interval_s)

    def _read_local(self, addr: int, nbytes: int) -> bytes:
        return self.local.read_bytes(addr, nbytes)
