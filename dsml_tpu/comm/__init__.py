"""gRPC control plane: wire-compatible device/coordinator services + client.

Layer map (mirrors SURVEY.md §1, rebuilt TPU-native):
  L0  dsml_tpu/comm/proto/     wire protocol (gpu_sim.proto + generated pb2)
  L1  dsml_tpu/comm/device     per-chip device runtime (HBM buffer registry)
  L2  dsml_tpu/comm/coordinator communicator lifecycle + collectives dispatch
  L4  dsml_tpu/comm/client      training-client library
"""

from dsml_tpu.comm import proto  # noqa: F401
