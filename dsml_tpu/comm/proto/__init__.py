"""Generated protobuf messages for the gpu_sim wire protocol.

Regenerate with:
    protoc --python_out=dsml_tpu/comm/proto -I dsml_tpu/comm/proto \
        dsml_tpu/comm/proto/gpu_sim.proto
"""

from dsml_tpu.comm.proto import gpu_sim_pb2  # noqa: F401
