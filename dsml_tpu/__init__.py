"""dsml_tpu — a TPU-native distributed ML training framework.

A ground-up re-design of the capabilities of
``Helenbzbz/Distributed-Machine-Learning-Pipeline`` (a gRPC-simulated
NCCL-style data-parallel pipeline, see ``SURVEY.md``) for real TPU hardware:

- ``dsml_tpu.ops``       — XLA collectives (ring all-reduce over ICI via
  ``ppermute``, dtype-aware ReduceOps), attention ops, Pallas kernels.
- ``dsml_tpu.parallel``  — device-mesh parallelism: DP, TP, PP, SP (ring
  attention), Ulysses/2D context parallelism, EP (MoE).
- ``dsml_tpu.models``    — model families (MLP, CNN, ResNet-18, GPT-2).
- ``dsml_tpu.comm``      — the reference's wire-compatible gRPC control plane
  (CommInit / Memcpy / streams / AllReduceRing / health monitoring) backed by
  real device buffers instead of simulated byte maps.
- ``dsml_tpu.runtime``   — native (C++) host runtime: buffer/address registry,
  stream engine, IDX data parsing.
- ``dsml_tpu.checkpoint`` — preemption-safe sharded checkpointing: native
  binary-piece + JSON-manifest format, async atomic commits, resumable
  data iterators (``docs/CHECKPOINT.md``).
- ``dsml_tpu.obs``       — unified observability: metrics registry
  (counters/gauges/histograms, Prometheus + JSONL exposition), span
  tracing (Chrome trace-event export), step-time breakdown and
  goodput/MFU accounting (``docs/OBSERVABILITY.md``).
- ``dsml_tpu.utils``     — config, logging, metrics, tracing, and the
  checkpoint compat front-end (``utils.checkpoint.Checkpointer``).

The package name is the importable form of the repo's
``distributed-machine-learning-pipeline_tpu`` framework ("DSML" is the
reference's own module name, ``/root/reference/DSML``).
"""

__version__ = "0.1.0"

# Old-jax (0.4.x) compat shims must be in place before ANY framework module
# (or test) touches jax.shard_map / lax.axis_size — the package init is the
# one spot that runs first on every dsml_tpu.* import path. Imports jax but
# does not initialize a backend, so platform selection still works after.
from dsml_tpu.utils import compat as _compat

_compat.install()

# Lazy subpackage access keeps the heavy subpackages (models, comm, …) out
# of the import path until used.
_SUBPACKAGES = ("ops", "parallel", "models", "comm", "runtime", "utils", "cli",
                "checkpoint", "obs", "serving")


def __getattr__(name):
    if name in _SUBPACKAGES:
        import importlib

        mod = importlib.import_module(f"{__name__}.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
