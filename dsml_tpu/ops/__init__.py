"""TPU compute ops: XLA collectives, attention, and Pallas kernels."""

from dsml_tpu.ops.collectives import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    naive_all_reduce,
    reduce_scatter,
    ring2_all_reduce,
    ring_all_reduce,
    ring_pass,
    ring_perm_tables,
)
from dsml_tpu.ops.flash import (  # noqa: F401
    flash_attention,
    flash_attention_lse,
    flash_block_grads,
    ring_flash_attention,
)
from dsml_tpu.ops.ring_attention import (  # noqa: F401
    causal_keep_fraction,
    ring_kv_wire_bytes,
)
