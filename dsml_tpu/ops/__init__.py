"""TPU compute ops: XLA collectives, attention, and Pallas kernels."""

from dsml_tpu.ops.collectives import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_reduce,
    all_to_all,
    naive_all_reduce,
    reduce_scatter,
    ring2_all_reduce,
    ring_all_reduce,
)
from dsml_tpu.ops.flash import (  # noqa: F401
    flash_attention,
    flash_attention_lse,
    ring_flash_attention,
)
