"""XLA collectives over the TPU ICI mesh — the framework's data plane.

This replaces the reference's hand-rolled gRPC "NCCL" (SURVEY.md §5.8): there,
a coordinator drove per-device ``BeginSend``/``BeginReceive``/``StreamSend``
RPCs in a 2(n-1)-step ring schedule
(``DSML/gpu_coordinator_service/gpu_coordinator_server.go:339-356,379-566``),
but the transport was a same-device loopback and the reduction byte-wise uint8
addition (SURVEY.md §8.1-8.3). Here the *intended* semantics are implemented
for real:

- :func:`ring_all_reduce` — the textbook ring all-reduce (scatter-reduce then
  all-gather, 2(n-1) ``ppermute`` steps over the ICI ring), dtype-aware, with
  every :class:`ReduceOp` honored. One jitted program; data never touches the
  host.
- :func:`naive_all_reduce` — gather→reduce(→implicit broadcast) baseline,
  the collective-space analogue of the reference's host-mediated naive path
  (``gpu_coordinator_server.go:611-717``).
- :func:`all_reduce` — dispatcher: XLA's native collectives (``lax.psum`` etc.,
  usually fastest — XLA picks the topology-optimal algorithm), the explicit
  ring, or the naive baseline.
- :func:`reduce_scatter` / :func:`all_gather` / :func:`all_to_all` /
  :func:`ppermute_ring` — the remaining primitives TP/SP/EP layers build on.

All functions in the "inside shard_map" group take an ``axis_name`` and must
be called under ``jax.shard_map`` (or ``pmap``); the "host API" group
(:func:`make_stacked_all_reduce`) builds a jitted mesh program for callers
that hold a host-side stack of per-device buffers (the gRPC coordinator).
"""

from __future__ import annotations

import enum
import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "ReduceOp",
    "ring_perm_tables",
    "ring_pass",
    "ring_all_reduce",
    "ring2_all_reduce",
    "naive_all_reduce",
    "all_reduce",
    "hierarchical_all_reduce",
    "reduce_scatter",
    "flat_reduce_scatter",
    "flat_all_gather",
    "all_gather",
    "all_to_all",
    "ppermute_ring",
    "ring_wire_bytes",
    "make_stacked_all_reduce",
    "device_buffers_all_reduce",
]


class ReduceOp(enum.IntEnum):
    """Reduction operator. Values match the wire enum ``gpu_sim.ReduceOp``
    (reference ``DSML/proto/gpu_sim.proto:162-168``); unlike the reference,
    every variant is actually honored (fixes SURVEY.md §8.3)."""

    SUM = 0
    PROD = 1
    MIN = 2
    MAX = 3
    AVG = 4  # commented out of the reference proto; supported natively here

    @property
    def combine(self) -> Callable[[jax.Array, jax.Array], jax.Array]:
        return _COMBINE[self]


_COMBINE = {
    ReduceOp.SUM: jnp.add,
    ReduceOp.AVG: jnp.add,
    ReduceOp.PROD: jnp.multiply,
    ReduceOp.MIN: jnp.minimum,
    ReduceOp.MAX: jnp.maximum,
}


def _axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def ring_perm_tables(n: int) -> dict[int, list[tuple[int, int]]]:
    """Explicit ppermute perm tables for BOTH ring directions: ``+1`` sends
    rank i → i+1 (the reference's forward schedule), ``-1`` the mirror.
    THE one definition of the ring neighborhood — the fp32 ring
    (:func:`ring_all_reduce`/``ring2``), the quantized ring
    (``ops.quantization.quantized_ring_all_reduce``), and ring attention
    (``ops.ring_attention``) all rotate through these tables, so the three
    ring schedules cannot drift apart."""
    return {
        +1: [(i, (i + 1) % n) for i in range(n)],
        -1: [(i, (i - 1) % n) for i in range(n)],
    }


def ring_pass(x, axis_name: str, sign: int = +1):
    """One rotate step of the ring schedule: every leaf of ``x`` hops to the
    ``sign``-direction neighbor (``+1`` = rank i → i+1, ``-1`` = the
    mirror). Accepts a pytree (K/V pairs, (wire, scales) tuples) so callers
    rotate their whole hop state in one call. Must run under ``shard_map``."""
    if sign not in (+1, -1):
        raise ValueError(f"ring_pass sign must be +1 or -1, got {sign!r}")
    perm = ring_perm_tables(_axis_size(axis_name))[sign]
    return jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), x)


# ---------------------------------------------------------------------------
# Inside-shard_map collectives
# ---------------------------------------------------------------------------


def _ring_all_reduce_impl(x: jax.Array, axis_name: str, op: ReduceOp, signs: tuple) -> jax.Array:
    """THE ring schedule, generalized over directions: the payload splits
    into ``len(signs)`` parts, each running the 2(n−1)-step
    scatter-reduce/all-gather schedule around the ring in its own
    direction (sign +1 = the reference's forward schedule, send segment
    ``(rank−step) mod n`` / receive ``(rank−step−1) mod n``,
    ``gpu_coordinator_server.go:393-404``; sign −1 = the same schedule
    under the rank relabeling r → −r mod n). Each step issues every
    direction's hop back-to-back so the scheduler can overlap them.

    Works on any shape/dtype; the flattened buffer zero-pads up to a
    multiple of ``len(signs)·n`` (like the reference,
    gpu_coordinator_server.go:297-334; pad positions only ever combine
    with other ranks' pad positions and are sliced off before return).
    Small ints accumulate in a wider type so SUM across ranks can't wrap
    (the reference's uint8 wraparound bug, SURVEY.md §8.2)."""
    op = ReduceOp(op)
    n = _axis_size(axis_name)
    if n == 1:
        return x

    orig_shape, orig_dtype = x.shape, x.dtype
    acc_dtype = (
        jnp.promote_types(orig_dtype, jnp.int32)
        if jnp.issubdtype(orig_dtype, jnp.integer) else orig_dtype
    )
    flat = x.astype(acc_dtype).reshape(-1)
    size = flat.shape[0]
    k = len(signs)
    padded = -(-size // (k * n)) * (k * n)
    if padded != size:
        flat = jnp.pad(flat, (0, padded - size))
    seg = padded // (k * n)
    part = padded // k
    bufs = [flat[i * part : (i + 1) * part].reshape(n, seg) for i in range(k)]

    rank = lax.axis_index(axis_name)

    def hop(buf, sign, send_idx, recv_idx, combine):
        chunk = lax.dynamic_index_in_dim(buf, send_idx, axis=0, keepdims=False)
        recv = ring_pass(chunk, axis_name, sign)
        resident = lax.dynamic_index_in_dim(buf, recv_idx, 0, keepdims=False)
        new = combine(resident, recv) if combine is not None else recv
        return lax.dynamic_update_index_in_dim(buf, new, recv_idx, axis=0)

    # Scatter-reduce: after step t, segment (rank − sign·(t+1)) mod n holds
    # the partial reduction of t+2 ranks' contributions.
    for step in range(n - 1):
        bufs = [
            hop(b, s, (rank - s * step) % n, (rank - s * (step + 1)) % n, op.combine)
            for b, s in zip(bufs, signs)
        ]
    # All-gather: circulate each fully-reduced segment around the ring.
    for step in range(n - 1):
        bufs = [
            hop(b, s, (rank - s * (step - 1)) % n, (rank - s * step) % n, None)
            for b, s in zip(bufs, signs)
        ]

    out = bufs[0].reshape(-1) if k == 1 else jnp.concatenate([b.reshape(-1) for b in bufs])
    out = out[:size]
    if op == ReduceOp.AVG:
        out = out / n
    return out.reshape(orig_shape).astype(orig_dtype)


def ring_all_reduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """Ring all-reduce of ``x`` (same shape on every rank) across
    ``axis_name`` — the reference's forward 2(n−1)-step schedule as one
    XLA program whose sends are ``lax.ppermute`` hops over ICI and whose
    combiner is dtype-aware (see :func:`_ring_all_reduce_impl`)."""
    return _ring_all_reduce_impl(x, axis_name, op, (+1,))


def ring2_all_reduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """BIDIRECTIONAL ring all-reduce: two half-payloads run the ring
    schedule in OPPOSITE directions simultaneously — TPU ICI links are
    full duplex, so the reverse hops ride otherwise-idle capacity and
    each direction moves only S/2 bytes: ~2× the unidirectional ring's
    bandwidth at the same step count. Exactness vs
    :func:`ring_all_reduce` is pinned in tests for every ReduceOp."""
    return _ring_all_reduce_impl(x, axis_name, op, (+1, -1))


def naive_all_reduce(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """Gather-everything-then-reduce baseline (reference
    ``NaiveAllReduce``, gpu_coordinator_server.go:611-717, minus the simulated
    sleeps — the gRPC layer adds those for API parity). Moves n× more data
    than the ring; exists to benchmark the ring against."""
    op = ReduceOp(op)
    n = _axis_size(axis_name)
    if n == 1:
        return x
    gathered = lax.all_gather(x, axis_name)  # [n, ...] on every rank
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jnp.sum(gathered, axis=0)
        if op == ReduceOp.AVG:
            out = out / n
    elif op == ReduceOp.PROD:
        out = jnp.prod(gathered, axis=0)
    elif op == ReduceOp.MIN:
        out = jnp.min(gathered, axis=0)
    else:
        out = jnp.max(gathered, axis=0)
    return out.astype(x.dtype)


def ring_wire_bytes(
    n_elems: int, n_ranks: int, itemsize: int = 4, bidirectional: bool = False
) -> int:
    """Analytic per-rank wire bytes of one full-precision ring all-reduce:
    2(n−1) hops × one segment of the (padded) payload each, at ``itemsize``
    bytes per element. The bidirectional ring moves the same total volume
    (two half-payloads, half the bytes per direction). The fp32 baseline
    the quantized schedules' ``*_wire_reduction`` bench rows divide by
    (their counterpart is ``ops.quantization.quantized_ring_wire_bytes``);
    static shapes ⇒ exact, not sampled."""
    if n_ranks <= 1:
        return 0
    k = 2 if bidirectional else 1
    quantum = k * n_ranks
    padded = -(-n_elems // quantum) * quantum
    return 2 * (n_ranks - 1) * (padded // n_ranks) * itemsize


@functools.lru_cache(maxsize=8)
def _measured_alpha_beta(path: str) -> tuple[float, float] | None:
    """(α ms/round, β ms/byte) solved from a calibrated collective profile
    (``obs/regress.py --profile`` output, ``DSML_COLLECTIVE_PROFILE``):
    the measured ring and naive p50 at one (payload, device count) give
    two equations in the two alpha-beta unknowns —

        naive = α + (n−1)·S·β          (one round, n−1 shards received)
        ring  = 2(n−1)·α + 2·S·β       (2(n−1) rounds, ~2S bytes)

    Returns None (→ the analytic default) when the profile is missing any
    constant, is malformed, or solves to a non-physical α/β ≤ 0 (e.g. a
    CPU-fallback capture where the "wire" costs nothing) — a bad profile
    must degrade selection to the prior, never crash a trace."""
    import json

    try:
        with open(path) as f:
            constants = json.load(f)["constants"]

        def med(name: str) -> float:
            entry = constants[name]
            return float(entry["median"] if "median" in entry
                         else entry["fresh"])

        naive_ms = med("allreduce_naive_p50_ms")
        ring_ms = med("allreduce_ring_p50_ms")
        payload_b = med("allreduce_payload_mb") * (1 << 20)
        n = int(med("allreduce_devices"))
    except (OSError, ValueError, KeyError, TypeError):
        return None
    denom = payload_b * (2 * (n - 1) ** 2 - 2)
    if n < 2 or denom <= 0:
        return None
    beta = (2 * (n - 1) * naive_ms - ring_ms) / denom
    alpha = naive_ms - (n - 1) * payload_b * beta
    if alpha <= 0 or beta <= 0:
        return None
    return alpha, beta


def auto_all_reduce_algorithm(nbytes: int, n_devices: int, latency_bytes: int = 32768) -> str:
    """Payload-aware algorithm selection (the Blink/TACOS §6 Communication
    literature point — SURVEY.md §2.4: pick the collective schedule by where
    it sits on the latency/bandwidth tradeoff, not one-size-fits-all).

    Alpha-beta model with per-round latency α and per-byte time β: naive
    gather+reduce costs α + (n−1)·S·β (ONE round, every rank receives the
    other n−1 shards); the explicit ring costs 2(n−1)·α + ~2S·β (2(n−1)
    serialized rounds, bandwidth-optimal volume). Naive wins iff
    (n−3)·S·β < (2n−3)·α, i.e. S below a crossover that DEPENDS on n:
    ``latency_bytes`` is α/β — the payload whose transfer time equals one
    round of link latency — and the crossover is
    ``latency_bytes · (2n−3)/(n−3)`` (≈ 2·latency_bytes for large n; at
    n ≤ 3 the ring's extra rounds can never pay for its ≤ 0 byte savings,
    so naive always wins). Both inputs are static at trace time, so the
    choice costs nothing at runtime.

    With ``DSML_COLLECTIVE_PROFILE=<path>`` pointing at a calibrated
    profile (the ``collective_profile.json`` that ``obs/regress.py
    --profile`` exports from bench history), α and β come from MEASURED
    ring/naive latencies instead of the ``latency_bytes`` prior, and the
    choice compares the two predicted costs directly — the first
    calibration step toward the ROADMAP's cost-model planner. A missing or
    malformed profile silently keeps the analytic default.
    """
    if n_devices <= 3:
        return "naive"
    import os

    profile = os.environ.get("DSML_COLLECTIVE_PROFILE")
    if profile:
        ab = _measured_alpha_beta(profile)
        if ab is not None:
            alpha, beta = ab
            naive_ms = alpha + (n_devices - 1) * nbytes * beta
            ring_ms = 2 * (n_devices - 1) * alpha + 2 * nbytes * beta
            return "naive" if naive_ms <= ring_ms else "ring"
    crossover = latency_bytes * (2 * n_devices - 3) / (n_devices - 3)
    return "naive" if nbytes <= crossover else "ring"


def all_reduce(
    x: jax.Array,
    axis_name: str,
    op: ReduceOp = ReduceOp.SUM,
    algorithm: str = "xla",
) -> jax.Array:
    """All-reduce with selectable algorithm.

    ``xla``   — let XLA choose (``lax.psum``/``pmin``/``pmax``/``pmean``);
                on TPU this lowers to topology-aware ICI collectives and is
                the default for training code.
    ``ring``  — the explicit 2(n-1)-step ring (honest ring-latency numbers,
                BASELINE.md metric).
    ``ring2`` — bidirectional ring: two half-payloads in opposite
                directions per step (full-duplex ICI → ~2× ring bandwidth).
    ``naive`` — gather+reduce baseline.
    ``auto``  — pick ring vs naive from the static payload size and axis
                size (:func:`auto_all_reduce_algorithm`): latency-optimal
                one-round gather for small payloads, bandwidth-optimal ring
                for large — for deployments that want the explicit schedules
                (e.g. the wire-API coordinator) with topology awareness.
    """
    op = ReduceOp(op)
    if algorithm == "auto":
        algorithm = auto_all_reduce_algorithm(
            x.size * x.dtype.itemsize, _axis_size(axis_name)
        )
    if algorithm == "ring":
        return ring_all_reduce(x, axis_name, op)
    if algorithm == "ring2":
        return ring2_all_reduce(x, axis_name, op)
    if algorithm == "naive":
        return naive_all_reduce(x, axis_name, op)
    if algorithm != "xla":
        raise ValueError(f"unknown all-reduce algorithm {algorithm!r}")
    if op == ReduceOp.SUM:
        return lax.psum(x, axis_name)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis_name)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis_name)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis_name)
    # XLA has no native product collective; fall back to the ring.
    return ring_all_reduce(x, axis_name, op)


def hierarchical_all_reduce(
    x: jax.Array,
    inner_axis: str,
    outer_axis: str,
    op: ReduceOp = ReduceOp.SUM,
    algorithm: str = "xla",
) -> jax.Array:
    """Topology-aware two-level all-reduce (Blink/TACOS-style hierarchical
    collectives — the reference's §6 Communication literature, SURVEY.md
    §2.4): reduce-scatter over the *inner* (fast, e.g. intra-slice ICI)
    axis, all-reduce only 1/n_inner of the payload over the *outer* (slow,
    e.g. DCN) axis, then all-gather back over the inner axis. The slow hop
    carries n_inner× less data than a flat all-reduce over both axes.

    Result equals ``all_reduce`` over both axes for every :class:`ReduceOp`.
    """
    op = ReduceOp(op)
    n_inner = _axis_size(inner_axis)
    if n_inner == 1:
        return all_reduce(x, outer_axis, op, algorithm)
    inner_op = outer_op = op
    if op == ReduceOp.AVG:
        # average exactly once: SUM through both levels, divide at the end
        inner_op = outer_op = ReduceOp.SUM
    orig_shape, orig_dtype = x.shape, x.dtype
    acc_dtype = (
        jnp.promote_types(orig_dtype, jnp.int32)
        if jnp.issubdtype(orig_dtype, jnp.integer)
        else orig_dtype
    )
    flat = x.astype(acc_dtype).reshape(-1)
    size = flat.shape[0]
    padded = -(-size // n_inner) * n_inner
    if padded != size:
        # pad with the op's identity so pad lanes can't perturb real lanes
        flat = jnp.pad(
            flat, (0, padded - size),
            constant_values=_identity_pad_value(op, acc_dtype),
        )
    shard = reduce_scatter(flat.reshape(n_inner, padded // n_inner), inner_axis, inner_op)
    shard = all_reduce(shard, outer_axis, outer_op, algorithm)
    out = lax.all_gather(shard, inner_axis, axis=0, tiled=False).reshape(-1)[:size]
    if op == ReduceOp.AVG:
        out = out / (n_inner * _axis_size(outer_axis))
    return out.reshape(orig_shape).astype(orig_dtype)


def _identity_pad_value(op: ReduceOp, dtype) -> int | float:
    """The reduction identity for ``op`` on ``dtype`` — what padding must be
    filled with so pad lanes can't perturb real lanes when lanes from
    different ranks combine."""
    op = ReduceOp(op)
    if op == ReduceOp.PROD:
        return 1
    if op in (ReduceOp.MIN, ReduceOp.MAX):
        if jnp.issubdtype(dtype, jnp.floating):
            hi, lo = jnp.inf, -jnp.inf
        else:
            info = jnp.iinfo(dtype)
            hi, lo = info.max, info.min
        return hi if op == ReduceOp.MIN else lo
    return 0  # SUM / AVG


def flat_reduce_scatter(
    flat: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM
) -> tuple[jax.Array, int]:
    """Reduce-scatter a flat vector: rank i is left with contiguous segment
    i of the reduction. Returns ``(shard, padded_size)`` where ``shard`` has
    ``padded_size // n`` elements and ``padded_size`` is the vector length
    rounded up to a multiple of the axis size (identity-padded, so pad lanes
    are inert). The bucketed-gradient primitive: ZeRO-2 grad sync emits one
    of these per bucket (``dsml_tpu.parallel.bucketing``), each an
    independent collective XLA can overlap with remaining backward compute.
    """
    op = ReduceOp(op)
    n = _axis_size(axis_name)
    size = flat.shape[0]
    padded = -(-size // n) * n
    if padded != size:
        flat = jnp.pad(
            flat, (0, padded - size),
            constant_values=_identity_pad_value(op, flat.dtype),
        )
    shard = reduce_scatter(flat.reshape(n, padded // n), axis_name, op)
    return shard.reshape(-1), padded


def flat_all_gather(shard: jax.Array, axis_name: str, size: int) -> jax.Array:
    """Inverse of :func:`flat_reduce_scatter`'s layout: concatenate every
    rank's flat segment and drop the padding, returning the first ``size``
    elements."""
    return lax.all_gather(shard, axis_name, axis=0, tiled=True).reshape(-1)[:size]


def reduce_scatter(x: jax.Array, axis_name: str, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
    """Reduce across ranks, leaving rank i with shard i along axis 0 —
    the first half of the ring all-reduce, exposed for FSDP/ZeRO-style
    sharded optimizers."""
    op = ReduceOp(op)
    n = _axis_size(axis_name)
    if x.shape[0] % n != 0:
        raise ValueError(f"reduce_scatter: leading dim {x.shape[0]} not divisible by axis size {n}")
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
        if op == ReduceOp.AVG:
            out = out / n
        return out
    # Non-additive ops: reduce fully, then slice this rank's shard.
    full = naive_all_reduce(x, axis_name, op)
    shard = x.shape[0] // n
    return lax.dynamic_slice_in_dim(full, lax.axis_index(axis_name) * shard, shard, axis=0)


def all_gather(x: jax.Array, axis_name: str, axis: int = 0, tiled: bool = True) -> jax.Array:
    """Concatenate every rank's ``x`` along ``axis``."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x: jax.Array, axis_name: str, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all: split ``x`` n-ways along ``split_axis``, exchange, concat
    along ``concat_axis`` — the Ulysses sequence-parallelism primitive
    (SURVEY.md §5.7: heads↔sequence re-sharding)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=True)


def ppermute_ring(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Rotate ``x`` ``shift`` hops around the ring (K/V rotation for ring
    attention; the reference's BeginSend→next-rank intent, gpu_sim.proto:38)."""
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


# ---------------------------------------------------------------------------
# Host-facing API (used by the gRPC coordinator)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _stacked_all_reduce_fn(
    mesh: Mesh, axis_name: str, op: ReduceOp, algorithm: str, repeats: int = 1
):
    # Keyed per (mesh, axis, op, algorithm, repeats); jax.jit itself
    # specializes per input shape/dtype and retains those executables.
    # ``repeats`` chains the collective back-to-back inside ONE program —
    # bench.py uses it to difference away per-dispatch overhead.
    spec = P(axis_name)

    @functools.partial(
        jax.jit,
        in_shardings=NamedSharding(mesh, spec),
        out_shardings=NamedSharding(mesh, spec),
        donate_argnums=(0,),
    )
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    def fn(stacked):  # stacked: [1, ...] per-device shard
        x = stacked[0]
        for _ in range(repeats):
            x = all_reduce(x, axis_name, op, algorithm)
        return x[None]

    return fn


@functools.lru_cache(maxsize=None)
def _buffer_all_reduce_fn(mesh: Mesh, axis_name: str, op: ReduceOp, algorithm: str, dtype_str: str):
    """Jitted byte-buffer all-reduce: per-shard [1, count] uint8 in/out,
    reinterpreted as ``dtype_str`` for the reduction. NO donation — the
    inputs are the device servers' live registry buffers, which must stay
    valid for later Memcpy reads."""
    spec = P(axis_name)
    dt = jnp.dtype(dtype_str)

    @functools.partial(
        jax.jit,
        in_shardings=NamedSharding(mesh, spec),
        out_shardings=NamedSharding(mesh, spec),
    )
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    def fn(stacked_u8):  # [1, count] uint8 per shard
        flat = stacked_u8[0]
        if dt.itemsize > 1:
            x = lax.bitcast_convert_type(flat.reshape(-1, dt.itemsize), dt)
        else:
            x = lax.bitcast_convert_type(flat, dt)
        x = all_reduce(x, axis_name, op, algorithm)
        u8 = lax.bitcast_convert_type(x, jnp.uint8)
        return u8.reshape(-1)[None]

    return fn


def device_buffers_all_reduce(
    buffers: Sequence[jax.Array],
    mesh: Mesh,
    op: ReduceOp = ReduceOp.SUM,
    algorithm: str = "ring",
    dtype: str = "float32",
) -> list[jax.Array]:
    """All-reduce per-chip byte buffers WITHOUT any host round-trip.

    ``buffers[i]`` is a flat uint8 ``jax.Array`` resident on
    ``mesh.devices.flat[i]`` (the device server's registry buffer, viewed as
    ``dtype`` for the reduction). The shards are assembled into one global
    array in place (``jax.make_array_from_single_device_arrays`` — no
    copies), the jitted ring/psum program runs over the mesh, and the result
    comes back as one on-device array per chip, ready for
    ``BufferRegistry.put_array``. This is the coordinator's local-chip fast
    path: the reference shipped every ring step through gRPC + host memory
    (``gpu_coordinator_server.go:427-515``); here the ends stay in HBM too.
    """
    axis_name = mesh.axis_names[0]
    n = mesh.shape[axis_name]
    if len(buffers) != n:
        raise ValueError(f"expected {n} buffers for mesh axis {axis_name!r}, got {len(buffers)}")
    count = buffers[0].shape[0]
    if count % np.dtype(dtype).itemsize:
        raise ValueError(f"{count} bytes is not a multiple of {dtype} itemsize")
    for i, b in enumerate(buffers):
        if b.ndim != 1 or b.dtype != jnp.uint8 or b.shape[0] != count:
            raise ValueError(f"buffer {i}: expected flat uint8[{count}], got {b.dtype}{b.shape}")
    sharding = NamedSharding(mesh, P(axis_name))
    global_arr = jax.make_array_from_single_device_arrays(
        (n, count), sharding, [b.reshape(1, count) for b in buffers]
    )
    out = _buffer_all_reduce_fn(mesh, axis_name, ReduceOp(op), algorithm, str(np.dtype(dtype)))(
        global_arr
    )
    per_device = {s.device: s.data for s in out.addressable_shards}
    return [per_device[d].reshape(-1) for d in mesh.devices.flat]


def make_stacked_all_reduce(
    mesh: Mesh, op: ReduceOp = ReduceOp.SUM, algorithm: str = "ring", axis_name: str | None = None
) -> Callable[[np.ndarray], jax.Array]:
    """Build a jitted all-reduce over a host-side stack of per-device buffers.

    Input: array of shape ``[n_devices, ...]`` where slice i is device i's
    contribution (the coordinator's view of one buffer per communicator rank).
    Output: same shape, every slice equal to the reduction — i.e. the
    postcondition the reference's ``AllReduceRing`` advertised but never
    delivered (SURVEY.md §8.4). The whole 2(n-1)-step ring runs as ONE jitted
    program over the mesh; the host only pays one H2D + one D2H.
    """
    axis_name = axis_name or mesh.axis_names[0]
    op = ReduceOp(op)

    def run(stacked: np.ndarray) -> jax.Array:
        n = mesh.shape[axis_name]
        if stacked.shape[0] != n:
            raise ValueError(f"expected leading dim {n}, got {stacked.shape}")
        fn = _stacked_all_reduce_fn(mesh, axis_name, op, algorithm)
        return fn(jnp.asarray(stacked))

    return run
