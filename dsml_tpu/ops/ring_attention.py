"""Context-parallel ring attention over the Pallas flash kernel — the
sequence axis sharded across the ``cp`` mesh axis, KV blocks STREAMED around
the ring instead of any chip ever holding full-length attention.

This is the sequence-scaling tentpole the length ladder pointed at: a single
chip with selective remat tops out ~32k tokens; here each of the ``cp`` ranks
holds its S/cp slice of Q/K/V and the mesh, not the chip, holds the context.
Three properties distinguish it from the simpler ``ops.flash.
ring_flash_attention`` (which it supersedes for training):

- **Bidirectional ring2 schedule** — the per-rank KV shard splits into two
  halves that rotate in OPPOSITE directions via
  ``ops.collectives.ring_pass`` (the same ±1 perm tables the fp32 and
  quantized ring all-reduces rotate through). TPU ICI links are full
  duplex, so each direction carries HALF the KV volume on otherwise-idle
  reverse capacity — the ring2 trick, applied to attention's KV stream.
- **Causal hop skipping** — a visiting KV block whose source rank is
  strictly later in the sequence is fully masked for every resident query;
  the flash call is skipped via ``lax.cond`` (rank-dynamic: each device
  evaluates its own predicate at runtime), so late hops don't burn MXU time
  computing an all-−inf score block. Compute retained is (n+1)/2n of the
  full grid — asymptotically the causal 2× (see
  :func:`causal_keep_fraction`).
- **KV re-streaming backward** — the ring-LEVEL ``custom_vjp`` saves only
  this rank's residents (q, k, v, out, lse): O(S/cp) residuals. Plain
  autodiff through the forward loop would instead save every VISITING kv
  pair — n shards = the full sequence per chip, silently defeating the
  memory point of sequence parallelism. The backward re-streams K/V around
  the ring a second time, recomputing each hop's block gradients from the
  merged (out, lse) statistics (``ops.flash.flash_block_grads`` — flash
  residuals stay resident), accumulating dq locally while dk/dv ride the
  ring WITH their blocks and take one final hop home to their owners.

Per-hop (out, lse) pairs merge with logsumexp weights —

    lse_tot = logsumexp_i(lse_i);  out = Σᵢ exp(lse_i − lse_tot) · out_i

— which reconstructs exact full attention; forward AND backward parity to
the single-device flash kernel is pinned in ``tests/test_ring_attention.py``
at cp ∈ {2, 4}, causal and not, odd lengths included (the padded flash path
owns residual blocks). Wire volume is exactly counted, never sampled:
:func:`ring_kv_wire_bytes`.

Used by the model families as ``attn_impl="ring2"`` on meshes with cp > 1
(``parallel.hybrid`` composes cp with dp/fsdp; per-rank positions are offset
by the shard origin exactly as for the legacy sp ring).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from dsml_tpu.ops.collectives import ring_pass
from dsml_tpu.ops.flash import (flash_attention, flash_attention_lse,
                                flash_block_grads, flash_stream_hop)

__all__ = ["ring_attention", "ring_fused_mode", "ring_kv_wire_bytes",
           "causal_keep_fraction", "causal_critical_path_fraction",
           "zigzag_indices", "zigzag_inverse"]

_LSE_FLOOR = -1e30  # "nothing seen": logaddexp identity, exp(floor − x) = 0


def ring_fused_mode() -> str | None:
    """The fused KV-stream knob: ``DSML_RING_FUSED`` ∈ {"0"/"off" (unset
    default — the XLA-ppermute oracle schedule), "1"/"on"/"sendahead"
    (hop ``i+1``'s KV ppermute issues BEFORE hop ``i``'s flash calls, so
    the async collective overlaps the math — portable to any mesh),
    "dma" (the per-hop flash call absorbs the neighbor exchange as an
    in-kernel remote async copy — ``ops.flash.flash_stream_hop``;
    requires the ring axis to be the mesh's only axis, since the kernel
    addresses neighbors by LOGICAL device id)}. Read at trace time.
    Every mode computes the same merges in the same order — parity is
    pinned at cp ∈ {2, 4}, fwd and bwd, both layouts."""
    raw = os.environ.get("DSML_RING_FUSED", "").strip().lower()
    if raw in ("1", "on", "true", "sendahead", "auto"):
        return "sendahead"
    if raw == "dma":
        return "dma"
    return None


def _halves(s_local: int) -> list[tuple[int, int, int]]:
    """(row_start, row_len, ring direction) for the two KV half-shards.
    The first (ceil) half rotates forward, the second backward; a length-0
    half (s_local == 1) drops out entirely — no calls, no rotations."""
    h0 = (s_local + 1) // 2
    return [(start, length, sign)
            for start, length, sign in ((0, h0, +1), (h0, s_local - h0, -1))
            if length > 0]


# ---------------------------------------------------------------------------
# zigzag/striped shard layout (the causal load-balance fix)
# ---------------------------------------------------------------------------
# Contiguous sharding makes rank r execute ~(r+1)/n of the causal hop grid:
# rank 0 sees almost nothing unmasked, rank n−1 everything — late ranks ARE
# the critical path, so causal skipping saves mean MXU time but not wall
# time. The zigzag layout splits the sequence into 2n STRIPES and hands
# rank r stripes {r, 2n−1−r} (an early stripe paired with a late one — the
# Llama-3 / zigzag-ring trick): each rank then executes exactly (2n+1) of
# its 4n (q-stripe × kv-stripe) pairs, CONSTANT across ranks, so the
# critical path drops from ~1.0 of the grid to (2n+1)/4n ≈ ½ — the further
# ~2× at large cp the ROADMAP names. Wire volume is unchanged (every block
# still tours the ring); only WHERE the unmasked work lands moves. The
# caller owns the row placement: shard `x[..., zigzag_indices(n, S), :]`
# over cp and un-permute outputs with `zigzag_inverse` (positions fed to
# the model must ride the same permutation — parity pinned in tests).


def zigzag_indices(n_ranks: int, s_global: int) -> "np.ndarray":
    """Row permutation placing stripes {r, 2n−1−r} on rank r: sharding
    ``x[..., zigzag_indices(n, S), :]`` contiguously over cp gives every
    rank its zigzag shard. Requires ``s_global % (2·n_ranks) == 0``."""
    import numpy as np

    n = int(n_ranks)
    if s_global % (2 * n):
        raise ValueError(
            f"zigzag needs 2·cp stripes: {s_global} rows not divisible by "
            f"{2 * n}"
        )
    stripe = s_global // (2 * n)
    order = []
    for r in range(n):
        order.extend(range(r * stripe, (r + 1) * stripe))
        order.extend(range((2 * n - 1 - r) * stripe, (2 * n - r) * stripe))
    return np.asarray(order, np.int32)


def zigzag_inverse(n_ranks: int, s_global: int) -> "np.ndarray":
    """Inverse permutation: ``out[..., zigzag_inverse(n, S), :]`` restores
    global row order from a zigzag-sharded result."""
    import numpy as np

    perm = zigzag_indices(n_ranks, s_global)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=np.int32)
    return inv


def _zig_halves(s_local: int) -> list[tuple[int, int, int]]:
    """The zigzag KV split: the two resident STRIPES are the two ring
    halves (early stripe forward, late stripe backward) — equal lengths
    by construction, so the full-duplex volume split stays exact."""
    if s_local % 2:
        raise ValueError(
            f"zigzag needs an even per-rank length, got {s_local}"
        )
    st = s_local // 2
    return [(0, st, +1), (st, st, -1)]


def _q_blocks(layout: str, rank, s_local: int, n):
    """(row_start, row_len, global_start) for this rank's query blocks.
    Contiguous: one block at rank·s_local. Zigzag: the two stripes at
    their interleaved global origins (``rank`` may be traced)."""
    if layout == "zigzag":
        st = s_local // 2
        return [(0, st, rank * st), (st, st, (2 * n - 1 - rank) * st)]
    return [(0, s_local, rank * s_local)]


def _kv_global_start(layout: str, src, start: int, s_local: int, n):
    """Global position of a visiting KV half's first row, given its
    source rank (traced) and local row offset."""
    if layout == "zigzag":
        st = s_local // 2
        return src * st if start == 0 else (2 * n - 1 - src) * st
    return src * s_local + start


def _merge(run_out, run_lse, o, l):
    """Fold one hop's (out, lse) into the running pair with logsumexp
    weights (both f32). Skipped hops contribute (0, _LSE_FLOOR) — weight 0."""
    new_lse = jnp.logaddexp(run_lse, l)
    w_prev = jnp.exp(run_lse - new_lse)[..., None]
    w_new = jnp.exp(l - new_lse)[..., None]
    return w_prev * run_out + w_new * o, new_lse


def _keep_pair(layout, causal, hop, src, rank, k_start, q_gs, q_len):
    """(statically_known_keep, traced_predicate_or_None) for one
    (q block, visiting kv half) pair. Contiguous keeps its pinned rule —
    hop 0 unconditionally, later hops predicate on ``src <= rank`` (the
    whole-shard form). Zigzag predicates STRIPE-level causality at every
    hop (``kv_start <= q_end``): a rank's late stripe admits every early
    stripe and its early stripe rejects almost everything — the per-rank
    executed-pair count lands constant at 2n+1 (see
    :func:`causal_keep_fraction`)."""
    if not causal:
        return True, None
    if layout == "zigzag":
        return False, k_start <= q_gs + q_len - 1
    if hop == 0:
        return True, None
    return False, src <= rank


def _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k, interpret,
                   layout, fused=None):
    """n-hop bidirectional forward. Returns (out f32, lse f32) — exact full
    attention for this rank's query shard (rows in shard-local order; the
    zigzag layout's rows are the rank's two stripes back to back).

    ``fused`` picks the hop SCHEDULE (:func:`ring_fused_mode`), never the
    math: ``None`` rotates residents with a ppermute after each hop's
    flash calls (the oracle); ``"sendahead"`` issues the rotation BEFORE
    the hop's flash calls — no data dependence between them, so the
    async collective overlaps the MXU work; ``"dma"`` hands each
    direction's hop to :func:`ops.flash.flash_stream_hop`, which streams
    the resident half to the neighbor inside the kernel while the same
    kernel computes on it. All three fold identical (out, lse) pairs in
    identical order."""
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape

    run_out = jnp.zeros((b, h, s_local, d), jnp.float32)
    run_lse = jnp.full((b, h, s_local), _LSE_FLOOR, jnp.float32)

    halves = _halves(s_local) if layout == "contiguous" else _zig_halves(s_local)
    qblocks = _q_blocks(layout, rank, s_local, n)
    resident = {sign: (k[:, :, start:start + length],
                       v[:, :, start:start + length])
                for start, length, sign in halves}

    for hop in range(n):
        incoming: dict = {}
        if fused == "sendahead" and hop != n - 1:
            # next hop's KV stream launches before this hop's math — the
            # flash calls don't consume it, so the collective flies under
            # the compute instead of serializing after it
            incoming = {sign: ring_pass(kv, axis_name, sign)
                        for sign, kv in resident.items()}
        for start, length, sign in halves:
            kh, vh = resident[sign]
            src = (rank - sign * hop) % n  # whose half is resident this hop
            k_start = _kv_global_start(layout, src, start, s_local, n)
            for q_idx, (q_row, q_len, q_gs) in enumerate(qblocks):
                qb = q[:, :, q_row:q_row + q_len]

                def compute(qb, kh, vh, k_start=k_start, q_gs=q_gs):
                    o, l = flash_attention_lse(
                        qb, kh, vh, causal,
                        q_start=q_gs, k_start=k_start,
                        block_q=block_q, block_k=block_k, interpret=interpret,
                    )
                    return o.astype(jnp.float32), l

                always, pred = _keep_pair(layout, causal, hop, src, rank,
                                          k_start, q_gs, q_len)
                if fused == "dma" and hop != n - 1 and q_idx == 0:
                    # the hop rides the first q block's kernel: flash +
                    # in-kernel remote copy of (kh, vh) to the next rank;
                    # the skip predicate travels into the kernel because
                    # masked hops still move their bytes
                    o, l, k_nxt, v_nxt = flash_stream_hop(
                        qb, kh, vh,
                        jnp.bool_(True) if always else pred,
                        dst=(rank + sign) % n, src=(rank - sign) % n,
                        causal=causal, q_start=q_gs, k_start=k_start,
                        block_q=block_q, block_k=block_k,
                        interpret=interpret,
                        collective_id=7 if sign > 0 else 8,
                    )
                    o = o.astype(jnp.float32)
                    incoming[sign] = (k_nxt, v_nxt)
                elif always:
                    o, l = compute(qb, kh, vh)
                else:
                    # fully-masked pair: skip the flash call (the MXU
                    # win; the block still rides the ring for others)
                    o, l = lax.cond(
                        pred,
                        compute,
                        lambda qb, kh, vh, _ql=q_len: (
                            jnp.zeros((b, h, _ql, d), jnp.float32),
                            jnp.full((b, h, _ql), _LSE_FLOOR, jnp.float32),
                        ),
                        qb, kh, vh,
                    )
                mo, ml = _merge(run_out[:, :, q_row:q_row + q_len],
                                run_lse[:, :, q_row:q_row + q_len], o, l)
                run_out = run_out.at[:, :, q_row:q_row + q_len].set(mo)
                run_lse = run_lse.at[:, :, q_row:q_row + q_len].set(ml)
        if hop != n - 1:
            resident = (incoming if fused in ("sendahead", "dma")
                        else {sign: ring_pass(kv, axis_name, sign)
                              for sign, kv in resident.items()})
    return run_out, run_lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring(q, k, v, axis_name, causal, block_q, block_k, interpret, layout,
          fused):
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k,
                            interpret, layout, fused)
    return out.astype(q.dtype)


def _ring_fwd_rule(q, k, v, axis_name, causal, block_q, block_k, interpret,
                   layout, fused):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal, block_q, block_k,
                              interpret, layout, fused)
    # residuals are this rank's RESIDENTS only — O(S/cp), the whole point
    return out.astype(q.dtype), (q, k, v, out.astype(q.dtype), lse)


def _ring_bwd_rule(axis_name, causal, block_q, block_k, interpret, layout,
                   fused, res, g):
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape

    dq = jnp.zeros((b, h, s_local, d), jnp.float32)
    halves = _halves(s_local) if layout == "contiguous" else _zig_halves(s_local)
    qblocks = _q_blocks(layout, rank, s_local, n)
    # per direction: (k_half, v_half, dk_acc, dv_acc) travel TOGETHER — each
    # visiting block accumulates every rank's contribution as it tours the
    # ring, then takes one final hop home to its owner
    state = {sign: (k[:, :, start:start + length],
                    v[:, :, start:start + length],
                    jnp.zeros((b, h, length, d), jnp.float32),
                    jnp.zeros((b, h, length, d), jnp.float32))
             for start, length, sign in halves}

    for hop in range(n):
        kv_ahead: dict = {}
        if fused and hop != n - 1:
            # the K/V legs of the rotation have no dependence on this
            # hop's grads — stream them ahead so the transfer overlaps
            # the block-gradient math; the dk/dv accumulators can only
            # leave AFTER the hop's compute has folded into them, so
            # they rotate behind (same wire volume, earlier departure
            # for the bytes that CAN go early). The in-kernel "dma"
            # forward shares this backward: the dkv payload is produced
            # by the very kernel that would have to send it.
            kv_ahead = {sign: ring_pass((s[0], s[1]), axis_name, sign)
                        for sign, s in state.items()}
        for start, length, sign in halves:
            kh, vh, dkh, dvh = state[sign]
            src = (rank - sign * hop) % n
            k_start = _kv_global_start(layout, src, start, s_local, n)
            for q_row, q_len, q_gs in qblocks:
                qb = q[:, :, q_row:q_row + q_len]
                ob = out[:, :, q_row:q_row + q_len]
                lb = lse[:, :, q_row:q_row + q_len]
                gb = g[:, :, q_row:q_row + q_len]

                def grads(qb, kh, vh, ob, lb, gb, k_start=k_start, q_gs=q_gs):
                    return flash_block_grads(
                        qb, kh, vh, ob, lb, gb, None, causal,
                        q_start=q_gs, k_start=k_start,
                        block_q=block_q, block_k=block_k, interpret=interpret,
                    )

                always, pred = _keep_pair(layout, causal, hop, src, rank,
                                          k_start, q_gs, q_len)
                if always:
                    dq_p, dk_p, dv_p = grads(qb, kh, vh, ob, lb, gb)
                else:
                    dq_p, dk_p, dv_p = lax.cond(
                        pred,
                        grads,
                        lambda qb, kh, vh, ob, lb, gb, _l=length, _ql=q_len: (
                            jnp.zeros((b, h, _ql, d), jnp.float32),
                            jnp.zeros((b, h, _l, d), jnp.float32),
                            jnp.zeros((b, h, _l, d), jnp.float32),
                        ),
                        qb, kh, vh, ob, lb, gb,
                    )
                dq = dq.at[:, :, q_row:q_row + q_len].add(dq_p)
                dkh = dkh + dk_p
                dvh = dvh + dv_p
            state[sign] = (kh, vh, dkh, dvh)
        if hop != n - 1:
            if fused:
                state = {sign: kv_ahead[sign] + ring_pass(
                    (s[2], s[3]), axis_name, sign)
                    for sign, s in state.items()}
            else:
                state = {sign: ring_pass(s, axis_name, sign)
                         for sign, s in state.items()}

    # final hop: after compute at hop n−1 the resident block belongs to rank
    # (rank + sign) mod n — one more rotation in the SAME direction lands
    # every dk/dv accumulator back on its owner (K/V no longer need to ride)
    homed = {sign: ring_pass((s[2], s[3]), axis_name, sign)
             for sign, s in state.items()}
    dk_parts = {sign: kv[0] for sign, kv in homed.items()}
    dv_parts = {sign: kv[1] for sign, kv in homed.items()}
    order = [sign for _, _, sign in halves]  # row order: forward half first
    dk = jnp.concatenate([dk_parts[s] for s in order], axis=2)
    dv = jnp.concatenate([dv_parts[s] for s in order], axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
    layout: str = "contiguous",
    fused: str | None = "env",
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis_name`` (the
    ``cp`` mesh axis), one flash call per visiting KV half-block — call
    under ``shard_map`` with q/k/v = this rank's shard
    [batch, heads, S/cp, head_dim].

    ``fused`` selects the hop schedule: ``"env"`` (default) defers to
    ``DSML_RING_FUSED`` (:func:`ring_fused_mode`), ``None``/"off" is the
    XLA-ppermute oracle, ``"sendahead"`` overlaps each hop's KV rotation
    with its flash calls, ``"dma"`` absorbs the rotation into the flash
    kernel as an in-kernel remote copy (single-axis meshes). The
    schedule never changes the math — fwd/bwd parity across all modes
    is pinned at cp ∈ {2, 4}, both layouts.

    Bidirectional KV streaming (each direction moves half the volume),
    causal hop skipping, and a memory-lean backward that re-streams KV
    instead of saving every visiting block — see the module docstring.
    Any per-rank length works (odd residual blocks ride the flash kernel's
    padded path). Differentiable; parity to single-device flash pinned in
    tests.

    ``layout="zigzag"`` interprets each rank's rows as its two
    INTERLEAVED stripes (place them with :func:`zigzag_indices`; even
    per-rank length required): causal skipping then load-balances — every
    rank executes the same (2n+1)/4n of its pair grid instead of rank
    n−1 running everything (the critical path halves at large cp).
    Tokens/gradients stay exact under either layout.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [batch, heads, seq, head_dim], got {q.shape}")
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(
            f"layout must be 'contiguous' or 'zigzag', got {layout!r}"
        )
    if fused == "env":
        fused = ring_fused_mode()
    elif fused in ("off", "none", "0"):
        fused = None
    if fused not in (None, "sendahead", "dma"):
        raise ValueError(
            f"fused must be None, 'sendahead' or 'dma', got {fused!r}"
        )
    n = lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(q, k, v, causal, block_q, block_k, interpret)
    if layout == "zigzag" and q.shape[2] % 2:
        raise ValueError(
            f"zigzag needs an even per-rank length, got {q.shape[2]}"
        )
    return _ring(q, k, v, axis_name, causal, block_q, block_k, interpret,
                 layout, fused)


def ring_kv_wire_bytes(
    s_local: int,
    n_ranks: int,
    n_heads: int,
    head_dim: int,
    batch: int = 1,
    itemsize: int = 4,
    bidirectional: bool = True,
    backward: bool = False,
) -> int:
    """EXACT per-rank wire bytes of one ring-attention call (static shapes ⇒
    counted, not sampled — same contract as ``collectives.ring_wire_bytes``).

    Forward: n−1 hops, each moving this rank's resident K and V halves
    (both directions together always carry the FULL shard per hop; the
    bidirectional split halves the per-LINK volume, not the total).
    Backward: the same K/V re-stream with f32 dk/dv accumulators riding
    along, plus the final homing hop of the accumulators alone. Causal
    skipping saves MXU time only — every block still tours the full ring,
    so wire volume is schedule-determined.
    """
    if n_ranks <= 1:
        return 0
    h0 = (s_local + 1) // 2
    halves = [h for h in ((h0, s_local - h0) if bidirectional else (s_local,)) if h]
    rows = batch * n_heads * head_dim
    kv_hop = sum(2 * rows * h * itemsize for h in halves)       # k + v
    if not backward:
        return (n_ranks - 1) * kv_hop
    dkv_hop = sum(2 * rows * h * 4 for h in halves)             # f32 dk + dv
    return (n_ranks - 1) * (kv_hop + dkv_hop) + dkv_hop


def causal_keep_fraction(n_ranks: int, layout: str = "contiguous") -> float:
    """MEAN fraction of the hop grid causal skipping still executes.
    Contiguous: rank r runs r+1 of the n forward-direction hops and 1+r
    of the n backward-direction hops, so Σ(2r+2) / 2n² = (n+1)/(2n) —
    asymptotically the causal-mask 2×, realized at the schedule level.
    Zigzag: every rank executes exactly (2n+1) of its 4n stripe pairs —
    the SAME asymptotic mean, but constant per rank (see
    :func:`causal_critical_path_fraction`). The docs/TUNING.md savings
    table is generated from this."""
    n = int(n_ranks)
    if n <= 1:
        return 1.0
    if layout == "zigzag":
        return (2 * n + 1) / (4 * n)
    return (n + 1) / (2 * n)


def causal_critical_path_fraction(n_ranks: int,
                                  layout: str = "contiguous") -> float:
    """The SLOWEST rank's executed fraction — what actually bounds wall
    time, since every rank waits at the ring barrier. Contiguous: rank
    n−1 executes its whole grid (1.0 — causal skipping saves mean MXU
    time, not wall time). Zigzag: per-rank work is constant, so the
    critical path IS the mean (2n+1)/4n → ~½ at large cp — the zigzag
    layout's ~2× wall win."""
    n = int(n_ranks)
    if n <= 1:
        return 1.0
    if layout == "zigzag":
        return (2 * n + 1) / (4 * n)
    return 1.0
