"""Chunked softmax cross-entropy — full logits never materialize.

For a tied-embedding LM the loss ``mean(logsumexp(h·Wᵀ) − h·W[target])``
normally materializes [batch·seq, vocab] float32 logits (GPT-2-small at
batch 8 × seq 1024 × vocab 50257 is ~1.6 GB — often the single largest
tensor of the step). This computes the same value by scanning the vocab in
chunks with an online logsumexp, so peak memory is [N, chunk]:

- forward: running (row-max, sum-exp) across chunks + the target logit
  (each target row lives in exactly one chunk);
- backward (custom VJP): per chunk, recompute ``p = exp(h·Wcᵀ − lse)``,
  subtract the one-hot target, and accumulate ``dh += p·Wc`` and
  ``dWc = pᵀ·h`` — the textbook softmax-CE gradient, chunk by chunk.

This is the single-shard counterpart of the TP path's distributed-logsumexp
loss (``models/gpt2.py::loss_spmd``), which splits vocab across chips
instead of across time. Used automatically by GPT-2 when the vocab is
unsharded and large.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["chunked_softmax_xent"]


def _pad_vocab(wte: jax.Array, chunk: int):
    v = wte.shape[0]
    n_chunks = -(-v // chunk)
    padded = n_chunks * chunk
    if padded != v:
        wte = jnp.pad(wte, ((0, padded - v), (0, 0)))
    return wte, n_chunks, v


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _chunked_xent(h: jax.Array, wte: jax.Array, targets: jax.Array, chunk: int):
    """Per-row loss ``lse − tgt_logit``. h [N, d] (any float dtype — promoted
    to f32 for the reductions), wte [V, d], targets [N] int32 → [N] f32."""
    loss, _ = _forward(h, wte, targets, chunk)
    return loss


def _forward(h, wte, targets, chunk):
    n = h.shape[0]
    h32 = h.astype(jnp.float32)
    wte_p, n_chunks, v = _pad_vocab(wte, chunk)
    # keep the scanned weights in their stored dtype; cast per chunk inside
    # the body so only [chunk, d] ever exists in f32 (a whole-vocab f32 copy
    # would cost more than the logits this module avoids)
    w_chunks = wte_p.reshape(n_chunks, chunk, -1)

    def body(carry, inputs):
        m, s, tgt = carry
        w_c, c_idx = inputs
        logits = h32 @ w_c.astype(jnp.float32).T  # [N, chunk]
        col = c_idx * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)  # mask vocab padding
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1)
        local = targets - c_idx * chunk
        in_c = (local >= 0) & (local < chunk)
        safe = jnp.clip(local, 0, chunk - 1)
        tgt = tgt + jnp.where(in_c, jnp.take_along_axis(logits, safe[:, None], 1)[:, 0], 0.0)
        return (m_new, s, tgt), None

    m0 = jnp.full((n,), -jnp.inf, jnp.float32)
    s0 = jnp.zeros((n,), jnp.float32)
    t0 = jnp.zeros((n,), jnp.float32)
    (m, s, tgt), _ = lax.scan(body, (m0, s0, t0), (w_chunks, jnp.arange(n_chunks)))
    lse = m + jnp.log(s)
    return lse - tgt, lse


def _fwd_rule(h, wte, targets, chunk):
    loss, lse = _forward(h, wte, targets, chunk)
    return loss, (h, wte, targets, lse)


def _bwd_rule(chunk, res, g):  # g: [N] cotangent of the per-row loss
    h, wte, targets, lse = res
    h32 = h.astype(jnp.float32)
    wte_p, n_chunks, v = _pad_vocab(wte, chunk)
    w_chunks = wte_p.reshape(n_chunks, chunk, -1)  # stored dtype; cast per chunk
    g32 = g.astype(jnp.float32)

    def body(dh, inputs):
        w_c, c_idx = inputs
        w_c32 = w_c.astype(jnp.float32)
        logits = h32 @ w_c32.T
        col = c_idx * chunk + jnp.arange(chunk)
        logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
        p = jnp.exp(logits - lse[:, None])  # softmax rows for this chunk
        local = targets - c_idx * chunk
        in_c = (local >= 0) & (local < chunk)
        onehot = (col[None, :] == targets[:, None]) & in_c[:, None]
        ds = (p - onehot.astype(jnp.float32)) * g32[:, None]  # [N, chunk]
        dh = dh + ds @ w_c32
        dw_c = ds.T @ h32  # [chunk, d]
        return dh, dw_c

    dh0 = jnp.zeros_like(h32)
    dh, dw_chunks = lax.scan(body, dh0, (w_chunks, jnp.arange(n_chunks)))
    dwte = dw_chunks.reshape(n_chunks * chunk, -1)[:v]
    return dh.astype(h.dtype), dwte.astype(wte.dtype), None


_chunked_xent.defvjp(_fwd_rule, _bwd_rule)


def chunked_softmax_xent(
    h: jax.Array,  # [..., d] final hidden states
    wte: jax.Array,  # [V, d] (tied) unembedding matrix
    targets: jax.Array,  # [...] int32
    chunk: int = 8192,
) -> jax.Array:
    """Mean next-token cross-entropy of ``h @ wte.T`` vs ``targets`` without
    ever materializing the logits. Differentiable in h and wte."""
    d = h.shape[-1]
    n_rows = 1
    for s in h.shape[:-1]:
        n_rows *= s
    loss_vec = _chunked_xent(
        h.reshape(n_rows, d), wte, targets.reshape(n_rows).astype(jnp.int32), int(chunk)
    )
    return loss_vec.mean()
