"""Pallas paged-attention decode kernel — gather-free reads of the KV page
pool (the vLLM paged-attention kernel shape, PAPERS.md).

The XLA paged path (``GPT2._paged_attn_inputs``) gathers ``pool[page_table]``
into a dense ``[b, H, max_seq, hd]`` view per layer per tick. On real chips
that round-trips the ENTIRE table width through HBM — gather read, dense
materialization write, attention read — every tick, which erases most of the
paged cache's bandwidth win (capacity still holds; traffic doesn't). This
kernel walks the page table directly instead:

- **One page per grid step.** The table rides as a SCALAR-PREFETCH operand
  (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index maps read
  ``table[b, t]`` and Pallas DMAs exactly that physical page's rows into
  VMEM for grid step ``(b, kv_head, t)`` — the dense view is never
  materialized, and HBM traffic is proportional to the pages the table
  actually names (:func:`paged_hbm_bytes` is the analytic accounting
  the bench's A/B table uses).
- **In-kernel dequantize.** int4 pages unpack their nibbles (the shared
  ``pack_int4`` layout: channel halves contiguous) and both int4/int8 fold
  the per-row scales from ``quantize_kv_rows`` exactly where the XLA path
  does — key scales after the q·k dot, value scales into the probabilities
  before the p·v dot — so the math is the same sum in a different order.
- **Running (out, lse) merge.** Pages fold into online-softmax accumulators
  (running row-max, running denominator — the same logsumexp-merge shape as
  ``ops.ring_attention``'s hop merge), held in VMEM scratch across the
  page-walk grid dimension.
- **Dead-page skipping.** The batcher's sanitized table points every entry
  past a slot's live depth (and every dead slot's entire row) at the
  scratch page 0; pages whose first row is beyond every resident query's
  position skip compute via ``pl.when``, and the repeated scratch-page
  block index collapses to one resident copy — live work, not pool size,
  sets the bill.
- **GQA for free.** Query heads group over their kv head exactly like
  ``Llama._decode_attention``: the grid walks KV heads and each step's q
  block is that head's query GROUP (``rep × C`` rows), so one kernel serves
  GPT-2 (rep=1) and Llama (rep>1), dense-parity pinned for both.

Routing: ``DSML_PAGED_ATTN=pallas|xla`` (:func:`paged_attn_impl`; default
pallas on TPU, xla elsewhere — the gather path stays the fallback and the
parity oracle). All three paged serving surfaces (decode / chunked prefill /
speculative verify) route through here via ``_decode_core_paged``: their
masks are all ``key_pos <= query_pos``, which is the one mask this kernel
implements. On non-TPU backends the kernel runs under the Pallas
interpreter, which is how CI pins parity on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu imports on CPU builds too; guard anyway (ops/flash.py idiom)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from dsml_tpu.ops.vmem_budget import fits_vmem, vmem_block_bytes, warn_once

__all__ = [
    "paged_attention",
    "paged_attn_impl",
    "paged_pipeline",
    "paged_vmem_bytes",
    "paged_hbm_bytes",
]

_NEG_INF = -1e30
_MAX_FLOOR = -1e20  # running-max floor: exp() stays sane on fully-masked rows


def paged_attn_impl(
    page_size: int | None = None,
    head_dim: int | None = None,
    mode: str | None = None,
    n_query_rows: int = 8,
) -> str:
    """The paged-attention routing knob: ``DSML_PAGED_ATTN`` ∈
    {"pallas", "xla"}; unset/malformed defaults to the Pallas kernel on
    TPU and the XLA gather elsewhere (the kernel still RUNS off-TPU via
    the interpreter — tests opt in explicitly — but interpreted ticks are
    the wrong default for a CPU serving loop). Read at trace time: a
    batcher compiles its programs once, so flip the env before
    construction, not between ticks.

    When the caller passes its page GEOMETRY the answer is additionally
    gated on the VMEM budget: a page whose kernel working set can't fit
    the chip's VMEM would die inside Mosaic with an opaque allocation
    error at compile time, so the route falls back to the ``xla`` gather
    path here, with a warn-once, instead. Geometry-less calls keep the
    env-only behavior (the knob test's contract)."""
    raw = os.environ.get("DSML_PAGED_ATTN", "").strip().lower()
    if raw not in ("pallas", "xla"):
        raw = "pallas" if jax.default_backend() == "tpu" else "xla"
    if raw == "pallas" and page_size is not None and head_dim is not None:
        need = paged_vmem_bytes(page_size, head_dim, mode,
                                n_query_rows=n_query_rows,
                                pipeline=paged_pipeline())
        if not fits_vmem(need):
            warn_once(
                f"paged-vmem-{page_size}-{head_dim}-{mode}",
                f"paged-attention kernel working set ({need} B at "
                f"page_size={page_size}, head_dim={head_dim}, mode={mode}) "
                "exceeds the VMEM budget; falling back to the XLA gather "
                "path (set DSML_VMEM_LIMIT_MB or shrink page_size)",
            )
            return "xla"
    return raw


def paged_pipeline() -> bool:
    """The double-buffer knob: ``DSML_PAGED_ATTN_PIPELINE`` ∈ {"1"/"on",
    "0"/"off"}; unset/"auto"/malformed enables the hand-pipelined kernel
    on real TPUs and keeps the single-buffer kernel under the interpreter
    (the interpreter executes DMAs synchronously, so manual pipelining
    there is pure bookkeeping overhead — CPU parity tests opt in
    explicitly). Read at trace time, like ``DSML_PAGED_ATTN``."""
    raw = os.environ.get("DSML_PAGED_ATTN_PIPELINE", "").strip().lower()
    if raw in ("1", "on", "true"):
        return True
    if raw in ("0", "off", "false"):
        return False
    return jax.default_backend() == "tpu"


def paged_vmem_bytes(
    page_size: int,
    head_dim: int,
    mode: str | None,
    n_query_rows: int = 8,
    pipeline: bool = True,
) -> int:
    """Analytic VMEM working set of one paged-attention grid step, at the
    Mosaic-padded footprint of every buffer (``vmem_budget`` sizing rule).
    Both kernels stream pages 2-deep — the pipelined kernel through its
    explicit scratch slots, the single-buffer kernel through Pallas'
    automatic BlockSpec double buffering — so the page term doubles either
    way; the pipelined kernel additionally keeps its own DMA slots for the
    scale columns, and both carry the q/out blocks plus the (acc, m, l)
    online-softmax scratch."""
    wk = head_dim // 2 if mode == "int4" else head_dim
    item = 1 if mode else 4
    depth = 2  # 2-deep streaming either way (manual slots / auto pipeline)
    page = depth * 2 * vmem_block_bytes((page_size, wk), item)
    scales = depth * 2 * vmem_block_bytes((page_size, 1), 4) if mode else 0
    qo = 2 * vmem_block_bytes((n_query_rows, head_dim), 4)
    acc = vmem_block_bytes((n_query_rows, head_dim), 4)
    ml = 2 * vmem_block_bytes((n_query_rows, 128), 4)
    pos = vmem_block_bytes((8, n_query_rows), 4)
    return page + scales + qo + acc + ml + pos


def _vmem_spec(block_shape, index_map):
    if pltpu is not None:
        return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)
    return pl.BlockSpec(block_shape, index_map)  # pragma: no cover


def _scratch(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.MemoryRef(shape, jnp.float32)  # pragma: no cover


def _kernel(table_ref, q_ref, pos_ref, k_ref, v_ref, *rest, mode, scale,
            page_size, n_pt, g_rows):
    """One (batch row, kv head, table entry) grid step: DMA'd page →
    dequantize → masked scores → online-softmax fold into the running
    (acc, m, l) scratch. ``rest`` is ``(k_s_ref, v_s_ref, o_ref, acc, m,
    l)`` for quantized pools and ``(o_ref, acc, m, l)`` for fp pages."""
    if mode:
        k_s_ref, v_s_ref, o_ref, acc, m_scr, l_scr = rest
    else:
        k_s_ref, v_s_ref = None, None
        o_ref, acc, m_scr, l_scr = rest
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, _MAX_FLOOR)
        l_scr[:] = jnp.zeros_like(l_scr)

    posq = pos_ref[0, 0].reshape(g_rows, 1)  # [G, 1] global query positions
    # pages whose FIRST row is past every resident query are fully masked
    # for this batch row — skip the compute (the sanitized table routes
    # them at the scratch page, whose repeated block index Pallas fetches
    # once; the skip is what keeps the MXU bill proportional to live rows)
    max_pos = jnp.max(posq)

    @pl.when(t * page_size <= max_pos)
    def _compute():
        # dequant → key scales AFTER the q·k dot, value scales into the
        # probabilities BEFORE the p·v dot — identical math to the XLA
        # path's scores * k_s^T / probs * v_s^T, shared verbatim with the
        # double-buffered kernel via _fold_page
        _fold_page(
            q_ref, posq, k_ref[0, 0], v_ref[0, 0],
            k_s_ref[0, 0] if mode else None,
            v_s_ref[0, 0] if mode else None,
            acc, m_scr, l_scr,
            mode=mode, scale=scale, page_size=page_size, g_rows=g_rows, t=t,
        )

    @pl.when(t == n_pt - 1)
    def _finish():
        l_fin = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc[:] / l_fin).astype(o_ref.dtype)


def _fold_page(q_ref, posq, k_page, v_page, ks_page, vs_page, acc, m_scr,
               l_scr, *, mode, scale, page_size, g_rows, t):
    """Fold ONE resident page into the online-softmax accumulators — the
    exact float sequence of :func:`_kernel`'s ``_compute`` body (dequant →
    masked scores → running-max merge), factored out so the single-buffer
    and double-buffered kernels share it: bit-identical outputs are an
    acceptance criterion, and sharing the math is how it stays pinned."""
    q = q_ref[0, 0].astype(jnp.float32)  # [G, hd]
    if mode == "int4":
        hi = (k_page >> 4).astype(jnp.int8) - 8
        lo = (k_page & 0xF).astype(jnp.int8) - 8
        k = jnp.concatenate([hi, lo], axis=-1).astype(jnp.float32)
    else:
        k = k_page.astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, page]
    if mode:
        s = s * ks_page.reshape(1, page_size)
    k_pos = t * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (g_rows, page_size), 1
    )
    s = jnp.where(k_pos <= posq, s, _NEG_INF)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_scr[:] = jnp.broadcast_to(
        l_scr[:, :1] * corr + jnp.sum(p, -1, keepdims=True), l_scr.shape
    )
    if mode == "int4":
        hi = (v_page >> 4).astype(jnp.int8) - 8
        lo = (v_page & 0xF).astype(jnp.int8) - 8
        v = jnp.concatenate([hi, lo], axis=-1).astype(jnp.float32)
    else:
        v = v_page.astype(jnp.float32)
    if mode:
        p = p * vs_page.reshape(1, page_size)
    acc[:] = acc[:] * corr + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)


def _pipelined_kernel(table_ref, q_ref, pos_ref, k_hbm, v_hbm, *rest, mode,
                      scale, page_size, n_pt, g_rows):
    """The hand-pipelined page walk: grid is (batch row, kv head) and the
    kernel itself streams that row's LIVE table entries through a 2-deep
    VMEM slot ring — while entry ``t`` computes, entry ``t+1``'s page DMA
    is already in flight (``pltpu.make_async_copy``), so the MXU never
    waits a full page-fetch latency between entries. The pool stays in
    HBM (``ANY`` memory space); only the walked pages ever reach VMEM.

    Dead/scratch entries never enter the pipeline at all: the loop bound
    is the row's live depth (``max_pos // page_size + 1``, straight from
    the resident positions), so a slot's dead-entry tail costs neither
    DMA nor a predicated bubble — the skip CANNOT stall the pipeline
    because skipped entries are never issued. A fully dead slot
    (all positions −1) runs zero iterations and emits zeros, exactly
    like the single-buffer kernel's all-skipped walk."""
    if mode:
        (ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf, vs_buf,
         acc, m_scr, l_scr, sem) = rest
    else:
        o_ref, k_buf, v_buf, acc, m_scr, l_scr, sem = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = None
    bi = pl.program_id(0)
    hi = pl.program_id(1)

    posq = pos_ref[0, 0].reshape(g_rows, 1)  # [G, 1] global query positions
    max_pos = jnp.max(posq)
    # live table entries for this batch row: positions 0..max_pos span
    # pages 0..max_pos // page_size (max_pos == -1 ⇒ zero live entries)
    n_live = jnp.minimum((max_pos + page_size) // page_size, n_pt)

    def _copies(slot, t):
        page = table_ref[bi, t]
        cps = [
            pltpu.make_async_copy(k_hbm.at[page, hi], k_buf.at[slot],
                                  sem.at[slot, 0]),
            pltpu.make_async_copy(v_hbm.at[page, hi], v_buf.at[slot],
                                  sem.at[slot, 1]),
        ]
        if mode:
            cps.append(pltpu.make_async_copy(ks_hbm.at[page, hi],
                                             ks_buf.at[slot], sem.at[slot, 2]))
            cps.append(pltpu.make_async_copy(vs_hbm.at[page, hi],
                                             vs_buf.at[slot], sem.at[slot, 3]))
        return cps

    acc[:] = jnp.zeros_like(acc)
    m_scr[:] = jnp.full_like(m_scr, _MAX_FLOOR)
    l_scr[:] = jnp.zeros_like(l_scr)

    @pl.when(n_live > 0)
    def _prologue():  # warm-up: slot 0's DMA issues before any compute
        for c in _copies(0, 0):
            c.start()

    def _body(t, carry):
        slot = lax.rem(t, 2)

        @pl.when(t + 1 < n_live)
        def _prefetch_next():  # next entry's DMA flies while t computes
            for c in _copies(lax.rem(t + 1, 2), t + 1):
                c.start()

        for c in _copies(slot, t):
            c.wait()
        _fold_page(
            q_ref, posq, k_buf[slot], v_buf[slot],
            ks_buf[slot] if mode else None,
            vs_buf[slot] if mode else None,
            acc, m_scr, l_scr,
            mode=mode, scale=scale, page_size=page_size, g_rows=g_rows, t=t,
        )
        return carry

    lax.fori_loop(0, n_live, _body, 0)

    l_fin = jnp.maximum(l_scr[:, :1], 1e-30)
    o_ref[0, 0] = (acc[:] / l_fin).astype(o_ref.dtype)


def _any_spec():
    return pl.BlockSpec(memory_space=pltpu.TPUMemorySpace.ANY)


def paged_attention(
    q: jax.Array,
    pool_layer: dict,
    page_table: jax.Array,
    positions: jax.Array,
    mode: str | None,
    interpret: bool | None = None,
    pipeline: bool | None = None,
) -> jax.Array:
    """Decode attention straight off the page pool — no dense
    ``[b, H, S, hd]`` view.

    ``q`` [b, hq, C, hd] (C = 1 for decode, the window/chunk width for
    verify/prefill); ``pool_layer`` is ONE layer's pool entry dict
    (``k``/``v`` [P, hkv, page_size, ·] plus ``k_s``/``v_s`` [P, hkv,
    page_size, 1] when quantized — ``init_page_pool``'s layout);
    ``page_table`` [b, n_pt] int32 physical page per (slot, logical page)
    — the batcher's SANITIZED table (dead slots/entries at scratch page
    0); ``positions`` [b, C] int32 global positions of the query rows.
    The mask is ``key_pos <= query_pos`` — exactly the ``valid`` mask all
    three paged serving surfaces pass the XLA path. ``mode`` ∈ {None,
    "int8", "int4"} is the pool codec. Returns [b, hq, C, hd] in
    ``q.dtype``; numeric parity with the gather path and greedy-token
    bit-identity through the paged batcher are pinned in tests.

    ``pipeline`` selects the kernel: ``True`` streams pages through the
    hand-pipelined 2-deep DMA slot ring (:func:`_pipelined_kernel` —
    entry ``t+1``'s fetch overlaps entry ``t``'s math), ``False`` the
    single-buffer grid walk, ``None`` defers to
    ``DSML_PAGED_ATTN_PIPELINE`` (:func:`paged_pipeline`). Both kernels
    fold pages through the SAME ``_fold_page`` float sequence over the
    SAME live-entry order, so outputs are bit-identical — the
    single-buffer kernel is the pipelined kernel's parity oracle. A slot
    ring that can't fit VMEM falls back to the single-buffer kernel with
    a warn-once (:mod:`dsml_tpu.ops.vmem_budget`)."""
    if mode not in (None, "int8", "int4"):
        raise ValueError(f"unknown page quant mode {mode!r}")
    b, hq, c, hd = q.shape
    n_pages, hkv, page_size, _ = pool_layer["k"].shape
    if hq % hkv:
        raise ValueError(f"query heads {hq} not grouped by kv heads {hkv}")
    n_pt = page_table.shape[1]
    rep = hq // hkv
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if pipeline is None:
        pipeline = paged_pipeline()
    if pipeline:
        need = paged_vmem_bytes(page_size, hd, mode, pipeline=True)
        if not fits_vmem(need):
            warn_once(
                f"paged-pipeline-vmem-{page_size}-{hd}-{mode}",
                f"double-buffered paged-attention slot ring ({need} B at "
                f"page_size={page_size}, head_dim={hd}, mode={mode}) "
                "exceeds the VMEM budget; falling back to the "
                "single-buffer kernel",
            )
            pipeline = False

    # group query heads over their kv head (the GQA grouping rule — head
    # h serves kv head h // rep, matching Llama._decode_attention), then
    # flatten (rep, C) into one query-row axis: all of a kv head's queries
    # share its pages, so one grid step scores the whole group
    qg = q.reshape(b, hkv, rep, c, hd).reshape(b, hkv, rep * c, hd)
    posq = jnp.broadcast_to(
        jnp.asarray(positions, jnp.int32)[:, None, :], (b, rep, c)
    ).reshape(b, rep * c)
    g = rep * c
    gp = max(8, -(-g // 8) * 8)  # sublane-tileable query-row count
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))
        # padded rows mask everything (-1 admits no key position); their
        # zero q rows produce finite garbage that is sliced off below
        posq = jnp.pad(posq, ((0, 0), (0, gp - g)), constant_values=-1)
    # positions ride VMEM broadcast over 8 sublanes (the flash lse trick:
    # the block shape stays Mosaic-tileable)
    pos8 = jnp.broadcast_to(posq[:, None, :], (b, 8, gp))

    if pltpu is None:  # pragma: no cover — pltpu importable on all builds
        raise RuntimeError("pallas TPU frontend unavailable")

    if pipeline:
        # grid walks (batch row, kv head); the kernel streams that row's
        # live table entries itself through the 2-deep DMA slot ring —
        # the pool operands stay in HBM (ANY), only walked pages land in
        # the VMEM scratch slots
        kernel = functools.partial(
            _pipelined_kernel, mode=mode, scale=hd ** -0.5,
            page_size=page_size, n_pt=n_pt, g_rows=gp,
        )
        in_specs = [
            _vmem_spec((1, 1, gp, hd), lambda bi, hi, tab: (bi, hi, 0, 0)),
            _vmem_spec((1, 8, gp), lambda bi, hi, tab: (bi, 0, 0)),
            _any_spec(), _any_spec(),
        ]
        operands = [qg, pos8, pool_layer["k"], pool_layer["v"]]
        kdt = pool_layer["k"].dtype
        scratch = [
            pltpu.VMEM((2, page_size, pool_layer["k"].shape[-1]), kdt),
            pltpu.VMEM((2, page_size, pool_layer["v"].shape[-1]), kdt),
        ]
        if mode:
            in_specs += [_any_spec(), _any_spec()]
            operands += [pool_layer["k_s"], pool_layer["v_s"]]
            scratch += [
                pltpu.VMEM((2, page_size, 1), jnp.float32),
                pltpu.VMEM((2, page_size, 1), jnp.float32),
            ]
        scratch += [
            _scratch((gp, hd)), _scratch((gp, 128)), _scratch((gp, 128)),
            pltpu.SemaphoreType.DMA((2, 4 if mode else 2)),
        ]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hkv),
            in_specs=in_specs,
            out_specs=_vmem_spec((1, 1, gp, hd),
                                 lambda bi, hi, tab: (bi, hi, 0, 0)),
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), jnp.float32),
            interpret=interpret,
        )(jnp.asarray(page_table, jnp.int32), *operands)
        out = out[:, :, :g].reshape(b, hkv, rep, c, hd).reshape(b, hq, c, hd)
        return out.astype(q.dtype)

    kernel = functools.partial(
        _kernel, mode=mode, scale=hd ** -0.5, page_size=page_size,
        n_pt=n_pt, g_rows=gp,
    )
    in_specs = [
        _vmem_spec((1, 1, gp, hd), lambda bi, hi, ti, tab: (bi, hi, 0, 0)),
        _vmem_spec((1, 8, gp), lambda bi, hi, ti, tab: (bi, 0, 0)),
        # the page walk: table[b, t] names the physical page this grid
        # step reads — Pallas DMAs that page's rows, nothing else
        _vmem_spec((1, 1, page_size, pool_layer["k"].shape[-1]),
                   lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
        _vmem_spec((1, 1, page_size, pool_layer["v"].shape[-1]),
                   lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
    ]
    operands = [qg, pos8, pool_layer["k"], pool_layer["v"]]
    if mode:
        in_specs += [
            _vmem_spec((1, 1, page_size, 1),
                       lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
            _vmem_spec((1, 1, page_size, 1),
                       lambda bi, hi, ti, tab: (tab[bi, ti], hi, 0, 0)),
        ]
        operands += [pool_layer["k_s"], pool_layer["v_s"]]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hkv, n_pt),
        in_specs=in_specs,
        out_specs=_vmem_spec((1, 1, gp, hd),
                             lambda bi, hi, ti, tab: (bi, hi, 0, 0)),
        scratch_shapes=[
            _scratch((gp, hd)), _scratch((gp, 128)), _scratch((gp, 128)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, hd), jnp.float32),
        interpret=interpret,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    out = out[:, :, :g].reshape(b, hkv, rep, c, hd).reshape(b, hq, c, hd)
    return out.astype(q.dtype)


def paged_hbm_bytes(
    n_slots: int,
    n_pt: int,
    page_size: int,
    n_kv_head: int,
    head_dim: int,
    mode: str | None,
    live_pages: int,
    impl: str,
    n_query_rows: int = 1,
    n_query_heads: int | None = None,
) -> int:
    """Analytic HBM bytes ONE layer's paged-attention read costs per
    decode tick — counted from the program structure, not sampled (the
    ``collectives.ring_wire_bytes`` contract), with the scratch-page
    term charged at its worst case. The bench's A/B table and the
    contract test's scales-with-live-work assertion both read this.

    Every quantized page moves its PAYLOAD and its SCALES: the kernel
    DMAs the per-row f32 scale columns (``k_s``/``v_s``, 4 bytes per K
    row and per V row) alongside the packed payload, and the gather path
    gathers them, so both bills carry an explicit per-row scale term —
    8 bytes per position under int8/int4, zero for fp pages. The split
    (``_paged_row_bytes``) is pinned against ``kv_row_bytes`` in
    ``test_paged_attention.py``; a model that counted packed payload
    alone would understate int4 traffic by 20% at head_dim 64.

    ``impl="xla"`` — the gather path's bill is TABLE-shaped: it reads one
    page per table entry for every slot (``n_slots × n_pt`` pages, the
    scratch page re-read per duplicate entry), writes the gathered dense
    view, and reads that view back in the attention dots — regardless of
    how many rows are live. ``impl="pallas"`` — the kernel's bill is
    LIVE-shaped: ``live_pages`` counts live TABLE ENTRIES summed over
    slots (a CoW-shared page counts once per slot naming it — each
    (slot, head) grid row DMAs its own copy), each entry fetches once
    per kv head, and each slot's dead-entry tail re-fetches the scratch
    page once per (slot, head) run — the ``+ n_slots`` term (a slot with
    zero dead entries skips it; this model charges the worst case; the
    double-buffered kernel never fetches the tail at all, so its bill
    is bounded above by this). Query/output bytes ride both and are
    counted for honesty — per QUERY head (``n_query_heads``, defaulting
    to ``n_kv_head`` for the rep=1 families; GQA callers pass their
    ``rep × n_kv_head``); they are noise next to the pool traffic."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown paged-attention impl {impl!r}")
    payload_row, scale_row = _paged_row_bytes(head_dim, mode)
    row = payload_row + scale_row  # one position's K + V + both scales
    page_bytes = n_kv_head * page_size * row
    hq = n_kv_head if n_query_heads is None else n_query_heads
    qo_bytes = 2 * n_slots * hq * n_query_rows * head_dim * 4
    if impl == "pallas":
        return (live_pages + n_slots) * page_bytes + qo_bytes
    gathered = n_slots * n_pt * page_bytes  # pool read, table-shaped
    # dense view materialized in the unpacked int8 (or fp) row width plus
    # scales, written once and read back by the attention dots
    dense_row = 2 * (head_dim + 4) if mode else 2 * 4 * head_dim
    dense = n_slots * n_pt * page_size * n_kv_head * dense_row
    return gathered + 2 * dense + qo_bytes


def _paged_row_bytes(head_dim: int, mode: str | None) -> tuple[int, int]:
    """(payload, scale) HBM bytes one POSITION moves through a paged
    read — K row + V row, and their two f32 scales when quantized. The
    sum equals ``2 * kv_row_bytes(head_dim, mode)`` by construction
    (pinned in tests); the split exists so callers and tests can see the
    scale traffic explicitly instead of trusting it is in there."""
    from dsml_tpu.ops.quantization import kv_row_bytes

    scale_row = 8 if mode else 0  # one f32 scale per K row + one per V row
    payload_row = 2 * kv_row_bytes(head_dim, mode) - scale_row
    return payload_row, scale_row
